"""The fleet serving gateway: route, admit, tick, scale, narrate.

`ServingGateway` fronts N DecodeEngine replicas (models/serving.py) with
the cluster-level request path the node layer cannot provide alone:

- requests enter through SLO-aware admission (admission.py: priority
  queues, watermark shedding, queue deadlines — typed
  :class:`OverloadedError`, never silent queueing),
- dispatch routes prefix-affinity-first with a least-loaded fallback
  (router.py), so the single-engine prefix cache (PR 9) becomes a fleet
  property: same-system-prompt traffic keeps landing where its KV is
  already warm,
- a per-tick autoscaler (autoscaler.py) closes the loop from fleet
  backlog to replica count through a pluggable provisioner (the PR-8
  batch allocator in the cluster sim),
- drain/failover is loss-classified: a DRAINING replica finishes its
  admitted requests and hands its queued ones back for re-routing (zero
  admitted loss); a GONE replica's in-flight requests surface as typed
  retryable :class:`ReplicaLostError`, never as silence.

Everything observable lands in three places: ``tpu_dra_gw_*`` metric
families, a 256-deep ring buffer served at ``/debug/gateway``
(``MetricsServer.set_gateway_provider``, same GET-only contract as
usage/defrag/rebalance), and deduped ``Gateway*`` Events. Chaos sites
``gateway.route`` / ``gateway.drain`` / ``gateway.scale`` make the
three state transitions injectable (utils/faults.py).

Per-request observability is opt-in via ``telemetry=`` (a
``serving_gateway/reqtrace.ServingTelemetry``): every submit then opens
a root span on the contextvars tracer (its trace id is returned on the
handle — and on the typed shed error — so callers, JSON log lines, and
engine events all correlate), a timeline follows the request through
class queue, routing, engine admission, prefill, decode, and its
terminal outcome, tick wall time decomposes into named phases, and
per-class SLO histograms/violations/exemplars accumulate for
``fleet_slo_summary()``. ``telemetry=None`` (the default) keeps every
hot path on its pre-observability branch.

The tick loop is host-side and single-threaded by design, like the
engine's: ``tick()`` advances admission, dispatch, every replica's
engine, and the autoscaler exactly once, so tests and benches replay
deterministically.
"""

from __future__ import annotations

import collections
import contextlib
import dataclasses
import logging
import time
from typing import Callable, Optional

from ..api.v1alpha1.slo import BATCH_CLASS, LATENCY_CLASSES
from ..kube.events import EventRecorder, ObjectRef
from ..utils import faults
from ..utils.metrics import Counter, Gauge, Registry
# Imported as a module: reqtrace's OUTCOME_* terminal-outcome names
# would shadow the autoscaler's OUTCOME_* decision names below.
from . import reqtrace
from .admission import (
    SHED_DEADLINE,
    SHED_REASONS,
    AdmissionController,
    AdmissionPolicy,
    OverloadedError,
)
from .autoscaler import (
    DIRECTION_UP,
    DIRECTIONS,
    OUTCOME_APPLIED,
    OUTCOME_FAILED,
    OUTCOMES,
    Autoscaler,
    ScaleError,
)
from .residency import ResidencyIndex
from .router import (
    POLICIES,
    REPLICA_DRAINING,
    REPLICA_GONE,
    REPLICA_HEALTHY,
    NoReplicaAvailableError,
    Replica,
    Router,
)

logger = logging.getLogger(__name__)

# Gateway-request lifecycle.
GW_QUEUED = "queued"
GW_DISPATCHED = "dispatched"
GW_FINISHED = "finished"
GW_FAILED = "failed"

RING_DEPTH = 256

# tpu_dra_gw_replicas only renders REGISTERED states: a GONE replica is
# deregistered from the router in the same call that marks it (its
# departure is observable in the ring records and Gateway* Events, and
# REPLICA_GONE stays readable on the returned handle).
_GAUGE_STATES = (REPLICA_HEALTHY, REPLICA_DRAINING)


class ReplicaLostError(RuntimeError):
    """The replica serving this request went away before finishing it.
    Retryable by contract: the prompt is intact on the handle and a
    resubmit re-routes it (usually onto a still-warm prefix)."""

    retryable = True

    def __init__(self, replica_id: str, reason: str = ""):
        self.replica_id = replica_id
        super().__init__(
            f"replica {replica_id} lost mid-flight"
            + (f": {reason}" if reason else "")
        )


@dataclasses.dataclass
class GatewayRequest:
    """One fleet request and its gateway-side state. ``tokens`` only
    means anything once ``state == "finished"``; a failed request
    carries its typed error in ``error``."""

    gid: int
    prompt: list[int]
    max_new_tokens: int
    latency_class: str
    submitted_at: float
    state: str = GW_QUEUED
    replica_id: str = ""
    engine_req: Optional[object] = None
    error: Optional[BaseException] = None
    dispatches: int = 0
    finished_at: Optional[float] = None
    # Filled only when the gateway runs with telemetry: the root span's
    # trace id (joins gateway and engine spans/log lines) and the
    # request's reqtrace timeline.
    trace_id: str = ""
    timeline: Optional[object] = None

    @property
    def done(self) -> bool:
        return self.state in (GW_FINISHED, GW_FAILED)

    @property
    def tokens(self) -> list[int]:
        return list(self.engine_req.tokens) if self.engine_req else []


class ServingGateway:
    """See module docstring. ``registry`` may be shared with the rest
    of the process, but metric families register once — construct ONE
    gateway per registry (a second raises the registry's duplicate-name
    error). ``autoscaler`` is optional; without it the replica set only
    changes through add_replica/drain_replica/fail_replica."""

    def __init__(
        self,
        registry: Optional[Registry] = None,
        *,
        router: Optional[Router] = None,
        admission_policy: Optional[AdmissionPolicy] = None,
        autoscaler: Optional[Autoscaler] = None,
        events: Optional[EventRecorder] = None,
        node_name: str = "",
        node_uid: str = "",
        clock: Callable[[], float] = time.monotonic,
        telemetry: Optional["reqtrace.ServingTelemetry"] = None,
    ):
        self.router = router or Router()
        self.admission = AdmissionController(admission_policy)
        self.autoscaler = autoscaler
        self.events = events
        self.telemetry = telemetry
        self.node_name = node_name
        self.node_uid = node_uid
        self._clock = clock
        self._gid = 0
        self.ticks = 0
        self._live: dict[int, GatewayRequest] = {}
        # replica_id -> {id(engine_req): GatewayRequest} for every
        # dispatched-but-unfinished request.
        self._dispatched: dict[str, dict[int, GatewayRequest]] = {}
        self._ring: collections.deque = collections.deque(maxlen=RING_DEPTH)
        self.counters = collections.Counter()

        registry = registry or Registry()
        self._m_routed = Counter(
            "tpu_dra_gw_routed_total",
            "Requests dispatched to a replica, by routing policy "
            "(affinity, p2c, round-robin)",
            registry,
        )
        self._m_affinity_lookups = Counter(
            "tpu_dra_gw_affinity_lookups_total",
            "Dispatches that computed a prefix-affinity key (the prompt "
            "had at least one full KV block)",
            registry,
        )
        self._m_affinity_hits = Counter(
            "tpu_dra_gw_affinity_hits_total",
            "Affinity dispatches whose target replica had served the "
            "same prefix key before (its KV cache is warm)",
            registry,
        )
        self._m_queue_depth = Gauge(
            "tpu_dra_gw_queue_depth",
            "Requests waiting in the gateway's admission queues, by "
            "latency class",
            registry,
        )
        self._m_shed = Counter(
            "tpu_dra_gw_shed_total",
            "Requests rejected with a typed Overloaded error, by "
            "latency class and reason (watermark, deadline)",
            registry,
        )
        self._m_replicas = Gauge(
            "tpu_dra_gw_replicas",
            "Registered replicas by state (healthy, draining); a lost "
            "or removed replica deregisters",
            registry,
        )
        self._m_scale = Counter(
            "tpu_dra_gw_scale_decisions_total",
            "Autoscaler decisions by direction and outcome (applied, "
            "failed, cooldown, dwell, clamped)",
            registry,
        )
        self._m_requests = Counter(
            "tpu_dra_gw_requests_total",
            "Gateway requests finished, by outcome (completed, failed)",
            registry,
        )
        self._m_affinity_ledger = Gauge(
            "tpu_dra_gw_affinity_ledger_keys",
            "Prefix keys in the router's per-replica affinity ledger "
            "(seen_keys), by replica; the series is removed when the "
            "replica deregisters",
            registry,
        )
        # Explicit zeros: dashboards must see every family (and the
        # label enums) before the first shed/scale ever happens.
        for policy in POLICIES:
            self._m_routed.inc(0.0, policy=policy)
        for lc in sorted(LATENCY_CLASSES):
            self._m_queue_depth.set(0, latency_class=lc)
            for reason in SHED_REASONS:
                self._m_shed.inc(0.0, latency_class=lc, reason=reason)
        for d in DIRECTIONS:
            for o in OUTCOMES:
                self._m_scale.inc(0.0, direction=d, outcome=o)
        for state in _GAUGE_STATES:
            self._m_replicas.set(0, state=state)
        for outcome in ("completed", "failed"):
            self._m_requests.inc(0.0, outcome=outcome)
        # Fleet-wide measured KV residency (residency.py): joins every
        # replica's engine-published digest against the affinity ledger
        # above. Shares this registry — its tpu_dra_residency_* gauges
        # refresh at scrape, and the /debug/residency provider is
        # self.residency.snapshot.
        self.residency = ResidencyIndex(self.router, registry=registry)
        registry.add_render_hook(self._sync_ledger_gauge)

    def _sync_ledger_gauge(self) -> None:
        # Scrape-time sync: ledger size changes on every dispatch, so a
        # render hook beats touching the gauge on the serving path.
        for r in self.router.replicas():
            self._m_affinity_ledger.set(
                len(r.seen_keys), replica=r.replica_id
            )

    def _forget_replica_series(self, replica: Replica) -> None:
        # Honest ledger bounds on deregistration (drain(remove=True) /
        # fail): drop the ledger itself — the Replica handle outlives
        # the router entry and must not pin thousands of keys — and
        # remove, not zero, its per-replica gauge series (the departed-
        # claim series pattern; a dead replica scraping as a live 0
        # forever is unbounded cardinality over churn).
        replica.seen_keys.clear()
        self._m_affinity_ledger.remove(replica=replica.replica_id)
        self.residency.forget_replica(replica.replica_id)

    # -- replica lifecycle -------------------------------------------------

    def add_replica(self, engine, replica_id: Optional[str] = None,
                    claim_uid: str = "") -> Replica:
        if replica_id is None:
            replica_id = f"replica-{len(self.router.replicas())}"
        replica = Replica(replica_id, engine, claim_uid=claim_uid)
        self.router.add(replica)
        self._dispatched.setdefault(replica_id, {})
        self._attach_profiler(replica)
        self._refresh_replica_gauge()
        return replica

    def _attach_profiler(self, replica: Replica) -> None:
        # Engine ticks share ONE TickProfiler (component="engine"); the
        # replica id travels as the ring entry's free-form tag, never a
        # metric label (replica ids are unbounded cardinality).
        if self.telemetry is None:
            return
        if hasattr(replica.engine, "set_profiler"):
            replica.engine.set_profiler(
                self.telemetry.profiler, tag=replica.replica_id
            )

    def replicas(self) -> list[Replica]:
        return self.router.replicas()

    def _refresh_replica_gauge(self) -> None:
        by_state = collections.Counter(
            r.state for r in self.router.replicas()
        )
        for state in _GAUGE_STATES:
            self._m_replicas.set(by_state.get(state, 0), state=state)

    # -- submission --------------------------------------------------------

    def fleet_queue_depth(self) -> int:
        """Gateway queues + every registered replica's backlog — the
        admission watermark and autoscaler signal. (GONE replicas never
        appear here: they deregister in the call that marks them.)"""
        return self.admission.depth() + sum(
            r.queue_depth() for r in self.router.replicas()
        )

    def submit(self, prompt, max_new_tokens: int,
               latency_class: str = BATCH_CLASS) -> GatewayRequest:
        """Admit a request into the fleet (or shed it, typed). The
        handle's tokens fill in as some replica serves it. With
        telemetry, the handle (and a shed's OverloadedError) carries
        ``trace_id`` so callers can join gateway and engine records."""
        now = self._clock()
        tel = self.telemetry
        span = None
        tl = None
        with contextlib.ExitStack() as stack:
            if tel is not None:
                span = stack.enter_context(tel.tracer.span(
                    "gateway/submit", latency_class=latency_class,
                ))
                tl = tel.new_timeline(
                    latency_class, now, trace_id=span.trace_id,
                    prompt_tokens=len(prompt),
                )
            try:
                self.admission.check(
                    latency_class, self.fleet_queue_depth()
                )
            except OverloadedError as e:
                if tel is not None:
                    span.set_error(f"shed: {e.reason}")
                    e.trace_id = span.trace_id
                    logger.warning(
                        "shed a %s request (%s) at fleet queue depth %d",
                        latency_class, e.reason, e.queue_depth,
                    )
                    tel.finish_timeline(
                        tl, reqtrace.OUTCOME_SHED, now,
                        reason=e.reason, queueDepth=e.queue_depth,
                    )
                self._shed(latency_class, e, now)
                raise
            req = GatewayRequest(
                gid=self._gid, prompt=[int(t) for t in prompt],
                max_new_tokens=max_new_tokens,
                latency_class=latency_class, submitted_at=now,
            )
            if tel is not None:
                req.trace_id = span.trace_id
                req.timeline = tl
                tl.gid = req.gid
                span.set_tag("gid", req.gid)
            self._gid += 1
            self._live[req.gid] = req
            self.admission.enqueue(req)
            return req

    def _shed(self, latency_class: str, err: OverloadedError,
              now: float) -> None:
        self.counters["shed"] += 1
        self._m_shed.inc(latency_class=latency_class, reason=err.reason)
        self._record({
            "kind": "shed", "latencyClass": latency_class,
            "reason": err.reason, "queueDepth": err.queue_depth,
        }, now)
        if self.events is not None:
            self.events.warning(
                self._node_ref(), "GatewayOverloaded",
                f"shed a {latency_class} request ({err.reason}) at fleet "
                f"queue depth {err.queue_depth} on {self.node_name}",
            )

    # -- the tick ----------------------------------------------------------

    def tick(self) -> None:
        """One gateway scheduling round: expire deadlines, dispatch in
        class-priority order while capacity exists, advance every
        replica engine one tick, harvest completions, then let the
        autoscaler look at the result. With telemetry the round runs
        inside a ``gateway/tick`` span (engine/scale log lines inherit
        its trace id) and decomposes into the GATEWAY_PHASES buckets of
        ``tpu_dra_srv_tick_phase_seconds``."""
        tel = self.telemetry
        if tel is None:
            self._tick_once(None)
            return
        with tel.tracer.span("gateway/tick", tick=self.ticks + 1):
            self._tick_once(tel.profiler)
        tel.profiler.end_tick("gateway", self.ticks)

    def _tick_once(self, prof) -> None:
        now = self._clock()
        self.ticks += 1
        with reqtrace.phase_ctx(prof, "gateway", "expire"):
            for req in self.admission.expire(now):
                err = OverloadedError(
                    "queued past its class deadline",
                    latency_class=req.latency_class,
                    reason=SHED_DEADLINE,
                    retry_after_s=self.admission.policy.retry_after_s,
                    queue_depth=self.fleet_queue_depth(),
                )
                self._fail(req, err, now)
                self._shed(req.latency_class, err, now)
        with reqtrace.phase_ctx(prof, "gateway", "dispatch"):
            self._dispatch(now)
        with reqtrace.phase_ctx(prof, "gateway", "replicas"):
            for replica in self.router.replicas():
                if replica.engine.idle:
                    continue
                replica.engine.tick()
        with reqtrace.phase_ctx(prof, "gateway", "harvest"):
            for replica in self.router.replicas():
                self._harvest(replica, now)
        with reqtrace.phase_ctx(prof, "gateway", "autoscale"):
            if self.autoscaler is not None:
                self._autoscale(now)
        for lc, depth in self.admission.depth_by_class().items():
            self._m_queue_depth.set(depth, latency_class=lc)

    def run(self, max_ticks: int = 100000) -> None:
        """Drive ticks until every submitted request has finished or
        failed."""
        for _ in range(max_ticks):
            if not self._live:
                return
            self.tick()
        raise RuntimeError(
            f"gateway not drained after {max_ticks} ticks "
            f"({len(self._live)} live requests)"
        )

    def _dispatch(self, now: float) -> None:
        while self.router.has_capacity():
            req = self.admission.pop(now)
            if req is None:
                return
            try:
                faults.fire("gateway.route")
                decision = self.router.route(req.prompt)
            except NoReplicaAvailableError:
                self.admission.push_back(req)
                return
            except faults.CrashPoint:
                # A simulated hard crash must not half-dispatch: the
                # request stays queued for the restarted gateway.
                self.admission.push_back(req)
                raise
            except Exception as e:
                # An injected routing fault: the request stays queued
                # and retries next tick; the failure is observable.
                self.admission.push_back(req)
                self._record({"kind": "route-failed", "error": str(e)},
                             now)
                return
            try:
                engine_req = decision.replica.engine.submit(
                    req.prompt, req.max_new_tokens
                )
            except Exception as e:
                # Typed engine-side refusal (pool too small for this
                # request, admission raced closed): surface it on the
                # handle — queueing it forever would be the silent
                # failure mode this layer exists to prevent.
                self._fail(req, e, now)
                continue
            req.state = GW_DISPATCHED
            req.replica_id = decision.replica.replica_id
            req.engine_req = engine_req
            req.dispatches += 1
            self._dispatched[decision.replica.replica_id][
                id(engine_req)
            ] = req
            self.counters["routed"] += 1
            self._m_routed.inc(policy=decision.policy)
            if decision.affinity_key is not None:
                self.counters["affinity_lookups"] += 1
                self._m_affinity_lookups.inc()
                if decision.affinity_hit:
                    self.counters["affinity_hits"] += 1
                    self._m_affinity_hits.inc()
            if req.timeline is not None:
                req.timeline.event(
                    "routed", now,
                    replica=decision.replica.replica_id,
                    policy=decision.policy,
                    affinityHit=decision.affinity_hit,
                    affinityKey=decision.affinity_key is not None,
                    replicaQueueDepth=decision.queue_depth,
                    dispatch=req.dispatches,
                )
                # Hand the timeline to the engine request so engine-side
                # events (admit, prefill chunks, first token, preemption,
                # retire) land on the same record.
                engine_req.timeline = req.timeline
            if self.telemetry is not None:
                self.telemetry.note_route(
                    decision.affinity_key, decision.affinity_hit
                )

    def _harvest(self, replica: Replica, now: float) -> None:
        table = self._dispatched.get(replica.replica_id) or {}
        finished = [
            (k, greq) for k, greq in table.items()
            if greq.engine_req is not None and greq.engine_req.done
        ]
        for k, greq in finished:
            del table[k]
            greq.state = GW_FINISHED
            greq.finished_at = now
            self._live.pop(greq.gid, None)
            self.counters["completed"] += 1
            self._m_requests.inc(outcome="completed")
            if greq.timeline is not None and self.telemetry is not None:
                # observe_request feeds the per-class SLO histograms and
                # violation/exemplar ledger, then seals the timeline.
                self.telemetry.observe_request(
                    greq.timeline, now,
                    tokens=len(
                        getattr(greq.engine_req, "generated", []) or []
                    ),
                )

    def _fail(self, req: GatewayRequest, err: BaseException,
              now: float) -> None:
        req.state = GW_FAILED
        req.error = err
        req.finished_at = now
        self._live.pop(req.gid, None)
        self.counters["failed"] += 1
        self._m_requests.inc(outcome="failed")
        if req.timeline is not None and self.telemetry is not None:
            outcome = (
                reqtrace.OUTCOME_EXPIRED
                if isinstance(err, OverloadedError)
                and err.reason == SHED_DEADLINE
                else reqtrace.OUTCOME_FAILED
            )
            self.telemetry.finish_timeline(
                req.timeline, outcome, now,
                error=f"{type(err).__name__}: {err}",
            )

    # -- drain / failover --------------------------------------------------

    def _maybe_span(self, name: str, **tags):
        """A tracer span when telemetry is on, else a no-op context —
        so drain/failover log lines and engine events correlate under
        one trace id without a second code path."""
        if self.telemetry is None:
            return contextlib.nullcontext()
        return self.telemetry.tracer.span(name, tags=tags)

    def drain_replica(self, replica_id: str, *, remove: bool = False,
                      reason: str = "") -> int:
        """Gracefully stop a replica: admission closes, its queued
        (never-prefilled) requests re-enter the gateway queues at the
        front, and its admitted requests run to completion — zero
        admitted-request loss. Returns the number of re-routed
        requests. ``remove=True`` deregisters it afterwards (the
        scale-down path)."""
        with self._maybe_span("gateway/drain", replica=replica_id,
                              reason=reason):
            return self._drain_replica(replica_id, remove=remove,
                                       reason=reason)

    def _drain_replica(self, replica_id: str, *, remove: bool,
                       reason: str) -> int:
        faults.fire("gateway.drain")
        now = self._clock()
        replica = self.router.get(replica_id)
        replica.state = REPLICA_DRAINING
        replica.state_reason = reason
        self._refresh_replica_gauge()
        rerouted = replica.engine.drain()
        table = self._dispatched.get(replica_id) or {}
        requeue = []
        for engine_req in rerouted:
            greq = table.pop(id(engine_req), None)
            if greq is None:
                continue
            greq.state = GW_QUEUED
            greq.replica_id = ""
            greq.engine_req = None
            if greq.timeline is not None:
                greq.timeline.event(
                    "requeued", now, replica=replica_id, reason="drain",
                )
            requeue.append(greq)
        # requeue_front is an appendleft: push in REVERSE so the oldest
        # re-routed request ends up at the head — arrival order within
        # the class is preserved, as the admission contract promises.
        for greq in reversed(requeue):
            self.admission.requeue_front(greq)
        n_rerouted = len(requeue)
        logger.info(
            "draining replica %s%s: %d queued request(s) re-routed",
            replica_id, f" ({reason})" if reason else "", n_rerouted,
        )
        # Everything admitted finished inside drain(): harvest them.
        self._harvest(replica, now)
        leftovers = list((self._dispatched.get(replica_id) or {}).values())
        for greq in leftovers:
            # Should be empty by construction; surfacing (not silently
            # dropping) any straggler keeps the zero-loss claim honest.
            self._fail(greq, ReplicaLostError(replica_id, "drain race"),
                       now)
        if remove:
            replica.state = REPLICA_GONE
            self.router.remove(replica_id)
            self._dispatched.pop(replica_id, None)
            self._forget_replica_series(replica)
        else:
            self._dispatched[replica_id] = {}
        self._refresh_replica_gauge()
        self._record({
            "kind": "drain", "replicaId": replica_id, "reason": reason,
            "rerouted": n_rerouted, "lost": len(leftovers),
            "removed": remove,
        }, now)
        if self.events is not None:
            self.events.normal(
                self._node_ref(), "GatewayReplicaDrained",
                f"replica {replica_id} drained on {self.node_name}"
                + (f" ({reason})" if reason else "")
                + f": {n_rerouted} queued request(s) re-routed, "
                  "admitted requests completed",
            )
        return n_rerouted

    def drain_claim(self, claim_uid: str, *, reason: str = "") -> list[str]:
        """Defrag executor drain contract: drain every live replica
        bound to ``claim_uid`` (each through the zero-loss
        :meth:`drain_replica` path) and return their ids so the caller
        can resume them once the claim's devices have moved. A claim
        with no serving replicas returns ``[]`` — draining is then a
        no-op, not an error (the claim may be a training gang)."""
        drained = []
        for r in self.router.replicas():
            if r.claim_uid != claim_uid or r.state == REPLICA_GONE:
                continue
            if r.state != REPLICA_DRAINING:
                self.drain_replica(r.replica_id, reason=reason)
            drained.append(r.replica_id)
        return drained

    def resume_replica(self, replica_id: str) -> None:
        """Reopen a drained replica for dispatch (the defrag executor's
        post-migration counterpart of :meth:`drain_replica`, and the
        rollback path's undo). Only DRAINING replicas transition; GONE
        ones stay gone."""
        now = self._clock()
        replica = self.router.get(replica_id)
        if replica.state != REPLICA_DRAINING:
            return
        replica.state = REPLICA_HEALTHY
        replica.state_reason = ""
        # drain() closed engine-level admission; a resumed replica must
        # accept dispatches again or it sits healthy-but-deaf.
        if hasattr(replica.engine, "resume_admission"):
            replica.engine.resume_admission()
        self._refresh_replica_gauge()
        self._record({"kind": "resume", "replicaId": replica_id}, now)

    def resume_claim(self, claim_uid: str) -> list[str]:
        """Resume every DRAINING replica bound to ``claim_uid``; returns
        the resumed ids. Idempotent — the executor calls it after a
        migration lands AND during rollback/recovery, where any subset
        of the claim's replicas may have been drained."""
        resumed = []
        for r in self.router.replicas():
            if r.claim_uid != claim_uid or r.state != REPLICA_DRAINING:
                continue
            self.resume_replica(r.replica_id)
            resumed.append(r.replica_id)
        return resumed

    def fail_replica(self, replica_id: str, reason: str = "") -> int:
        """Hard failover: the replica is gone (chip unplugged, pod
        killed). Its queued requests re-route — they held no computed
        state — and its in-flight ones fail with a typed, retryable
        :class:`ReplicaLostError`. Returns the number of lost in-flight
        requests."""
        with self._maybe_span("gateway/failover", replica=replica_id,
                              reason=reason):
            return self._fail_replica(replica_id, reason)

    def _fail_replica(self, replica_id: str, reason: str) -> int:
        now = self._clock()
        replica = self.router.get(replica_id)
        replica.state = REPLICA_GONE
        replica.state_reason = reason
        table = self._dispatched.get(replica_id) or {}
        waiting_ids = {id(r) for r in replica.engine.waiting}
        requeue = []
        lost = []
        for k, greq in list(table.items()):
            del table[k]
            if k in waiting_ids:
                greq.state = GW_QUEUED
                greq.replica_id = ""
                greq.engine_req = None
                if greq.timeline is not None:
                    greq.timeline.event(
                        "requeued", now, replica=replica_id,
                        reason="replica-lost",
                    )
                requeue.append(greq)
            else:
                lost.append(greq)
                self._fail(
                    greq, ReplicaLostError(replica_id, reason), now
                )
        # Reversed for the same arrival-order reason as drain_replica.
        for greq in reversed(requeue):
            self.admission.requeue_front(greq)
        n_rerouted = len(requeue)
        logger.warning(
            "replica %s lost%s: %d queued re-routed, %d in-flight "
            "failed retryable",
            replica_id, f" ({reason})" if reason else "",
            n_rerouted, len(lost),
        )
        self.router.remove(replica_id)
        self._dispatched.pop(replica_id, None)
        self._forget_replica_series(replica)
        self._refresh_replica_gauge()
        self._record({
            "kind": "replica-lost", "replicaId": replica_id,
            "reason": reason, "rerouted": n_rerouted, "lost": len(lost),
        }, now)
        if self.events is not None:
            self.events.warning(
                self._node_ref(), "GatewayReplicaLost",
                f"replica {replica_id} lost on {self.node_name}"
                + (f" ({reason})" if reason else "")
                + f": {n_rerouted} queued re-routed, {len(lost)} "
                  "in-flight surfaced as retryable errors",
            )
        return len(lost)

    def resubmit(self, req: GatewayRequest) -> GatewayRequest:
        """Retry a failed request (the ReplicaLostError contract): a
        fresh handle through normal admission, same prompt and class."""
        return self.submit(req.prompt, req.max_new_tokens,
                           latency_class=req.latency_class)

    # -- autoscaling -------------------------------------------------------

    def _fleet_ttft_p99_ms(self) -> float:
        # Only computed when the TTFT signal is armed: the percentile
        # sorts ServingStats' unbounded sample lists, so running it per
        # tick for a disabled signal would make a long-lived gateway's
        # loop progressively slower for nothing.
        vals = []
        for r in self.router.replicas():
            if r.state != REPLICA_HEALTHY:
                continue
            stats = getattr(r.engine, "stats", None)
            if stats is not None and hasattr(stats, "p99_ttft_ms"):
                vals.append(stats.p99_ttft_ms())
            else:
                vals.append(r.engine.snapshot().get("ttftP99Ms", 0.0))
        return max(vals) if vals else 0.0

    def _autoscale(self, now: float) -> None:
        # The replica count the policy bands (and min/max clamps) apply
        # to is the HEALTHY set — a draining replica is already leaving
        # and must neither count as capacity nor shield the last
        # healthy replica from the scale-down clamp (the victim pool
        # below is healthy-only too, so clamp and victim agree).
        healthy = [r for r in self.router.replicas()
                   if r.state == REPLICA_HEALTHY]
        ttft = (
            self._fleet_ttft_p99_ms()
            if self.autoscaler.policy.ttft_p99_target_ms > 0 else 0.0
        )
        decision = self.autoscaler.evaluate(
            n_replicas=len(healthy),
            fleet_queue_depth=self.fleet_queue_depth(),
            ttft_p99_ms=ttft,
            now=now,
        )
        if decision is None:
            return
        if decision["outcome"] is None:
            decision = self._apply_scale(decision, now)
        self.counters[f"scale_{decision['outcome']}"] += 1
        self._m_scale.inc(direction=decision["direction"],
                          outcome=decision["outcome"])
        self._record({"kind": "scale", **decision}, now)

    def _apply_scale(self, decision: dict, now: float) -> dict:
        direction = decision["direction"]
        try:
            faults.fire("gateway.scale")
            if direction == DIRECTION_UP:
                replica = self.autoscaler.provisioner.scale_up()
                self.router.add(replica)
                self._dispatched.setdefault(replica.replica_id, {})
                self._attach_profiler(replica)
                decision = {**decision, "outcome": OUTCOME_APPLIED,
                            "replicaId": replica.replica_id}
                if self.events is not None:
                    self.events.normal(
                        self._node_ref(), "GatewayScaleUp",
                        f"scaled up to {len(self.router.replicas())} "
                        f"replica(s) on {self.node_name}: "
                        f"{decision['reason']}",
                    )
            else:
                healthy = [r for r in self.router.replicas()
                           if r.state == REPLICA_HEALTHY]
                if not healthy:
                    raise ScaleError(
                        "no healthy replica to scale down"
                    )
                victim = min(healthy, key=lambda r: r.queue_depth())
                self.drain_replica(victim.replica_id, remove=True,
                                   reason="scale-down")
                self.autoscaler.provisioner.scale_down(victim)
                decision = {**decision, "outcome": OUTCOME_APPLIED,
                            "replicaId": victim.replica_id}
                if self.events is not None:
                    self.events.normal(
                        self._node_ref(), "GatewayScaleDown",
                        f"scaled down to {len(self.router.replicas())} "
                        f"replica(s) on {self.node_name}: "
                        f"{decision['reason']}",
                    )
        except faults.CrashPoint:
            raise
        except Exception as e:
            decision = {**decision, "outcome": OUTCOME_FAILED,
                        "detail": f"{type(e).__name__}: {e}"}
            logger.warning("gateway scale %s failed: %s", direction, e)
        if decision.get("outcome") == OUTCOME_APPLIED:
            # Inside the tick span when telemetry is on: the log line
            # carries the tick's trace id.
            logger.info(
                "gateway scale %s applied (replica %s): %s",
                direction, decision.get("replicaId", ""),
                decision.get("reason", ""),
            )
        self._refresh_replica_gauge()
        self.autoscaler.note_scaled(now)
        return decision

    # -- observability -----------------------------------------------------

    def _node_ref(self) -> ObjectRef:
        return ObjectRef.node(self.node_name, self.node_uid)

    def _record(self, doc: dict, now: float) -> None:
        self._ring.append({"ts": round(now, 6), "tick": self.ticks,
                           **doc})

    def affinity_hit_rate(self) -> float:
        return (self.counters["affinity_hits"]
                / max(self.counters["affinity_lookups"], 1))

    def fleet_slo_summary(self) -> Optional[dict]:
        """The soak-harness SLO artifact (reqtrace's pinned-key JSON
        document), or None when the gateway runs without telemetry."""
        if self.telemetry is None:
            return None
        return self.telemetry.fleet_slo_summary()

    def snapshot(self) -> dict:
        """The /debug/gateway document: replicas, queues, counters,
        policy knobs, and the recent event ring."""
        now = self._clock()
        depth = self.fleet_queue_depth()
        doc = {
            "node": self.node_name,
            "generatedAt": round(now, 6),
            "ticks": self.ticks,
            "policy": {
                "router": {
                    "policy": self.router.policy,
                    "blockSize": self.router.block_size,
                    "affinityBlocks": self.router.affinity_blocks,
                    "saturationDepth": self.router.saturation_depth,
                },
                "admission": self.admission.policy.to_dict(),
                **(
                    {"autoscaler": self.autoscaler.policy.to_dict()}
                    if self.autoscaler is not None else {}
                ),
            },
            "replicas": {
                r.replica_id: r.snapshot()
                for r in self.router.replicas()
            },
            "queues": self.admission.depth_by_class(),
            "fleetQueueDepth": depth,
            "overloaded": depth >= self.admission.policy.shed_watermark,
            "counters": {
                "routed": self.counters["routed"],
                "completed": self.counters["completed"],
                "failed": self.counters["failed"],
                "shed": self.counters["shed"],
                "affinityLookups": self.counters["affinity_lookups"],
                "affinityHits": self.counters["affinity_hits"],
                "affinityHitRate": round(self.affinity_hit_rate(), 4),
            },
            "events": list(self._ring),
        }
        return doc
