"""Serving-path request observability: timelines, tick phases, SLO telemetry.

The fleet serving stack (DecodeEngine -> gateway -> autoscaler) exposed
only aggregates — ServingStats percentiles and ``tpu_dra_gw_*`` counters —
so "why was *this* request's TTFT 4x p50?" was unanswerable. This module
is the per-request and per-tick measurement layer that closes that gap,
in three pieces:

1. **Request timelines** (:class:`RequestTimeline`): every gateway submit
   opens a root span on the PR-1 contextvars tracer (``utils/tracing.py``)
   and starts a timeline that accumulates timestamped events across both
   the gateway (admission, class-queue wait, routing decision) and the
   engine (engine admission, per-prefill-chunk lane/occupancy, first
   token, preemptions, retire). The trace id is stamped on the timeline
   and returned to the caller, so gateway spans, engine events, and JSON
   log lines all join on it. Terminal events (``finished`` / ``shed`` /
   ``expired`` / ``failed``) are never dropped: *every* submitted request
   seals into the bounded finished ring, served as JSONL at
   ``GET /debug/requests``.

2. **Tick phase profiler** (:class:`TickProfiler`): decomposes
   ``ServingGateway.tick()`` and ``DecodeEngine.tick()`` wall time into
   named phases (dispatch, prefill launch, decode dispatch, host harvest,
   autoscale, ...) feeding the ``tpu_dra_srv_tick_phase_seconds``
   histogram plus the ``/debug/requests?view=ticks`` profile view — "the
   engine is slow" becomes "harvest is 60% of the tick". Nested phases
   record *self time* (a parent's recorded seconds exclude its
   children's), so one tick's phases sum to the tick's wall time.

3. **Fleet SLO telemetry** (:class:`ServingTelemetry`): per-latency-class
   TTFT / token-interval / e2e histograms and violation counters — one
   class vocabulary with ``api/v1alpha1/slo.py``, explicit zeros so
   absence-of-traffic and absence-of-instrumentation are
   distinguishable. Each violation *onset* (a class flipping from
   meeting to missing its SLO on a signal) captures the offending
   request's full timeline into a bounded exemplar ledger; repeat
   violations while the class is already in violation count but do not
   re-capture, so the ledger holds regime changes, not every slow
   request of a sustained incident. :meth:`ServingTelemetry.
   fleet_slo_summary` is the JSON artifact the ROADMAP item-5 soak
   harness gates on.

Cost discipline: telemetry is opt-in (``ServingGateway(telemetry=...)``;
``None`` keeps every hot path on its old branch), events are host-side
dict appends bounded per request, and the engine emits only when a
request carries a timeline — ``tools/run_trace_smoke.py`` gates the
overhead (token streams, tick counts, and compile counts must be
identical ON vs OFF; wall-clock req/s within a tripwire).

TPM05 ownership: this module owns the ``tpu_dra_srv_`` metric family
prefix (``tools/lint.py``) — the one serving-observability vocabulary.
"""

from __future__ import annotations

import collections
import json
import threading
import time
from typing import Any, Callable, Optional

from ..api.v1alpha1.slo import LATENCY_CLASSES
from ..utils.metrics import Counter, Histogram, Registry
from ..utils.tracing import Tracer

# Terminal timeline outcomes (stable label values; /debug/requests and the
# tpu_dra_srv_timelines_total{outcome} enum).
OUTCOME_FINISHED = "finished"
OUTCOME_SHED = "shed"
OUTCOME_EXPIRED = "expired"
OUTCOME_FAILED = "failed"
OUTCOMES = (OUTCOME_FINISHED, OUTCOME_SHED, OUTCOME_EXPIRED, OUTCOME_FAILED)

# SLO signals (the tpu_dra_srv_slo_violations_total{signal} enum).
SIGNAL_TTFT = "ttft"
SIGNAL_E2E = "e2e"
SLO_SIGNALS = (SIGNAL_TTFT, SIGNAL_E2E)

# Tick-phase vocabulary (the tpu_dra_srv_tick_phase_seconds{component,
# phase} enum). Replicas do not get their own component label — replica
# churn under autoscaling would make the cardinality unbounded; the
# per-tick ring entries carry a free-form ``tag`` instead.
COMPONENT_GATEWAY = "gateway"
COMPONENT_ENGINE = "engine"
GATEWAY_PHASES = ("expire", "dispatch", "replicas", "harvest", "autoscale")
ENGINE_PHASES = ("admit", "prefill", "decode", "harvest")

# Timeline phase names derived from event boundaries (dominant-phase
# vocabulary; docs/operations.md has one runbook row per entry).
TIMELINE_PHASES = ("queueWait", "engineQueue", "prefill", "decode")

RING_DEPTH = 256        # finished-timeline ring bound
TICK_RING_DEPTH = 256   # per-tick profile ring bound
EXEMPLAR_DEPTH = 32     # violation exemplar ledger bound
MAX_EVENTS = 512        # per-timeline event bound (terminal event exempt)
SAMPLE_WINDOW = 4096    # per-class latency samples kept for percentiles

# Requests-endpoint views (/debug/requests?view=...).
VIEWS = ("", "requests", "ticks", "exemplars", "slo")

_E2E_BUCKETS = (0.01, 0.05, 0.1, 0.5, 1, 2.5, 5, 10, 30, 60, 120, 600)
_INTERVAL_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1, 5, 30)
_PHASE_BUCKETS = (5e-5, 2e-4, 1e-3, 5e-3, 0.02, 0.1, 0.5, 2)


def _pctl(xs, q: float) -> float:
    """Same nearest-rank percentile as ``ServingStats.pctl`` (kept in
    lockstep so fleet_slo_summary p99s are comparable to engine stats
    without importing the jax-backed module here)."""
    if not xs:
        return 0.0
    s = sorted(xs)
    return s[min(len(s) - 1, int(q * len(s)))]


class _NullPhase:
    """No-op phase context: the disabled-telemetry fast path."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_PHASE = _NullPhase()


def phase_ctx(profiler: Optional["TickProfiler"], component: str,
              name: str):
    """``profiler.phase(...)`` or a shared no-op when profiling is off —
    the one-liner the gateway/engine tick bodies wrap phases with."""
    if profiler is None:
        return _NULL_PHASE
    return profiler.phase(component, name)


class RequestTimeline:
    """Timestamped event log for one gateway request, gateway and engine
    sides joined by the submit root span's trace id. Events are bounded
    (``MAX_EVENTS``; overflow counted in ``dropped_events``) except the
    terminal event, which is always recorded — a shed/expired/failed
    request must never be silently absent from /debug/requests."""

    __slots__ = (
        "trace_id", "gid", "latency_class", "submitted_at",
        "prompt_tokens", "outcome", "finished_at", "events",
        "dropped_events",
    )

    def __init__(self, latency_class: str, submitted_at: float,
                 trace_id: str = "", prompt_tokens: int = 0):
        self.trace_id = trace_id
        self.gid = ""
        self.latency_class = latency_class
        self.submitted_at = submitted_at
        self.prompt_tokens = prompt_tokens
        self.outcome = ""          # empty while live; OUTCOMES when sealed
        self.finished_at = 0.0
        self.events: list[dict] = []
        self.dropped_events = 0

    def event(self, name: str, t: float, **attrs: Any) -> None:
        if self.outcome or len(self.events) >= MAX_EVENTS:
            if not self.outcome:
                self.dropped_events += 1
            return
        self.events.append({"event": name, "t": round(t, 6), **attrs})

    def _terminal(self, outcome: str, t: float, **attrs: Any) -> None:
        self.events.append({"event": outcome, "t": round(t, 6), **attrs})
        self.outcome = outcome
        self.finished_at = t

    def _first(self, name: str) -> Optional[float]:
        for e in self.events:
            if e["event"] == name:
                return e["t"]
        return None

    def phase_durations(self) -> dict[str, float]:
        """Contiguous named intervals derived from event boundaries:
        submit -> routed -> engine-admit -> first-token -> terminal.
        A missing boundary collapses its phase to zero (an expired
        request that never routed is all ``queueWait``), so the phases
        always sum to the measured e2e latency."""
        end = self.finished_at or (self.events[-1]["t"] if self.events
                                   else self.submitted_at)
        t_first = self._first("first-token")
        if t_first is None:
            t_first = end
        t_admit = self._first("engine-admit")
        if t_admit is None:
            t_admit = t_first
        t_routed = self._first("routed")
        if t_routed is None:
            t_routed = t_admit
        marks = (self.submitted_at, t_routed, t_admit, t_first, end)
        out = {}
        for name, a, b in zip(TIMELINE_PHASES, marks, marks[1:]):
            out[name] = round(max(0.0, b - a), 6)
        return out

    def dominant_phase(self) -> str:
        phases = self.phase_durations()
        return max(TIMELINE_PHASES, key=lambda p: phases[p])

    def to_doc(self) -> dict:
        e2e = max(0.0, self.finished_at - self.submitted_at)
        return {
            "traceId": self.trace_id,
            "gid": self.gid,
            "latencyClass": self.latency_class,
            "outcome": self.outcome,
            "submittedAt": round(self.submitted_at, 6),
            "finishedAt": round(self.finished_at, 6),
            "e2eS": round(e2e, 6),
            "promptTokens": self.prompt_tokens,
            "phases": self.phase_durations(),
            "dominantPhase": self.dominant_phase(),
            "droppedEvents": self.dropped_events,
            "events": list(self.events),
        }


class _PhaseSpan:
    """One open profiler phase. Self-time accounting: on exit, the
    elapsed time minus any nested phases' elapsed is recorded under this
    phase, and the full elapsed is charged to the parent's child total —
    so a tick's recorded phases partition its wall time."""

    __slots__ = ("_prof", "component", "name", "_t0", "_child")

    def __init__(self, prof: "TickProfiler", component: str, name: str):
        self._prof = prof
        self.component = component
        self.name = name
        self._t0 = 0.0
        self._child = 0.0

    def __enter__(self):
        self._t0 = time.perf_counter()
        self._prof._stack.append(self)
        return self

    def __exit__(self, *exc):
        elapsed = time.perf_counter() - self._t0
        stack = self._prof._stack
        if stack and stack[-1] is self:
            stack.pop()
        if stack:
            stack[-1]._child += elapsed
        self._prof._record(
            self.component, self.name, max(0.0, elapsed - self._child)
        )
        return False


class TickProfiler:
    """Wall-time decomposition of gateway/engine ticks into named phases.

    Single-ticker contract: ``phase()`` / ``end_tick()`` are called from
    the one thread driving the tick loop (the stack is not locked);
    the accumulated state and ring are lock-protected so a concurrent
    ``/debug/requests?view=ticks`` scrape sees a consistent snapshot.
    """

    def __init__(self, observe: Optional[Callable[[str, str, float], None]]
                 = None, ring_depth: int = TICK_RING_DEPTH):
        self._observe = observe
        self._lock = threading.Lock()
        self._stack: list[_PhaseSpan] = []
        self._current: dict[tuple[str, str], float] = {}
        self._cum: dict[tuple[str, str], float] = {}
        self._ring: "collections.deque[dict]" = collections.deque(
            maxlen=ring_depth
        )
        self._ticks = 0

    def phase(self, component: str, name: str) -> _PhaseSpan:
        return _PhaseSpan(self, component, name)

    def _record(self, component: str, name: str, seconds: float) -> None:
        key = (component, name)
        with self._lock:
            self._current[key] = self._current.get(key, 0.0) + seconds
            self._cum[key] = self._cum.get(key, 0.0) + seconds
        if self._observe is not None:
            self._observe(component, name, seconds)

    def end_tick(self, component: str, tick_no: int, tag: str = "") -> None:
        """Seal ``component``'s phases accumulated since its last
        end_tick into one ring entry (the ?view=ticks line)."""
        with self._lock:
            phases = {
                p: round(s, 9)
                for (c, p), s in self._current.items() if c == component
            }
            for p in phases:
                del self._current[(component, p)]
            entry = {
                "kind": "tick",
                "component": component,
                "tick": tick_no,
                "phases": phases,
                "totalS": round(sum(phases.values()), 9),
            }
            if tag:
                entry["tag"] = tag
            self._ring.append(entry)
            self._ticks += 1

    def summary(self) -> dict:
        """Cumulative seconds per component/phase plus each phase's share
        of its component's total — the "harvest is 60% of the tick"
        readout."""
        with self._lock:
            cum = dict(self._cum)
            ticks = self._ticks
        totals: dict[str, float] = {}
        for (c, _), s in cum.items():
            totals[c] = totals.get(c, 0.0) + s
        return {
            "kind": "summary",
            "ticks": ticks,
            "phaseSeconds": {
                f"{c}/{p}": round(s, 9) for (c, p), s in sorted(cum.items())
            },
            "phaseShare": {
                f"{c}/{p}": round(s / totals[c], 4) if totals[c] else 0.0
                for (c, p), s in sorted(cum.items())
            },
        }

    def export_jsonl(self) -> str:
        """Summary line followed by the per-tick ring, one JSON object
        per line (the ``?view=ticks`` wire format)."""
        with self._lock:
            entries = list(self._ring)
        lines = [json.dumps(self.summary(), sort_keys=True)]
        lines.extend(json.dumps(e, sort_keys=True) for e in entries)
        return "\n".join(lines) + "\n"


class ServingTelemetry:
    """The serving observability spine: owns the request-timeline ring,
    the tick profiler, the ``tpu_dra_srv_*`` metric families, the SLO
    violation/exemplar machinery, and the contextvars tracer the gateway
    opens submit root spans on. One instance per Registry (duplicate
    family names otherwise) — typically one per gateway.

    ``slo`` maps latency class -> ``{"ttftS": ..., "e2eS": ...}`` budgets
    in clock seconds; omitted classes default to the class deadline from
    ``api/v1alpha1/slo.py`` for e2e and a fifth of it for TTFT (a
    request may spend its queueing grace, but first output should come
    well inside it).
    """

    # fleet_slo_summary() contract: key sets are pinned by
    # tests/test_request_trace.py — the item-5 soak harness parses this.
    SLO_SUMMARY_KEYS = (
        "affinityHitRate", "classes", "exemplars", "requests", "sheds",
        "violations",
    )
    SLO_CLASS_KEYS = (
        "e2eP50S", "e2eP99S", "requests", "sheds", "tokenIntervalP50S",
        "tokenIntervalP99S", "ttftP50S", "ttftP99S", "violationSeconds",
        "violations",
    )

    def __init__(self, registry: Registry, *,
                 tracer: Optional[Tracer] = None,
                 slo: Optional[dict] = None,
                 ring_depth: int = RING_DEPTH,
                 exemplar_depth: int = EXEMPLAR_DEPTH):
        self.tracer = tracer if tracer is not None else Tracer()
        self._lock = threading.Lock()
        self._ring: "collections.deque[dict]" = collections.deque(
            maxlen=ring_depth
        )
        self._exemplars: "collections.deque[dict]" = collections.deque(
            maxlen=exemplar_depth
        )
        self._slo = {
            cls: {
                "ttftS": float(grace) / 5.0,
                "e2eS": float(grace),
                **dict((slo or {}).get(cls) or {}),
            }
            for cls, grace in LATENCY_CLASSES.items()
        }
        self._in_violation: dict[tuple[str, str], bool] = {}
        self._samples: dict[str, dict[str, collections.deque]] = {
            cls: {
                "ttft": collections.deque(maxlen=SAMPLE_WINDOW),
                "e2e": collections.deque(maxlen=SAMPLE_WINDOW),
                "interval": collections.deque(maxlen=SAMPLE_WINDOW),
            }
            for cls in LATENCY_CLASSES
        }
        self._violation_s: dict[str, float] = dict.fromkeys(
            LATENCY_CLASSES, 0.0
        )
        self._sheds: dict[str, int] = dict.fromkeys(LATENCY_CLASSES, 0)
        self._routed = 0
        self._affinity_routed = 0
        self._affinity_hits = 0

        self._h_ttft = Histogram(
            "tpu_dra_srv_ttft_seconds",
            "Per-class time to first token, gateway submit to first "
            "emitted token",
            registry, buckets=_E2E_BUCKETS,
        )
        self._h_e2e = Histogram(
            "tpu_dra_srv_e2e_seconds",
            "Per-class end-to-end request latency, gateway submit to "
            "harvest",
            registry, buckets=_E2E_BUCKETS,
        )
        self._h_interval = Histogram(
            "tpu_dra_srv_token_interval_seconds",
            "Per-class mean inter-token interval over each finished "
            "request's decode",
            registry, buckets=_INTERVAL_BUCKETS,
        )
        self._h_phase = Histogram(
            "tpu_dra_srv_tick_phase_seconds",
            "Self-time of one named gateway/engine tick phase",
            registry, buckets=_PHASE_BUCKETS,
        )
        self._c_violations = Counter(
            "tpu_dra_srv_slo_violations_total",
            "Requests that missed their class SLO, by signal",
            registry,
        )
        self._c_violation_seconds = Counter(
            "tpu_dra_srv_violation_seconds_total",
            "Cumulative seconds by which violating requests exceeded "
            "their class budget",
            registry,
        )
        self._c_timelines = Counter(
            "tpu_dra_srv_timelines_total",
            "Request timelines sealed into the /debug/requests ring, by "
            "terminal outcome",
            registry,
        )
        self._c_exemplars = Counter(
            "tpu_dra_srv_exemplars_total",
            "Violation-onset timelines captured into the exemplar ledger",
            registry,
        )
        # Explicit zeros: every enum cell exists from scrape one, so
        # "no violations" and "telemetry not wired" are distinguishable.
        for cls in LATENCY_CLASSES:
            self._h_ttft.zero(latency_class=cls)
            self._h_e2e.zero(latency_class=cls)
            self._h_interval.zero(latency_class=cls)
            self._c_exemplars.inc(0, latency_class=cls)
            self._c_violation_seconds.inc(0, latency_class=cls)
            for signal in SLO_SIGNALS:
                self._c_violations.inc(0, latency_class=cls, signal=signal)
        for outcome in OUTCOMES:
            self._c_timelines.inc(0, outcome=outcome)
        for p in GATEWAY_PHASES:
            self._h_phase.zero(component=COMPONENT_GATEWAY, phase=p)
        for p in ENGINE_PHASES:
            self._h_phase.zero(component=COMPONENT_ENGINE, phase=p)

        self.profiler = TickProfiler(observe=self._observe_phase)

    def _observe_phase(self, component: str, phase: str,
                       seconds: float) -> None:
        self._h_phase.observe(seconds, component=component, phase=phase)

    # -- timelines ---------------------------------------------------------

    def new_timeline(self, latency_class: str, now: float,
                     trace_id: str = "",
                     prompt_tokens: int = 0) -> RequestTimeline:
        return RequestTimeline(
            latency_class, now, trace_id=trace_id,
            prompt_tokens=prompt_tokens,
        )

    def finish_timeline(self, tl: RequestTimeline, outcome: str,
                        now: float, **attrs: Any) -> None:
        """Seal ``tl`` with a terminal event and move its doc into the
        finished ring. Idempotent: a timeline seals once."""
        if tl.outcome:
            return
        tl._terminal(outcome, now, **attrs)
        if outcome == OUTCOME_SHED:
            with self._lock:
                if tl.latency_class in self._sheds:
                    self._sheds[tl.latency_class] += 1
        self._c_timelines.inc(outcome=outcome)
        doc = tl.to_doc()
        with self._lock:
            self._ring.append(doc)

    def observe_request(self, tl: RequestTimeline, now: float,
                        tokens: int = 0) -> None:
        """SLO accounting for one *finished* request, then seal it.
        Violation onset (a class flipping from meeting to missing a
        signal's budget) captures the timeline as an exemplar; a
        compliant sample clears the flag."""
        cls = tl.latency_class
        e2e = max(0.0, now - tl.submitted_at)
        t_first = tl._first("first-token")
        ttft = max(0.0, t_first - tl.submitted_at) if t_first is not None \
            else e2e
        interval = 0.0
        if tokens > 1 and t_first is not None:
            interval = max(0.0, now - t_first) / (tokens - 1)
        self._h_ttft.observe(ttft, latency_class=cls)
        self._h_e2e.observe(e2e, latency_class=cls)
        if tokens > 1:
            self._h_interval.observe(interval, latency_class=cls)
        with self._lock:
            samples = self._samples.get(cls)
            if samples is not None:
                samples["ttft"].append(ttft)
                samples["e2e"].append(e2e)
                if tokens > 1:
                    samples["interval"].append(interval)
        budgets = self._slo.get(cls) or {}
        worst = None  # (excess, signal, observed, limit)
        for signal, value, limit in (
            (SIGNAL_TTFT, ttft, budgets.get("ttftS")),
            (SIGNAL_E2E, e2e, budgets.get("e2eS")),
        ):
            if limit is None:
                continue
            key = (cls, signal)
            if value > limit:
                self._c_violations.inc(latency_class=cls, signal=signal)
                self._c_violation_seconds.inc(
                    value - limit, latency_class=cls
                )
                with self._lock:
                    self._violation_s[cls] = (
                        self._violation_s.get(cls, 0.0) + (value - limit)
                    )
                    onset = not self._in_violation.get(key, False)
                    self._in_violation[key] = True
                if onset and (worst is None or value - limit > worst[0]):
                    worst = (value - limit, signal, value, limit)
            else:
                with self._lock:
                    self._in_violation[key] = False
        self.finish_timeline(
            tl, OUTCOME_FINISHED, now,
            ttftS=round(ttft, 6), e2eS=round(e2e, 6), tokens=tokens,
        )
        if worst is not None:
            _, signal, value, limit = worst
            exemplar = {
                "signal": signal,
                "latencyClass": cls,
                "observedS": round(value, 6),
                "thresholdS": round(limit, 6),
                "dominantPhase": tl.dominant_phase(),
                "traceId": tl.trace_id,
                "timeline": tl.to_doc(),
            }
            self._c_exemplars.inc(latency_class=cls)
            with self._lock:
                self._exemplars.append(exemplar)

    # -- gateway-side counters --------------------------------------------

    def note_route(self, affinity_key, affinity_hit: bool) -> None:
        with self._lock:
            self._routed += 1
            if affinity_key is not None:
                self._affinity_routed += 1
                if affinity_hit:
                    self._affinity_hits += 1

    # -- export ------------------------------------------------------------

    def exemplars(self) -> list[dict]:
        with self._lock:
            return list(self._exemplars)

    def timelines(self) -> list[dict]:
        """Sealed timeline docs, oldest first."""
        with self._lock:
            return list(self._ring)

    def fleet_slo_summary(self) -> dict:
        """Per-class SLO snapshot (pinned keys: ``SLO_SUMMARY_KEYS`` /
        ``SLO_CLASS_KEYS``) — what the soak harness gates on."""
        with self._lock:
            samples = {
                cls: {k: list(v) for k, v in per.items()}
                for cls, per in self._samples.items()
            }
            violation_s = dict(self._violation_s)
            sheds = dict(self._sheds)
            n_exemplars = len(self._exemplars)
            affinity_routed = self._affinity_routed
            affinity_hits = self._affinity_hits
        classes = {}
        total_requests = 0
        total_violations = 0
        for cls in sorted(LATENCY_CLASSES):
            per = samples[cls]
            violations = sum(
                int(self._c_violations.value(latency_class=cls,
                                             signal=signal))
                for signal in SLO_SIGNALS
            )
            classes[cls] = {
                "requests": len(per["e2e"]),
                "violations": violations,
                "violationSeconds": round(violation_s.get(cls, 0.0), 6),
                "sheds": sheds.get(cls, 0),
                "ttftP50S": round(_pctl(per["ttft"], 0.50), 6),
                "ttftP99S": round(_pctl(per["ttft"], 0.99), 6),
                "e2eP50S": round(_pctl(per["e2e"], 0.50), 6),
                "e2eP99S": round(_pctl(per["e2e"], 0.99), 6),
                "tokenIntervalP50S": round(
                    _pctl(per["interval"], 0.50), 6),
                "tokenIntervalP99S": round(
                    _pctl(per["interval"], 0.99), 6),
            }
            total_requests += len(per["e2e"])
            total_violations += violations
        return {
            "affinityHitRate": round(
                affinity_hits / affinity_routed, 4
            ) if affinity_routed else 0.0,
            "classes": classes,
            "exemplars": n_exemplars,
            "requests": total_requests,
            "sheds": sum(sheds.values()),
            "violations": total_violations,
        }

    def export_requests(self, view: str = "") -> str:
        """The ``/debug/requests`` wire format: JSONL per view.
        Unknown views raise ``ValueError`` (the endpoint's 400)."""
        if view in ("", "requests"):
            docs = self.timelines()
            out = [json.dumps(d, sort_keys=True) for d in docs]
            return "\n".join(out) + ("\n" if out else "")
        if view == "ticks":
            return self.profiler.export_jsonl()
        if view == "exemplars":
            out = [json.dumps(e, sort_keys=True)
                   for e in self.exemplars()]
            return "\n".join(out) + ("\n" if out else "")
        if view == "slo":
            return json.dumps(self.fleet_slo_summary(),
                              sort_keys=True) + "\n"
        raise ValueError(
            f"unknown view {view!r} (want one of "
            f"{[v for v in VIEWS if v]})"
        )
