"""Fleet-wide measured KV residency: the gateway-global index over
replica residency digests.

The router's affinity ledger (router.py ``Replica.seen_keys``) is a
*prediction*: it remembers which prefix keys were routed where, but has
no idea what each engine actually evicted since. Engines now export a
*measured* digest (models/paged.PrefixCache.residency_digest — cached
prefix runs with their affinity key chains), published through the
replica snapshot scrape. :class:`ResidencyIndex` joins the two:

- **which replica holds which prefix run** (the ``byKey`` join, capped),
- **fleet-wide measured hit rate** (summed engine hit counters — the
  number the router's affinity hit rate merely approximates),
- **cross-replica duplication ratio** (key instances / unique keys:
  how much cache capacity the fleet burns holding the same prefix in
  several places — the signal item 3's residency router will minimize),
- **evicted-but-ledgered staleness** (keys the router still believes a
  replica holds whose engine no longer does — predicted-vs-measured
  divergence, per replica),
- **counter drift** (a replica whose digest violates ``indexedBlocks ==
  insertedBlocks - evictedBlocks`` — the doctor's drift finding).

Both key schemes hash the same block-aligned token spans
(``models/paged.prefix_run_key`` == ``router.prefix_affinity_key``; a
test pins them equal), so the join is exact, not heuristic.

Everything here is pull-model: ``snapshot()`` walks the live replicas
on demand (the ``/debug/residency`` provider), and the
``tpu_dra_residency_*`` gauges refresh from a registry render hook —
nothing touches the serving path.
"""

from __future__ import annotations

from typing import Optional

from ..utils.metrics import Gauge, Registry

#: Cap on the per-key holder join exported in the snapshot (the full
#: join lives in memory only for the duration of one snapshot call).
_MAX_EXPORTED_KEYS = 32


class ResidencyIndex:
    """See module docstring. Construct once per gateway, with the
    gateway's router (the replica registry is the source of truth for
    liveness — a removed replica vanishes from the index on the next
    refresh) and optionally its metric registry."""

    def __init__(self, router, registry: Optional[Registry] = None):
        self.router = router
        self._g_hit_rate = self._g_dup = None
        self._g_unique = self._g_stale = self._g_indexed = None
        if registry is not None:
            self._g_hit_rate = Gauge(
                "tpu_dra_residency_fleet_hit_rate_ratio",
                "Measured fleet prefix-cache hit rate: summed engine "
                "hit counters over summed lookups (not the router's "
                "predicted affinity hit rate).",
                registry,
            )
            self._g_dup = Gauge(
                "tpu_dra_residency_duplication_ratio",
                "Cross-replica prefix duplication: measured key "
                "instances over unique keys (1.0 = every cached prefix "
                "lives on exactly one replica).",
                registry,
            )
            self._g_unique = Gauge(
                "tpu_dra_residency_unique_keys",
                "Distinct prefix keys measured resident anywhere in "
                "the fleet.",
                registry,
            )
            self._g_stale = Gauge(
                "tpu_dra_residency_stale_ledger_keys",
                "Affinity-ledger keys the router predicts warm on a "
                "replica whose measured digest no longer holds them "
                "(evicted-but-ledgered), by replica.",
                registry,
            )
            self._g_indexed = Gauge(
                "tpu_dra_residency_replica_indexed_blocks",
                "Blocks each replica's prefix cache measures as "
                "indexed, by replica.",
                registry,
            )
            self._g_hit_rate.set(0.0)
            self._g_dup.set(0.0)
            self._g_unique.set(0)
            registry.add_render_hook(self._sync)

    def forget_replica(self, replica_id: str) -> None:
        """Drop a deregistered replica's per-replica gauge series (the
        PR-10 departed-series pattern — a gone replica must not scrape
        as a live zero forever). The snapshot join forgets it
        automatically: it only walks currently registered replicas."""
        if self._g_stale is not None:
            self._g_stale.remove(replica=replica_id)
            self._g_indexed.remove(replica=replica_id)

    def _measured_keys(self, digest: Optional[dict]) -> set:
        keys = set()
        if digest:
            for run in digest.get("runs", ()):
                keys.update(run.get("keys", ()))
        return keys

    def snapshot(self) -> dict:
        """The ``/debug/residency`` document. Walks every registered
        replica's measured digest and affinity ledger; on-demand only."""
        replicas_doc = {}
        holders: dict[str, list] = {}
        lookups = hits = hit_tokens = instances = 0
        for rep in self.router.replicas():
            rid = rep.replica_id
            kv = getattr(rep.engine, "kv_residency", None)
            digest = kv() if callable(kv) else None
            esnap = rep.engine.snapshot()
            lookups += esnap.get("prefixLookups", 0)
            hits += esnap.get("prefixHits", 0)
            hit_tokens += esnap.get("prefixHitTokens", 0)
            measured = self._measured_keys(digest)
            for k in measured:
                holders.setdefault(k, []).append(rid)
            instances += len(measured)
            predicted = set(rep.seen_keys)
            stale = len(predicted - measured)
            inserted = digest.get("insertedBlocks", 0) if digest else 0
            evicted = digest.get("evictedBlocks", 0) if digest else 0
            indexed = digest.get("indexedBlocks", 0) if digest else 0
            replicas_doc[rid] = {
                "state": rep.state,
                "indexedBlocks": indexed,
                "insertedBlocks": inserted,
                "evictedBlocks": evicted,
                "runs": (
                    len(digest.get("runs", ()))
                    + digest.get("truncatedRuns", 0)
                ) if digest else 0,
                "measuredKeys": len(measured),
                "counterDrift": (
                    digest is not None
                    and indexed != inserted - evicted
                ),
                "ledger": {
                    "predictedKeys": len(predicted),
                    "measuredAndPredicted": len(predicted & measured),
                    "staleKeys": stale,
                    "unledgeredKeys": len(measured - predicted),
                    "divergence": round(
                        stale / max(len(predicted), 1), 4
                    ),
                },
            }
        unique = len(holders)
        duplicated = sorted(
            (k for k, v in holders.items() if len(v) > 1),
        )
        doc = {
            "schema": "tpu-dra-residency-v1",
            "replicas": replicas_doc,
            "fleet": {
                "lookups": lookups,
                "hits": hits,
                "hitTokens": hit_tokens,
                "measuredHitRate": round(hits / max(lookups, 1), 4),
                "uniqueKeys": unique,
                "keyInstances": instances,
                "duplicationRatio": round(
                    instances / unique, 4
                ) if unique else 1.0,
                "duplicatedKeys": len(duplicated),
            },
            "duplicated": [
                {"key": k, "replicas": sorted(holders[k])}
                for k in duplicated[:_MAX_EXPORTED_KEYS]
            ],
            "truncatedDuplicated": max(
                0, len(duplicated) - _MAX_EXPORTED_KEYS
            ),
        }
        return doc

    def _sync(self) -> None:
        doc = self.snapshot()
        fleet = doc["fleet"]
        self._g_hit_rate.set(fleet["measuredHitRate"])
        self._g_dup.set(fleet["duplicationRatio"])
        self._g_unique.set(fleet["uniqueKeys"])
        for rid, rep in doc["replicas"].items():
            self._g_stale.set(rep["ledger"]["staleKeys"], replica=rid)
            self._g_indexed.set(rep["indexedBlocks"], replica=rid)
