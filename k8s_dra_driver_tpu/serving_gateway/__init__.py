"""Fleet serving gateway: prefix-affinity routing, SLO-aware admission,
and replica autoscaling over DecodeEngine replicas. See gateway.py for
the architecture overview and docs/serving.md for operator guidance."""

from .admission import (
    AdmissionController,
    AdmissionPolicy,
    OverloadedError,
)
from .autoscaler import (
    Autoscaler,
    AutoscalerPolicy,
    ReplicaProvisioner,
    ScaleError,
)
from .gateway import (
    GatewayRequest,
    ReplicaLostError,
    ServingGateway,
)
from .reqtrace import (
    RequestTimeline,
    ServingTelemetry,
    TickProfiler,
)
from .router import (
    NoReplicaAvailableError,
    Replica,
    RouteDecision,
    Router,
    prefix_affinity_key,
)

__all__ = [
    "AdmissionController",
    "AdmissionPolicy",
    "Autoscaler",
    "AutoscalerPolicy",
    "GatewayRequest",
    "NoReplicaAvailableError",
    "OverloadedError",
    "Replica",
    "ReplicaLostError",
    "ReplicaProvisioner",
    "RequestTimeline",
    "RouteDecision",
    "Router",
    "ScaleError",
    "ServingGateway",
    "ServingTelemetry",
    "TickProfiler",
    "prefix_affinity_key",
]
