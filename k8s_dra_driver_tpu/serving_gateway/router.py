"""Replica registry + two-level request router for the fleet gateway.

Routing policy (serving_gateway/gateway.py drives it):

1. **Prefix affinity first.** The affinity key is the request's leading
   *full KV blocks* of prompt tokens — the same block-granularity radix
   key scheme ``models/paged.PrefixCache`` indexes cached KV under, so
   "two prompts share an affinity key" is exactly "two prompts would hit
   the same cached prefix blocks". The key is consistent-hashed onto a
   ring of replica virtual nodes: same-system-prompt traffic lands on
   the replica whose prefix cache is already warm, and adding/removing a
   replica only remaps the keys adjacent to its ring points (no fleet-
   wide cache invalidation on a scale event).
2. **Least-loaded fallback.** When the prompt has no full block, the
   affinity target is saturated (queue depth at or past the saturation
   threshold), or affinity is disabled, the router picks the less-loaded
   of two seeded-random candidates (power-of-two-choices): near-optimal
   load spread at O(1) cost, without the thundering-herd coordination a
   global argmin would need.

A ``round-robin`` policy is kept as the A/B baseline the gateway bench
(``_decodebench.run_gateway_bench``) compares affinity against.

The registry tracks which affinity keys each replica has already been
routed (a bounded LRU): an affinity route whose target has seen the key
before is an **affinity hit** — the router-level analog of the engine's
prefix-cache hit rate, and the ``tpu_dra_gw_affinity_hits_total``
numerator.
"""

from __future__ import annotations

import dataclasses
import hashlib
from collections import OrderedDict
from typing import Optional

# Replica lifecycle states (stable label values; /debug/gateway contract).
REPLICA_HEALTHY = "healthy"
REPLICA_DRAINING = "draining"
REPLICA_GONE = "gone"
REPLICA_STATES = (REPLICA_HEALTHY, REPLICA_DRAINING, REPLICA_GONE)

# Routing policy labels (the tpu_dra_gw_routed_total{policy} enum).
POLICY_AFFINITY = "affinity"
POLICY_P2C = "p2c"
POLICY_ROUND_ROBIN = "round-robin"
POLICIES = (POLICY_AFFINITY, POLICY_P2C, POLICY_ROUND_ROBIN)

_VNODES = 32          # ring points per replica
_SEEN_KEYS_MAX = 4096  # per-replica affinity-key LRU bound


class NoReplicaAvailableError(RuntimeError):
    """No healthy, admitting replica to route to. Retryable: the
    autoscaler may be mid-scale-up, or every replica is draining."""

    retryable = True


def _hash64(data: str) -> int:
    return int.from_bytes(
        hashlib.blake2b(data.encode(), digest_size=8).digest(), "big"
    )


def prefix_affinity_key(
    prompt, block_size: int, max_blocks: int
) -> Optional[str]:
    """Affinity key for a prompt: a digest of its leading full blocks
    (up to ``max_blocks``), block-aligned exactly like the PrefixCache
    radix edges. ``None`` when the prompt has no full block — nothing
    cacheable to be affine to."""
    n_blocks = min(len(prompt) // block_size, max_blocks)
    if n_blocks <= 0:
        return None
    span = prompt[: n_blocks * block_size]
    return hashlib.blake2b(
        ",".join(str(int(t)) for t in span).encode(), digest_size=8
    ).hexdigest()


class Replica:
    """One registered DecodeEngine replica: identity, the engine (or any
    object with its serving surface — see serving_gateway/sim.py), the
    backing ResourceClaim, and gateway-side health state."""

    def __init__(self, replica_id: str, engine, claim_uid: str = ""):
        self.replica_id = replica_id
        self.engine = engine
        self.claim_uid = claim_uid
        self.state = REPLICA_HEALTHY
        self.state_reason = ""
        # Affinity keys this replica has served (bounded LRU): the hit-
        # rate ledger, and what a drain hands to no one — the ring remap
        # re-warms naturally.
        self.seen_keys: "OrderedDict[str, None]" = OrderedDict()

    @property
    def accepting(self) -> bool:
        return (self.state == REPLICA_HEALTHY
                and getattr(self.engine, "admission_open", True))

    def queue_depth(self) -> int:
        """Demand signal for routing: waiting + occupied slots."""
        return len(self.engine.waiting) + self.engine.num_active

    def note_key(self, key: str) -> bool:
        """Record an affinity key routed here; True when already seen
        (an affinity hit)."""
        hit = key in self.seen_keys
        if hit:
            self.seen_keys.move_to_end(key)
        else:
            self.seen_keys[key] = None
            while len(self.seen_keys) > _SEEN_KEYS_MAX:
                self.seen_keys.popitem(last=False)
        return hit

    def snapshot(self) -> dict:
        # kvResidency is the measured prefix-residency digest (engines
        # that predate the ledger, or run with caching off, publish
        # None). Duck-typed so sim engines can participate; computed
        # here — not in engine.snapshot() — because the digest walks
        # the radix index and only the scrape path should pay for it.
        kv = getattr(self.engine, "kv_residency", None)
        return {
            "replicaId": self.replica_id,
            "claimUid": self.claim_uid,
            "state": self.state,
            "stateReason": self.state_reason,
            "queueDepth": self.queue_depth(),
            "affinityKeys": len(self.seen_keys),
            "kvResidency": kv() if callable(kv) else None,
            "engine": self.engine.snapshot(),
        }


@dataclasses.dataclass
class RouteDecision:
    replica: Replica
    policy: str                      # POLICIES member
    affinity_key: Optional[str] = None
    affinity_hit: bool = False       # key previously routed to replica
    affinity_spilled: bool = False   # key existed but target saturated
    queue_depth: int = 0             # chosen replica's depth at decision

    def __post_init__(self):
        # Snapshot the target's load at decision time: the request
        # timeline records what the router actually saw, not what the
        # replica looks like when someone reads the timeline later.
        self.queue_depth = self.replica.queue_depth()


class Router:
    """The two-level policy over a replica registry (see module
    docstring). Pure scheduling — metrics/events/fault sites live in
    the gateway, which owns the observable surface."""

    def __init__(
        self,
        *,
        policy: str = POLICY_AFFINITY,
        block_size: int = 64,
        affinity_blocks: int = 4,
        saturation_depth: Optional[int] = None,
        seed: int = 0,
    ):
        if policy not in POLICIES:
            raise ValueError(
                f"unknown routing policy {policy!r} (want one of "
                f"{POLICIES})"
            )
        import random

        self.policy = policy
        self.block_size = block_size
        self.affinity_blocks = affinity_blocks
        # Default saturation: an affinity target with more than 2x its
        # batch slots queued spills to least-loaded — cache warmth never
        # justifies unbounded queueing behind one replica.
        self.saturation_depth = saturation_depth
        self._rng = random.Random(seed)
        self._replicas: dict[str, Replica] = {}
        self._ring: list[tuple[int, str]] = []
        self._rr_next = 0

    # -- registry ----------------------------------------------------------

    def add(self, replica: Replica) -> None:
        if replica.replica_id in self._replicas:
            raise ValueError(
                f"replica {replica.replica_id!r} already registered"
            )
        self._replicas[replica.replica_id] = replica
        self._rebuild_ring()

    def remove(self, replica_id: str) -> Replica:
        replica = self._replicas.pop(replica_id)
        self._rebuild_ring()
        return replica

    def get(self, replica_id: str) -> Replica:
        return self._replicas[replica_id]

    def replicas(self) -> list[Replica]:
        return [self._replicas[k] for k in sorted(self._replicas)]

    def _rebuild_ring(self) -> None:
        self._ring = sorted(
            (_hash64(f"{rid}#{v}"), rid)
            for rid in self._replicas
            for v in range(_VNODES)
        )

    # -- routing -----------------------------------------------------------

    def has_capacity(self) -> bool:
        """True when some accepting replica is below its saturation
        depth — the gateway's dispatch gate. Holding the rest in the
        class-priority queues (instead of stuffing replica FIFOs) is
        what preserves SLO ordering under overload."""
        return any(
            r.accepting and not self._saturated(r)
            for r in self._replicas.values()
        )

    def _saturated(self, replica: Replica) -> bool:
        limit = self.saturation_depth
        if limit is None:
            limit = 2 * getattr(replica.engine, "batch_slots", 4)
        return replica.queue_depth() >= limit

    def _ring_target(self, key: str, accepting: set[str]) -> Optional[Replica]:
        """First ring point at or after hash(key) owned by an accepting
        replica — the consistent-hash successor walk."""
        if not self._ring:
            return None
        h = _hash64(key)
        # Binary search would be O(log n); the ring is small (replicas x
        # vnodes) and this runs per request on the host, so a biased
        # linear scan from the successor index keeps it simple.
        import bisect

        i = bisect.bisect_left(self._ring, (h, ""))
        for j in range(len(self._ring)):
            _, rid = self._ring[(i + j) % len(self._ring)]
            if rid in accepting:
                return self._replicas[rid]
        return None

    def route(self, prompt) -> RouteDecision:
        """Pick a replica for ``prompt`` under the configured policy.
        Raises :class:`NoReplicaAvailableError` when nothing accepts."""
        candidates = [r for r in self.replicas() if r.accepting]
        if not candidates:
            raise NoReplicaAvailableError(
                "no healthy replica is accepting admissions"
            )
        if self.policy == POLICY_ROUND_ROBIN:
            choice = candidates[self._rr_next % len(candidates)]
            self._rr_next += 1
            return RouteDecision(choice, POLICY_ROUND_ROBIN)
        key = None
        spilled = False
        if self.policy == POLICY_AFFINITY:
            key = prefix_affinity_key(
                prompt, self.block_size, self.affinity_blocks
            )
            if key is not None:
                target = self._ring_target(
                    key, {r.replica_id for r in candidates}
                )
                if target is not None and not self._saturated(target):
                    return RouteDecision(
                        target, POLICY_AFFINITY, affinity_key=key,
                        affinity_hit=target.note_key(key),
                    )
                spilled = target is not None
        # Power-of-two-choices fallback (also the whole policy when
        # affinity is off): prefer unsaturated candidates so a spilled
        # affinity key doesn't bounce straight back into the hot spot.
        pool = [r for r in candidates if not self._saturated(r)] or candidates
        if len(pool) == 1:
            choice = pool[0]
        else:
            a, b = self._rng.sample(pool, 2)
            choice = a if a.queue_depth() <= b.queue_depth() else b
        if key is not None:
            choice.note_key(key)
        return RouteDecision(
            choice, POLICY_P2C, affinity_key=key,
            affinity_spilled=spilled,
        )
