"""SLO-aware admission + backpressure for the fleet gateway.

Every request declares a latency class (``api/v1alpha1/slo.py`` — the
same enum the dynamic-sharing rebalancer arbitrates chips under, so one
vocabulary covers both layers of the stack). Admission is three rules:

- **Priority queues.** Realtime dispatches before interactive before
  batch, strictly: a burst of batch traffic can delay batch, never a
  realtime request that fits.
- **Watermark shedding, batch first.** When the fleet queue depth
  (gateway queues + every replica's backlog) crosses ``shed_watermark``,
  new BATCH requests are rejected with a typed :class:`OverloadedError`
  carrying ``retry_after_s``; past ``hard_watermark`` everything is
  rejected. Shedding at the door is deliberate: an overloaded fleet
  must say so immediately, not accept work it will miss deadlines on.
- **No silent queueing past a deadline.** A queued request that has
  waited longer than its class's grace window (``LATENCY_CLASSES`` —
  realtime seconds, batch minutes) is expired with the same typed
  error instead of eventually serving an answer nobody is waiting for.

The controller is pure queue arithmetic; metrics, ring-buffer records,
and Events live in the gateway. The one observability seam here: a
request carrying a ``timeline`` (serving_gateway/reqtrace.py) gets its
class-queue transitions recorded — enqueue depth, dequeue wait — since
only the queue owner can time the class-queue wait precisely.
"""

from __future__ import annotations

import dataclasses
from collections import deque
from typing import Optional

from ..api.v1alpha1.slo import (
    BATCH_CLASS,
    INTERACTIVE_CLASS,
    LATENCY_CLASSES,
    REALTIME_CLASS,
)

# Dispatch order: realtime first. (LATENCY_CLASSES maps class -> grace
# seconds; this tuple fixes priority, which grace alone doesn't imply.)
CLASS_ORDER = (REALTIME_CLASS, INTERACTIVE_CLASS, BATCH_CLASS)

# Shed reasons (stable label values on tpu_dra_gw_shed_total).
SHED_WATERMARK = "watermark"
SHED_DEADLINE = "deadline"
SHED_REASONS = (SHED_WATERMARK, SHED_DEADLINE)


class OverloadedError(RuntimeError):
    """The fleet cannot take (or keep) this request right now. Carries
    ``retry_after_s`` so clients back off instead of hammering, plus
    the shed reason and the queue depth that triggered it."""

    retryable = True

    def __init__(self, message: str, *, latency_class: str,
                 reason: str, retry_after_s: float, queue_depth: int):
        self.latency_class = latency_class
        self.reason = reason
        self.retry_after_s = retry_after_s
        self.queue_depth = queue_depth
        super().__init__(
            f"{message} (class {latency_class}, fleet queue depth "
            f"{queue_depth}; retry after {retry_after_s:.1f}s)"
        )


@dataclasses.dataclass
class AdmissionPolicy:
    """Operator knobs (docs/serving.md names them)."""

    shed_watermark: int = 256      # fleet depth where batch is shed
    hard_watermark: int = 1024     # fleet depth where everything is shed
    retry_after_s: float = 1.0
    # Per-class queue deadline override; None = the class's grace window
    # from LATENCY_CLASSES (realtime 5s, interactive 60s, batch 600s).
    max_queue_delay_s: Optional[dict] = None

    def deadline_s(self, latency_class: str) -> float:
        if self.max_queue_delay_s and latency_class in self.max_queue_delay_s:
            return float(self.max_queue_delay_s[latency_class])
        return LATENCY_CLASSES[latency_class]

    def to_dict(self) -> dict:
        return {
            "shedWatermark": self.shed_watermark,
            "hardWatermark": self.hard_watermark,
            "retryAfterSeconds": self.retry_after_s,
            "queueDeadlineSeconds": {
                lc: self.deadline_s(lc) for lc in CLASS_ORDER
            },
        }


class AdmissionController:
    """Priority queues + watermark/deadline enforcement. Holds gateway
    requests (anything with ``latency_class`` and ``submitted_at``
    attributes) between ``submit`` and dispatch."""

    def __init__(self, policy: Optional[AdmissionPolicy] = None):
        self.policy = policy or AdmissionPolicy()
        self._queues: dict[str, deque] = {
            lc: deque() for lc in CLASS_ORDER
        }

    def depth(self) -> int:
        return sum(len(q) for q in self._queues.values())

    def depth_by_class(self) -> dict[str, int]:
        return {lc: len(q) for lc, q in self._queues.items()}

    def check(self, latency_class: str, fleet_depth: int) -> None:
        """Admission gate for a NEW request at the given fleet queue
        depth (gateway queues + replica backlogs). Raises the typed
        overload; no state change."""
        if latency_class not in LATENCY_CLASSES:
            raise ValueError(
                f"unknown latency class {latency_class!r} (want one of "
                f"{sorted(LATENCY_CLASSES)})"
            )
        p = self.policy
        if fleet_depth >= p.hard_watermark:
            raise OverloadedError(
                "fleet past its hard watermark",
                latency_class=latency_class, reason=SHED_WATERMARK,
                retry_after_s=p.retry_after_s, queue_depth=fleet_depth,
            )
        if latency_class == BATCH_CLASS and fleet_depth >= p.shed_watermark:
            raise OverloadedError(
                "batch traffic shed first past the watermark",
                latency_class=latency_class, reason=SHED_WATERMARK,
                retry_after_s=p.retry_after_s, queue_depth=fleet_depth,
            )

    def enqueue(self, request) -> None:
        q = self._queues[request.latency_class]
        q.append(request)
        tl = getattr(request, "timeline", None)
        if tl is not None:
            tl.event(
                "class-queued", request.submitted_at,
                latencyClass=request.latency_class, depth=len(q),
            )

    def requeue_front(self, request) -> None:
        """Put a re-routed (drained/failed-over) request back at the
        FRONT of its class queue: it keeps its arrival priority."""
        self._queues[request.latency_class].appendleft(request)

    def pop(self, now: Optional[float] = None) -> Optional[object]:
        """Next request in strict class-priority order (FIFO within a
        class); None when all queues are empty. ``now`` (when the
        caller has a clock in hand) times the class-queue wait onto the
        request's timeline."""
        for lc in CLASS_ORDER:
            if self._queues[lc]:
                request = self._queues[lc].popleft()
                tl = getattr(request, "timeline", None)
                if tl is not None and now is not None:
                    tl.event(
                        "dequeued", now,
                        waitedS=round(
                            max(0.0, now - request.submitted_at), 6
                        ),
                    )
                return request
        return None

    def push_back(self, request) -> None:
        """Undo a pop (routing found no replica): back to the front so
        order is preserved."""
        self._queues[request.latency_class].appendleft(request)

    def expire(self, now: float) -> list:
        """Remove and return every queued request past its class
        deadline — the caller fails them with a typed error. Never
        silent: a request leaves these queues dispatched or rejected."""
        expired = []
        for lc, q in self._queues.items():
            limit = self.policy.deadline_s(lc)
            keep = deque()
            while q:
                r = q.popleft()
                if now - r.submitted_at > limit:
                    expired.append(r)
                else:
                    keep.append(r)
            self._queues[lc] = keep
        return expired
