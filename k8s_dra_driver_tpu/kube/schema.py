"""Structural validation for resource.k8s.io objects (apiserver analog).

The round-4 verdict's residual risk: with the kind e2e gate unrunnable in
this environment (no docker), nothing applied real API-server validation
to the objects this driver emits — FakeKubeClient happily stored any
shape. This module encodes the upstream validation contract for the
object kinds the driver touches, in every served dialect (v1alpha3
through v1), so the fake can reject what a real apiserver would reject.

Rules and limits are transcribed from the reference's vendored API types
(lengrongfu/k8s-dra-driver, vendor/k8s.io/api/resource/v1alpha3/types.go):

- QualifiedName: C identifier, optionally ``<dns-subdomain>/`` prefixed;
  domain <= 63, identifier <= 32 (types.go:226-248)
- DeviceAttribute: exactly one of int/bool/string/version; string and
  version values <= 64 chars (types.go:251-283)
- ResourceSliceMaxDevices = 128, ResourceSliceMaxSharedCapacity = 128,
  ResourceSliceMaxAttributesAndCapacitiesPerDevice = 32,
  PoolNameMaxLength = 253 (types.go:184-224)
- exactly one of spec.nodeName / nodeSelector / allNodes (types.go:120-160)
- DeviceRequestsMaxSize / DeviceConstraintsMaxSize / DeviceConfigMaxSize /
  DeviceSelectorsMaxSize / AllocationResultsMaxSize /
  ResourceClaimReservedForMaxSize = 32 (types.go:374-376,460,660,737)

Dialect deltas (kube/resourceapi.py): v1alpha3 capacities are bare
quantity strings; v1beta1 wraps them as DeviceCapacity ``{"value": ...}``;
v1beta2/v1 inline the device payload (no ``basic``) and nest claim-request
payloads under ``exactly``.
``sharedCounters``/``consumesCounters`` (this driver's partitionable-
devices extension) always use the wrapped Counter form.
"""

from __future__ import annotations

import re

# -- limits (types.go references above) --------------------------------------

MAX_DEVICES_PER_SLICE = 128
MAX_SHARED_COUNTERS = 128
MAX_ATTRS_AND_CAPS_PER_DEVICE = 32
MAX_DOMAIN_LEN = 63
MAX_ID_LEN = 32
MAX_ATTR_VALUE_LEN = 64
MAX_POOL_NAME_LEN = 253
MAX_REQUESTS = 32
MAX_SELECTORS = 32
MAX_CONSTRAINTS = 32
MAX_CONFIGS = 32
MAX_ALLOCATION_RESULTS = 32
MAX_RESERVED_FOR = 32

_DNS_LABEL = re.compile(r"^[a-z0-9]([-a-z0-9]*[a-z0-9])?$")
_C_IDENT = re.compile(r"^[A-Za-z_][A-Za-z0-9_]*$")
# k8s resource.Quantity surface syntax (decimal, optional SI/binary suffix).
_QUANTITY = re.compile(
    r"^[+-]?([0-9]+|[0-9]*\.[0-9]+)([eE][+-]?[0-9]+|[kKMGTPE]i?|m|u|n)?$"
)
# semver-ish (semver.org 2.0.0 core, optional pre-release/build).
_VERSION = re.compile(
    r"^[0-9]+\.[0-9]+\.[0-9]+(-[0-9A-Za-z.-]+)?(\+[0-9A-Za-z.-]+)?$"
)

SUPPORTED_VERSIONS = ("v1alpha3", "v1beta1", "v1beta2", "v1")

# Dialects with the flattened-Device / exactly-nested-request shape.
_FLAT_VERSIONS = ("v1beta2", "v1")


class SchemaError(ValueError):
    """One or more violations a real API server would reject with 422."""

    def __init__(self, kind: str, issues: list[str]):
        self.kind = kind
        self.issues = issues
        super().__init__(
            f"{kind} fails validation ({len(issues)} issue(s)): "
            + "; ".join(issues[:10])
        )


# -- primitive validators ----------------------------------------------------


def _is_int(value) -> bool:
    """True for int64-shaped values. bool is a subclass of int in
    Python; a JSON true is NOT an integer to the apiserver."""
    return isinstance(value, int) and not isinstance(value, bool)


def _dict_items(value, path, issues):
    """Iterate a list-of-objects field defensively: a non-list value or a
    non-dict element is a schema issue (422), never a Python crash out of
    the validator."""
    if value is None:
        return []
    if not isinstance(value, list):
        issues.append(f"{path}: must be a list")
        return []
    out = []
    for i, el in enumerate(value):
        if isinstance(el, dict):
            out.append((i, el))
        else:
            issues.append(f"{path}[{i}]: must be an object")
    return out


def _map_items(value, path, issues):
    if value is None:
        return {}
    if not isinstance(value, dict):
        issues.append(f"{path}: must be a map")
        return {}
    return value


def _dns_label(value, path, issues, max_len=63):
    if not isinstance(value, str) or not value:
        issues.append(f"{path}: required DNS-1123 label, got {value!r}")
        return
    if len(value) > max_len or not _DNS_LABEL.match(value):
        issues.append(f"{path}: invalid DNS-1123 label {value!r}")


def _dns_subdomain(value, path, issues, max_len=253):
    if not isinstance(value, str) or not value:
        issues.append(f"{path}: required DNS-1123 subdomain, got {value!r}")
        return
    if len(value) > max_len:
        issues.append(f"{path}: {value!r} exceeds {max_len} chars")
        return
    for part in value.split("."):
        if not _DNS_LABEL.match(part):
            issues.append(f"{path}: invalid DNS-1123 subdomain {value!r}")
            return


def _qualified_name(name, path, issues):
    """C identifier with an optional DNS-subdomain/ prefix
    (types.go:226-248)."""
    if not isinstance(name, str) or not name:
        issues.append(f"{path}: empty qualified name")
        return
    domain, slash, ident = name.rpartition("/")
    if slash and not domain:
        issues.append(f"{path}: {name!r} has an empty domain")
        return
    if domain:
        _dns_subdomain(domain, f"{path} (domain of {name!r})", issues,
                       max_len=MAX_DOMAIN_LEN)
    if len(ident) > MAX_ID_LEN:
        issues.append(
            f"{path}: identifier of {name!r} exceeds {MAX_ID_LEN} chars"
        )
    elif not _C_IDENT.match(ident):
        issues.append(f"{path}: {name!r} is not a C identifier")


def _quantity(value, path, issues):
    if not isinstance(value, (str, int)):
        issues.append(f"{path}: quantity must be a string, got {type(value).__name__}")
        return
    if not _QUANTITY.match(str(value)):
        issues.append(f"{path}: invalid quantity {value!r}")


def _counter_map(counters, path, issues):
    """Counter maps (sharedCounters[].counters / consumesCounters[].counters):
    qualified names -> {"value": quantity} in both dialects."""
    if not isinstance(counters, dict):
        issues.append(f"{path}: must be a map")
        return
    for cname, cval in counters.items():
        _qualified_name(cname, f"{path}[{cname!r}]", issues)
        if not isinstance(cval, dict) or set(cval) != {"value"}:
            issues.append(
                f"{path}[{cname!r}]: counter must be {{'value': <quantity>}}"
            )
            continue
        _quantity(cval["value"], f"{path}[{cname!r}].value", issues)


def _attribute(value, path, issues):
    """DeviceAttribute: exactly one of int/bool/string/version
    (types.go:251-283)."""
    if not isinstance(value, dict):
        issues.append(f"{path}: attribute must be a value union, got "
                      f"{type(value).__name__}")
        return
    fields = set(value) & {"int", "bool", "string", "version"}
    if len(set(value)) != 1 or len(fields) != 1:
        issues.append(
            f"{path}: exactly one of int/bool/string/version required, "
            f"got {sorted(value)}"
        )
        return
    (field,) = fields
    v = value[field]
    # bool is a subclass of int in Python; a JSON true is NOT an int64.
    if field == "int" and (isinstance(v, bool) or not isinstance(v, int)):
        issues.append(f"{path}.int: not an integer: {v!r}")
    if field == "bool" and not isinstance(v, bool):
        issues.append(f"{path}.bool: not a boolean: {v!r}")
    if field in ("string", "version"):
        if not isinstance(v, str):
            issues.append(f"{path}.{field}: not a string: {v!r}")
        elif len(v) > MAX_ATTR_VALUE_LEN:
            issues.append(
                f"{path}.{field}: value exceeds {MAX_ATTR_VALUE_LEN} chars"
            )
        elif field == "version" and not _VERSION.match(v):
            issues.append(f"{path}.version: not a semver value: {v!r}")


def _node_selector(sel, path, issues):
    if not isinstance(sel, dict):
        issues.append(f"{path}: must be a v1.NodeSelector object")
        return
    terms = sel.get("nodeSelectorTerms")
    if not isinstance(terms, list) or not terms:
        issues.append(f"{path}.nodeSelectorTerms: required non-empty list")
        return
    for i, term in _dict_items(terms, f"{path}.nodeSelectorTerms", issues):
        for j, expr in _dict_items(
            term.get("matchExpressions"),
            f"{path}.nodeSelectorTerms[{i}].matchExpressions", issues,
        ):
            p = f"{path}.nodeSelectorTerms[{i}].matchExpressions[{j}]"
            if not expr.get("key"):
                issues.append(f"{p}.key: required")
            if expr.get("operator") not in (
                "In", "NotIn", "Exists", "DoesNotExist", "Gt", "Lt"
            ):
                issues.append(f"{p}.operator: invalid {expr.get('operator')!r}")


def _cel_selectors(selectors, path, issues):
    if selectors is None:
        return
    if not isinstance(selectors, list):
        issues.append(f"{path}: must be a list")
        return
    if len(selectors) > MAX_SELECTORS:
        issues.append(f"{path}: more than {MAX_SELECTORS} selectors")
    for i, sel in _dict_items(selectors, path, issues):
        cel = sel.get("cel")
        if not isinstance(cel, dict) or not isinstance(
            cel.get("expression"), str
        ) or not cel["expression"].strip():
            issues.append(
                f"{path}[{i}]: exactly 'cel' with a non-empty expression "
                "is required"
            )


# -- object validators -------------------------------------------------------


def _check_type_meta(obj, kind, issues):
    api_version = obj.get("apiVersion", "")
    group, _, version = api_version.partition("/")
    if group != "resource.k8s.io" or version not in SUPPORTED_VERSIONS:
        issues.append(
            f"apiVersion: {api_version!r} is not a supported "
            f"resource.k8s.io dialect {SUPPORTED_VERSIONS}"
        )
        version = None
    if obj.get("kind") != kind:
        issues.append(f"kind: {obj.get('kind')!r} != {kind!r}")
    name = (obj.get("metadata") or {}).get("name", "")
    if name:
        _dns_subdomain(name, "metadata.name", issues)
    elif not (obj.get("metadata") or {}).get("generateName"):
        issues.append("metadata.name: required")
    return version


def validate_resource_slice(obj: dict) -> None:
    """Apply upstream ResourceSlice validation (both dialects; the
    capacity shape checked is the one the object's apiVersion declares)."""
    issues: list[str] = []
    version = _check_type_meta(obj, "ResourceSlice", issues)
    spec = obj.get("spec")
    if not isinstance(spec, dict):
        raise SchemaError("ResourceSlice", issues + ["spec: required"])
    _dns_subdomain(spec.get("driver", ""), "spec.driver", issues)

    pool = spec.get("pool")
    if not isinstance(pool, dict):
        issues.append("spec.pool: required")
    else:
        pname = pool.get("name", "")
        if not pname or len(pname) > MAX_POOL_NAME_LEN:
            issues.append(f"spec.pool.name: required, <= {MAX_POOL_NAME_LEN}")
        else:
            for seg in pname.split("/"):
                _dns_subdomain(seg, "spec.pool.name segment", issues)
        if not _is_int(pool.get("generation")):
            issues.append("spec.pool.generation: required integer")
        if not _is_int(pool.get("resourceSliceCount")) or (
            pool["resourceSliceCount"] < 1
        ):
            issues.append("spec.pool.resourceSliceCount: required, >= 1")

    node_fields = [
        f for f in ("nodeName", "nodeSelector", "allNodes")
        if spec.get(f)
    ]
    if len(node_fields) != 1:
        issues.append(
            "spec: exactly one of nodeName/nodeSelector/allNodes is "
            f"required, got {node_fields or 'none'}"
        )
    if spec.get("nodeName"):
        _dns_subdomain(spec["nodeName"], "spec.nodeName", issues)
    if spec.get("nodeSelector") is not None:
        _node_selector(spec["nodeSelector"], "spec.nodeSelector", issues)

    devices = _dict_items(spec.get("devices"), "spec.devices", issues)
    if len(devices) > MAX_DEVICES_PER_SLICE:
        issues.append(
            f"spec.devices: {len(devices)} devices exceeds "
            f"{MAX_DEVICES_PER_SLICE}"
        )
    seen_devices = set()
    for i, dev in devices:
        p = f"spec.devices[{i}]"
        _dns_label(dev.get("name", ""), f"{p}.name", issues)
        if dev.get("name") in seen_devices:
            issues.append(f"{p}.name: duplicate {dev.get('name')!r}")
        seen_devices.add(dev.get("name"))
        if version in _FLAT_VERSIONS:
            # v1beta2/v1 removed the wrapper: the payload lives on the
            # Device itself, and a lingering 'basic' is wrong-dialect.
            if "basic" in dev:
                issues.append(
                    f"{p}.basic: not a {version} field (device payload "
                    "is inline)"
                )
                continue
            basic = dev
        else:
            basic = dev.get("basic")
            if not isinstance(basic, dict):
                issues.append(f"{p}.basic: required")
                continue
        attrs = _map_items(basic.get("attributes"), f"{p}.attributes", issues)
        caps = _map_items(basic.get("capacity"), f"{p}.capacity", issues)
        if len(attrs) + len(caps) > MAX_ATTRS_AND_CAPS_PER_DEVICE:
            issues.append(
                f"{p}: {len(attrs)}+{len(caps)} attributes+capacities "
                f"exceeds {MAX_ATTRS_AND_CAPS_PER_DEVICE}"
            )
        for aname, aval in attrs.items():
            _qualified_name(aname, f"{p}.attributes", issues)
            _attribute(aval, f"{p}.attributes[{aname!r}]", issues)
        for cname, cval in caps.items():
            _qualified_name(cname, f"{p}.capacity", issues)
            cp = f"{p}.capacity[{cname!r}]"
            if version == "v1alpha3":
                # Bare resource.Quantity (types.go:220).
                if isinstance(cval, dict):
                    issues.append(
                        f"{cp}: v1alpha3 capacity must be a bare quantity "
                        "string, got an object"
                    )
                else:
                    _quantity(cval, cp, issues)
            else:
                # v1beta1 DeviceCapacity {"value": quantity}.
                if not isinstance(cval, dict) or set(cval) != {"value"}:
                    issues.append(
                        f"{cp}: v1beta1 capacity must be "
                        "{'value': <quantity>}"
                    )
                else:
                    _quantity(cval["value"], f"{cp}.value", issues)
        for j, cc in _dict_items(
            basic.get("consumesCounters"), f"{p}.consumesCounters", issues
        ):
            cp = f"{p}.consumesCounters[{j}]"
            _dns_label(cc.get("counterSet", ""), f"{cp}.counterSet", issues,
                       max_len=253)
            _counter_map(cc.get("counters"), f"{cp}.counters", issues)

    shared = _dict_items(
        spec.get("sharedCounters"), "spec.sharedCounters", issues
    )
    if len(shared) > MAX_SHARED_COUNTERS:
        issues.append(
            f"spec.sharedCounters: {len(shared)} exceeds "
            f"{MAX_SHARED_COUNTERS}"
        )
    declared = set()
    for i, cs in shared:
        p = f"spec.sharedCounters[{i}]"
        _dns_label(cs.get("name", ""), f"{p}.name", issues, max_len=253)
        declared.add(cs.get("name"))
        _counter_map(cs.get("counters"), f"{p}.counters", issues)
    for i, dev in devices:
        basic = dev if version in _FLAT_VERSIONS else dev.get("basic")
        if not isinstance(basic, dict):
            continue
        for j, cc in _dict_items(
            basic.get("consumesCounters"),
            f"spec.devices[{i}].consumesCounters", [],
        ):
            if cc.get("counterSet") not in declared:
                issues.append(
                    f"spec.devices[{i}].consumesCounters[{j}]: counterSet "
                    f"{cc.get('counterSet')!r} not declared in "
                    "spec.sharedCounters"
                )
    if issues:
        raise SchemaError("ResourceSlice", issues)


_FLAT_REQUEST_FIELDS = (
    "deviceClassName", "selectors", "allocationMode", "count", "adminAccess",
)


def _validate_claim_spec(spec, path, issues, version=None):
    devices = _map_items(spec.get("devices"), f"{path}.devices", issues)
    requests = _dict_items(
        devices.get("requests"), f"{path}.devices.requests", issues
    )
    if len(requests) > MAX_REQUESTS:
        issues.append(f"{path}.devices.requests: exceeds {MAX_REQUESTS}")
    req_names = set()
    for i, req in requests:
        p = f"{path}.devices.requests[{i}]"
        _dns_label(req.get("name", ""), f"{p}.name", issues)
        if req.get("name") in req_names:
            issues.append(f"{p}.name: duplicate {req.get('name')!r}")
        req_names.add(req.get("name"))
        if version in _FLAT_VERSIONS:
            # v1beta2/v1 nest the payload: exactly one of exactly /
            # firstAvailable; flat fields on the request itself are the
            # older dialects' shape.
            flat = [f for f in _FLAT_REQUEST_FIELDS if f in req]
            if flat:
                issues.append(
                    f"{p}: fields {flat} must nest under 'exactly' in "
                    f"{version}"
                )
            nested = [f for f in ("exactly", "firstAvailable") if f in req]
            if len(nested) != 1:
                issues.append(
                    f"{p}: exactly one of exactly/firstAvailable required"
                )
                continue
            if nested == ["firstAvailable"]:
                for j, sub in _dict_items(
                    req["firstAvailable"], f"{p}.firstAvailable", issues
                ):
                    _dns_label(sub.get("name", ""),
                               f"{p}.firstAvailable[{j}].name", issues)
                    _dns_subdomain(
                        sub.get("deviceClassName", ""),
                        f"{p}.firstAvailable[{j}].deviceClassName", issues,
                    )
                    # Allocations from a prioritized list record
                    # '<request>/<subrequest>' — those are the legal
                    # names for status results / config references.
                    req_names.add(f"{req.get('name')}/{sub.get('name')}")
                continue
            req = req["exactly"]
            if not isinstance(req, dict):
                issues.append(f"{p}.exactly: must be an object")
                continue
            p = f"{p}.exactly"
        _dns_subdomain(
            req.get("deviceClassName", ""), f"{p}.deviceClassName", issues
        )
        mode = req.get("allocationMode", "")
        if mode not in ("", "ExactCount", "All"):
            issues.append(f"{p}.allocationMode: invalid {mode!r}")
        count = req.get("count")
        if count is not None:
            if not _is_int(count) or count < 1:
                issues.append(f"{p}.count: must be a positive integer")
            if mode == "All":
                issues.append(f"{p}.count: must be unset with "
                              "allocationMode=All")
        if "adminAccess" in req and not isinstance(
            req["adminAccess"], bool
        ):
            issues.append(f"{p}.adminAccess: must be a boolean")
        _cel_selectors(req.get("selectors"), f"{p}.selectors", issues)
    constraints = _dict_items(
        devices.get("constraints"), f"{path}.devices.constraints", issues
    )
    if len(constraints) > MAX_CONSTRAINTS:
        issues.append(f"{path}.devices.constraints: exceeds {MAX_CONSTRAINTS}")
    for i, con in constraints:
        p = f"{path}.devices.constraints[{i}]"
        ma = con.get("matchAttribute")
        if not ma:
            issues.append(f"{p}.matchAttribute: required")
            continue
        _qualified_name(ma, f"{p}.matchAttribute", issues)
        if "/" not in str(ma):
            issues.append(
                f"{p}.matchAttribute: {ma!r} must be fully qualified "
                "(domain/name)"
            )
        for rname in con.get("requests") or []:
            if rname not in req_names:
                issues.append(
                    f"{p}.requests: {rname!r} names no request"
                )
    configs = _dict_items(
        devices.get("config"), f"{path}.devices.config", issues
    )
    if len(configs) > MAX_CONFIGS:
        issues.append(f"{path}.devices.config: exceeds {MAX_CONFIGS}")
    for i, cfg in configs:
        p = f"{path}.devices.config[{i}]"
        opaque = cfg.get("opaque")
        if opaque is not None:
            _dns_subdomain(
                opaque.get("driver", ""), f"{p}.opaque.driver", issues
            )
            if "parameters" not in opaque:
                issues.append(f"{p}.opaque.parameters: required")
        for rname in cfg.get("requests") or []:
            if rname not in req_names:
                issues.append(f"{p}.requests: {rname!r} names no request")
    return req_names


def validate_resource_claim(obj: dict) -> None:
    issues: list[str] = []
    version = _check_type_meta(obj, "ResourceClaim", issues)
    spec = obj.get("spec")
    if not isinstance(spec, dict):
        raise SchemaError("ResourceClaim", issues + ["spec: required"])
    req_names = _validate_claim_spec(spec, "spec", issues, version)

    status = _map_items(obj.get("status"), "status", issues)
    alloc = _map_items(status.get("allocation"), "status.allocation", issues)
    results = _dict_items(
        _map_items(
            alloc.get("devices"), "status.allocation.devices", issues
        ).get("results"),
        "status.allocation.devices.results", issues,
    )
    if len(results) > MAX_ALLOCATION_RESULTS:
        issues.append(
            f"status.allocation.devices.results: exceeds "
            f"{MAX_ALLOCATION_RESULTS}"
        )
    for i, res in results:
        p = f"status.allocation.devices.results[{i}]"
        if res.get("request") not in req_names:
            issues.append(
                f"{p}.request: {res.get('request')!r} names no spec request"
            )
        _dns_subdomain(res.get("driver", ""), f"{p}.driver", issues)
        if not res.get("pool"):
            issues.append(f"{p}.pool: required")
        _dns_label(res.get("device", ""), f"{p}.device", issues)
    reserved = status.get("reservedFor") or []
    if len(reserved) > MAX_RESERVED_FOR:
        issues.append(f"status.reservedFor: exceeds {MAX_RESERVED_FOR}")
    if issues:
        raise SchemaError("ResourceClaim", issues)


def validate_resource_claim_template(obj: dict) -> None:
    issues: list[str] = []
    version = _check_type_meta(obj, "ResourceClaimTemplate", issues)
    inner = (obj.get("spec") or {}).get("spec")
    if not isinstance(inner, dict):
        raise SchemaError(
            "ResourceClaimTemplate", issues + ["spec.spec: required"]
        )
    _validate_claim_spec(inner, "spec.spec", issues, version)
    if issues:
        raise SchemaError("ResourceClaimTemplate", issues)


def validate_device_class(obj: dict) -> None:
    issues: list[str] = []
    _check_type_meta(obj, "DeviceClass", issues)
    spec = obj.get("spec")
    if not isinstance(spec, dict):
        raise SchemaError("DeviceClass", issues + ["spec: required"])
    _cel_selectors(spec.get("selectors"), "spec.selectors", issues)
    for i, cfg in _dict_items(spec.get("config"), "spec.config", issues):
        opaque = cfg.get("opaque")
        if opaque is not None:
            # DeviceConfiguration is shared between claim and class
            # config upstream: driver AND parameters are required.
            _dns_subdomain(
                opaque.get("driver", ""), f"spec.config[{i}].opaque.driver",
                issues,
            )
            if "parameters" not in opaque:
                issues.append(f"spec.config[{i}].opaque.parameters: required")
    if issues:
        raise SchemaError("DeviceClass", issues)


VALIDATORS = {
    "ResourceSlice": validate_resource_slice,
    "ResourceClaim": validate_resource_claim,
    "ResourceClaimTemplate": validate_resource_claim_template,
    "DeviceClass": validate_device_class,
}

# REST collection name -> kind: a real apiserver decodes the payload as
# the kind the request PATH addresses, so dispatch must not trust the
# object's self-declared kind (an object omitting ``kind`` would
# otherwise bypass validation entirely).
RESOURCE_KINDS = {
    "resourceslices": "ResourceSlice",
    "resourceclaims": "ResourceClaim",
    "resourceclaimtemplates": "ResourceClaimTemplate",
    "deviceclasses": "DeviceClass",
}


def _checked(kind: str, obj: dict) -> None:
    """Run a validator with a structural safety net: whatever shape the
    caller hands in, the outcome is SchemaError (the 422 analog), never
    a bare TypeError/AttributeError from inside the validator."""
    try:
        VALIDATORS[kind](obj if isinstance(obj, dict) else {})
    except SchemaError:
        raise
    except Exception as e:
        raise SchemaError(
            kind, [f"malformed object structure ({type(e).__name__}: {e})"]
        )


def validate(obj: dict) -> None:
    """Dispatch on the object's kind; unknown kinds pass (the fake stores
    plenty of core-group objects this module does not model)."""
    kind = (obj or {}).get("kind", "")
    if kind in VALIDATORS:
        _checked(kind, obj)


def validate_for_resource(resource: str, obj: dict) -> None:
    """Dispatch on the REST collection (apiserver semantics): the path,
    not the payload, decides which schema applies."""
    kind = RESOURCE_KINDS.get(resource)
    if kind is not None:
        _checked(kind, obj)
