"""Kubernetes Event emission: best-effort, async, deduped, count-aggregated.

Role of client-go's EventBroadcaster/EventAggregator (the reference driver
emits no Events at all — a prepare failure is invisible to ``kubectl
describe``). This recorder makes claim-lifecycle failures show up where
operators actually look: Events on the ResourceClaim (plugin side) and on
the Node (controller reconcile errors).

Semantics, mirroring the client-go correlator:

- **Async delivery**: ``normal()``/``warning()`` only enqueue; a single
  daemon worker does the API I/O. The claim hot path (which runs under
  the driver's global claim lock) never blocks on the API server — an
  overloaded apiserver retrying 429s must not serialize every other
  claim's Prepare behind an Event write.
- **Dedup + aggregation**: repeats with the same (object, type, reason)
  become one Event with ``count`` incremented, ``lastTimestamp`` advanced,
  and the message refreshed — NOT keyed on the message text, because
  callers embed raw exception strings and any variability there would
  defeat dedup and flood etcd with near-duplicate objects.
- **Best-effort**: a full queue or an API error drops the Event (logged at
  debug, counted in ``tpu_dra_events_emit_failures_total``) and never
  surfaces to the caller.
- **Deterministic names**: the Event name derives from a digest of the
  dedup key, so a restarted plugin aggregates onto the Event its previous
  incarnation created instead of duplicating it.
"""

from __future__ import annotations

import dataclasses
import hashlib
import logging
import queue
import threading
import time
from typing import Optional

from ..utils.metrics import Counter, Registry
from .client import EVENTS, KubeClient
from .errors import AlreadyExistsError, ConflictError, NotFoundError

logger = logging.getLogger(__name__)

EVENT_TYPE_NORMAL = "Normal"
EVENT_TYPE_WARNING = "Warning"


@dataclasses.dataclass(frozen=True)
class ObjectRef:
    """The involved object an Event attaches to."""

    kind: str
    name: str
    namespace: str = ""
    uid: str = ""
    api_version: str = "v1"

    @classmethod
    def claim(cls, name: str, namespace: str, uid: str = "",
              api_version: str = "resource.k8s.io/v1beta1") -> "ObjectRef":
        """``api_version`` should be the dialect the driver discovered
        (``ResourceApi.api_version``) so involvedObject resolves on every
        cluster generation; the default matches 1.32 clusters."""
        return cls(
            kind="ResourceClaim",
            name=name,
            namespace=namespace,
            uid=uid,
            api_version=api_version,
        )

    @classmethod
    def node(cls, name: str, uid: str = "") -> "ObjectRef":
        return cls(kind="Node", name=name, uid=uid)


def _iso_now() -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())


class EventRecorder:
    """Emit v1 Events through a KubeClient; ``client=None`` is a no-op
    recorder (kube-less dev mode keeps the same call sites)."""

    # Bounded delivery queue: past this, emits drop (counted) rather than
    # block the caller or grow without bound during an apiserver outage.
    QUEUE_SIZE = 256
    # Bounded dedup cache: key -> event name. Past this, oldest entries
    # fall out and a repeat re-aggregates via the AlreadyExists path.
    MAX_CACHE = 512

    def __init__(
        self,
        client: Optional[KubeClient],
        component: str,
        namespace: str = "default",
        registry: Optional[Registry] = None,
    ):
        self.client = client
        self.component = component
        self.namespace = namespace
        self._queue: "queue.Queue[tuple]" = queue.Queue(maxsize=self.QUEUE_SIZE)
        self._worker: Optional[threading.Thread] = None
        self._worker_lock = threading.Lock()
        self._seen: dict[str, str] = {}  # dedup key -> event name (worker-only)
        reg = registry or Registry()
        self._m_emitted = Counter(
            "tpu_dra_events_emitted_total",
            "Kubernetes Events written (aggregated repeats count once here "
            "per API write)",
            reg,
        )
        self._m_failures = Counter(
            "tpu_dra_events_emit_failures_total",
            "Kubernetes Events dropped (queue full or API write failed; "
            "best-effort)",
            reg,
        )

    # -- public API --------------------------------------------------------

    def normal(self, ref: ObjectRef, reason: str, message: str) -> None:
        self._enqueue(EVENT_TYPE_NORMAL, ref, reason, message)

    def warning(self, ref: ObjectRef, reason: str, message: str) -> None:
        self._enqueue(EVENT_TYPE_WARNING, ref, reason, message)

    def flush(self, timeout: float = 5.0) -> bool:
        """Wait until every enqueued Event has been delivered (or dropped).
        Test/shutdown seam; returns False on timeout."""
        deadline = time.monotonic() + timeout
        while self._queue.unfinished_tasks:
            if time.monotonic() >= deadline:
                return False
            time.sleep(0.005)
        return True

    # -- enqueue side (caller threads; must never block) -------------------

    def _enqueue(self, type_: str, ref: ObjectRef, reason: str,
                 message: str) -> None:
        if self.client is None:
            return
        self._ensure_worker()
        try:
            self._queue.put_nowait((type_, ref, reason, message))
        except queue.Full:
            self._m_failures.inc()
            logger.debug(
                "event queue full; dropping %s/%s on %s/%s",
                type_, reason, ref.kind, ref.name,
            )

    def _ensure_worker(self) -> None:
        if self._worker is not None and self._worker.is_alive():
            return
        with self._worker_lock:
            if self._worker is not None and self._worker.is_alive():
                return
            self._worker = threading.Thread(
                target=self._run, daemon=True, name="event-recorder"
            )
            self._worker.start()

    def _run(self) -> None:
        while True:
            item = self._queue.get()
            try:
                self._deliver(*item)
            except Exception as e:
                type_, ref, reason, _ = item
                self._m_failures.inc()
                logger.debug(
                    "event %s/%s on %s/%s dropped: %s",
                    type_, reason, ref.kind, ref.name, e,
                )
            finally:
                self._queue.task_done()

    # -- delivery side (worker thread only) --------------------------------

    def _key(self, type_: str, ref: ObjectRef, reason: str) -> str:
        """Aggregation key: (object, type, reason) — deliberately NOT the
        message, which embeds variable exception text (client-go's
        aggregator likewise collapses differing messages)."""
        ident = "/".join((
            type_, ref.kind, ref.namespace, ref.name, ref.uid, reason,
        ))
        return hashlib.sha256(ident.encode()).hexdigest()[:16]

    def _deliver(self, type_: str, ref: ObjectRef, reason: str,
                 message: str) -> None:
        key = self._key(type_, ref, reason)
        # Deterministic, collision-resistant, ≤253 chars (DNS subdomain).
        name = f"{ref.name[:230].lower().rstrip('.-') or 'event'}.{key}"
        namespace = ref.namespace or self.namespace
        if key in self._seen:
            try:
                self._aggregate(name, namespace, message)
            except NotFoundError:
                # Evicted server-side (Events are TTL'd): recreate.
                self.client.create(
                    EVENTS,
                    self._new_event(name, namespace, type_, ref,
                                    reason, message),
                    namespace=namespace,
                )
        else:
            try:
                self.client.create(
                    EVENTS,
                    self._new_event(name, namespace, type_, ref,
                                    reason, message),
                    namespace=namespace,
                )
            except AlreadyExistsError:
                # A previous incarnation (or a cache eviction) already
                # created it: aggregate onto the existing Event.
                self._aggregate(name, namespace, message)
        self._m_emitted.inc(type=type_)
        self._seen[key] = name
        while len(self._seen) > self.MAX_CACHE:
            self._seen.pop(next(iter(self._seen)))

    def _new_event(self, name: str, namespace: str, type_: str,
                   ref: ObjectRef, reason: str, message: str) -> dict:
        now = _iso_now()
        return {
            "apiVersion": "v1",
            "kind": "Event",
            "metadata": {"name": name, "namespace": namespace},
            "involvedObject": {
                "apiVersion": ref.api_version,
                "kind": ref.kind,
                "name": ref.name,
                "namespace": ref.namespace,
                "uid": ref.uid,
            },
            "reason": reason,
            "message": message,
            "type": type_,
            "count": 1,
            "firstTimestamp": now,
            "lastTimestamp": now,
            "source": {"component": self.component},
            "reportingComponent": self.component,
        }

    def _aggregate(self, name: str, namespace: str, message: str) -> None:
        """count+1 / lastTimestamp / latest message on the existing Event;
        one conflict retry (another replica may be aggregating too)."""
        for attempt in (0, 1):
            ev = self.client.get(EVENTS, name, namespace=namespace)
            ev["count"] = int(ev.get("count", 1)) + 1
            ev["lastTimestamp"] = _iso_now()
            ev["message"] = message
            try:
                self.client.update(EVENTS, ev, namespace=namespace)
                return
            except ConflictError:
                if attempt:
                    raise
