"""Reference DRA allocator: scheduler-sim for tests and dev clusters.

The real allocation happens in the Kubernetes scheduler's structured-
parameters allocator (SURVEY.md §3.5 — the layer deliberately NOT in the
reference repo). This module re-implements the subset this driver's
published attributes exercise, so the full claim lifecycle can be simulated
hermetically: DeviceClass → device-type mapping, request counts, attribute
selectors, and cross-request ``matchAttribute`` constraints (the gang /
same-parent mechanism of tpu-test4/6).

Selectors come in two forms: programmatic (attribute, op, value) triples,
and real CEL expressions from DeviceClass specs / request ``selectors``
(evaluated by the cel module's subset engine, so the demo specs run through
the sim verbatim). The production path still uses the real scheduler.

**Allocation explainability.** Every solve records a per-request *candidate
funnel* — how many devices entered, how many each named stage rejected and
why — plus backtrack count and per-stage latency, into an
:class:`Explanation`. Failures raise :class:`AllocationError` carrying the
explanation and a terminal ``reason`` drawn from :data:`REASONS`;
successes keep a compact funnel (counts, no per-device samples). Decisions
land in a bounded ring buffer served as JSONL at ``/debug/allocations``
(``MetricsServer.set_allocations_provider``) and feed the
``tpu_dra_alloc_*`` metric families, so "why won't my claim schedule?" is
answerable from a scrape instead of a debugger (kube-scheduler's
``Unschedulable`` filter messages are the model; docs/operations.md maps
each terminal reason to an operator action).

**Fleet-scale solving.** Three mechanisms keep the solver fast and the
fleet defragmented at north-star scale (thousands of nodes, high claim
churn):

- *Incremental re-solve* (:class:`InventoryIndex`): the flattened
  inventory, shared-counter capacities, and the static filter verdicts
  (invalid-slice / class CEL / request CEL per device) persist across
  solves in a generation-keyed index, invalidated per-pool by
  ResourceSlice deltas detected with a cheap ``list_meta`` signature
  probe. Steady-state solves re-evaluate nothing; only the delta after a
  health transition / device add/remove is re-filtered. Reservation
  changes never invalidate the index — the ``reserved`` stage is applied
  per solve on top of the cached survivors. ``incremental=False`` forces
  a from-scratch rebuild per solve (the bench baseline and the parity
  oracle in tests/test_allocator_scale.py).
- *Topology-aware placement* (:meth:`ReferenceAllocator._score_placement`):
  instead of first-fit in inventory order, multi-chip gangs are placed
  best-fit into the smallest free contiguous sub-mesh that satisfies
  them, with a corner/edge bias (ParvaGPU's spatial-packing discipline),
  so churn stops shredding the large contiguous boxes future gangs need.
  The chosen box and its score land in the decision record
  (``placements``) so ``/debug/allocations`` explains *why this
  placement* as well as why-not.
- *Batch solving* (:meth:`ReferenceAllocator.allocate_batch`): queued
  claims solve most-constrained-first (largest gangs before singles)
  under one :meth:`ReferenceAllocator.snapshot`, sharing one index
  refresh instead of re-probing per claim; every claim still emits its
  own funnel.

When a gang goes unsat with terminal reason ``gang``/``shortfall`` and a
:class:`~.defrag.DefragPlanner` is attached (``self.defrag``), a
read-only migration plan is computed and surfaced at ``/debug/defrag``.
"""

from __future__ import annotations

import collections
import contextlib
import dataclasses
import json
import logging
import os
import threading
import time
from typing import Any, Optional

from ..tpulib.topology import (
    Coord,
    MeshShape,
    box_shapes,
    free_components,
    is_contiguous_submesh,
)
from ..utils.metrics import Counter, Histogram, Registry
from ..utils.tracing import child_span
from .cel import CelError, evaluate_detailed as cel_evaluate_detailed
from .client import KubeClient
from .resourceapi import ResourceApi

logger = logging.getLogger(__name__)

# DeviceClass name → the `type` attribute the node plugin publishes.
DEVICE_CLASS_TYPES = {
    "tpu.google.com": "chip",
    "tensorcore.tpu.google.com": "tensorcore",
    "ici.tpu.google.com": "ici",
}

# -- funnel stages (pipeline order; the enum `stage` metric labels and
#    /debug/allocations records are confined to — lint rule TPM06) --------
STAGE_INVALID_SLICE = "invalid-slice"
STAGE_CLASS_CEL = "class-cel"
STAGE_REQUEST_CEL = "request-cel"
STAGE_UNHEALTHY = "unhealthy"
STAGE_RESERVED = "reserved"
STAGE_COUNTERS = "counters"
STAGE_CONSTRAINT = "constraint"
STAGE_GANG = "gang"

STAGES = (
    STAGE_INVALID_SLICE,
    STAGE_CLASS_CEL,
    STAGE_REQUEST_CEL,
    STAGE_UNHEALTHY,
    STAGE_RESERVED,
    STAGE_COUNTERS,
    STAGE_CONSTRAINT,
    STAGE_GANG,
)
# Filter stages timed per candidate pass (everything before the search).
_CANDIDATE_STAGES = STAGES[:5]

# Stages applied while FILTERING candidates (before the search): a deepest
# rejection here with survivors left means the request simply wants more
# devices than match — reported as `shortfall`, not as the filter stage.
_FILTER_STAGES = (STAGE_INVALID_SLICE, STAGE_CLASS_CEL, STAGE_REQUEST_CEL,
                  STAGE_UNHEALTHY)

# -- terminal reasons (the enum `reason` metric labels are confined to).
#    Kept a full literal (not STAGES + extras) so tools/lint.py TPM06 can
#    read the values without evaluating expressions; the assert below
#    keeps the two in sync.
REASON_SHORTFALL = "shortfall"
REASON_NO_DEVICES = "no-devices"
REASON_CEL_ERROR = "cel-error"
REASON_UNKNOWN_CLASS = "unknown-class"
REASON_UNKNOWN_MODE = "unknown-mode"
REASON_BACKTRACK_BUDGET = "backtrack-budget"
REASON_INTERNAL = "internal"

REASONS = (
    "invalid-slice",
    "class-cel",
    "request-cel",
    "unhealthy",
    "reserved",
    "counters",
    "constraint",
    "gang",
    "shortfall",
    "no-devices",
    "cel-error",
    "unknown-class",
    "unknown-mode",
    "backtrack-budget",
    "internal",
)
assert set(STAGES) <= set(REASONS)

# Terminal reason → the operator's next move. Single source for the
# doctor's `explain` cross-check, the inspector's live view, and the
# "why won't my claim schedule?" runbook in docs/operations.md.
RUNBOOK_HINTS = {
    "invalid-slice": (
        "a published ResourceSlice is misconfigured (devices consume "
        "counters their slice never declared); fix the slice publisher "
        "and check plugin logs for 'undeclared counters'"
    ),
    "class-cel": (
        "no device satisfies the DeviceClass selector; inspect `kubectl "
        "get deviceclass -o yaml` for a typo'd expression or a "
        "class/driver mismatch"
    ),
    "request-cel": (
        "the claim's request selectors reject every device; check the "
        "request's CEL expressions and attribute names against the "
        "published ResourceSlice attributes"
    ),
    "unhealthy": (
        "every matching device sits on a chip the health poll marked "
        "degraded; this solve required healthy devices (an elastic "
        "gang re-solve always does) — wait for recovery, drain the sick "
        "chips, or add capacity"
    ),
    "reserved": (
        "every matching device is already held by another claim; free "
        "capacity (delete idle claims) or wait for running workloads to "
        "finish"
    ),
    "counters": (
        "the shared counter budget is exhausted (e.g. chips already "
        "carved into core partitions); deallocate partition claims or "
        "target another pool"
    ),
    "constraint": (
        "the matchAttribute constraint cannot be satisfied by the "
        "remaining devices (e.g. the gang would span ICI slices); relax "
        "the constraint or free devices on one slice"
    ),
    "gang": (
        "no contiguous ICI submesh of the requested shape is free; the "
        "slice is fragmented — drain/repack smaller claims or request a "
        "smaller gang"
    ),
    "shortfall": (
        "fewer matching devices exist than the request asks for; lower "
        "the request count or add capacity"
    ),
    "no-devices": (
        "no ResourceSlices are published for this driver; check that the "
        "node plugin and controller are running and publishing"
    ),
    "cel-error": (
        "a selector expression is malformed and cannot be evaluated; fix "
        "the expression quoted in the error"
    ),
    "unknown-class": (
        "the request names a DeviceClass this driver does not serve; "
        "check the deviceClassName spelling"
    ),
    "unknown-mode": (
        "the request uses an allocationMode this driver does not "
        "implement; use ExactCount or All"
    ),
    "backtrack-budget": (
        "the solver hit its backtrack budget before finding a placement; "
        "the claim is pathologically constrained — simplify constraints "
        "or raise TPU_DRA_MAX_BACKTRACK_STEPS"
    ),
    "internal": (
        "the allocator failed unexpectedly; check plugin logs for the "
        "stack trace"
    ),
}
assert set(RUNBOOK_HINTS) == set(REASONS)

# Distinct request shapes whose static filter verdicts the inventory
# index retains (LRU): each record holds one verdict per device, so
# per-claim-unique selectors (coord pins etc.) must recycle old records
# instead of leaking one O(#devices) map per claim forever — and every
# retained record is re-filtered against a pool's devices on each delta,
# so the bound also caps delta-application work.
MAX_FILTER_RECORDS = 64
# A pathological claim (dense matchAttribute groups over a fragmented
# slice) can drive the backtracking search exponential. The budget turns
# that into a typed `backtrack-budget` failure instead of a wedged
# allocator; generous enough that every legitimate solve in the scale
# suite stays orders of magnitude below it.
DEFAULT_MAX_BACKTRACK_STEPS = 200_000
# Solve decisions kept for /debug/allocations.
DEFAULT_DECISION_BUFFER = 256


class AllocationError(RuntimeError):
    """An unallocatable claim. ``reason`` is the terminal cause (one of
    :data:`REASONS`); ``explanation`` carries the full candidate funnel
    once ``allocate()`` has finalized the solve record."""

    def __init__(self, message: str, reason: str = REASON_INTERNAL,
                 explanation: Optional["Explanation"] = None):
        super().__init__(message)
        self.reason = reason
        self.explanation = explanation


@dataclasses.dataclass
class RequestFunnel:
    """One request's candidate funnel: devices entering, per-stage
    rejection counts with sampled per-device reasons, survivors, and the
    count the request wanted."""

    request: str
    entering: int = 0
    wanted: int = 0
    survivors: int = 0
    rejected: dict[str, int] = dataclasses.field(default_factory=dict)
    reasons: dict[str, list[str]] = dataclasses.field(default_factory=dict)

    def to_dict(self) -> dict[str, Any]:
        return {
            "request": self.request,
            "entering": self.entering,
            "wanted": self.wanted,
            "survivors": self.survivors,
            "rejected": dict(self.rejected),
            "reasons": {k: list(v) for k, v in self.reasons.items()},
        }


class Explanation:
    """Structured record of one solve: the per-request funnels, the
    terminal reason on failure, backtrack count, CEL evaluation count
    (the memo's effectiveness is observable), and per-stage + end-to-end
    latency. Rendered as one JSONL line at ``/debug/allocations``."""

    # Per-device reason strings kept per (request, stage); counts are
    # exact, samples are bounded so a 192-device funnel stays one line.
    MAX_REASON_SAMPLES = 4

    def __init__(self, claim_uid: str = "", claim_name: str = "",
                 claim_namespace: str = ""):
        self.claim_uid = claim_uid
        self.claim_name = claim_name
        self.claim_namespace = claim_namespace
        self.outcome = "ok"  # ok | unsat | error
        self.reason = ""
        self.detail = ""
        self.failing_request = ""
        self.backtracks = 0
        self.cel_evaluations = 0
        self.duration_seconds = 0.0
        self.stage_seconds: dict[str, float] = {}
        self.timestamp = 0.0
        # request name -> placement-score record (the topology scorer's
        # "why THIS placement" half of the explanation).
        self.placements: dict[str, dict] = {}
        self._funnels: dict[str, RequestFunnel] = {}
        self._seen: set = set()
        self._fail_depth = -1

    # -- recording (solver side) ------------------------------------------

    def funnel(self, request: str) -> RequestFunnel:
        f = self._funnels.get(request)
        if f is None:
            f = self._funnels[request] = RequestFunnel(request=request)
        return f

    def reject(self, request: str, stage: str, key: Any,
               detail: str = "") -> None:
        """Count one rejection of candidate ``key`` at ``stage``. Deduped
        per (request, stage, key): backtracking revisits the same device
        under different partial solutions, and re-counting each probe
        would turn the funnel into a measure of search effort, not of
        inventory."""
        seen_key = (request, stage, key)
        if seen_key in self._seen:
            return
        self._seen.add(seen_key)
        f = self.funnel(request)
        f.rejected[stage] = f.rejected.get(stage, 0) + 1
        if detail:
            samples = f.reasons.setdefault(stage, [])
            if len(samples) < self.MAX_REASON_SAMPLES \
                    and detail not in samples:
                samples.append(detail)

    def add_stage_seconds(self, stage: str, seconds: float) -> None:
        self.stage_seconds[stage] = (
            self.stage_seconds.get(stage, 0.0) + seconds
        )

    def note_request_failure(self, depth: int, request: str) -> None:
        """The DEEPEST request to exhaust its candidates is the terminal
        one — earlier requests failing merely means the solver is
        unwinding through them."""
        if depth > self._fail_depth:
            self._fail_depth = depth
            self.failing_request = request

    # -- reading ----------------------------------------------------------

    @property
    def funnels(self) -> list[RequestFunnel]:
        return list(self._funnels.values())

    def terminal(self) -> tuple[str, str]:
        """(reason, human detail) for a failed solve, derived from the
        terminal request's funnel: the deepest stage that rejected
        candidates — except that filter-stage rejections with survivors
        left read as `shortfall` (the devices that DID match were simply
        too few)."""
        f = self._funnels.get(self.failing_request)
        if f is None:
            return (
                REASON_INTERNAL,
                "solver failed before exploring any request",
            )
        deepest = None
        for stage in STAGES:
            if f.rejected.get(stage):
                deepest = stage
        prefix = f"request {f.request!r}"
        if deepest is None and f.entering == 0:
            return REASON_NO_DEVICES, (
                f"{prefix}: no published devices to consider"
            )
        if (
            f.survivors > 0
            and f.survivors < max(f.wanted, 1)
            and (deepest is None or deepest in _FILTER_STAGES)
        ):
            return REASON_SHORTFALL, (
                f"{prefix}: only {f.survivors} of {f.wanted} matching "
                "device(s) available"
            )
        if deepest is None:
            return REASON_INTERNAL, (
                f"{prefix}: search exhausted with no recorded rejections"
            )
        msg = (
            f"{prefix}: {f.rejected[deepest]} candidate(s) rejected at "
            f"stage {deepest!r}"
        )
        samples = f.reasons.get(deepest)
        if samples:
            msg += f" (e.g. {samples[0]})"
        return deepest, msg

    def compact(self) -> None:
        """Successes keep the funnel counts but drop per-device samples —
        the ring buffer must stay cheap on the happy path."""
        for f in self._funnels.values():
            f.reasons = {}

    def to_dict(self) -> dict[str, Any]:
        return {
            "ts": round(self.timestamp, 3),
            "claim": {
                "uid": self.claim_uid,
                "name": self.claim_name,
                "namespace": self.claim_namespace,
            },
            "outcome": self.outcome,
            "reason": self.reason,
            "detail": self.detail,
            "failingRequest": self.failing_request,
            "backtracks": self.backtracks,
            "celEvaluations": self.cel_evaluations,
            "durationSeconds": round(self.duration_seconds, 6),
            "stageSeconds": {
                k: round(v, 6)
                for k, v in sorted(self.stage_seconds.items())
            },
            "placements": {k: dict(v) for k, v in self.placements.items()},
            "funnels": [f.to_dict() for f in self._funnels.values()],
        }


@dataclasses.dataclass
class Selector:
    """Attribute predicate: op ∈ {eq, ne, lt, le, gt, ge, in}."""

    attribute: str
    op: str
    value: Any

    def matches(self, attrs: dict) -> bool:
        raw = attrs.get(self.attribute)
        if raw is None:
            return False
        val = next(iter(raw.values())) if isinstance(raw, dict) else raw
        if self.op == "eq":
            return val == self.value
        if self.op == "ne":
            return val != self.value
        if self.op == "lt":
            return val < self.value
        if self.op == "le":
            return val <= self.value
        if self.op == "gt":
            return val > self.value
        if self.op == "ge":
            return val >= self.value
        if self.op == "in":
            return val in self.value
        raise ValueError(f"unknown op {self.op!r}")


def _attr_value(attrs: dict, name: str):
    raw = attrs.get(name)
    if raw is None:
        return None
    return next(iter(raw.values())) if isinstance(raw, dict) else raw


def _consumption_entries(dev: dict):
    """(pool, counter set, counter, amount) for each counter a device
    consumes. Index-built devices carry the parsed list precomputed
    (``_consumes``); plain dicts fall back to parsing."""
    cached = dev.get("_consumes")
    if cached is not None:
        return cached
    out = []
    for cc in dev.get("consumes", []):
        for cname, cval in cc.get("counters", {}).items():
            out.append(
                (dev["pool"], cc["counterSet"], cname, int(cval["value"]))
            )
    return out


def _gang_contiguous(chosen: list[dict]) -> tuple[bool, str]:
    """A multi-chip request is a gang: its chips must be one contiguous
    ICI sub-mesh within a single slice (SURVEY.md §7 hard part (a); the
    reference's analog is same-parent MIG constraints,
    demo/specs/quickstart/gpu-test4.yaml:42-44). XLA's collective
    performance model assumes mesh neighbours, so a fragmented pick is
    useless to the workload and must be rejected, not granted.

    Returns (ok, why_not) so the explainer can say WHICH invariant the
    combination broke.
    """
    chips = [
        d for d in chosen
        if _attr_value(d["attributes"], "type") == "chip"
    ]
    if len(chips) < 2:
        return True, ""
    slice_ids = {_attr_value(d["attributes"], "sliceId") for d in chips}
    if len(slice_ids) > 1:
        return False, f"gang:chips span ICI slices {sorted(map(str, slice_ids))}"
    coords = []
    for d in chips:
        c = _attr_value(d["attributes"], "coord")
        if c is None:
            return False, f"gang:chip {d['name']!r} publishes no coord"
        coords.append(Coord.parse(c))
    if not is_contiguous_submesh(coords):
        return False, (
            "gang:non-contiguous coords "
            f"[{', '.join(str(c) for c in coords)}]"
        )
    return True, ""


def _cel_mismatch_detail(expr: str, why: str) -> str:
    return f"cel:mismatch expr={expr!r}" + (f" ({why})" if why else "")


class _FilterRecord:
    """Static filter verdicts for one request shape: per device key,
    ``None`` (survivor) or ``(stage, detail)`` for the rejecting stage.
    The shape is (device class, CEL selector expressions, programmatic
    selector signature) — everything about a request that is stable
    across solves. Reservations and health gating are deliberately NOT
    part of the record; they are applied per solve on top."""

    __slots__ = ("class_name", "cel_exprs", "prog_selectors", "by_device")

    def __init__(self, class_name, cel_exprs, prog_selectors):
        self.class_name = class_name
        self.cel_exprs = cel_exprs
        self.prog_selectors = prog_selectors
        self.by_device: dict[tuple, Optional[tuple[str, str]]] = {}


class InventoryIndex:
    """Persistent, generation-keyed view of the published inventory.

    Replaces the per-solve ``_inventory()`` pass: the flattened device
    dicts, shared-counter capacities, per-slice topology metadata, and
    the per-request-shape static filter verdicts all survive across
    solves. ``refresh()`` probes slice (name, resourceVersion)
    signatures via ``KubeClient.list_meta`` — O(#slices), no device
    copying — and rebuilds only the pools whose slices changed,
    re-filtering only those pools' devices into every cached
    :class:`_FilterRecord`. ``generation`` increments on every applied
    delta, so solve records can say which inventory they solved against.

    All access runs under the owning allocator's lock.
    """

    def __init__(self, allocator: "ReferenceAllocator"):
        self._alloc = allocator
        self.generation = 0
        self.devices: list[dict] = []
        self.by_key: dict[tuple[str, str], dict] = {}
        self.capacity: dict[tuple[str, str, str], int] = {}
        # Observability: list_meta probes vs pools actually rebuilt, and
        # CEL evaluated eagerly while applying deltas (bench + tests).
        self.probes = 0
        self.rebuilds = 0
        self.refresh_cel_evaluations = 0
        self._sig: dict[str, str] = {}
        self._slice_pool: dict[str, str] = {}         # slice name -> pool
        self._pool_slices: dict[str, list[dict]] = {}  # pool -> slice dicts
        self._pool_devices: dict[str, list[dict]] = {}
        self._filters: dict[tuple, _FilterRecord] = {}
        # sliceId -> (MeshShape, {coord tuple: chip device dict})
        self._slice_meta: dict[str, tuple[MeshShape, dict]] = {}

    # -- refresh ----------------------------------------------------------

    def refresh(self, force: bool = False) -> bool:
        """Bring the index up to date; returns True when anything
        changed. ``force`` rebuilds everything (the from-scratch
        baseline), dropping every cached verdict."""
        client, api = self._alloc.client, self._alloc.api
        self.probes += 1
        sig = dict(client.list_meta(api.slices))
        if not force and sig == self._sig:
            return False
        slices = [
            api.slice_from_wire(s)
            for s in client.list(api.slices)
            if s["spec"].get("driver") == self._alloc.driver_name
        ]
        by_pool: dict[str, list[dict]] = {}
        slice_pool: dict[str, str] = {}
        for s in slices:
            pool = s["spec"]["pool"]["name"]
            by_pool.setdefault(pool, []).append(s)
            name = (s.get("metadata") or {}).get("name", "")
            if name:
                slice_pool[name] = pool
        if force:
            affected = set(by_pool) | set(self._pool_devices)
            self._filters.clear()
        else:
            changed = {
                n for n in set(sig) | set(self._sig)
                if sig.get(n) != self._sig.get(n)
            }
            affected = {
                self._slice_pool[n] for n in changed if n in self._slice_pool
            } | {
                slice_pool[n] for n in changed if n in slice_pool
            }
        self._sig = sig
        self._slice_pool = slice_pool
        if not affected:
            # Foreign-driver churn only: signatures moved, our pools
            # did not.
            return False
        for pool in sorted(affected):
            self._rebuild_pool(pool, by_pool.get(pool, []))
        self._reflatten()
        self.generation += 1
        return True

    def _rebuild_pool(self, pool: str, pool_slices: list[dict]) -> None:
        self.rebuilds += 1
        old = self._pool_devices.pop(pool, [])
        old_keys = [d["_key"] for d in old]
        self._pool_slices.pop(pool, None)
        for key in [k for k in self.capacity if k[0] == pool]:
            del self.capacity[key]
        new_devs: list[dict] = []
        if pool_slices:
            # Highest pool generation wins — a half-rolled-out republish
            # must not double-count devices.
            gen = max(s["spec"]["pool"]["generation"] for s in pool_slices)
            live = sorted(
                (s for s in pool_slices
                 if s["spec"]["pool"]["generation"] == gen),
                key=lambda s: (s.get("metadata") or {}).get("name", ""),
            )
            self._pool_slices[pool] = live
            for s in live:
                for cs in s["spec"].get("sharedCounters", []):
                    for cname, cval in cs.get("counters", {}).items():
                        self.capacity[(pool, cs["name"], cname)] = int(
                            cval["value"]
                        )
            for s in live:
                for dev in s["spec"].get("devices", []):
                    new_devs.append(self._build_device(pool, s, dev))
            for d in new_devs:
                self._finalize_device(d)
            self._pool_devices[pool] = new_devs
        # Update every cached filter record for just this pool's delta:
        # stale verdicts out, fresh devices evaluated in. A record whose
        # selectors cannot be evaluated any more (CEL error, vanished
        # device class) is dropped and will rebuild — and raise its
        # typed failure — on the next solve that wants it.
        for fkey, rec in list(self._filters.items()):
            for k in old_keys:
                rec.by_device.pop(k, None)
            try:
                for d in new_devs:
                    rec.by_device[d["_key"]] = self.static_verdict(
                        d, rec.class_name, rec.prog_selectors,
                        rec.cel_exprs, on_cel_miss=self._count_refresh_cel,
                    )
            except AllocationError:
                del self._filters[fkey]

    def _count_refresh_cel(self) -> None:
        self.refresh_cel_evaluations += 1

    def _build_device(self, pool: str, s: dict, dev: dict) -> dict:
        basic = dev.get("basic", {})
        d = {
            "pool": pool,
            "node": s["spec"].get("nodeName", ""),
            "node_selector": s["spec"].get("nodeSelector"),
            "name": dev["name"],
            "attributes": basic.get("attributes", {}),
            "capacity": basic.get("capacity", {}),
            "consumes": basic.get("consumesCounters", []),
        }
        d["_key"] = (pool, dev["name"])
        attrs = d["attributes"]
        d["_type"] = _attr_value(attrs, "type")
        d["_healthy"] = _attr_value(attrs, "healthy")
        d["_slice_id"] = _attr_value(attrs, "sliceId")
        coord = _attr_value(attrs, "coord")
        d["_coord"] = Coord.parse(coord) if coord is not None else None
        d["_consumes"] = [
            (pool, cc["counterSet"], cname, int(cval["value"]))
            for cc in d["consumes"]
            for cname, cval in cc.get("counters", {}).items()
        ]
        d["_cel"] = {}
        return d

    def _finalize_device(self, d: dict) -> None:
        """Invalid-slice detection (undeclared counters), against the
        pool's freshly rebuilt capacity."""
        missing = [
            (cset, cname)
            for _, cset, cname, _ in d["_consumes"]
            if (d["pool"], cset, cname) not in self.capacity
        ]
        if missing:
            d["invalid"] = True
            warned = self._alloc._warned_invalid
            if d["_key"] not in warned:
                warned.add(d["_key"])
                logger.warning(
                    "device %r in pool %r consumes undeclared counters "
                    "%s; treating device as unallocatable",
                    d["name"], d["pool"], missing,
                )

    def _reflatten(self) -> None:
        ordered = []
        for pool in sorted(self._pool_devices):
            ordered.extend(self._pool_devices[pool])
        self.devices = ordered
        self.by_key = {d["_key"]: d for d in ordered}
        meta: dict[str, tuple[MeshShape, dict]] = {}
        coords: dict[str, dict] = {}
        for d in ordered:
            if d["_type"] == "chip" and d["_coord"] is not None \
                    and d["_slice_id"]:
                coords.setdefault(str(d["_slice_id"]), {})[
                    d["_coord"].as_tuple()
                ] = d
        for slice_id, cells in coords.items():
            shape = MeshShape(
                max(c[0] for c in cells) + 1,
                max(c[1] for c in cells) + 1,
                max(c[2] for c in cells) + 1,
            )
            meta[slice_id] = (shape, cells)
        self._slice_meta = meta

    # -- reading ----------------------------------------------------------

    def slice_meta(
        self, slice_id
    ) -> Optional[tuple[MeshShape, dict]]:
        """(mesh shape, {coord tuple -> chip device}) for a published
        ICI slice, or None when it publishes no grounded chip coords."""
        return self._slice_meta.get(str(slice_id))

    def slice_ids(self) -> list[str]:
        return sorted(self._slice_meta)

    # -- static filtering -------------------------------------------------

    def cel_on(self, d: dict, expr: str, on_miss=None) -> tuple[bool, str]:
        """CEL verdict for one (expression, device), cached on the device
        dict — rebuilt devices shed their cache with their dict. CelError
        maps to the allocator's typed cel-error contract."""
        cache = d["_cel"]
        hit = cache.get(expr)
        if hit is None:
            if on_miss is not None:
                on_miss()
            try:
                hit = cel_evaluate_detailed(
                    expr, self._alloc.driver_name, d["attributes"],
                    d.get("capacity"),
                )
            except CelError as e:
                raise AllocationError(
                    f"invalid CEL selector: {e}",
                    reason=REASON_CEL_ERROR,
                ) from e
            cache[expr] = hit
        return hit

    def class_verdict(
        self, class_name: str, d: dict, on_miss=None
    ) -> tuple[bool, str]:
        device_classes = self._alloc.device_classes
        if device_classes is not None:
            exprs = device_classes.get(class_name)
            if exprs is None:
                raise AllocationError(
                    f"unknown device class {class_name!r}",
                    reason=REASON_UNKNOWN_CLASS,
                )
            for e in exprs:
                ok, why = self.cel_on(d, e, on_miss)
                if not ok:
                    return False, _cel_mismatch_detail(e, why)
            return True, ""
        dtype = DEVICE_CLASS_TYPES.get(class_name)
        if dtype is None:
            raise AllocationError(
                f"unknown device class {class_name!r}",
                reason=REASON_UNKNOWN_CLASS,
            )
        if d["_type"] != dtype:
            return False, f"class:device type {d['_type']!r} != {dtype!r}"
        return True, ""

    def static_verdict(
        self, d: dict, class_name: str, prog_selectors, cel_exprs,
        on_cel_miss=None, stage_seconds: Optional[dict] = None,
    ) -> Optional[tuple[str, str]]:
        """The request-independent-of-state filter pipeline for one
        device: invalid-slice -> class CEL -> request selectors. Returns
        (stage, detail) for a rejection, None for a survivor. With
        ``stage_seconds`` the per-stage cost is accumulated (the
        cache-build pass keeps the PR-4 stage-latency contract)."""
        t = time.perf_counter()
        invalid = d.get("invalid", False)
        if stage_seconds is not None:
            stage_seconds[STAGE_INVALID_SLICE] += time.perf_counter() - t
        if invalid:
            return (
                STAGE_INVALID_SLICE,
                "slice:device consumes counters its slice never declared",
            )
        t = time.perf_counter()
        ok, why = self.class_verdict(class_name, d, on_cel_miss)
        if stage_seconds is not None:
            stage_seconds[STAGE_CLASS_CEL] += time.perf_counter() - t
        if not ok:
            return (STAGE_CLASS_CEL, why)
        t = time.perf_counter()
        why = ""
        for s in prog_selectors:
            if not s.matches(d["attributes"]):
                why = (
                    f"selector:{s.attribute} {s.op} {s.value!r} mismatch"
                )
                break
        if not why:
            for e in cel_exprs:
                ok, cel_why = self.cel_on(d, e, on_cel_miss)
                if not ok:
                    why = _cel_mismatch_detail(e, cel_why)
                    break
        if stage_seconds is not None:
            stage_seconds[STAGE_REQUEST_CEL] += time.perf_counter() - t
        if why:
            return (STAGE_REQUEST_CEL, why)
        return None

    def filter_record(
        self, class_name: str, prog_selectors, cel_exprs,
        on_cel_miss=None, stage_seconds: Optional[dict] = None,
    ) -> _FilterRecord:
        """The cached static verdicts for a request shape, building (and
        persisting) them on first sight. The build pass covers the WHOLE
        index, not just a node scope — the record must be reusable by
        any later solve."""
        prog_sig = tuple(
            (s.attribute, s.op, repr(s.value)) for s in prog_selectors
        )
        key = (class_name, tuple(cel_exprs), prog_sig)
        rec = self._filters.get(key)
        if rec is not None:
            # LRU touch (dicts iterate in insertion order).
            del self._filters[key]
            self._filters[key] = rec
            return rec
        rec = _FilterRecord(class_name, list(cel_exprs),
                            list(prog_selectors))
        for d in self.devices:
            rec.by_device[d["_key"]] = self.static_verdict(
                d, class_name, prog_selectors, cel_exprs,
                on_cel_miss=on_cel_miss, stage_seconds=stage_seconds,
            )
        while len(self._filters) >= MAX_FILTER_RECORDS:
            self._filters.pop(next(iter(self._filters)))
        self._filters[key] = rec
        return rec


class ReferenceAllocator:
    """Allocates claims against published ResourceSlices."""

    def __init__(
        self,
        client: KubeClient,
        driver_name: str = "tpu.google.com",
        device_classes: Optional[dict[str, list[str]]] = None,
        resource_api: Optional[ResourceApi] = None,
        registry: Optional[Registry] = None,
        recorder=None,
        max_backtrack_steps: Optional[int] = None,
        incremental: bool = True,
        placement_scoring: Optional[bool] = None,
    ):
        """``device_classes`` maps DeviceClass name → CEL selector
        expressions (from the class spec). When given, class membership is
        decided by evaluating those (the production mechanism); otherwise
        the built-in DEVICE_CLASS_TYPES name → type mapping applies.
        ``resource_api`` selects the resource.k8s.io dialect slices are
        read in (default: discover from the client). ``registry`` receives
        the attempt/backtrack counters and the ``tpu_dra_alloc_*``
        explainability families. ``recorder`` (an
        ``events.EventRecorder``) gets a deduped ``UnsatisfiableClaim``
        Warning on the claim for every failed solve.
        ``max_backtrack_steps`` bounds the search (default
        ``TPU_DRA_MAX_BACKTRACK_STEPS`` env or
        ``DEFAULT_MAX_BACKTRACK_STEPS``). ``incremental=False`` disables
        the persistent inventory index — every solve rebuilds and
        re-filters from scratch (the bench baseline; production wants the
        default). ``placement_scoring`` toggles the topology-aware
        best-fit scorer (default: ``TPU_DRA_PLACEMENT_SCORING`` env, on
        unless set to ``0``); off means first-fit in inventory order.
        """
        self.client = client
        self.driver_name = driver_name
        self.device_classes = device_classes
        self.api = resource_api or ResourceApi.discover(client)
        self.recorder = recorder
        if max_backtrack_steps is None:
            max_backtrack_steps = int(os.environ.get(
                "TPU_DRA_MAX_BACKTRACK_STEPS", DEFAULT_MAX_BACKTRACK_STEPS
            ))
        self.max_backtrack_steps = max_backtrack_steps
        self.incremental = incremental
        if placement_scoring is None:
            placement_scoring = os.environ.get(
                "TPU_DRA_PLACEMENT_SCORING", "1"
            ) != "0"
        self.placement_scoring = placement_scoring
        # A DefragPlanner (kube/defrag.py) attaches itself here; gang/
        # shortfall unsats then get a read-only migration plan computed.
        self.defrag = None
        # Re-entrant: snapshot() holds the lock across a batch while the
        # per-claim allocate() calls re-enter it.
        self._lock = threading.RLock()
        self._snapshot_depth = 0
        reg = registry if registry is not None else Registry()
        self._m_attempts = Counter(
            "tpu_dra_allocation_attempts_total",
            "Claim allocation attempts by result",
            reg,
        )
        self._m_backtracks = Counter(
            "tpu_dra_allocation_backtracks_total",
            "Device picks undone by the allocation solver",
            reg,
        )
        self._m_solve_seconds = Histogram(
            "tpu_dra_alloc_solve_seconds",
            "End-to-end allocation solve latency",
            reg,
        )
        self._m_funnel_rejections = Counter(
            "tpu_dra_alloc_funnel_rejections_total",
            "Candidate devices rejected by the allocation funnel, by stage",
            reg,
        )
        self._m_unsat = Counter(
            "tpu_dra_alloc_unsat_total",
            "Failed allocation attempts by terminal reason",
            reg,
        )
        # Steps undone during the current solve; folded into the counter
        # once per allocate() (all access is under self._lock).
        self._backtrack_steps = 0
        # Solve decisions (Explanation dicts) for /debug/allocations.
        self._decisions: collections.deque = collections.deque(
            maxlen=int(os.environ.get(
                "TPU_DRA_ALLOC_DECISION_BUFFER", DEFAULT_DECISION_BUFFER
            ))
        )
        # (pool, device) -> claim uid holding it. reservation_version
        # bumps on every mutation — cheap change detection for the
        # defrag planner's retry dedup (hashing 10k reservations per
        # unsat would cost more than the planning it avoids).
        self._reservations: dict[tuple[str, str], str] = {}
        self.reservation_version = 0
        # (pool, counter set, counter) -> amount consumed by reservations.
        self._consumed: dict[tuple[str, str, str], int] = {}
        # claim uid -> [(pool, counter set, counter, amount)] for release.
        self._claim_consumption: dict[str, list[tuple[str, str, str, int]]] = {}
        # (pool, device) pairs already warned about misconfigured counters,
        # so a static slice defect is diagnosed once, not per allocate().
        self._warned_invalid: set[tuple[str, str]] = set()
        # The persistent inventory index (see module docstring): flattened
        # devices, capacities, topology metadata, and static filter
        # verdicts, invalidated by ResourceSlice deltas only.
        self.index = InventoryIndex(self)

    # -- inventory ---------------------------------------------------------

    def _inventory(self) -> tuple[list[dict], dict[tuple[str, str, str], int]]:
        """The index-backed inventory: flattened (pool, node, device)
        dicts + shared-counter capacities keyed (pool, counter set,
        counter). Refreshes the index first unless a snapshot is pinned
        (``snapshot()``); with ``incremental=False`` every call rebuilds
        from scratch."""
        if self._snapshot_depth == 0:
            self.index.refresh(force=not self.incremental)
        return self.index.devices, self.index.capacity

    @contextlib.contextmanager
    def snapshot(self):
        """Pin ONE refreshed inventory snapshot across several solves.

        The batch path and the elastic descending re-solve both probe
        many candidate solutions against the same moment-in-time
        inventory; re-probing the apiserver per attempt buys nothing but
        latency (and lets the inventory shift mid-descent). Re-entrant
        and lock-holding: a snapshot serializes against concurrent
        solves by construction. Reservations still move inside a
        snapshot — only the published inventory is pinned.
        """
        with self._lock:
            self.index.refresh(force=not self.incremental)
            self._snapshot_depth += 1
            try:
                yield self.index
            finally:
                self._snapshot_depth -= 1

    # -- decision record ---------------------------------------------------

    def recent_decisions(self) -> list[dict]:
        """Newest-last snapshot of the solve-decision ring buffer."""
        with self._lock:
            return list(self._decisions)

    def export_allocations_jsonl(self) -> str:
        """The ``/debug/allocations`` payload: one JSON object per solve,
        oldest first (the newest decision is the last line)."""
        return "".join(
            json.dumps(d, sort_keys=True) + "\n"
            for d in self.recent_decisions()
        )

    def _finish(self, expl: Explanation, t0: float, outcome: str,
                reason: str = "", detail: str = "") -> None:
        """Finalize the solve record: stamp outcome/latency, feed the
        funnel-rejection counters, and push onto the ring buffer."""
        expl.outcome = outcome
        expl.reason = reason
        if detail:
            expl.detail = detail
        expl.duration_seconds = time.monotonic() - t0
        expl.timestamp = time.time()
        self._m_solve_seconds.observe(expl.duration_seconds)
        for f in expl.funnels:
            for stage, n in f.rejected.items():
                self._m_funnel_rejections.inc(n, stage=stage)
        if outcome == "ok":
            expl.compact()
        self._decisions.append(expl.to_dict())

    def _emit_unsat_event(self, expl: Explanation) -> None:
        """Deduped UnsatisfiableClaim Warning on the claim — the kubectl-
        describe-visible form of the explanation. Best-effort by the
        recorder's own contract; a nameless claim (pure sim object) is
        skipped."""
        if self.recorder is None or not expl.claim_name:
            return
        from .events import ObjectRef

        hint = RUNBOOK_HINTS.get(expl.reason, "")
        message = f"cannot allocate: {expl.detail or expl.reason}"
        if hint:
            message += f" — {hint}"
        self.recorder.warning(
            ObjectRef.claim(
                expl.claim_name,
                expl.claim_namespace,
                expl.claim_uid,
                api_version=self.api.api_version,
            ),
            "UnsatisfiableClaim",
            message,
        )

    # -- allocation --------------------------------------------------------

    def allocate(
        self,
        claim: dict,
        node_name: Optional[str] = None,
        selectors: Optional[dict[str, list[Selector]]] = None,
        require_healthy: bool = False,
    ) -> dict:
        """Fill claim.status.allocation; returns the claim (mutated).

        ``selectors`` maps request name → extra Selector predicates (the
        CEL-lite substitute). ``node_name`` restricts node-local pools.
        ``require_healthy`` rejects devices whose published ``healthy``
        attribute is false (the elastic gang re-solve: a shrink must
        never land back on the chip that just sickened) — rejections are
        funnel-visible at the ``unhealthy`` stage. On failure raises
        :class:`AllocationError` with ``reason`` and ``explanation``
        populated; either way the decision is recorded for
        ``/debug/allocations``.
        """
        spec = claim.get("spec", {}).get("devices", {})
        requests = spec.get("requests", [])
        constraints = spec.get("constraints", [])
        selectors = selectors or {}
        md = claim.get("metadata", {})
        expl = Explanation(
            claim_uid=md.get("uid", ""),
            claim_name=md.get("name", ""),
            claim_namespace=md.get("namespace", ""),
        )
        # adminAccess requests "ignore all ordinary claims with respect to
        # access modes and any resource allocations" (types.go:448-456):
        # they may land on reserved devices and neither reserve nor consume
        # counters themselves.
        admin_reqs = {r["name"] for r in requests if r.get("adminAccess")}
        with self._lock, child_span(
            "allocator/allocate",
            claim_uid=md.get("uid", ""),
        ) as sp:
            t0 = time.monotonic()
            devices, capacity = self._inventory()
            inventory = [
                d
                for d in devices
                if (not node_name or not d["node"] or d["node"] == node_name)
            ]
            self._backtrack_steps = 0
            try:
                results, picked_devs = self._solve(
                    requests, constraints, selectors, inventory, capacity,
                    expl, require_healthy=require_healthy,
                )
            except Exception as e:
                if self._backtrack_steps:
                    self._m_backtracks.inc(self._backtrack_steps)
                expl.backtracks = self._backtrack_steps
                sp.set_tag("backtracks", self._backtrack_steps)
                self._m_attempts.inc(result="error")
                sp.set_error(str(e))
                if isinstance(e, AllocationError):
                    self._finish(expl, t0, "unsat", e.reason, str(e))
                    if e.explanation is None:
                        e.explanation = expl
                    self._emit_unsat_event(expl)
                else:
                    self._finish(
                        expl, t0, "error", REASON_INTERNAL, str(e)
                    )
                self._m_unsat.inc(reason=expl.reason)
                sp.set_tag("reason", expl.reason)
                # Fragmentation diagnosis: a gang/shortfall unsat on a
                # fleet whose free capacity would fit the claim gets a
                # read-only migration plan (kube/defrag.py). Planning is
                # best-effort; it must never turn an unsat into a crash.
                if self.defrag is not None and expl.reason in (
                    REASON_SHORTFALL, STAGE_GANG,
                ):
                    try:
                        self.defrag.note_unsat(
                            claim, expl, selectors=selectors,
                            require_healthy=require_healthy,
                        )
                    except Exception:
                        logger.exception("defrag planning failed")
                raise
            if self._backtrack_steps:
                self._m_backtracks.inc(self._backtrack_steps)
            expl.backtracks = self._backtrack_steps
            sp.set_tag("backtracks", self._backtrack_steps)
            self._m_attempts.inc(result="ok")
            sp.set_tag("devices", len(picked_devs))
            uid = claim["metadata"]["uid"]
            self.reservation_version += 1
            for r, d in zip(results, picked_devs):
                if r["request"] in admin_reqs:
                    continue
                self._reservations[(r["pool"], r["device"])] = uid
                for pool, cset, cname, amount in _consumption_entries(d):
                    self._consumed[(pool, cset, cname)] = (
                        self._consumed.get((pool, cset, cname), 0) + amount
                    )
                    self._claim_consumption.setdefault(uid, []).append(
                        (pool, cset, cname, amount)
                    )
            self._finish(expl, t0, "ok")
        claim.setdefault("status", {})["allocation"] = {
            "devices": {
                "results": results,
                "config": self._carry_config(spec),
            }
        }
        return claim

    def allocate_batch(
        self,
        claims: list[dict],
        node_name: Optional[str] = None,
        selectors_by_claim: Optional[dict[str, dict[str, list["Selector"]]]] = None,
        require_healthy: bool = False,
    ) -> list[tuple[dict, Optional[AllocationError]]]:
        """Solve a queue of pending claims as one batch.

        All claims share a single index snapshot (one inventory probe,
        one filter-cache warmup) and solve in descending constrainedness
        order — largest device ask first, constraint count as the
        tie-break — because a big gang placed after the singles have
        shredded the mesh is a self-inflicted ``gang`` unsat. Every
        claim still runs through :meth:`allocate`, so per-claim funnels,
        metrics, and ``/debug/allocations`` records are emitted exactly
        as in the one-at-a-time path.

        Returns ``[(claim, error-or-None)]`` in the INPUT order;
        successfully allocated claims carry ``status.allocation``.
        ``selectors_by_claim`` maps claim uid -> the per-request Selector
        lists ``allocate`` takes.
        """
        selectors_by_claim = selectors_by_claim or {}

        def constrainedness(claim: dict) -> tuple[int, int]:
            spec = claim.get("spec", {}).get("devices", {})
            wanted = 0
            for r in spec.get("requests", []):
                if r.get("adminAccess"):
                    continue
                if r.get("allocationMode", "ExactCount") == "ExactCount":
                    wanted += int(r.get("count", 1))
            return (wanted, len(spec.get("constraints", [])))

        order = sorted(
            range(len(claims)),
            key=lambda i: constrainedness(claims[i]),
            reverse=True,
        )
        outcomes: list[Optional[AllocationError]] = [None] * len(claims)
        with self.snapshot():
            for i in order:
                claim = claims[i]
                uid = claim.get("metadata", {}).get("uid", "")
                try:
                    self.allocate(
                        claim,
                        node_name=node_name,
                        selectors=selectors_by_claim.get(uid),
                        require_healthy=require_healthy,
                    )
                except AllocationError as e:
                    outcomes[i] = e
        return [(claims[i], outcomes[i]) for i in range(len(claims))]

    def _carry_config(self, spec: dict) -> list[dict]:
        """Claim-spec configs become FromClaim allocation configs (the
        scheduler does this verbatim copy)."""
        out = []
        for cfg in spec.get("config", []):
            entry = dict(cfg)
            entry["source"] = "FromClaim"
            out.append(entry)
        return out

    def _note_backtrack(self, n: int) -> None:
        self._backtrack_steps += n
        if self._backtrack_steps > self.max_backtrack_steps:
            raise AllocationError(
                f"backtrack budget exhausted after "
                f"{self._backtrack_steps} steps (max "
                f"{self.max_backtrack_steps}; TPU_DRA_MAX_BACKTRACK_STEPS "
                "overrides)",
                reason=REASON_BACKTRACK_BUDGET,
            )

    def _solve(self, requests, constraints, selectors, inventory, capacity,
               expl: Explanation, require_healthy: bool = False):
        """Greedy backtracking over requests with matchAttribute checks,
        shared-counter budgets, and ICI contiguity for multi-chip gangs.

        Returns (allocation results, picked device dicts). Every
        rejection is recorded into ``expl``'s per-request funnels, and
        both candidate lists and CEL evaluations are memoized per solve —
        the search re-enters ``candidates()`` on every probe, and before
        the memo each re-entry re-ran every CEL expression against every
        device (quadratic-and-worse under backtracking).
        """
        match_groups = [
            (set(c.get("requests", [])), c["matchAttribute"].split("/")[-1])
            for c in constraints
            if "matchAttribute" in c
        ]
        # Counters consumed by the in-progress solution, on top of the
        # amounts already reserved by other claims.
        tentative: dict[tuple[str, str, str], int] = {}
        # Per-solve candidate memo: (request name, include_reserved) →
        # candidate list — the search re-enters candidates() on every
        # probe. The static filtering BEHIND it (class/request CEL,
        # invalid-slice) persists across solves in the InventoryIndex;
        # only health and reservations are re-checked here.
        cand_memo: dict[tuple, list] = {}
        index = self.index

        def on_cel_miss():
            expl.cel_evaluations += 1

        def candidates(req, include_reserved=False):
            memo_key = (req["name"], bool(include_reserved))
            memoized = cand_memo.get(memo_key)
            if memoized is not None:
                return memoized
            cel_selectors = [
                s["cel"]["expression"]
                for s in req.get("selectors", [])
                if "cel" in s
            ]
            admin = req.get("adminAccess", False)
            # Static verdicts, cached across solves; the build pass (a
            # cold request shape, or a from-scratch solve) records exact
            # per-stage latencies through static_verdict. CelError and
            # unknown-class surface from here as typed AllocationErrors.
            stage_t = dict.fromkeys(_CANDIDATE_STAGES, 0.0)
            rec = index.filter_record(
                req.get("deviceClassName", ""),
                selectors.get(req["name"], []),
                cel_selectors,
                on_cel_miss=on_cel_miss,
                stage_seconds=stage_t,
            )
            # Only the primary pass populates the funnel: the
            # include_reserved variant exists solely for allocationMode=
            # All's completeness check.
            record = not include_reserved
            if record:
                expl.funnel(req["name"]).entering = len(inventory)
            t0 = time.perf_counter()
            out = []
            reservations = self._reservations
            for d in inventory:
                dk = d["_key"]
                verdict = rec.by_device.get(dk)
                if verdict is not None:
                    # Misconfigured slice / class CEL / request CEL —
                    # replayed from the cached verdict so the funnel
                    # reads identically to a from-scratch solve.
                    if record:
                        expl.reject(req["name"], verdict[0], dk,
                                    verdict[1])
                    continue
                # Health gate (opt-in): the elastic re-solve must steer
                # around chips the node marked degraded — a gone chip is
                # already absent from the republished slice, but a wedged
                # one stays published with healthy=false and would
                # otherwise be picked right back.
                if require_healthy and d["_healthy"] is False:
                    if record:
                        expl.reject(
                            req["name"], STAGE_UNHEALTHY, dk,
                            "unhealthy:published healthy=false",
                        )
                    continue
                # Ordinary requests never see reserved devices; admin
                # requests observe them (monitoring over live workloads).
                # Checked LAST so the funnel reads "the right devices
                # exist but are held", not "nothing matched".
                if not (admin or include_reserved) and dk in reservations:
                    if record:
                        expl.reject(
                            req["name"], STAGE_RESERVED, dk,
                            "reserved:held by claim "
                            f"{reservations[dk]}",
                        )
                    continue
                out.append(d)
            # Replay + per-solve gates run as ONE fused pass (that is the
            # hot-path point); its cost is amortized evenly across the
            # candidate stages, on top of the exact build-pass times.
            share = (time.perf_counter() - t0) / len(_CANDIDATE_STAGES)
            if record:
                expl.funnel(req["name"]).survivors = len(out)
                for stage in _CANDIDATE_STAGES:
                    expl.add_stage_seconds(stage, stage_t[stage] + share)
            cand_memo[memo_key] = out
            return out

        def counters_fit(dev) -> tuple[bool, str]:
            for pool, cset, cname, amount in _consumption_entries(dev):
                key = (pool, cset, cname)
                cap = capacity.get(key)
                if cap is None:
                    # unreachable: _inventory flags these as invalid
                    return False, f"counters:{cset}/{cname} undeclared"
                used = self._consumed.get(key, 0) + tentative.get(key, 0)
                if used + amount > cap:
                    return False, (
                        f"counters:{cset}/{cname} {used}/{cap} used, "
                        f"need {amount}"
                    )
            return True, ""

        def consume(dev) -> None:
            for pool, cset, cname, amount in _consumption_entries(dev):
                key = (pool, cset, cname)
                tentative[key] = tentative.get(key, 0) + amount

        def unconsume(dev) -> None:
            for pool, cset, cname, amount in _consumption_entries(dev):
                key = (pool, cset, cname)
                tentative[key] -= amount

        picked: list[tuple[str, dict]] = []  # (request name, device)
        admin_request_names = {
            r["name"] for r in requests if r.get("adminAccess")
        }

        def picked_blocker(req_admin: bool, d) -> Optional[str]:
            """Admin picks are invisible to ordinary placement and vice
            versa (types.go:448-456) — exclusion applies only between
            requests of the same access kind. Returns the blocking
            request's name (for the funnel) or None."""
            for other_name, p in picked:
                if p is d and (
                    (other_name in admin_request_names) == req_admin
                ):
                    return other_name
            return None

        def consistent(req_name, dev) -> tuple[bool, str]:
            for group, attr in match_groups:
                if req_name not in group:
                    continue
                want = _attr_value(dev["attributes"], attr)
                for other_name, other in picked:
                    if other_name in group:
                        have = _attr_value(other["attributes"], attr)
                        if have != want:
                            return False, (
                                f"constraint:{attr} {want!r} conflicts "
                                f"with request {other_name!r} ({have!r})"
                            )
            return True, ""

        def backtrack(ri: int) -> bool:
            if ri == len(requests):
                return True
            req = requests[ri]
            admin = req.get("adminAccess", False)
            mode = req.get("allocationMode", "ExactCount")
            cands = []
            for d in candidates(req):
                blocker = picked_blocker(admin, d)
                if blocker is not None:
                    # Held by an earlier request of this same claim: a
                    # funnel-visible rejection, or multi-request
                    # contention would misread as whatever filter stage
                    # happened to reject unrelated devices.
                    expl.reject(
                        req["name"], STAGE_RESERVED,
                        (d["pool"], d["name"]),
                        f"reserved:held by request {blocker!r} of "
                        "this claim",
                    )
                    continue
                cands.append(d)
            if mode == "All":
                # Every matching device in scope (types.go:427-429): fails
                # when some are already allocated — unless adminAccess,
                # whose candidates() already includes reserved devices.
                count = len(cands)
                if count == 0:
                    expl.note_request_failure(ri, req["name"])
                    return False
                if not admin:
                    with_reserved = candidates(
                        req, include_reserved=True
                    )
                    if count != len(with_reserved):
                        # Some matching devices already allocated.
                        for d in with_reserved:
                            dk = (d["pool"], d["name"])
                            holder = self._reservations.get(dk)
                            if holder is not None:
                                expl.reject(
                                    req["name"], STAGE_RESERVED, dk,
                                    "reserved:allocationMode=All needs "
                                    "every matching device; held by "
                                    f"claim {holder}",
                                )
                        expl.note_request_failure(ri, req["name"])
                        return False
            elif mode == "ExactCount":
                count = req.get("count", 1)
            else:
                # "Clients must refuse to handle requests with unknown
                # modes" (types.go:435-436).
                raise AllocationError(
                    f"unknown allocationMode {mode!r} in request "
                    f"{req.get('name')!r}",
                    reason=REASON_UNKNOWN_MODE,
                )
            expl.funnel(req["name"]).wanted = count
            # Topology scoring: order candidates so the DFS lands the
            # gang best-fit into the smallest free contiguous sub-mesh
            # (corner-biased) instead of first-fit in inventory order.
            # Pure reordering — the search stays complete, so anything
            # first-fit could satisfy, the scored order can too. The one
            # exception is deliberate: for a pure chip gang (every
            # candidate a coordinate-grounded chip, count >= 2) the box
            # enumeration is COMPLETE — a contiguous sub-mesh IS a dense
            # axis-aligned box — so "no box anywhere" proves the gang
            # unsat and short-circuits what would otherwise be an
            # exponential doomed backtracking search.
            if (
                self.placement_scoring and not admin
                and mode == "ExactCount" and len(cands) > count > 0
            ):
                t = time.perf_counter()
                cands, placement, provably_unsat = self._score_placement(
                    req["name"], cands, count, match_groups
                )
                expl.add_stage_seconds(
                    STAGE_GANG, time.perf_counter() - t
                )
                if placement is not None:
                    expl.placements[req["name"]] = placement
                if provably_unsat and count >= 2:
                    last = cands[-1]
                    expl.reject(
                        req["name"], STAGE_GANG,
                        (last["pool"], last["name"]),
                        f"gang:no free contiguous {count}-chip sub-mesh "
                        "on any slice (scored placement exhausted every "
                        "box)",
                    )
                    expl.note_request_failure(ri, req["name"])
                    return False

            def pick_n(chosen: list) -> bool:
                if len(chosen) == count:
                    # Contiguity is a WORKLOAD constraint (ICI collectives);
                    # admin picks observe, so fragmented sets are fine.
                    if not admin:
                        t = time.perf_counter()
                        ok, why = _gang_contiguous(chosen)
                        expl.add_stage_seconds(
                            STAGE_GANG, time.perf_counter() - t
                        )
                        if not ok:
                            # Keyed by the device that completed the
                            # failing combination — NOT the combination
                            # itself, which backtracking enumerates in
                            # exponential numbers and would turn the
                            # funnel into a measure of search effort.
                            last = chosen[-1]
                            expl.reject(
                                req["name"], STAGE_GANG,
                                (last["pool"], last["name"]), why,
                            )
                            return False
                    for d in chosen:
                        picked.append((req["name"], d))
                    if backtrack(ri + 1):
                        return True
                    for _ in chosen:
                        picked.pop()
                    self._note_backtrack(len(chosen))
                    return False
                start = cands.index(chosen[-1]) + 1 if chosen else 0
                for d in cands[start:]:
                    if d in chosen:
                        continue
                    blocker = picked_blocker(admin, d)
                    if blocker is not None:
                        expl.reject(
                            req["name"], STAGE_RESERVED,
                            (d["pool"], d["name"]),
                            f"reserved:held by request {blocker!r} of "
                            "this claim",
                        )
                        continue
                    t = time.perf_counter()
                    ok, why = consistent(req["name"], d)
                    expl.add_stage_seconds(
                        STAGE_CONSTRAINT, time.perf_counter() - t
                    )
                    if not ok:
                        expl.reject(
                            req["name"], STAGE_CONSTRAINT,
                            (d["pool"], d["name"]), why,
                        )
                        continue
                    # Admin picks consume nothing, so counters are moot.
                    if not admin:
                        t = time.perf_counter()
                        ok, why = counters_fit(d)
                        expl.add_stage_seconds(
                            STAGE_COUNTERS, time.perf_counter() - t
                        )
                        if not ok:
                            expl.reject(
                                req["name"], STAGE_COUNTERS,
                                (d["pool"], d["name"]), why,
                            )
                            continue
                    chosen.append(d)
                    if not admin:
                        consume(d)
                    # Intra-request matchAttribute consistency.
                    if not self._group_ok(req["name"], chosen, match_groups):
                        # Keyed by the newly-added device (see the gang
                        # rejection above): counts stay bounded by
                        # inventory, not by combinations explored.
                        expl.reject(
                            req["name"], STAGE_CONSTRAINT,
                            (d["pool"], d["name"]),
                            "constraint:matchAttribute conflict within "
                            "request",
                        )
                        group_ok = False
                    else:
                        group_ok = True
                    if group_ok and pick_n(chosen):
                        return True
                    if not admin:
                        unconsume(d)
                    chosen.pop()
                    self._note_backtrack(1)
                return False

            ok = pick_n([])
            if not ok:
                expl.note_request_failure(ri, req["name"])
            return ok

        if not backtrack(0):
            reason, detail = expl.terminal()
            raise AllocationError(
                f"no satisfying allocation found: {detail}",
                reason=reason,
            )
        if expl.placements:
            # Did the search land on the scorer's box, or did later
            # stages (counters, constraints, other requests) push it
            # elsewhere? /debug/allocations should say which.
            picked_by_req: dict[str, set] = {}
            for name, dev in picked:
                picked_by_req.setdefault(name, set()).add(dev["name"])
            for rname, pl in expl.placements.items():
                pl["applied"] = (
                    set(pl.get("devices", ())) == picked_by_req.get(rname)
                )
        return [
            {
                "request": name,
                "driver": self.driver_name,
                "pool": dev["pool"],
                "device": dev["name"],
            }
            for name, dev in picked
        ], [dev for _, dev in picked]

    @staticmethod
    def _group_ok(req_name, chosen, match_groups) -> bool:
        for group, attr in match_groups:
            if req_name not in group:
                continue
            vals = {_attr_value(d["attributes"], attr) for d in chosen}
            if len(vals) > 1:
                return False
        return True

    # -- topology scoring --------------------------------------------------

    def _score_placement(
        self, req_name: str, cands: list[dict], count: int, match_groups,
    ) -> tuple[list[dict], Optional[dict], bool]:
        """Best-fit gang placement over the free ICI topology.

        Enumerates every dense ``count``-cell box over each slice's free
        candidate cells and scores it ``(free-component size, corner
        distance)``, both minimized: the smallest free contiguous
        sub-mesh that still fits the gang is consumed first (ParvaGPU's
        best-fit spatial packing), and within it the box hugs a mesh
        corner, so the remaining free cells stay one large unbroken
        region instead of a ring. Boxes that would break a
        ``matchAttribute`` group containing this request are skipped
        up front rather than discovered by backtracking.

        Returns ``(candidates, placement, provably_unsat)``: the
        candidate list reordered (box cells first) plus the placement
        record for ``/debug/allocations``. ``(cands, None, False)``
        when the request is not scorable (non-chip devices, missing or
        duplicated coords) — the solver then behaves exactly as before.
        ``provably_unsat`` is True only when the enumeration covered the
        ENTIRE candidate space (every candidate a scorable chip) and no
        dense box exists: since a contiguous sub-mesh is exactly a
        dense axis-aligned box on one slice, the caller may fail the
        gang immediately instead of backtracking through doomed
        combinations.
        """
        chips = [
            d for d in cands
            if d.get("_type") == "chip" and d.get("_coord") is not None
            and d.get("_slice_id")
        ]
        if len(chips) != len(cands) or len(chips) < count:
            return cands, None, False
        per_slice: dict[str, list[dict]] = {}
        for d in chips:
            per_slice.setdefault(str(d["_slice_id"]), []).append(d)
        group_attrs = [
            attr for group, attr in match_groups if req_name in group
        ]
        best = None  # (comp size, corner, slice_id, origin, dims, cells)
        # Best-fit at slice granularity first: slices ordered by free
        # candidate count ascending, and the scan STOPS at the first
        # slice that yields any box — the tightest slice that still fits
        # the gang absorbs it, keeping emptier slices whole for larger
        # gangs. (Scanning every slice per solve was the allocator's
        # hottest path at 10k devices; provable unsat still requires —
        # and gets — the full scan, because no slice yields a box.)
        ordered_slices = sorted(
            per_slice.items(), key=lambda kv: (len(kv[1]), kv[0])
        )
        for slice_id, devs in ordered_slices:
            if len(devs) < count:
                continue
            meta = self.index.slice_meta(slice_id)
            if meta is None:
                continue
            shape, _ = meta
            by_coord = {d["_coord"].as_tuple(): d for d in devs}
            if len(by_coord) != len(devs):
                return cands, None, False  # duplicated coords: not scorable
            free = set(by_coord)
            comp_size: dict[tuple, int] = {}
            for comp in free_components(free):
                if len(comp) < count:
                    continue  # a count-cell box cannot fit there anyway
                for cell in comp:
                    comp_size[cell] = len(comp)
            for dx, dy, dz in box_shapes(count, shape):
                for ox in range(shape.x - dx + 1):
                    for oy in range(shape.y - dy + 1):
                        for oz in range(shape.z - dz + 1):
                            origin = (ox, oy, oz)
                            comp = comp_size.get(origin)
                            if comp is None:
                                continue
                            cells = [
                                (ox + ix, oy + iy, oz + iz)
                                for ix in range(dx)
                                for iy in range(dy)
                                for iz in range(dz)
                            ]
                            if not free.issuperset(cells):
                                continue
                            if group_attrs and not self._box_uniform(
                                by_coord, cells, group_attrs
                            ):
                                continue
                            corner = (
                                min(ox, shape.x - ox - dx)
                                + min(oy, shape.y - oy - dy)
                                + min(oz, shape.z - oz - dz)
                            )
                            key = (comp, corner, slice_id, origin,
                                   (dx, dy, dz), cells)
                            if best is None or key[:4] < best[:4]:
                                best = key
                                if comp == count and corner == 0:
                                    break  # perfect fit; stop searching
                        else:
                            continue
                        break
                    else:
                        continue
                    break
                if best is not None and best[0] == count and best[1] == 0:
                    break
            if best is not None:
                break  # tightest fitting slice found; emptier ones stay whole
        if best is None:
            # Provable only without matchAttribute involvement: a box
            # skipped for group non-uniformity would fail the solver at
            # the `constraint` stage, and that terminal reason (not
            # `gang`) is the explainability contract for it.
            return cands, None, not group_attrs
        comp, corner, slice_id, origin, dims, cells = best
        chosen = {
            c: d for c, d in (
                (d["_coord"].as_tuple(), d) for d in per_slice[slice_id]
            ) if c in set(cells)
        }
        ordered = [chosen[c] for c in cells]
        ordered_keys = {d["_key"] for d in ordered}
        rest = [d for d in cands if d["_key"] not in ordered_keys]
        placement = {
            "strategy": "best-fit",
            "sliceId": slice_id,
            "origin": f"{origin[0]},{origin[1]},{origin[2]}",
            "box": f"{dims[0]}x{dims[1]}x{dims[2]}",
            "score": {"freeComponent": comp, "cornerDistance": corner},
            "devices": [d["name"] for d in ordered],
            "applied": False,
        }
        return ordered + rest, placement, False

    @staticmethod
    def _box_uniform(by_coord, cells, group_attrs) -> bool:
        """Every matchAttribute group value uniform across the box."""
        for attr in group_attrs:
            vals = {
                _attr_value(by_coord[c]["attributes"], attr) for c in cells
            }
            if len(vals) > 1:
                return False
        return True

    # -- release -----------------------------------------------------------

    def deallocate(self, claim_uid: str) -> None:
        with self._lock:
            self.reservation_version += 1
            self._reservations = {
                k: v for k, v in self._reservations.items() if v != claim_uid
            }
            for pool, cset, cname, amount in self._claim_consumption.pop(
                claim_uid, []
            ):
                self._consumed[(pool, cset, cname)] -= amount

    def restore_reservations(
        self, claim_uid: str, results: list[dict]
    ) -> None:
        """Re-register reservations (and counter consumption) for a
        claim whose devices are ALREADY prepared on a node.

        The elastic coordinator's failure seam: a gang re-solve starts
        with ``deallocate``, and when every candidate size goes unsat
        the claim keeps running on its existing devices — which must not
        be left looking free, or the next solve double-books chips that
        are exclusively held. ``results`` is the claim's current
        allocation (wire form); devices already reserved by this claim
        are skipped, so the call is idempotent.
        """
        with self._lock:
            self.reservation_version += 1
            devices, _ = self._inventory()
            by_key = {(d["pool"], d["name"]): d for d in devices}
            for r in results:
                key = (r["pool"], r["device"])
                if self._reservations.get(key) == claim_uid:
                    continue
                holder = self._reservations.get(key)
                if holder is not None:
                    logger.warning(
                        "restore_reservations: device %s/%s already held "
                        "by %s; leaving it", key[0], key[1], holder,
                    )
                    continue
                self._reservations[key] = claim_uid
                dev = by_key.get(key)
                if dev is None:
                    continue
                for pool, cset, cname, amount in _consumption_entries(dev):
                    self._consumed[(pool, cset, cname)] = (
                        self._consumed.get((pool, cset, cname), 0) + amount
                    )
                    self._claim_consumption.setdefault(
                        claim_uid, []
                    ).append((pool, cset, cname, amount))
