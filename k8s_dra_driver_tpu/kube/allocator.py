"""Reference DRA allocator: scheduler-sim for tests and dev clusters.

The real allocation happens in the Kubernetes scheduler's structured-
parameters allocator (SURVEY.md §3.5 — the layer deliberately NOT in the
reference repo). This module re-implements the subset this driver's
published attributes exercise, so the full claim lifecycle can be simulated
hermetically: DeviceClass → device-type mapping, request counts, attribute
selectors, and cross-request ``matchAttribute`` constraints (the gang /
same-parent mechanism of tpu-test4/6).

Selectors come in two forms: programmatic (attribute, op, value) triples,
and real CEL expressions from DeviceClass specs / request ``selectors``
(evaluated by the cel module's subset engine, so the demo specs run through
the sim verbatim). The production path still uses the real scheduler.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
from typing import Any, Optional

from ..utils.metrics import Counter, Registry
from ..utils.tracing import child_span
from .cel import CelError, evaluate as cel_evaluate
from .client import KubeClient
from .resourceapi import ResourceApi

logger = logging.getLogger(__name__)

# DeviceClass name → the `type` attribute the node plugin publishes.
DEVICE_CLASS_TYPES = {
    "tpu.google.com": "chip",
    "tensorcore.tpu.google.com": "tensorcore",
    "ici.tpu.google.com": "ici",
}


class AllocationError(RuntimeError):
    pass


@dataclasses.dataclass
class Selector:
    """Attribute predicate: op ∈ {eq, ne, lt, le, gt, ge, in}."""

    attribute: str
    op: str
    value: Any

    def matches(self, attrs: dict) -> bool:
        raw = attrs.get(self.attribute)
        if raw is None:
            return False
        val = next(iter(raw.values())) if isinstance(raw, dict) else raw
        if self.op == "eq":
            return val == self.value
        if self.op == "ne":
            return val != self.value
        if self.op == "lt":
            return val < self.value
        if self.op == "le":
            return val <= self.value
        if self.op == "gt":
            return val > self.value
        if self.op == "ge":
            return val >= self.value
        if self.op == "in":
            return val in self.value
        raise ValueError(f"unknown op {self.op!r}")


def _attr_value(attrs: dict, name: str):
    raw = attrs.get(name)
    if raw is None:
        return None
    return next(iter(raw.values())) if isinstance(raw, dict) else raw


def _consumption_entries(dev: dict):
    """(pool, counter set, counter, amount) for each counter a device
    consumes."""
    for cc in dev.get("consumes", []):
        for cname, cval in cc.get("counters", {}).items():
            yield dev["pool"], cc["counterSet"], cname, int(cval["value"])


def _gang_contiguous(chosen: list[dict]) -> bool:
    """A multi-chip request is a gang: its chips must be one contiguous
    ICI sub-mesh within a single slice (SURVEY.md §7 hard part (a); the
    reference's analog is same-parent MIG constraints,
    demo/specs/quickstart/gpu-test4.yaml:42-44). XLA's collective
    performance model assumes mesh neighbours, so a fragmented pick is
    useless to the workload and must be rejected, not granted.
    """
    chips = [
        d for d in chosen
        if _attr_value(d["attributes"], "type") == "chip"
    ]
    if len(chips) < 2:
        return True
    from ..tpulib.topology import Coord, is_contiguous_submesh

    if len({_attr_value(d["attributes"], "sliceId") for d in chips}) > 1:
        return False
    coords = []
    for d in chips:
        c = _attr_value(d["attributes"], "coord")
        if c is None:
            return False
        coords.append(Coord.parse(c))
    return is_contiguous_submesh(coords)


class ReferenceAllocator:
    """Allocates claims against published ResourceSlices."""

    def __init__(
        self,
        client: KubeClient,
        driver_name: str = "tpu.google.com",
        device_classes: Optional[dict[str, list[str]]] = None,
        resource_api: Optional[ResourceApi] = None,
        registry: Optional[Registry] = None,
    ):
        """``device_classes`` maps DeviceClass name → CEL selector
        expressions (from the class spec). When given, class membership is
        decided by evaluating those (the production mechanism); otherwise
        the built-in DEVICE_CLASS_TYPES name → type mapping applies.
        ``resource_api`` selects the resource.k8s.io dialect slices are
        read in (default: discover from the client). ``registry`` receives
        the attempt/backtrack counters (a solver that starts thrashing
        shows up as a backtrack-rate spike long before latency does).
        """
        self.client = client
        self.driver_name = driver_name
        self.device_classes = device_classes
        self.api = resource_api or ResourceApi.discover(client)
        self._lock = threading.Lock()
        reg = registry if registry is not None else Registry()
        self._m_attempts = Counter(
            "tpu_dra_allocation_attempts_total",
            "Claim allocation attempts by result",
            reg,
        )
        self._m_backtracks = Counter(
            "tpu_dra_allocation_backtracks_total",
            "Device picks undone by the allocation solver",
            reg,
        )
        # Steps undone during the current solve; folded into the counter
        # once per allocate() (all access is under self._lock).
        self._backtrack_steps = 0
        # (pool, device) -> claim uid holding it
        self._reservations: dict[tuple[str, str], str] = {}
        # (pool, counter set, counter) -> amount consumed by reservations.
        self._consumed: dict[tuple[str, str, str], int] = {}
        # claim uid -> [(pool, counter set, counter, amount)] for release.
        self._claim_consumption: dict[str, list[tuple[str, str, str, int]]] = {}
        # (pool, device) pairs already warned about misconfigured counters,
        # so a static slice defect is diagnosed once, not per allocate().
        self._warned_invalid: set[tuple[str, str]] = set()

    # -- inventory ---------------------------------------------------------

    def _inventory(self) -> tuple[list[dict], dict[tuple[str, str, str], int]]:
        """One pass over the current slices (highest pool generation only):
        flattened (pool, node, device) inventory + shared-counter
        capacities keyed (pool, counter set, counter)."""
        slices = [
            self.api.slice_from_wire(s)
            for s in self.client.list(self.api.slices)
            if s["spec"].get("driver") == self.driver_name
        ]
        max_gen: dict[str, int] = {}
        for s in slices:
            pool = s["spec"]["pool"]
            max_gen[pool["name"]] = max(
                max_gen.get(pool["name"], 0), pool["generation"]
            )
        devices = []
        capacity: dict[tuple[str, str, str], int] = {}
        for s in slices:
            pool = s["spec"]["pool"]
            if pool["generation"] != max_gen[pool["name"]]:
                continue
            for dev in s["spec"].get("devices", []):
                devices.append(
                    {
                        "pool": pool["name"],
                        "node": s["spec"].get("nodeName", ""),
                        "node_selector": s["spec"].get("nodeSelector"),
                        "name": dev["name"],
                        "attributes": dev.get("basic", {}).get("attributes", {}),
                        "capacity": dev.get("basic", {}).get("capacity", {}),
                        "consumes": dev.get("basic", {}).get(
                            "consumesCounters", []
                        ),
                    }
                )
            for cs in s["spec"].get("sharedCounters", []):
                for cname, cval in cs.get("counters", {}).items():
                    capacity[(pool["name"], cs["name"], cname)] = int(
                        cval["value"]
                    )
        # A device consuming a counter its slice never declared is a
        # misconfigured slice; the upstream DRA allocator treats such a
        # device as invalid. Flag it ONCE here — not in the solver's
        # backtracking hot path, which would re-diagnose (and re-log) the
        # same static defect per candidate probe.
        for dev in devices:
            missing = [
                (cset, cname)
                for _, cset, cname, _ in _consumption_entries(dev)
                if (dev["pool"], cset, cname) not in capacity
            ]
            if missing:
                dev["invalid"] = True
                if (dev["pool"], dev["name"]) not in self._warned_invalid:
                    self._warned_invalid.add((dev["pool"], dev["name"]))
                    logger.warning(
                        "device %r in pool %r consumes undeclared counters "
                        "%s; treating device as unallocatable",
                        dev["name"], dev["pool"], missing,
                    )
        return devices, capacity

    # -- allocation --------------------------------------------------------

    def allocate(
        self,
        claim: dict,
        node_name: Optional[str] = None,
        selectors: Optional[dict[str, list[Selector]]] = None,
    ) -> dict:
        """Fill claim.status.allocation; returns the claim (mutated).

        ``selectors`` maps request name → extra Selector predicates (the
        CEL-lite substitute). ``node_name`` restricts node-local pools.
        """
        spec = claim.get("spec", {}).get("devices", {})
        requests = spec.get("requests", [])
        constraints = spec.get("constraints", [])
        selectors = selectors or {}
        # adminAccess requests "ignore all ordinary claims with respect to
        # access modes and any resource allocations" (types.go:448-456):
        # they may land on reserved devices and neither reserve nor consume
        # counters themselves.
        admin_reqs = {r["name"] for r in requests if r.get("adminAccess")}
        with self._lock, child_span(
            "allocator/allocate",
            claim_uid=claim.get("metadata", {}).get("uid", ""),
        ) as sp:
            devices, capacity = self._inventory()
            inventory = [
                d
                for d in devices
                if (not node_name or not d["node"] or d["node"] == node_name)
            ]
            self._backtrack_steps = 0
            try:
                results, picked_devs = self._solve(
                    requests, constraints, selectors, inventory, capacity
                )
            except Exception as e:
                self._m_attempts.inc(result="error")
                sp.set_error(str(e))
                raise
            finally:
                if self._backtrack_steps:
                    self._m_backtracks.inc(self._backtrack_steps)
                sp.set_tag("backtracks", self._backtrack_steps)
            self._m_attempts.inc(result="ok")
            sp.set_tag("devices", len(picked_devs))
            uid = claim["metadata"]["uid"]
            for r, d in zip(results, picked_devs):
                if r["request"] in admin_reqs:
                    continue
                self._reservations[(r["pool"], r["device"])] = uid
                for pool, cset, cname, amount in _consumption_entries(d):
                    self._consumed[(pool, cset, cname)] = (
                        self._consumed.get((pool, cset, cname), 0) + amount
                    )
                    self._claim_consumption.setdefault(uid, []).append(
                        (pool, cset, cname, amount)
                    )
        claim.setdefault("status", {})["allocation"] = {
            "devices": {
                "results": results,
                "config": self._carry_config(spec),
            }
        }
        return claim

    def _carry_config(self, spec: dict) -> list[dict]:
        """Claim-spec configs become FromClaim allocation configs (the
        scheduler does this verbatim copy)."""
        out = []
        for cfg in spec.get("config", []):
            entry = dict(cfg)
            entry["source"] = "FromClaim"
            out.append(entry)
        return out

    def _solve(self, requests, constraints, selectors, inventory, capacity):
        """Greedy backtracking over requests with matchAttribute checks,
        shared-counter budgets, and ICI contiguity for multi-chip gangs.

        Returns (allocation results, picked device dicts).
        """
        match_groups = [
            (set(c.get("requests", [])), c["matchAttribute"].split("/")[-1])
            for c in constraints
            if "matchAttribute" in c
        ]
        # Counters consumed by the in-progress solution, on top of the
        # amounts already reserved by other claims.
        tentative: dict[tuple[str, str, str], int] = {}

        def counters_fit(dev) -> bool:
            for pool, cset, cname, amount in _consumption_entries(dev):
                key = (pool, cset, cname)
                cap = capacity.get(key)
                if cap is None:
                    return False  # unreachable: _inventory flags these
                used = self._consumed.get(key, 0) + tentative.get(key, 0)
                if used + amount > cap:
                    return False
            return True

        def consume(dev) -> None:
            for pool, cset, cname, amount in _consumption_entries(dev):
                key = (pool, cset, cname)
                tentative[key] = tentative.get(key, 0) + amount

        def unconsume(dev) -> None:
            for pool, cset, cname, amount in _consumption_entries(dev):
                key = (pool, cset, cname)
                tentative[key] -= amount

        def cel_matches(expr: str, d: dict) -> bool:
            try:
                return cel_evaluate(
                    expr, self.driver_name, d["attributes"], d.get("capacity")
                )
            except CelError as e:
                # Bad expressions make the claim unallocatable, matching the
                # solver's error contract for malformed specs.
                raise AllocationError(f"invalid CEL selector: {e}") from e

        def class_matches(class_name: str, d: dict) -> bool:
            if self.device_classes is not None:
                exprs = self.device_classes.get(class_name)
                if exprs is None:
                    raise AllocationError(
                        f"unknown device class {class_name!r}"
                    )
                return all(cel_matches(e, d) for e in exprs)
            dtype = DEVICE_CLASS_TYPES.get(class_name)
            if dtype is None:
                raise AllocationError(f"unknown device class {class_name!r}")
            return _attr_value(d["attributes"], "type") == dtype

        def candidates(req, include_reserved=False):
            cel_selectors = [
                s["cel"]["expression"]
                for s in req.get("selectors", [])
                if "cel" in s
            ]
            admin = req.get("adminAccess", False)
            out = []
            for d in inventory:
                if d.get("invalid"):
                    continue  # misconfigured slice: unallocatable, and it
                    # must not inflate allocationMode=All's target count
                # Ordinary requests never see reserved devices; admin
                # requests observe them (monitoring over live workloads).
                if not (admin or include_reserved) and (
                    (d["pool"], d["name"]) in self._reservations
                ):
                    continue
                if not class_matches(req.get("deviceClassName", ""), d):
                    continue
                if not all(
                    s.matches(d["attributes"])
                    for s in selectors.get(req["name"], [])
                ):
                    continue
                if not all(cel_matches(e, d) for e in cel_selectors):
                    continue
                out.append(d)
            return out

        picked: list[tuple[str, dict]] = []  # (request name, device)
        admin_request_names = {
            r["name"] for r in requests if r.get("adminAccess")
        }

        def picked_blocks(req_admin: bool, d) -> bool:
            """Admin picks are invisible to ordinary placement and vice
            versa (types.go:448-456) — exclusion applies only between
            requests of the same access kind."""
            for other_name, p in picked:
                if p is d and (
                    (other_name in admin_request_names) == req_admin
                ):
                    return True
            return False

        def consistent(req_name, dev) -> bool:
            for group, attr in match_groups:
                if req_name not in group:
                    continue
                want = _attr_value(dev["attributes"], attr)
                for other_name, other in picked:
                    if other_name in group:
                        if _attr_value(other["attributes"], attr) != want:
                            return False
            return True

        def backtrack(ri: int) -> bool:
            if ri == len(requests):
                return True
            req = requests[ri]
            admin = req.get("adminAccess", False)
            mode = req.get("allocationMode", "ExactCount")
            cands = [
                d for d in candidates(req)
                if not picked_blocks(admin, d)
            ]
            if mode == "All":
                # Every matching device in scope (types.go:427-429): fails
                # when some are already allocated — unless adminAccess,
                # whose candidates() already includes reserved devices.
                count = len(cands)
                if count == 0:
                    return False
                if not admin and count != len(
                    candidates(req, include_reserved=True)
                ):
                    return False  # some matching devices already allocated
            elif mode == "ExactCount":
                count = req.get("count", 1)
            else:
                # "Clients must refuse to handle requests with unknown
                # modes" (types.go:435-436).
                raise AllocationError(
                    f"unknown allocationMode {mode!r} in request "
                    f"{req.get('name')!r}"
                )

            def pick_n(chosen: list) -> bool:
                if len(chosen) == count:
                    # Contiguity is a WORKLOAD constraint (ICI collectives);
                    # admin picks observe, so fragmented sets are fine.
                    if not admin and not _gang_contiguous(chosen):
                        return False
                    for d in chosen:
                        picked.append((req["name"], d))
                    if backtrack(ri + 1):
                        return True
                    for _ in chosen:
                        picked.pop()
                    self._backtrack_steps += len(chosen)
                    return False
                start = cands.index(chosen[-1]) + 1 if chosen else 0
                for d in cands[start:]:
                    if picked_blocks(admin, d) or d in chosen:
                        continue
                    if not consistent(req["name"], d):
                        continue
                    # Admin picks consume nothing, so counters are moot.
                    if not admin and not counters_fit(d):
                        continue
                    chosen.append(d)
                    if not admin:
                        consume(d)
                    # Intra-request matchAttribute consistency.
                    if self._group_ok(
                        req["name"], chosen, match_groups
                    ) and pick_n(chosen):
                        return True
                    if not admin:
                        unconsume(d)
                    chosen.pop()
                    self._backtrack_steps += 1
                return False

            return pick_n([])

        if not backtrack(0):
            raise AllocationError("no satisfying allocation found")
        return [
            {
                "request": name,
                "driver": self.driver_name,
                "pool": dev["pool"],
                "device": dev["name"],
            }
            for name, dev in picked
        ], [dev for _, dev in picked]

    @staticmethod
    def _group_ok(req_name, chosen, match_groups) -> bool:
        for group, attr in match_groups:
            if req_name not in group:
                continue
            vals = {_attr_value(d["attributes"], attr) for d in chosen}
            if len(vals) > 1:
                return False
        return True

    # -- release -----------------------------------------------------------

    def deallocate(self, claim_uid: str) -> None:
        with self._lock:
            self._reservations = {
                k: v for k, v in self._reservations.items() if v != claim_uid
            }
            for pool, cset, cname, amount in self._claim_consumption.pop(
                claim_uid, []
            ):
                self._consumed[(pool, cset, cname)] -= amount
