"""Reference DRA allocator: scheduler-sim for tests and dev clusters.

The real allocation happens in the Kubernetes scheduler's structured-
parameters allocator (SURVEY.md §3.5 — the layer deliberately NOT in the
reference repo). This module re-implements the subset this driver's
published attributes exercise, so the full claim lifecycle can be simulated
hermetically: DeviceClass → device-type mapping, request counts, attribute
selectors, and cross-request ``matchAttribute`` constraints (the gang /
same-parent mechanism of tpu-test4/6).

Not a CEL engine: selectors are (attribute, op, value) triples covering what
the demo specs express. The production path still uses the real scheduler.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Any, Callable, Optional

from .client import RESOURCE_SLICES, KubeClient

# DeviceClass name → the `type` attribute the node plugin publishes.
DEVICE_CLASS_TYPES = {
    "tpu.google.com": "chip",
    "tensorcore.tpu.google.com": "tensorcore",
    "ici.tpu.google.com": "ici",
}


class AllocationError(RuntimeError):
    pass


@dataclasses.dataclass
class Selector:
    """Attribute predicate: op ∈ {eq, ne, lt, le, gt, ge, in}."""

    attribute: str
    op: str
    value: Any

    def matches(self, attrs: dict) -> bool:
        raw = attrs.get(self.attribute)
        if raw is None:
            return False
        val = next(iter(raw.values())) if isinstance(raw, dict) else raw
        if self.op == "eq":
            return val == self.value
        if self.op == "ne":
            return val != self.value
        if self.op == "lt":
            return val < self.value
        if self.op == "le":
            return val <= self.value
        if self.op == "gt":
            return val > self.value
        if self.op == "ge":
            return val >= self.value
        if self.op == "in":
            return val in self.value
        raise ValueError(f"unknown op {self.op!r}")


def _attr_value(attrs: dict, name: str):
    raw = attrs.get(name)
    if raw is None:
        return None
    return next(iter(raw.values())) if isinstance(raw, dict) else raw


class ReferenceAllocator:
    """Allocates claims against published ResourceSlices."""

    def __init__(self, client: KubeClient, driver_name: str = "tpu.google.com"):
        self.client = client
        self.driver_name = driver_name
        self._lock = threading.Lock()
        # (pool, device) -> claim uid holding it
        self._reservations: dict[tuple[str, str], str] = {}

    # -- inventory ---------------------------------------------------------

    def _devices(self) -> list[dict]:
        """Flattened (pool, node, device) inventory from current slices,
        highest pool generation only."""
        slices = [
            s
            for s in self.client.list(RESOURCE_SLICES)
            if s["spec"].get("driver") == self.driver_name
        ]
        max_gen: dict[str, int] = {}
        for s in slices:
            pool = s["spec"]["pool"]
            max_gen[pool["name"]] = max(
                max_gen.get(pool["name"], 0), pool["generation"]
            )
        out = []
        for s in slices:
            pool = s["spec"]["pool"]
            if pool["generation"] != max_gen[pool["name"]]:
                continue
            for dev in s["spec"].get("devices", []):
                out.append(
                    {
                        "pool": pool["name"],
                        "node": s["spec"].get("nodeName", ""),
                        "node_selector": s["spec"].get("nodeSelector"),
                        "name": dev["name"],
                        "attributes": dev.get("basic", {}).get("attributes", {}),
                    }
                )
        return out

    # -- allocation --------------------------------------------------------

    def allocate(
        self,
        claim: dict,
        node_name: Optional[str] = None,
        selectors: Optional[dict[str, list[Selector]]] = None,
    ) -> dict:
        """Fill claim.status.allocation; returns the claim (mutated).

        ``selectors`` maps request name → extra Selector predicates (the
        CEL-lite substitute). ``node_name`` restricts node-local pools.
        """
        spec = claim.get("spec", {}).get("devices", {})
        requests = spec.get("requests", [])
        constraints = spec.get("constraints", [])
        selectors = selectors or {}
        with self._lock:
            inventory = [
                d
                for d in self._devices()
                if (d["pool"], d["name"]) not in self._reservations
                and (not node_name or not d["node"] or d["node"] == node_name)
            ]
            results = self._solve(requests, constraints, selectors, inventory)
            uid = claim["metadata"]["uid"]
            for r in results:
                self._reservations[(r["pool"], r["device"])] = uid
        claim.setdefault("status", {})["allocation"] = {
            "devices": {
                "results": results,
                "config": self._carry_config(spec),
            }
        }
        return claim

    def _carry_config(self, spec: dict) -> list[dict]:
        """Claim-spec configs become FromClaim allocation configs (the
        scheduler does this verbatim copy)."""
        out = []
        for cfg in spec.get("config", []):
            entry = dict(cfg)
            entry["source"] = "FromClaim"
            out.append(entry)
        return out

    def _solve(self, requests, constraints, selectors, inventory) -> list[dict]:
        """Greedy backtracking over requests with matchAttribute checks."""
        match_groups = [
            (set(c.get("requests", [])), c["matchAttribute"].split("/")[-1])
            for c in constraints
            if "matchAttribute" in c
        ]

        def candidates(req):
            dtype = DEVICE_CLASS_TYPES.get(req.get("deviceClassName", ""))
            if dtype is None:
                raise AllocationError(
                    f"unknown device class {req.get('deviceClassName')!r}"
                )
            out = []
            for d in inventory:
                if _attr_value(d["attributes"], "type") != dtype:
                    continue
                if not all(
                    s.matches(d["attributes"])
                    for s in selectors.get(req["name"], [])
                ):
                    continue
                out.append(d)
            return out

        picked: list[tuple[str, dict]] = []  # (request name, device)

        def consistent(req_name, dev) -> bool:
            for group, attr in match_groups:
                if req_name not in group:
                    continue
                want = _attr_value(dev["attributes"], attr)
                for other_name, other in picked:
                    if other_name in group:
                        if _attr_value(other["attributes"], attr) != want:
                            return False
            return True

        def backtrack(ri: int) -> bool:
            if ri == len(requests):
                return True
            req = requests[ri]
            count = req.get("count", 1)
            cands = [
                d for d in candidates(req)
                if not any(d is p for _, p in picked)
            ]

            def pick_n(chosen: list) -> bool:
                if len(chosen) == count:
                    for d in chosen:
                        picked.append((req["name"], d))
                    if backtrack(ri + 1):
                        return True
                    for _ in chosen:
                        picked.pop()
                    return False
                start = cands.index(chosen[-1]) + 1 if chosen else 0
                for d in cands[start:]:
                    if any(d is p for _, p in picked) or d in chosen:
                        continue
                    if not consistent(req["name"], d):
                        continue
                    chosen.append(d)
                    # Intra-request matchAttribute consistency.
                    if self._group_ok(
                        req["name"], chosen, match_groups
                    ) and pick_n(chosen):
                        return True
                    chosen.pop()
                return False

            return pick_n([])

        if not backtrack(0):
            raise AllocationError("no satisfying allocation found")
        return [
            {
                "request": name,
                "driver": self.driver_name,
                "pool": dev["pool"],
                "device": dev["name"],
            }
            for name, dev in picked
        ]

    @staticmethod
    def _group_ok(req_name, chosen, match_groups) -> bool:
        for group, attr in match_groups:
            if req_name not in group:
                continue
            vals = {_attr_value(d["attributes"], attr) for d in chosen}
            if len(vals) > 1:
                return False
        return True

    # -- release -----------------------------------------------------------

    def deallocate(self, claim_uid: str) -> None:
        with self._lock:
            self._reservations = {
                k: v for k, v in self._reservations.items() if v != claim_uid
            }
