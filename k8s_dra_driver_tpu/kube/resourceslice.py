"""ResourceSlice publishing controller.

Re-implementation of the vendored resourceslice controller the reference
relies on (lengrongfu/k8s-dra-driver,
vendor/k8s.io/dynamic-resource-allocation/resourceslice/
resourceslicecontroller.go:55-472): a reconciler that keeps the cluster's
``ResourceSlice`` objects in sync with a driver-provided ``DriverResources``
snapshot. Supports node-local pools (owner = the node, spec.nodeName set)
and network pools (spec.nodeSelector set — how ICI channels are published
per slice-domain, mirroring IMEX's network resources).

Differences from upstream: deterministic slice names (``<pool>-<driver>-<i>``)
instead of GenerateName, so reconcile is a pure name-keyed diff; and a
plain worker thread + event trigger instead of an informer/workqueue stack.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
from typing import Optional

from ..utils.backoff import Backoff
from .client import GVR, KubeClient
from .errors import AlreadyExistsError, ConflictError, NotFoundError
from .resourceapi import ResourceApi

logger = logging.getLogger(__name__)

# Canonical (in-memory) stamp; the served dialect is negotiated per
# controller via ResourceApi and applied at the wire boundary.
API_VERSION = "resource.k8s.io/v1beta1"

# Devices per ResourceSlice (the reference publishes IMEX channels 128 per
# slice, imex.go:43; upstream's limit is 128 devices/slice).
MAX_DEVICES_PER_SLICE = 128

# Label marking which publisher instance owns a slice. Multiple publishers
# (one per node plugin + the cluster controller) share one driver name; each
# must only prune its own slices.
OWNER_LABEL = "tpu.google.com/owned-by"


@dataclasses.dataclass
class Pool:
    """One pool of devices (DriverResources.Pools entry analog). The pool
    generation is managed by the controller (bumped on content change), not
    supplied by callers."""

    devices: list[dict]
    shared_counters: list[dict] = dataclasses.field(default_factory=list)
    node_name: str = ""                       # node-local pools
    node_selector: Optional[dict] = None      # network pools


@dataclasses.dataclass
class DriverResources:
    """Desired state handed to the controller (draplugin.go:376-420 analog)."""

    pools: dict[str, Pool] = dataclasses.field(default_factory=dict)


class ResourceSliceController:
    """Syncs DriverResources → ResourceSlice objects."""

    def __init__(
        self,
        client: KubeClient,
        driver_name: str,
        scope: str,
        owner: Optional[dict] = None,
        resync_seconds: float = 600.0,
        gvr: Optional[GVR] = None,
        api: Optional[ResourceApi] = None,
    ):
        """``scope`` identifies THIS publisher (node name for node plugins,
        e.g. "controller" for the cluster controller); create/update/delete
        only ever touches slices labeled with it. ``api`` selects the served
        resource.k8s.io dialect (default: discover it from the client —
        never silently pin, that is the round-4 404-on-1.32 bug);
        ``gvr`` overrides the collection address for tests."""
        self.client = client
        self.driver_name = driver_name
        self.scope = scope
        self.owner = owner  # ownerReference dict (node or pod), optional
        self.resync_seconds = resync_seconds
        self.api = api or ResourceApi.discover(client)
        self.gvr = gvr or self.api.slices
        self._gvr_pinned = gvr is not None  # test override: never re-target
        self._desired = DriverResources()
        self._lock = threading.Lock()
        self._sync_lock = threading.Lock()  # one reconcile pass at a time
        self._trigger = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.sync_errors = 0  # observability counter
        self._last_sync_error = ""  # "" = last pass succeeded
        self.last_success_monotonic = 0.0  # of the last successful pass

    # -- public API --------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="resourceslice-controller"
        )
        self._thread.start()

    def stop(self, delete_slices: bool = False) -> None:
        """Stop reconciling; optionally remove everything we published
        (cleanupResourceSlices analog, imex.go:308-326)."""
        self._stop.set()
        self._trigger.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        if delete_slices:
            for sl in self._list_driver_slices():
                self._delete(sl["metadata"]["name"])

    def update(self, resources: DriverResources) -> None:
        """Replace desired state and nudge the reconciler
        (DRAPlugin.PublishResources analog, draplugin.go:376-420)."""
        with self._lock:
            self._desired = resources
        self._trigger.set()

    def sync_once(self) -> None:
        """One reconcile pass (exposed for tests and for callers that want
        synchronous publication before serving). Serialized against the
        background reconciler.

        A NotFoundError from the verbs may mean the served dialect changed
        out from under us (startup discovery fell back during an apiserver
        outage, or the control plane was upgraded in place): re-discover,
        and when the answer differs, re-target and retry the pass — the
        pod must not need a restart to recover."""
        import time as _time

        with self._sync_lock:
            with self._lock:
                desired = self._desired
            try:
                self._sync(desired)
            except NotFoundError:
                if not self._rediscover():
                    raise
                self._sync(desired)
            self._last_sync_error = ""
            self.last_success_monotonic = _time.monotonic()

    def _rediscover(self) -> bool:
        """Re-run version discovery; returns True when the dialect moved
        (and the controller now targets the new one)."""
        if self._gvr_pinned:
            return False
        new = ResourceApi.try_discover(self.client)
        if new is None or new.version == self.api.version:
            return False
        logger.warning(
            "resource.k8s.io dialect changed %s -> %s; re-targeting "
            "slice publication", self.api.version, new.version,
        )
        self.api = new
        self.gvr = new.slices
        return True

    # -- reconcile loop ----------------------------------------------------

    def _run(self) -> None:
        # Jittered exponential retry: during an apiserver blackout every
        # plugin's publisher queues republishes behind this — full jitter
        # keeps a node-pool's worth of them from stampeding the recovering
        # server in lockstep.
        backoff = Backoff(
            initial=0.5, cap=min(60.0, self.resync_seconds), jitter=True
        )
        while not self._stop.is_set():
            self._trigger.wait(timeout=self.resync_seconds)
            self._trigger.clear()
            if self._stop.is_set():
                return
            try:
                self.sync_once()  # clears _last_sync_error on success
                backoff.reset()
            except Exception as e:
                self.sync_errors += 1
                self._last_sync_error = str(e)
                delay = backoff.next_delay()
                logger.exception(
                    "resourceslice sync failed; retrying in %.1fs", delay
                )
                # Transient-error retry (imex.go:143-162 analog).
                self._trigger.set()
                self._stop.wait(timeout=delay)

    def sync_health(self):
        """(ok, detail): whether the last reconcile pass against the
        apiserver succeeded — the plugin's degraded-readiness input."""
        if self._last_sync_error:
            return False, f"slice republish failing: {self._last_sync_error}"
        return True, "slices in sync"

    def _slice_name(self, pool_name: str, index: int) -> str:
        return f"{pool_name}-{self.driver_name.replace('.', '-')}-{index}"

    def _build_slices(
        self, pool_name: str, pool: Pool, generation: int
    ) -> list[dict]:
        chunks = [
            pool.devices[i : i + MAX_DEVICES_PER_SLICE]
            for i in range(0, len(pool.devices), MAX_DEVICES_PER_SLICE)
        ] or [[]]
        out = []
        for i, chunk in enumerate(chunks):
            spec: dict = {
                "driver": self.driver_name,
                "pool": {
                    "name": pool_name,
                    "generation": generation,
                    "resourceSliceCount": len(chunks),
                },
                "devices": chunk,
            }
            if pool.node_name:
                spec["nodeName"] = pool.node_name
            if pool.node_selector is not None:
                spec["nodeSelector"] = pool.node_selector
            if pool.shared_counters:
                spec["sharedCounters"] = pool.shared_counters
            md: dict = {
                "name": self._slice_name(pool_name, i),
                "labels": {OWNER_LABEL: self.scope},
            }
            if self.owner is not None:
                md["ownerReferences"] = [self.owner]
            out.append(
                {
                    "apiVersion": API_VERSION,
                    "kind": "ResourceSlice",
                    "metadata": md,
                    "spec": spec,
                }
            )
        return out

    def _list_driver_slices(self) -> list[dict]:
        """Slices published by THIS instance: same driver AND same scope
        label — never another node's or the controller's slices. Returned
        in canonical form so the reconcile diff runs in one shape."""
        return [
            self.api.slice_from_wire(s)
            for s in self.client.list(
                self.gvr, label_selector=f"{OWNER_LABEL}={self.scope}"
            )
            if s.get("spec", {}).get("driver") == self.driver_name
        ]

    @staticmethod
    def _spec_sans_generation(spec: dict) -> dict:
        clone = dict(spec)
        clone["pool"] = {
            k: v for k, v in spec.get("pool", {}).items() if k != "generation"
        }
        return clone

    def _sync(self, desired: DriverResources) -> None:
        """Name-keyed create/update/delete diff.

        Pool generation is bumped whenever the pool's content changes, so
        during a multi-slice transition (some slices updated, stale ones not
        yet deleted) schedulers can discard lower-generation slices — the
        upstream resourceslice controller's protocol.
        """
        have = {s["metadata"]["name"]: s for s in self._list_driver_slices()}
        gen_by_pool: dict[str, int] = {}
        for s in have.values():
            pool_md = s.get("spec", {}).get("pool", {})
            name = pool_md.get("name", "")
            gen_by_pool[name] = max(
                gen_by_pool.get(name, 0), pool_md.get("generation", 0)
            )

        want: dict[str, dict] = {}
        for pool_name, pool in desired.pools.items():
            current_gen = gen_by_pool.get(pool_name, 0) or 1
            trial = self._build_slices(pool_name, pool, current_gen)
            changed = False
            for sl in trial:
                existing = have.get(sl["metadata"]["name"])
                if existing is None or self._spec_sans_generation(
                    existing["spec"]
                ) != self._spec_sans_generation(sl["spec"]):
                    changed = True
                    break
            # Any stale slice of this pool beyond the trial set also counts
            # as a change (shrinking pool).
            trial_names = {sl["metadata"]["name"] for sl in trial}
            stale = [
                n for n, s in have.items()
                if s["spec"].get("pool", {}).get("name") == pool_name
                and n not in trial_names
            ]
            if stale:
                changed = True
            if changed and gen_by_pool.get(pool_name):
                trial = self._build_slices(pool_name, pool, current_gen + 1)
            for sl in trial:
                want[sl["metadata"]["name"]] = sl

        for name, sl in want.items():
            existing = have.get(name)
            if existing is None:
                try:
                    self.client.create(self.gvr, self.api.slice_to_wire(sl))
                except AlreadyExistsError:
                    # Raced a concurrent writer; converge next pass.
                    self._trigger.set()
            elif existing.get("spec") != sl["spec"]:
                merged = dict(sl)
                merged["metadata"] = dict(sl["metadata"])
                merged["metadata"]["resourceVersion"] = existing["metadata"].get(
                    "resourceVersion", ""
                )
                try:
                    self.client.update(self.gvr, self.api.slice_to_wire(merged))
                except ConflictError:
                    # Raced another writer; next pass will converge.
                    self._trigger.set()
        for name in set(have) - set(want):
            self._delete(name)

    def _delete(self, name: str) -> None:
        try:
            self.client.delete(self.gvr, name)
        except NotFoundError:
            pass
