"""Kubernetes API error model.

Role of apimachinery's errors package as used by the reference (e.g.
`errors.IsNotFound` in lengrongfu/k8s-dra-driver
cmd/nvidia-dra-plugin/sharing.go:380-383): a small typed hierarchy so callers
can branch on status codes without string matching.
"""

from __future__ import annotations


class ApiError(Exception):
    """An error returned by the Kubernetes API (or the fake)."""

    code = 500
    reason = "InternalError"

    def __init__(
        self,
        message: str = "",
        code: int | None = None,
        retry_after: float | None = None,
    ):
        super().__init__(message or self.reason)
        if code is not None:
            self.code = code
        # Server-suggested retry delay (the Retry-After header a real API
        # server attaches to 429/503 under priority-and-fairness load
        # shedding); None when the server sent none.
        self.retry_after = retry_after

    @property
    def status(self) -> dict:
        return {
            "kind": "Status",
            "apiVersion": "v1",
            "status": "Failure",
            "message": str(self),
            "reason": self.reason,
            "code": self.code,
        }


class NotFoundError(ApiError):
    code = 404
    reason = "NotFound"


class AlreadyExistsError(ApiError):
    code = 409
    reason = "AlreadyExists"


class ConflictError(ApiError):
    """resourceVersion mismatch on update."""

    code = 409
    reason = "Conflict"


class InvalidError(ApiError):
    code = 422
    reason = "Invalid"


def is_not_found(err: Exception) -> bool:
    return isinstance(err, NotFoundError)


def is_conflict(err: Exception) -> bool:
    return isinstance(err, (ConflictError, AlreadyExistsError))
