"""CEL-subset evaluator for DeviceClass / request selectors.

The real scheduler evaluates CEL expressions like

    device.driver == 'tpu.google.com' &&
    device.attributes['tpu.google.com'].iciX < 2

against each published device (reference behavior:
demo/specs/quickstart/gpu-test6.yaml:22-31 is evaluated by the k8s
structured-parameters allocator). This module implements the subset those
expressions use, so the hermetic scheduler-sim can execute the demo specs
rather than merely parse them:

- member access / indexing: ``device.attributes['domain'].name``,
  ``device.capacity['domain'].name``
- literals: strings, ints, floats, booleans, lists
- comparisons: ``==  !=  <  <=  >  >=  in``
- boolean logic: ``&&  ||  !``, parentheses

Semantics of missing attributes follow CEL's commutative logical operators:
a reference to an absent attribute is an error that ``||`` absorbs when the
other operand is true and ``&&`` absorbs when the other operand is false;
an error surviving to the top makes the device not match (the scheduler
likewise skips devices a selector cannot evaluate against).
"""

from __future__ import annotations

import re
from typing import Any, Callable

from ..api.v1alpha1.quantity import InvalidQuantityError, parse_quantity


class CelError(ValueError):
    """A malformed expression (tokenizer/parser/structural error).

    ``expression`` carries the offending source once known — a claim can
    hold several selectors, and "invalid CEL selector" without a pointer
    to WHICH one sent operators grepping every DeviceClass in the
    cluster. ``evaluate``/``evaluate_detailed`` attach it on the way out;
    internal raise sites may leave it empty."""

    def __init__(self, message: str, expression: str = ""):
        super().__init__(message)
        self.expression = expression


class _EvalError(Exception):
    """A runtime evaluation error (absent attribute, type mismatch).

    CEL's commutative ``&&``/``||`` absorb these when the other operand
    decides the result; one surviving to the top makes the device not
    match. Python exceptions (e.g. TypeError from ``'str' >= int``) must
    never escape ``evaluate`` — the round-2 advisor found exactly that
    killing the allocator loop."""


class _Missing(_EvalError):
    """An attribute referenced by the expression is absent on the device.
    Carries the attribute name so mismatch diagnostics can say WHICH
    reference failed, not just that one did."""

    def __init__(self, attribute: str = ""):
        super().__init__(attribute)
        self.attribute = attribute


class _TypeMismatch(_EvalError):
    """Operands of incompatible types reached a comparison operator."""


_TOKEN_RE = re.compile(
    r"""
    \s*(
        (?P<float>\d+\.\d+)
      | (?P<int>\d+)
      | (?P<string>'(?:[^'\\]|\\.)*'|"(?:[^"\\]|\\.)*")
      | (?P<ident>[A-Za-z_][A-Za-z0-9_]*)
      | (?P<op>&&|\|\||==|!=|<=|>=|[<>!\[\].(),])
    )
    """,
    re.VERBOSE,
)


def _tokenize(src: str) -> list[tuple[str, str]]:
    out = []
    pos = 0
    while pos < len(src):
        m = _TOKEN_RE.match(src, pos)
        if m is None:
            if src[pos:].strip() == "":
                break
            raise CelError(f"cannot tokenize at {src[pos:pos + 20]!r}")
        pos = m.end()
        for kind in ("float", "int", "string", "ident", "op"):
            tok = m.group(kind)
            if tok is not None:
                out.append((kind, tok))
                break
    out.append(("end", ""))
    return out


class _AttrMap:
    """``device.attributes['domain']`` — resolves unqualified attribute
    names published by this driver, unwrapping the DRA value union.

    Capacity maps additionally parse their quantity-string values to
    integer bytes/counts, so ``device.capacity['d'].hbm >= 17179869184``
    compares numerically the way real CEL compares Quantity values."""

    def __init__(self, attrs: dict, domain: str, want_domain: str,
                 is_capacity: bool = False):
        self._attrs = attrs
        self._match = domain == want_domain
        self._is_capacity = is_capacity

    def get(self, name: str):
        if not self._match:
            raise _Missing(name)
        raw = self._attrs.get(name)
        if raw is None:
            raise _Missing(name)
        if isinstance(raw, dict):
            if not raw:
                raise _Missing(name)  # empty value union carries no value
            raw = next(iter(raw.values()))
        if self._is_capacity:
            try:
                return parse_quantity(raw)
            except InvalidQuantityError:
                return raw
        return raw


class _Device:
    """The ``device`` root variable."""

    def __init__(self, driver: str, attributes: dict, capacity: dict):
        self.driver = driver
        self.attributes = attributes
        self.capacity = capacity


# A compiled node: nullary thunk, evaluated after parsing completes.
Thunk = Callable[[], Any]


class _Parser:
    """Recursive descent over the token list, producing thunks so logical
    operators can implement CEL's error-absorbing semantics."""

    def __init__(self, tokens: list[tuple[str, str]], driver: str,
                 device: _Device):
        self.toks = tokens
        self.i = 0
        self.driver = driver
        self.device = device

    def peek(self):
        return self.toks[self.i]

    def next(self):
        t = self.toks[self.i]
        self.i += 1
        return t

    def expect(self, val: str):
        _, tok = self.next()
        if tok != val:
            raise CelError(f"expected {val!r}, got {tok!r}")

    def parse(self) -> Thunk:
        v = self.or_()
        if self.peek()[0] != "end":
            raise CelError(f"trailing tokens at {self.peek()[1]!r}")
        return v

    def or_(self) -> Thunk:
        operands = [self.and_()]
        while self.peek()[1] == "||":
            self.next()
            operands.append(self.and_())
        if len(operands) == 1:
            return operands[0]

        def run():
            err = None
            for op in operands:
                try:
                    if bool(op()):
                        return True  # true absorbs errors (CEL or)
                except _EvalError as e:
                    err = e
            if err is not None:
                raise err
            return False

        return run

    def and_(self) -> Thunk:
        operands = [self.not_()]
        while self.peek()[1] == "&&":
            self.next()
            operands.append(self.not_())
        if len(operands) == 1:
            return operands[0]

        def run():
            err = None
            for op in operands:
                try:
                    if not bool(op()):
                        return False  # false absorbs errors (CEL and)
                except _EvalError as e:
                    err = e
            if err is not None:
                raise err
            return True

        return run

    def not_(self) -> Thunk:
        if self.peek()[1] == "!":
            self.next()
            inner = self.not_()
            return lambda: not bool(inner())
        return self.cmp()

    _OPS = {
        "==": lambda a, b: a == b,
        "!=": lambda a, b: a != b,
        "<": lambda a, b: a < b,
        "<=": lambda a, b: a <= b,
        ">": lambda a, b: a > b,
        ">=": lambda a, b: a >= b,
        "in": lambda a, b: a in b,
    }

    @staticmethod
    def _check_overload(op: str, a: Any, b: Any) -> None:
        """Modern CEL (the cel-go runtime Kubernetes uses) defines
        heterogeneous equality — ``1 == '1'`` is simply false, ``!=`` true
        — so ==/!= fall through to Python semantics. Only the ORDERING
        operators and ``in`` have no cross-type overloads: those raise an
        evaluation error the logical operators may absorb."""
        if op in ("==", "!="):
            return

        def cat(v: Any) -> str:
            if isinstance(v, bool):  # before int: bool is an int subclass
                return "bool"
            if isinstance(v, (int, float)):
                return "number"
            if isinstance(v, str):
                return "string"
            if isinstance(v, list):
                return "list"
            return type(v).__name__

        if op == "in":
            if cat(b) != "list":
                raise _TypeMismatch(
                    f"'in' requires a list, got {cat(b)}"
                )
            return
        if cat(a) != cat(b):
            raise _TypeMismatch(
                f"no matching overload for {op!r} applied to "
                f"({cat(a)}, {cat(b)})"
            )

    def cmp(self) -> Thunk:
        left = self.primary()
        _, tok = self.peek()
        if tok in self._OPS:
            self.next()
            right = self.primary()
            fn = self._OPS[tok]

            def run():
                a, b = left(), right()
                self._check_overload(tok, a, b)
                try:
                    return fn(a, b)
                except TypeError:
                    # Belt and braces: anything _check_overload missed is
                    # still an evaluation error the logical operators may
                    # absorb, never a Python crash.
                    raise _TypeMismatch(
                        f"no matching overload for {tok!r} applied to "
                        f"({type(a).__name__}, {type(b).__name__})"
                    ) from None

            return run
        return left

    def primary(self) -> Thunk:
        kind, tok = self.next()
        if kind == "int":
            return self.postfix(lambda v=int(tok): v)
        if kind == "float":
            return self.postfix(lambda v=float(tok): v)
        if kind == "string":
            body = (
                tok[1:-1]
                .replace("\\'", "'")
                .replace('\\"', '"')
                .replace("\\\\", "\\")
            )
            return self.postfix(lambda v=body: v)
        if tok == "(":
            v = self.or_()
            self.expect(")")
            return self.postfix(v)
        if tok == "[":
            items = []
            if self.peek()[1] != "]":
                items.append(self.or_())
                while self.peek()[1] == ",":
                    self.next()
                    items.append(self.or_())
            self.expect("]")
            return lambda: [it() for it in items]
        if kind == "ident":
            if tok == "true":
                return lambda: True
            if tok == "false":
                return lambda: False
            if tok == "device":
                return self.postfix(lambda: self.device)
            raise CelError(f"unknown identifier {tok!r}")
        raise CelError(f"unexpected token {tok!r}")

    def postfix(self, v: Thunk) -> Thunk:
        """Member access and indexing chains."""
        while True:
            _, tok = self.peek()
            if tok == ".":
                self.next()
                k2, name = self.next()
                if k2 != "ident":
                    raise CelError(f"expected member name, got {name!r}")
                v = self._member(v, name)
            elif tok == "[":
                self.next()
                idx = self.or_()
                self.expect("]")
                v = self._index(v, idx)
            else:
                return v

    def _member(self, v: Thunk, name: str) -> Thunk:
        def run():
            obj = v()
            if isinstance(obj, _Device):
                if name == "driver":
                    return obj.driver
                if name in ("attributes", "capacity"):
                    return ("attrmap", getattr(obj, name), name)
                raise CelError(f"unknown device member {name!r}")
            if isinstance(obj, _AttrMap):
                return obj.get(name)
            raise CelError(
                f"cannot access member {name!r} on {type(obj).__name__}"
            )

        return run

    def _index(self, v: Thunk, idx: Thunk) -> Thunk:
        def run():
            obj = v()
            if isinstance(obj, tuple) and obj and obj[0] == "attrmap":
                return _AttrMap(obj[1], str(idx()), self.driver,
                                is_capacity=obj[2] == "capacity")
            raise CelError(f"cannot index {type(obj).__name__}")

        return run


def evaluate_detailed(
    expression: str,
    driver: str,
    attributes: dict,
    capacity: dict | None = None,
) -> tuple[bool, str]:
    """Evaluate a selector expression against one device.

    Returns ``(matched, why_not)``: ``why_not`` is empty for a match (and
    for a plain boolean non-match), and names the absorbed evaluation
    error — the absent attribute, the type mismatch — when that is what
    made the device not match. The allocation explainer threads this into
    per-device rejection reasons, so a typo'd attribute name reads as
    ``attribute 'iciY' absent``, not as a silent non-match.

    A malformed expression raises :class:`CelError` with ``expression``
    attached (every raise path here is wrapped, including structural
    errors that only surface at evaluation time, e.g. an unknown
    ``device`` member)."""
    device = _Device(driver, attributes, capacity or {})
    try:
        thunk = _Parser(_tokenize(expression), driver, device).parse()
        result = bool(thunk())
    except _Missing as e:
        return False, (
            f"attribute {e.attribute!r} absent on device"
            if e.attribute else "referenced attribute absent on device"
        )
    except _TypeMismatch as e:
        return False, str(e)
    except _EvalError as e:
        return False, str(e) or "evaluation error"
    except CelError as e:
        if not e.expression:
            raise CelError(
                f"{e} in expression {expression!r}", expression=expression
            ) from e
        raise
    return result, ""


def evaluate(
    expression: str,
    driver: str,
    attributes: dict,
    capacity: dict | None = None,
) -> bool:
    """Evaluate a selector expression against one device. Returns False when
    the expression (irrecoverably) references attributes the device doesn't
    carry."""
    return evaluate_detailed(expression, driver, attributes, capacity)[0]
