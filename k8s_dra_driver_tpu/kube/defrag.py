"""Read-only defragmentation planner for gang claims stuck on a
fragmented fleet.

When a gang claim goes unsat with terminal reason ``gang`` or
``shortfall`` even though the fleet's total free chip capacity would fit
it, the capacity exists but is shredded across the ICI mesh. This module
answers the operator's next question — *which* claims would have to move
*where* to admit the gang — without moving anything: it proposes a
minimal migration plan (fewest displaced claims first), scored with the
same best-fit/corner-bias discipline the allocator's placement scorer
uses, so the plan it proposes is one the scorer would actually have
picked.

The planner attaches to a :class:`~.allocator.ReferenceAllocator`
(``allocator.defrag = planner``, done by the constructor); the allocator
calls :meth:`note_unsat` from its unsat path, under its own lock. Plans
land in a bounded ring buffer served as JSON at ``/debug/defrag``
(``MetricsServer.set_defrag_provider``) and feed the
``tpu_dra_defrag_*`` metric families.

The planner itself never moves anything. Execution lives in
:mod:`.defrag_executor` (opt-in, ``--defrag-execute`` on the driver):
each plan is stamped with a ``planId`` and the ``sig`` (inventory
generation + reservation version) it was computed against, so the
executor can refuse a stale plan, and when an executor is attached
(``planner.executor``, set by its constructor) the ``/debug/defrag``
payload grows an ``executions`` trail next to the plans.
"""

from __future__ import annotations

import collections
import logging
import time
from typing import Any, Optional

from ..tpulib.topology import box_shapes, free_components
from ..utils.metrics import Counter, Gauge, Histogram, Registry

logger = logging.getLogger(__name__)

# Plan outcomes (the `outcome` label on tpu_dra_defrag_plans_total).
OUTCOME_PLANNED = "planned"
OUTCOME_UNPLANNABLE = "unplannable"
OUTCOME_INSUFFICIENT = "insufficient-capacity"
OUTCOME_NO_TOPOLOGY = "no-topology"

OUTCOMES = (
    OUTCOME_PLANNED,
    OUTCOME_UNPLANNABLE,
    OUTCOME_INSUFFICIENT,
    OUTCOME_NO_TOPOLOGY,
)

# Plans kept for /debug/defrag.
DEFAULT_PLAN_BUFFER = 32
# Candidate target boxes examined per plan, across all slices — planning
# runs inline on the unsat path and must stay bounded no matter how
# pathological the mesh is.
DEFAULT_MAX_BOXES = 512


class DefragPlanner:
    """Proposes (never executes) migrations that would free a contiguous
    sub-mesh for a stuck gang claim."""

    def __init__(
        self,
        allocator,
        registry: Optional[Registry] = None,
        max_plans: int = DEFAULT_PLAN_BUFFER,
        max_boxes: int = DEFAULT_MAX_BOXES,
    ):
        self.allocator = allocator
        self.max_boxes = max_boxes
        reg = registry if registry is not None else Registry()
        self._m_plans = Counter(
            "tpu_dra_defrag_plans_total",
            "Defragmentation plans computed for unsat gang claims, by "
            "outcome",
            reg,
        )
        self._m_migrations = Gauge(
            "tpu_dra_defrag_last_plan_migrations",
            "Migrations proposed by the most recent defrag plan "
            "(0 when the last plan found no feasible migration set)",
            reg,
        )
        self._m_freed = Gauge(
            "tpu_dra_defrag_last_plan_freed_devices",
            "Devices the most recent plan's target box would free for "
            "the stuck gang",
            reg,
        )
        self._m_seconds = Histogram(
            "tpu_dra_defrag_plan_seconds",
            "Defrag planning latency per unsat gang claim",
            reg,
        )
        self._plans: collections.deque = collections.deque(maxlen=max_plans)
        # claim uid -> (index generation, reservation version) of the
        # last computed plan: a scheduler retrying a stuck claim every
        # sync period must not re-plan (and re-append near-identical
        # plans, evicting everyone else's) while nothing has changed.
        self._last_sig: dict[str, tuple] = {}
        # Monotonic plan-id counter; an attached DefragExecutor (set by
        # its constructor) contributes the executions view to
        # export_json and keys its trail on these ids.
        self._plan_seq = 0
        self.executor = None
        allocator.defrag = self

    # -- reading -----------------------------------------------------------

    def recent_plans(self) -> list[dict]:
        """Newest-last snapshot of the plan ring buffer."""
        return list(self._plans)

    def export_json(self) -> dict[str, Any]:
        """The ``/debug/defrag`` payload."""
        out: dict[str, Any] = {
            "plans": self.recent_plans(),
            "note": (
                "plans are proposals until executed; execution (opt-in "
                "--defrag-execute) drains/reshards the listed claims "
                "through the gateway and elastic resize protocols "
                "(docs/operations.md: fleet is fragmented)"
            ),
        }
        if self.executor is not None:
            out["executions"] = self.executor.export_executions()
        return out

    # -- planning ----------------------------------------------------------

    def note_unsat(
        self, claim: dict, expl, selectors=None,
        require_healthy: bool = False,
    ) -> dict:
        """Compute (and record) a plan for one unsat gang claim.

        Called by the allocator under its lock, so reservations and the
        inventory index are coherent for the duration. ``selectors`` /
        ``require_healthy`` are the solve's own arguments: the target
        box is restricted to devices the CLAIM could actually use, so a
        "planned" outcome is never a proposal on a slice the claim's
        selectors exclude (or on the wedged chip a healthy-only re-solve
        is steering around). Never raises into the solve path — the
        allocator wraps it — but returns the plan record for tests and
        tools.
        """
        t0 = time.monotonic()
        md = claim.get("metadata", {})
        uid = md.get("uid", "")
        # Retry dedup: same inventory generation + same reservation
        # state = same plan; return the recorded one instead of
        # re-enumerating boxes inline on the solve path.
        sig = (
            self.allocator.index.generation,
            self.allocator.reservation_version,
        )
        if self._last_sig.get(uid) == sig:
            for p in reversed(self._plans):
                if p["claim"]["uid"] == uid:
                    return p
        plan: dict[str, Any] = {
            "ts": round(time.time(), 3),
            "claim": {
                "uid": md.get("uid", ""),
                "name": md.get("name", ""),
                "namespace": md.get("namespace", ""),
            },
            "reason": expl.reason,
            "wanted": 0,
            "outcome": OUTCOME_UNPLANNABLE,
            "detail": "",
            "migrations": [],
        }
        wanted = self._wanted_chips(claim)
        plan["wanted"] = wanted
        if wanted < 1:
            plan["outcome"] = OUTCOME_NO_TOPOLOGY
            plan["detail"] = (
                "claim requests no chip-class devices; defrag plans only "
                "cover ICI chip gangs"
            )
            return self._finish(plan, t0)
        index = self.allocator.index
        reservations = self.allocator._reservations
        slice_ids = index.slice_ids()
        if not slice_ids:
            plan["outcome"] = OUTCOME_NO_TOPOLOGY
            plan["detail"] = "no slice publishes grounded chip coordinates"
            return self._finish(plan, t0)
        # Devices the stuck claim could actually land on (its class +
        # selectors + health gate); None = not derivable (multi-request
        # gang), plan on the full topology as a best effort.
        eligible = self._eligible_keys(claim, selectors, require_healthy)
        free_total = 0
        per_slice: list[tuple[str, Any, dict, dict, set]] = []
        for sid in slice_ids:
            shape, cells = index.slice_meta(sid)
            free = {}
            held = {}
            dest_free = set()  # mover destinations: any unreserved cell
            for coord, dev in cells.items():
                if dev.get("invalid"):
                    continue
                holder = reservations.get(dev["_key"])
                usable = eligible is None or dev["_key"] in eligible
                if holder is None:
                    dest_free.add(coord)
                    if usable:
                        free[coord] = dev
                elif usable:
                    held[coord] = (dev, holder)
            free_total += len(free)
            per_slice.append((sid, shape, free, held, dest_free))
        if free_total < wanted:
            plan["outcome"] = OUTCOME_INSUFFICIENT
            plan["detail"] = (
                f"only {free_total} free chip(s) fleet-wide for a "
                f"{wanted}-chip gang — this is a capacity problem, not "
                "fragmentation"
            )
            return self._finish(plan, t0)
        plan["freeDevices"] = free_total
        best = None
        examined = 0
        # Claim uid -> all its reserved device keys (movability needs the
        # claim's WHOLE holding, not just the chips inside one box).
        holdings: dict[str, list] = {}
        for dev_key, uid in reservations.items():
            holdings.setdefault(uid, []).append(dev_key)
        for sid, shape, free, held, dest_free in per_slice:
            if len(free) + len(held) < wanted:
                continue
            candidate = self._plan_slice(
                sid, shape, free, held, dest_free, holdings, wanted,
                index, budget=self.max_boxes - examined,
            )
            examined += candidate.pop("examined", 0)
            if candidate.get("migrations") is not None and (
                best is None
                or len(candidate["migrations"]) < len(best["migrations"])
            ):
                best = candidate
            if examined >= self.max_boxes:
                break
        plan["examinedBoxes"] = examined
        if best is None:
            plan["detail"] = (
                f"no {wanted}-chip box can be freed by migrating movable "
                f"claims (examined {examined} candidate box(es))"
            )
            return self._finish(plan, t0)
        plan.update(best)
        plan["outcome"] = OUTCOME_PLANNED
        plan["detail"] = (
            f"moving {len(best['migrations'])} claim(s) frees a "
            f"{best['box']} box at {best['origin']} on slice "
            f"{best['sliceId']} for the {wanted}-chip gang"
        )
        return self._finish(plan, t0)

    def _finish(self, plan: dict, t0: float) -> dict:
        # Execution pinning: the id names this plan in the executor's
        # trail, and the sig is the exact allocator state the migrations
        # were computed against — the executor refuses to run a plan
        # whose sig no longer matches (anything could have moved).
        self._plan_seq += 1
        plan["planId"] = f"plan-{self._plan_seq}"
        plan["sig"] = {
            "generation": self.allocator.index.generation,
            "reservationVersion": self.allocator.reservation_version,
        }
        self._m_plans.inc(outcome=plan["outcome"])
        self._m_seconds.observe(time.monotonic() - t0)
        self._m_migrations.set(len(plan["migrations"]))
        self._m_freed.set(
            plan["wanted"] if plan["outcome"] == OUTCOME_PLANNED else 0
        )
        self._plans.append(plan)
        if len(self._last_sig) > 512:
            self._last_sig.clear()  # crude bound; dedup rebuilds fast
        self._last_sig[plan["claim"]["uid"]] = (
            self.allocator.index.generation,
            self.allocator.reservation_version,
        )
        return plan

    def _eligible_keys(
        self, claim: dict, selectors, require_healthy: bool,
    ) -> Optional[set]:
        """Device keys the claim's (single) chip request accepts, via
        the allocator's own cached filter machinery — the plan's target
        box must be placeable FOR THIS CLAIM, not just geometrically
        free. None when the claim has several chip requests (their
        per-request filters differ; plan unrestricted, best-effort)."""
        from .allocator import DEVICE_CLASS_TYPES

        spec = claim.get("spec", {}).get("devices", {})
        reqs = [
            r for r in spec.get("requests", [])
            if not r.get("adminAccess")
            and r.get("allocationMode", "ExactCount") == "ExactCount"
            and DEVICE_CLASS_TYPES.get(
                r.get("deviceClassName", ""), "chip") == "chip"
        ]
        if len(reqs) != 1:
            return None
        req = reqs[0]
        cel_exprs = [
            s["cel"]["expression"]
            for s in req.get("selectors", []) if "cel" in s
        ]
        prog = (selectors or {}).get(req.get("name", ""), [])
        index = self.allocator.index
        try:
            rec = index.filter_record(
                req.get("deviceClassName", ""), prog, cel_exprs,
            )
        except Exception:
            return None  # malformed selectors: plan unrestricted
        out = set()
        for key, verdict in rec.by_device.items():
            if verdict is not None:
                continue
            if require_healthy:
                dev = index.by_key.get(key)
                if dev is not None and dev.get("_healthy") is False:
                    continue
            out.add(key)
        return out

    @staticmethod
    def _wanted_chips(claim: dict) -> int:
        """Total chip-class devices the claim asks for (the gang size a
        freed box must cover)."""
        from .allocator import DEVICE_CLASS_TYPES

        wanted = 0
        spec = claim.get("spec", {}).get("devices", {})
        for r in spec.get("requests", []):
            if r.get("adminAccess"):
                continue
            if r.get("allocationMode", "ExactCount") != "ExactCount":
                continue
            cls = r.get("deviceClassName", "")
            # With class CEL the type is not derivable from the name;
            # assume chip (the planner's output is advisory either way).
            if DEVICE_CLASS_TYPES.get(cls, "chip") == "chip":
                wanted += int(r.get("count", 1))
        return wanted

    def _plan_slice(
        self, sid, shape, free, held, dest_free, holdings, wanted, index,
        budget,
    ) -> dict:
        """Try to free one ``wanted``-cell box on this slice. Returns a
        dict with ``migrations`` (None when no box works) plus the count
        of boxes ``examined``."""
        all_cells = set(free) | set(held)
        candidates = []  # (n blocker claims, blocked cells, corner, ...)
        examined = 0
        for dims in box_shapes(wanted, shape):
            dx, dy, dz = dims
            for ox in range(shape.x - dx + 1):
                for oy in range(shape.y - dy + 1):
                    for oz in range(shape.z - dz + 1):
                        if examined >= budget:
                            break
                        examined += 1
                        cells = [
                            (ox + ix, oy + iy, oz + iz)
                            for ix in range(dx)
                            for iy in range(dy)
                            for iz in range(dz)
                        ]
                        if not all(c in all_cells for c in cells):
                            continue  # box leaves the published mesh
                        blockers = {
                            held[c][1] for c in cells if c in held
                        }
                        if not blockers:
                            # A fully-free box exists — the claim's unsat
                            # was not this slice's fragmentation; no plan
                            # from here.
                            continue
                        corner = (
                            min(ox, shape.x - ox - dx)
                            + min(oy, shape.y - oy - dy)
                            + min(oz, shape.z - oz - dz)
                        )
                        candidates.append(
                            (len(blockers),
                             sum(1 for c in cells if c in held),
                             corner, (ox, oy, oz), dims, cells, blockers)
                        )
                    if examined >= budget:
                        break
                if examined >= budget:
                    break
            if examined >= budget:
                break
        candidates.sort(key=lambda c: c[:4])
        for nblock, _, _, origin, dims, cells, blockers in candidates:
            migrations = self._relocate_blockers(
                sid, shape, dest_free, holdings, cells, blockers, index,
            )
            if migrations is not None:
                return {
                    "examined": examined,
                    "sliceId": sid,
                    "origin": f"{origin[0]},{origin[1]},{origin[2]}",
                    "box": f"{dims[0]}x{dims[1]}x{dims[2]}",
                    "migrations": migrations,
                }
        return {"examined": examined, "migrations": None}

    def _relocate_blockers(
        self, sid, shape, dest_free, holdings, box_cells, blockers, index,
    ) -> Optional[list[dict]]:
        """Greedy sequential re-placement of every blocker claim out of
        the target box; None when any blocker cannot move. Earlier
        movers' vacated cells (outside the box) become destinations for
        later movers — the order is smallest holding first, so the easy
        moves don't strand the hard ones. Destinations draw from EVERY
        unreserved cell (``dest_free``), not just cells the stuck claim
        could use: the movers' own constraints are unknown, so their
        placements are advisory."""
        _, slice_cells = index.slice_meta(sid)
        box = set(box_cells)
        avail = {c for c in dest_free if c not in box}
        migrations = []
        for uid in sorted(
            blockers, key=lambda u: (len(holdings.get(u, [])), u)
        ):
            dev_keys = holdings.get(uid, [])
            coords = []
            for dk in dev_keys:
                dev = index.by_key.get(dk)
                if (
                    dev is None or dev.get("_type") != "chip"
                    or dev.get("_coord") is None
                    or str(dev.get("_slice_id")) != str(sid)
                ):
                    # The claim holds devices the planner cannot re-place
                    # (partitions, other slices, unpublished): immovable.
                    return None
                coords.append(dev["_coord"].as_tuple())
            dims = self._bounding_dims(coords)
            if dims is None:
                return None  # not a dense box; re-placement ill-defined
            dest = self._find_destination(shape, avail, dims)
            if dest is None:
                return None
            dest_cells, score = dest
            for c in dest_cells:
                avail.discard(c)
            for c in coords:
                if c not in box:
                    avail.add(c)
            migrations.append({
                "claimUid": uid,
                "devices": sorted(
                    slice_cells[c]["name"] for c in coords
                    if c in slice_cells
                ),
                "to": sorted(
                    slice_cells[c]["name"] for c in dest_cells
                    if c in slice_cells
                ),
                # Destination coordinates in selector form ("x,y,z"):
                # the executor re-solves each mover pinned to exactly
                # these cells, so the applied placement IS the planned
                # one (not merely a placement of the same shape).
                "toCoords": sorted(
                    f"{c[0]},{c[1]},{c[2]}" for c in dest_cells
                ),
                "box": f"{dims[0]}x{dims[1]}x{dims[2]}",
                "score": score,
            })
        return migrations

    @staticmethod
    def _bounding_dims(coords) -> Optional[tuple[int, int, int]]:
        xs = [c[0] for c in coords]
        ys = [c[1] for c in coords]
        zs = [c[2] for c in coords]
        dims = (
            max(xs) - min(xs) + 1,
            max(ys) - min(ys) + 1,
            max(zs) - min(zs) + 1,
        )
        if dims[0] * dims[1] * dims[2] != len(set(coords)):
            return None
        return dims

    @staticmethod
    def _find_destination(shape, avail, dims):
        """Best-fit placement of a dims-shaped box into the available
        cells: smallest free component, then corner bias — the same
        scoring the allocator applies, so a plan's destinations are ones
        a subsequent scored re-solve would actually choose."""
        comp_size = {}
        need = dims[0] * dims[1] * dims[2]
        for comp in free_components(avail):
            if len(comp) < need:
                continue
            for cell in comp:
                comp_size[cell] = len(comp)
        best = None
        dx, dy, dz = dims
        for ox in range(shape.x - dx + 1):
            for oy in range(shape.y - dy + 1):
                for oz in range(shape.z - dz + 1):
                    origin = (ox, oy, oz)
                    if origin not in comp_size:
                        continue
                    cells = [
                        (ox + ix, oy + iy, oz + iz)
                        for ix in range(dx)
                        for iy in range(dy)
                        for iz in range(dz)
                    ]
                    if not all(c in avail for c in cells):
                        continue
                    corner = (
                        min(ox, shape.x - ox - dx)
                        + min(oy, shape.y - oy - dy)
                        + min(oz, shape.z - oz - dz)
                    )
                    key = (comp_size[origin], corner, origin)
                    if best is None or key < best[0]:
                        best = (key, cells)
        if best is None:
            return None
        (comp, corner, _), cells = best
        return cells, {"freeComponent": comp, "cornerDistance": corner}
