"""Defrag plan execution: orchestrated live migrations that un-strand
gang claims.

The :mod:`.defrag` planner proposes which blocker claims must move where
to free a contiguous box for a stuck gang claim — and stops there. This
module is the actuation half: it takes one ``planned`` plan and executes
it end to end, crash-consistently, with zero admitted-request loss for
drained serving replicas and loss continuity for live-resharded training
gangs.

Execution discipline (the PR-6/PR-10 two-phase intent protocol extended
from one claim to a multi-claim plan):

1. **Pin.** The whole execution runs under one ``allocator.snapshot()``;
   the plan's ``sig`` (inventory generation + reservation version) must
   still match, or the plan is refused as stale (:class:`StalePlanError`)
   — anything could have moved since it was computed.
2. **Intent.** A per-plan execution intent (the plan, each blocker's
   current holdings in allocation wire form, per-step status) is written
   atomically to ``intent_path`` BEFORE anything moves
   (``defrag.intent-write``). From here a crash rolls *forward*.
3. **Migrate.** Per blocker: drain its serving replicas through the
   gateway's zero-loss drain (``defrag.drain``), re-place it with the
   allocator's best-fit scorer pinned to the planned destination cells
   (``defrag.replace``), rewrite node state through the elastic resize
   protocol when the claim is prepared locally, notify migration
   listeners (training gangs live-reshard via
   ``ElasticTrainer.relocate``), resume the drained replicas, then
   checkpoint the step as done.
4. **Admit.** Solve the originally-stuck claim (``defrag.admit``), clear
   the intent, and record the execution as ``completed``.

A NON-crash failure at any step rolls the whole plan back in reverse —
movers return to their original devices (``restore_reservations`` + an
elastic resize back), drained replicas resume — and the intent is
cleared; the execution records as ``rolled-back``. A crash
(``faults.CrashPoint``, the SIGKILL analog) runs no rollback: the intent
stays on disk and the restarted executor's :meth:`DefragExecutor.recover`
converges it, forward when the migrations can still complete, back
otherwise. An intent neither path can clear is surfaced by the
StateAuditor's ``defrag`` check — loud, never silent.

Executions land in a bounded ring served as the ``executions`` view of
``/debug/defrag`` (the planner delegates here when an executor is
attached) and feed the ``tpu_dra_defrag_exec_*`` metric family.
"""

from __future__ import annotations

import collections
import contextlib
import json
import logging
import os
import threading
import time
from typing import Callable, Optional

from ..utils import faults
from ..utils.fs import atomic_write_json
from ..utils.metrics import Counter, Gauge, Histogram, Registry
from .allocator import Selector
from .defrag import OUTCOME_PLANNED

logger = logging.getLogger(__name__)

# Execution record states (the /debug/defrag `executions` view).
STATE_IN_FLIGHT = "in-flight"
STATE_COMPLETED = "completed"
STATE_ROLLED_BACK = "rolled-back"
STATE_REFUSED = "refused"
STATE_FAILED = "failed"

# tpu_dra_defrag_exec_executions_total outcome labels. "stale-plan" is a
# refusal because the allocator moved under the plan; "refused" a
# refusal for any other reason (not a planned plan, execution already in
# flight); "failed" means the rollback itself failed and the intent was
# left on disk for the auditor.
EXEC_OUTCOMES = (
    "completed", "rolled-back", "stale-plan", "refused", "failed",
)
STEP_KINDS = ("intent-write", "drain", "replace", "admit")
STEP_OUTCOMES = ("ok", "failed")

DEFAULT_EXECUTION_BUFFER = 32


class DefragExecutionError(RuntimeError):
    """A defrag execution was refused or failed. Unless the message says
    the intent was left on disk, the fleet reads exactly as before the
    attempt."""


class StalePlanError(DefragExecutionError):
    """The plan's ``sig`` no longer matches the allocator: reservations
    or inventory moved since it was computed, so its migrations describe
    a fleet that no longer exists. Re-plan (the next unsat solve does)
    and execute the fresh plan instead."""


class DefragExecutor:
    """Executes one ``planned`` DefragPlanner plan crash-consistently.

    Collaborators are all optional except the planner/allocator pair:
    ``state`` (a :class:`~..plugin.device_state.DeviceState`) rewrites
    node-local holds/CDI through the elastic resize protocol for movers
    prepared on this node; ``gateway`` (a
    :class:`~..serving_gateway.gateway.ServingGateway`) drains and
    resumes serving replicas bound to a mover's claim; ``events`` (a
    :class:`~.events.EventRecorder`) narrates the execution on the stuck
    claim. Migration listeners registered with
    :meth:`add_migration_listener` are told each mover's new device set
    (and, on rollback, its original one) — the seam training harnesses
    use to live-reshard an :class:`~..parallel.elastic.ElasticTrainer`
    onto the relocated gang.
    """

    def __init__(
        self,
        planner,
        allocator,
        *,
        intent_path: str,
        state=None,
        gateway=None,
        registry: Optional[Registry] = None,
        events=None,
        driver_name: str = "tpu.google.com",
        device_class: str = "tpu.google.com",
        node_name: str = "",
        max_executions: int = DEFAULT_EXECUTION_BUFFER,
    ):
        self.planner = planner
        self.allocator = allocator
        self.intent_path = intent_path
        self.state = state
        self.gateway = gateway
        self.events = events
        self.driver_name = driver_name
        self.device_class = device_class
        self.node_name = node_name
        self._listeners: list[Callable[[str, list[str]], None]] = []
        self._executions: collections.deque = collections.deque(
            maxlen=max_executions
        )
        self._lock = threading.RLock()
        self._executing = False
        self._inflight: frozenset[str] = frozenset()
        reg = registry if registry is not None else Registry()
        self._m_execs = Counter(
            "tpu_dra_defrag_exec_executions_total",
            "Defrag plan executions, by outcome (completed, rolled-back, "
            "stale-plan, refused, failed)",
            reg,
        )
        self._m_steps = Counter(
            "tpu_dra_defrag_exec_steps_total",
            "Defrag execution steps, by kind (intent-write/drain/replace/"
            "admit) and outcome",
            reg,
        )
        self._m_seconds = Histogram(
            "tpu_dra_defrag_exec_seconds",
            "End-to-end defrag plan execution latency (including "
            "rollback when one runs)",
            reg,
        )
        self._m_last_ts = Gauge(
            "tpu_dra_defrag_exec_last_execution_timestamp_seconds",
            "Wall-clock time of the most recently finished defrag "
            "execution (0 until one runs)",
            reg,
        )
        self._m_in_flight = Gauge(
            "tpu_dra_defrag_exec_in_flight",
            "1 while a defrag plan execution (or crash recovery) is in "
            "flight, else 0",
            reg,
        )
        for o in EXEC_OUTCOMES:
            self._m_execs.inc(0, outcome=o)
        for k in STEP_KINDS:
            for o in STEP_OUTCOMES:
                self._m_steps.inc(0, kind=k, outcome=o)
        self._m_last_ts.set(0)
        self._m_in_flight.set(0)
        planner.executor = self

    # -- reading -----------------------------------------------------------

    def export_executions(self) -> list[dict]:
        """Newest-last execution records (the ``executions`` view the
        planner splices into ``/debug/defrag``). JSON round-trip so the
        HTTP thread never serializes a record mid-mutation."""
        with self._lock:
            return json.loads(json.dumps(list(self._executions)))

    def add_migration_listener(
        self, fn: Callable[[str, list[str]], None]
    ) -> None:
        """``fn(claim_uid, device_names)`` is called after each mover's
        placement is applied (and again with the ORIGINAL names if the
        plan rolls back). A listener exception fails the migration —
        loss continuity for a training gang depends on the reshard
        actually happening, so it must not be fire-and-forget."""
        self._listeners.append(fn)

    def in_flight_uids(self) -> frozenset[str]:
        """Claim uids an in-flight execution is allowed to leave
        mid-transition (the auditor's resize-check exclusion)."""
        if not self._executing:
            return frozenset()
        return self._inflight

    def orphaned_intent(self) -> Optional[dict]:
        """The on-disk execution intent when NO execution is in flight —
        recovery/rollback should have cleared it, so its existence is
        drift (the auditor's ``defrag`` check reports it)."""
        if self._executing:
            return None
        doc = self._load_intent()
        if doc is not None and "error" not in doc:
            doc = dict(doc)
            doc["path"] = self.intent_path
        return doc

    # -- execution ---------------------------------------------------------

    def execute(
        self,
        plan: dict,
        claim: Optional[dict] = None,
        *,
        selectors: Optional[dict[str, list[Selector]]] = None,
        require_healthy: bool = False,
    ) -> dict:
        """Execute one ``planned`` plan; returns the execution record.

        ``claim``/``selectors``/``require_healthy`` are the stuck
        claim's own solve arguments when the caller has them (the admit
        step re-runs the exact solve that went unsat); without them the
        admit claim is synthesized from the plan. Raises
        :class:`StalePlanError` when the allocator moved under the plan,
        :class:`DefragExecutionError` after a successful rollback (the
        message says why) or a failed one (the message says the intent
        was left on disk). A simulated crash (``CrashPoint``) propagates
        with NO rollback — that is the point: :meth:`recover` on the
        restarted executor converges the on-disk intent.
        """
        t0 = time.monotonic()
        with self._lock:
            record = self._new_record(
                plan.get("planId", ""), plan.get("claim", {})
            )
            if self._executing:
                record["state"] = STATE_REFUSED
                record["detail"] = "an execution is already in flight"
                self._finish(record, t0, "refused")
                raise DefragExecutionError(record["detail"])
            if plan.get("outcome") != OUTCOME_PLANNED:
                record["state"] = STATE_REFUSED
                record["detail"] = (
                    f"plan outcome {plan.get('outcome')!r} is not "
                    f"executable (only {OUTCOME_PLANNED!r} plans are)"
                )
                self._finish(record, t0, "refused")
                raise DefragExecutionError(record["detail"])
            self._begin(record, plan)
            try:
                with self.allocator.snapshot():
                    try:
                        self._check_sig(plan)
                        intent = self._build_intent(
                            plan, claim, selectors, require_healthy
                        )
                    except StalePlanError as e:
                        record["state"] = STATE_REFUSED
                        record["detail"] = str(e)
                        self._finish(record, t0, "stale-plan")
                        raise
                    try:
                        self._write_intent(intent, record)
                        for mig in intent["migrations"]:
                            self._run_migration(intent, mig, record)
                            mig["status"] = "done"
                            atomic_write_json(self.intent_path, intent)
                        self._admit(intent, record)
                    except Exception as e:
                        self._fail_and_rollback(intent, record, t0, e)
                    # The admit solved a sanitized copy; hand the
                    # allocation back so the caller's claim reads
                    # exactly as a normal admission would have left it.
                    if claim is not None and "status" in intent["admitClaim"]:
                        claim["status"] = intent["admitClaim"]["status"]
                self._clear_intent()
                record["state"] = STATE_COMPLETED
                record["detail"] = (
                    f"executed {len(intent['migrations'])} migration(s) "
                    f"and admitted the {intent['wanted']}-chip gang"
                )
                self._finish(record, t0, "completed")
                return record
            finally:
                self._end()

    def recover(self) -> Optional[dict]:
        """Converge a crashed execution's on-disk intent: roll it
        FORWARD (each non-done migration re-runs or is recognized as
        already applied, then the stuck claim is admitted), or — when
        forward progress fails — roll the whole plan BACK. Returns the
        execution record, or None when there is no intent. Idempotent
        and re-entrant: a crash during recovery leaves an intent a later
        :meth:`recover` converges the same way. Call once at startup
        before enabling execution."""
        intent = self._load_intent()
        if intent is None:
            return None
        t0 = time.monotonic()
        with self._lock:
            record = self._new_record(
                intent.get("planId", ""), intent.get("claim", {})
            )
            record["recovered"] = True
            if "error" in intent:
                record["state"] = STATE_FAILED
                record["detail"] = intent["error"]
                self._finish(record, t0, "failed")
                raise DefragExecutionError(record["detail"])
            self._begin(record, intent)
            try:
                with self.allocator.snapshot():
                    try:
                        for mig in intent["migrations"]:
                            if mig.get("status") == "done":
                                # Crash can land between the done-write
                                # and the next step; resume is a no-op
                                # when the step finished cleanly.
                                self._resume(mig)
                                continue
                            self._recover_migration(intent, mig, record)
                            mig["status"] = "done"
                            atomic_write_json(self.intent_path, intent)
                        self._recover_admit(intent, record)
                    except Exception as e:
                        self._fail_and_rollback(intent, record, t0, e)
                self._clear_intent()
                record["state"] = STATE_COMPLETED
                record["detail"] = (
                    "crash recovery rolled the plan forward: "
                    f"{len(intent['migrations'])} migration(s) applied, "
                    f"{intent['wanted']}-chip gang admitted"
                )
                self._finish(record, t0, "completed")
                return record
            finally:
                self._end()

    def abort(self) -> Optional[dict]:
        """Operator escape hatch (runbook: aborting a stuck plan): roll
        the on-disk intent BACK without attempting forward progress —
        movers return to their original devices, drained replicas
        resume, the intent is cleared. Returns the execution record, or
        None when there is nothing to abort. Raises when the rollback
        itself fails (the intent stays for the auditor)."""
        intent = self._load_intent()
        if intent is None:
            return None
        t0 = time.monotonic()
        with self._lock:
            record = self._new_record(
                intent.get("planId", ""), intent.get("claim", {})
            )
            record["recovered"] = True
            if "error" in intent:
                record["state"] = STATE_FAILED
                record["detail"] = intent["error"]
                self._finish(record, t0, "failed")
                raise DefragExecutionError(record["detail"])
            self._begin(record, intent)
            try:
                with self.allocator.snapshot():
                    self._fail_and_rollback(
                        intent, record, t0,
                        DefragExecutionError("operator abort"),
                    )
            except DefragExecutionError:
                if record["state"] == STATE_ROLLED_BACK:
                    return record
                raise
            finally:
                self._end()

    # -- plan pinning ------------------------------------------------------

    def _check_sig(self, plan: dict) -> None:
        sig = plan.get("sig") or {}
        want = (sig.get("generation"), sig.get("reservationVersion"))
        cur = (
            self.allocator.index.generation,
            self.allocator.reservation_version,
        )
        if want != cur:
            raise StalePlanError(
                f"stale plan {plan.get('planId')}: computed against "
                f"generation={want[0]} reservationVersion={want[1]}, "
                f"allocator is at generation={cur[0]} "
                f"reservationVersion={cur[1]} — re-plan and retry"
            )

    def _build_intent(
        self, plan, claim, selectors, require_healthy,
    ) -> dict:
        migrations = []
        for mig in plan.get("migrations", []):
            uid = mig["claimUid"]
            held = self._holdings(uid)
            if {n for _, n in held} != set(mig["devices"]):
                raise StalePlanError(
                    f"stale plan {plan.get('planId')}: claim {uid} no "
                    "longer holds the devices the plan would move"
                )
            reqname = self._request_name(uid)
            migrations.append({
                "claimUid": uid,
                "devices": list(mig["devices"]),
                "to": list(mig["to"]),
                "toCoords": list(mig.get("toCoords", [])),
                "requestName": reqname,
                "originalResults": [
                    {"request": reqname, "driver": self.driver_name,
                     "pool": p, "device": n}
                    for p, n in sorted(held)
                ],
                "status": "pending",
            })
        if claim is not None:
            admit_claim = {
                "metadata": dict(claim.get("metadata", {})),
                "spec": claim.get("spec", {}),
            }
        else:
            admit_claim = self._synth_admit_claim(plan)
        return {
            "planId": plan.get("planId", ""),
            "ts": round(time.time(), 3),
            "node": self.node_name,
            "claim": dict(plan.get("claim", {})),
            "sliceId": plan.get("sliceId"),
            "wanted": plan.get("wanted", 0),
            "sig": plan.get("sig"),
            "admitClaim": admit_claim,
            "admitSelectors": _serialize_selectors(selectors),
            "requireHealthy": bool(require_healthy),
            "migrations": migrations,
        }

    def _synth_admit_claim(self, plan: dict) -> dict:
        c = plan.get("claim", {})
        return {
            "metadata": {
                "uid": c.get("uid", ""),
                "name": c.get("name", ""),
                "namespace": c.get("namespace", ""),
            },
            "spec": {"devices": {"requests": [{
                "name": "r0",
                "deviceClassName": self.device_class,
                "allocationMode": "ExactCount",
                "count": int(plan.get("wanted", 0)),
            }]}},
        }

    # -- steps -------------------------------------------------------------

    def _write_intent(self, intent: dict, record: dict) -> None:
        try:
            faults.fire("defrag.intent-write")
            atomic_write_json(self.intent_path, intent)
        except Exception as e:
            self._step(record, "intent-write", "", "failed", str(e))
            raise
        self._step(record, "intent-write", "", "ok",
                   f"execution intent checkpointed to {self.intent_path}")

    def _run_migration(self, intent, mig, record) -> None:
        uid = mig["claimUid"]
        try:
            faults.fire("defrag.drain")
            drained = []
            if self.gateway is not None:
                drained = self.gateway.drain_claim(
                    uid, reason=f"defrag {intent['planId']}"
                )
            mig["drainedReplicas"] = drained
        except Exception as e:
            self._step(record, "drain", uid, "failed", str(e))
            raise
        self._step(
            record, "drain", uid, "ok",
            f"drained {len(drained)} serving replica(s)" if drained
            else "no serving replicas bound to this claim",
        )
        try:
            faults.fire("defrag.replace")
            self._replace(intent, mig)
        except Exception as e:
            self._step(record, "replace", uid, "failed", str(e))
            raise
        self._resume(mig)
        self._step(
            record, "replace", uid, "ok",
            f"re-placed onto {len(mig['to'])} device(s): "
            + ", ".join(mig["to"]),
        )

    def _replace(self, intent, mig) -> None:
        """Move one blocker: deallocate, re-solve pinned to the planned
        destination cells, rewrite node state, notify listeners. Any
        failure restores the allocator to the mover's original devices
        before re-raising (the caller then rolls the whole plan back)."""
        uid = mig["claimUid"]
        self.allocator.deallocate(uid)
        synth = {
            "metadata": {
                "uid": uid,
                "name": f"defrag-move-{uid}",
                "namespace": "",
            },
            "spec": {"devices": {"requests": [{
                "name": mig["requestName"],
                "deviceClassName": self.device_class,
                "allocationMode": "ExactCount",
                "count": len(mig["to"]),
            }]}},
        }
        sels = []
        if intent.get("sliceId") is not None:
            sels.append(Selector("sliceId", "eq", str(intent["sliceId"])))
        if mig.get("toCoords"):
            sels.append(Selector("coord", "in", list(mig["toCoords"])))
        try:
            self.allocator.allocate(
                synth, selectors={mig["requestName"]: sels}
            )
        except Exception:
            self.allocator.restore_reservations(
                uid, mig["originalResults"]
            )
            raise
        results = synth["status"]["allocation"]["devices"]["results"]
        mig["newResults"] = results
        try:
            if (
                self.state is not None
                and self.state.gang_view(uid) is not None
            ):
                self.state.resize_claim(uid, results)
            self._notify(uid, [r["device"] for r in results])
        except Exception:
            # The allocator restore must not mask the real error; a
            # failure in IT leaves the intent for the auditor instead.
            try:
                self.allocator.deallocate(uid)
                self.allocator.restore_reservations(
                    uid, mig["originalResults"]
                )
            except Exception:
                logger.exception(
                    "defrag: allocator restore failed for %s", uid
                )
            raise

    def _admit(self, intent, record) -> None:
        uid = intent["claim"].get("uid", "")
        claim = intent["admitClaim"]
        selectors = _deserialize_selectors(intent.get("admitSelectors"))
        try:
            faults.fire("defrag.admit")
            self.allocator.allocate(
                claim,
                selectors=selectors,
                require_healthy=intent.get("requireHealthy", False),
            )
        except Exception as e:
            self._step(record, "admit", uid, "failed", str(e))
            raise
        self._step(
            record, "admit", uid, "ok",
            f"admitted the {intent['wanted']}-chip gang onto slice "
            f"{intent.get('sliceId')}",
        )

    # -- crash recovery ----------------------------------------------------

    def _recover_migration(self, intent, mig, record) -> None:
        uid = mig["claimUid"]
        held = {n for _, n in self._holdings(uid)}
        if held == set(mig["to"]):
            # The re-place landed before the crash: converge node state
            # and listeners onto it, resume replicas, and move on.
            results = [
                {"request": mig["requestName"], "driver": self.driver_name,
                 "pool": p, "device": n}
                for p, n in sorted(self._holdings(uid))
            ]
            mig["newResults"] = results
            if self.state is not None:
                view = self.state.gang_view(uid)
                if view is not None and {
                    n for n, _ in view["devices"]
                } != set(mig["to"]):
                    self.state.resize_claim(uid, results)
            self._notify(uid, sorted(mig["to"]))
            self._resume(mig)
            self._step(record, "replace", uid, "ok",
                       "recovered: planned placement already applied")
            return
        if held != set(mig["devices"]):
            # Crash mid-transition (e.g. inside the node-state resize):
            # pin the allocator back to the originals so the re-run
            # starts from a clean slate. restore_reservations is
            # idempotent and skips devices held by others.
            self.allocator.deallocate(uid)
            self.allocator.restore_reservations(
                uid, mig["originalResults"]
            )
        self._run_migration(intent, mig, record)

    def _recover_admit(self, intent, record) -> None:
        uid = intent["claim"].get("uid", "")
        held = self._holdings(uid)
        if len(held) >= int(intent.get("wanted", 0)) and held:
            self._step(record, "admit", uid, "ok",
                       "recovered: gang already admitted")
            return
        self._admit(intent, record)

    # -- rollback ----------------------------------------------------------

    def _fail_and_rollback(self, intent, record, t0, err) -> None:
        """Roll the whole plan back and raise DefragExecutionError; on
        rollback failure, record the execution as failed and leave the
        intent on disk for the auditor."""
        try:
            self._rollback(intent, record)
        except Exception as re:
            record["state"] = STATE_FAILED
            record["detail"] = f"{err}; rollback failed: {re}"
            self._finish(record, t0, "failed")
            raise DefragExecutionError(record["detail"]) from err
        record["state"] = STATE_ROLLED_BACK
        record["detail"] = f"rolled back: {err}"
        self._finish(record, t0, "rolled-back")
        raise DefragExecutionError(record["detail"]) from err

    def _rollback(self, intent, record) -> None:
        failures = []
        with contextlib.suppress(Exception):
            # The admit step is last, so reaching rollback means it did
            # not commit; dropping any partial reservation is a no-op in
            # the common case and a repair after a recovery re-admit.
            self.allocator.deallocate(intent["claim"].get("uid", ""))
        for mig in reversed(intent.get("migrations", [])):
            entry = {
                "claimUid": mig["claimUid"],
                "outcome": "ok",
                "detail": "restored original placement",
            }
            try:
                self._revert_mover(mig)
            except Exception as e:
                logger.exception(
                    "defrag rollback failed for mover %s",
                    mig["claimUid"],
                )
                entry["outcome"] = "failed"
                entry["detail"] = str(e)
                failures.append(mig["claimUid"])
            record["rollbacks"].append(entry)
        if failures:
            raise DefragExecutionError(
                f"rollback failed for mover(s) {', '.join(failures)}; "
                f"execution intent left at {self.intent_path} "
                "(surfaces as the auditor's 'defrag' finding)"
            )
        self._clear_intent()

    def _revert_mover(self, mig) -> None:
        """Return one mover to its original devices. Idempotent: safe on
        a mover that never moved (the allocator ends where it started)
        and on one that fully moved (reservations, node state, replicas
        and listeners all return)."""
        uid = mig["claimUid"]
        self.allocator.deallocate(uid)
        self.allocator.restore_reservations(uid, mig["originalResults"])
        if self.state is not None:
            view = self.state.gang_view(uid)
            if view is not None and {
                n for n, _ in view["devices"]
            } == set(mig["to"]) and set(mig["to"]) != set(mig["devices"]):
                self.state.resize_claim(uid, mig["originalResults"])
        self._notify(
            uid, [r["device"] for r in mig["originalResults"]]
        )
        self._resume(mig)

    # -- plumbing ----------------------------------------------------------

    def _holdings(self, uid: str) -> list[tuple[str, str]]:
        """(pool, device) pairs the allocator currently reserves for
        ``uid``. Called under snapshot(), which holds the allocator
        lock, so the read is coherent."""
        return [
            (p, n)
            for (p, n), holder in self.allocator._reservations.items()
            if holder == uid
        ]

    def _request_name(self, uid: str) -> str:
        if self.state is not None:
            view = self.state.gang_view(uid)
            if view and view.get("request_names"):
                return view["request_names"][0]
        return "r0"

    def _notify(self, uid: str, devices: list[str]) -> None:
        for fn in self._listeners:
            fn(uid, list(devices))

    def _resume(self, mig) -> None:
        if self.gateway is not None:
            self.gateway.resume_claim(mig["claimUid"])

    def _step(self, record, kind, uid, outcome, detail) -> None:
        record["steps"].append({
            "kind": kind,
            "claimUid": uid,
            "outcome": outcome,
            "detail": detail,
        })
        self._m_steps.inc(kind=kind, outcome=outcome)

    def _new_record(self, plan_id: str, claim: dict) -> dict:
        return {
            "planId": plan_id,
            "ts": round(time.time(), 3),
            "claim": {
                "uid": claim.get("uid", ""),
                "name": claim.get("name", ""),
                "namespace": claim.get("namespace", ""),
            },
            "state": STATE_IN_FLIGHT,
            "detail": "",
            "steps": [],
            "rollbacks": [],
        }

    def _begin(self, record: dict, plan_or_intent: dict) -> None:
        self._executions.append(record)
        self._executing = True
        uids = {
            m["claimUid"] for m in plan_or_intent.get("migrations", [])
        }
        uids.add(record["claim"].get("uid", ""))
        self._inflight = frozenset(uids)
        self._m_in_flight.set(1)
        self._emit(record, "DefragExecutionStarted", warning=False)

    def _end(self) -> None:
        self._executing = False
        self._inflight = frozenset()
        self._m_in_flight.set(0)

    def _finish(self, record: dict, t0: float, outcome: str) -> None:
        self._m_execs.inc(outcome=outcome)
        self._m_seconds.observe(time.monotonic() - t0)
        self._m_last_ts.set(time.time())
        reason = {
            STATE_COMPLETED: "DefragExecutionCompleted",
            STATE_ROLLED_BACK: "DefragExecutionRolledBack",
            STATE_REFUSED: "DefragExecutionRefused",
            STATE_FAILED: "DefragExecutionFailed",
        }.get(record["state"], "DefragExecutionFinished")
        self._emit(record, reason,
                   warning=record["state"] != STATE_COMPLETED)

    def _emit(self, record: dict, reason: str, warning: bool) -> None:
        if self.events is None or not record["claim"].get("name"):
            return
        from .events import ObjectRef

        ref = ObjectRef.claim(
            record["claim"]["name"],
            record["claim"].get("namespace", ""),
            record["claim"].get("uid", ""),
        )
        msg = f"defrag plan {record['planId']}: {record['detail'] or record['state']}"
        try:
            if warning:
                self.events.warning(ref, reason, msg)
            else:
                self.events.normal(ref, reason, msg)
        except Exception:
            logger.exception("defrag event emit failed")

    def _load_intent(self) -> Optional[dict]:
        if not os.path.exists(self.intent_path):
            return None
        try:
            with open(self.intent_path) as f:
                return json.load(f)
        except Exception as e:
            return {
                "error": f"unreadable execution intent: {e}",
                "path": self.intent_path,
            }

    def _clear_intent(self) -> None:
        with contextlib.suppress(FileNotFoundError):
            os.remove(self.intent_path)


def _serialize_selectors(selectors) -> Optional[dict]:
    if not selectors:
        return None
    return {
        req: [
            {"attribute": s.attribute, "op": s.op, "value": s.value}
            for s in sels
        ]
        for req, sels in selectors.items()
    }


def _deserialize_selectors(doc) -> Optional[dict]:
    if not doc:
        return None
    return {
        req: [
            Selector(s["attribute"], s["op"], s["value"]) for s in sels
        ]
        for req, sels in doc.items()
    }
