"""resource.k8s.io API-version negotiation and wire conversion.

The reference pins a single API generation at build time (go.mod:5 pins
k8s.io/api with resource/v1alpha3; the vendored kubeletplugin hardcodes the
matching gRPC service, vendor/k8s.io/dynamic-resource-allocation/
kubeletplugin/draplugin.go:320-335) and so never faces version skew: a
cluster either serves exactly that generation or the driver does not work.
This driver instead discovers the served ``resource.k8s.io`` version at
startup and speaks it on the wire, because the clusters it targets straddle
THREE boundaries: k8s 1.31 serves only ``v1alpha3``, 1.32 serves
``v1beta1`` (and typically not v1alpha3 at all), 1.33 adds ``v1beta2``
with a reshaped Device and claim-request schema, and 1.34 GAs that shape
as ``v1``.

Design: every object INSIDE the driver uses one canonical shape — the
v1beta1 one, where device capacities are ``{"value": "<quantity>"}``
(DeviceCapacity) rather than v1alpha3's bare quantity strings. Conversion
happens only at the wire boundary:

- ``slice_to_wire``   canonical ResourceSlice -> served dialect
- ``slice_from_wire`` served dialect -> canonical (tolerant: accepts either
  shape, so mixed-version transcripts and already-canonical fakes both work)
- ``claim_to_wire`` / ``claim_from_wire`` — restamp for v1alpha3/v1beta1
  (identical claim structure); wrap/unwrap the ``exactly`` request
  nesting for v1beta2/v1. DeviceClass is identical everywhere.

``sharedCounters`` / ``consumesCounters`` (the partitionable-devices
extension this driver publishes for sub-chip TensorCore exclusivity) carry
``{"value": ...}`` counters in EVERY dialect: the older generations never
defined them upstream — they are the 1.33-era shape, passed through
untouched so the allocator sees one form.
"""

from __future__ import annotations

import dataclasses
import logging
import time

from .client import GVR, KubeClient

logger = logging.getLogger(__name__)

GROUP = "resource.k8s.io"

# Dialects this driver can speak, newest (preferred) first. The deltas:
#
# - v1alpha3 (k8s 1.31): device capacities are BARE quantity strings
#   (types.go:220); devices wrap their payload in ``basic``.
# - v1beta1 (k8s 1.32): capacities become DeviceCapacity
#   ``{"value": ...}``; ``basic`` wrapper retained. This is the
#   CANONICAL in-memory shape.
# - v1beta2 (k8s 1.33): the ``basic`` wrapper is REMOVED (attributes/
#   capacity/consumesCounters live directly on the Device), and claim
#   requests nest their payload under ``exactly`` (ExactDeviceRequest)
#   to make room for ``firstAvailable`` prioritized-list requests.
# - v1 (k8s 1.34, DRA GA): structurally v1beta2 — the GA promotion kept
#   the flattened Device and exactly-nested request shapes.
SUPPORTED_VERSIONS = ("v1", "v1beta2", "v1beta1", "v1alpha3")

# Dialects whose wire shape is the flattened/exactly-nested one.
_FLAT_DEVICE_VERSIONS = ("v1", "v1beta2")

# Canonical apiVersion stamp for in-memory objects.
CANONICAL_VERSION = "v1beta1"

# The version assumed when discovery is impossible (no client, or the
# group is absent): the oldest supported one, matching the clusters the
# original deploy scripts targeted.
DEFAULT_VERSION = "v1alpha3"


@dataclasses.dataclass(frozen=True)
class ResourceApi:
    """One served dialect of the resource.k8s.io group."""

    version: str = DEFAULT_VERSION

    def __post_init__(self):
        if self.version not in SUPPORTED_VERSIONS:
            raise ValueError(
                f"unsupported resource.k8s.io version {self.version!r}; "
                f"supported: {SUPPORTED_VERSIONS}"
            )

    # -- addressing --------------------------------------------------------

    @property
    def api_version(self) -> str:
        return f"{GROUP}/{self.version}"

    @property
    def slices(self) -> GVR:
        return GVR(self.api_version, "resourceslices")

    @property
    def claims(self) -> GVR:
        return GVR(self.api_version, "resourceclaims", namespaced=True)

    @property
    def device_classes(self) -> GVR:
        return GVR(self.api_version, "deviceclasses")

    # -- discovery ---------------------------------------------------------

    @classmethod
    def discover(
        cls,
        client: KubeClient | None,
        retries: int = 2,
        retry_delay: float = 1.0,
    ) -> "ResourceApi":
        """Pick the newest supported dialect the server serves.

        GET ``/apis/resource.k8s.io`` (k8s API group discovery). Transient
        failures (the apiserver is routinely unreachable for a beat during
        node bring-up) are retried; only then does it fall back to
        ``DEFAULT_VERSION`` — loudly — so a driver pointed at a broken
        server still starts and surfaces the real failure on first write.
        Long outages are covered by the NotFound-triggered re-discovery in
        the slice controller and claim fetch path, so a wrong fallback is
        corrected without a pod restart.
        """
        if client is None:
            return cls(DEFAULT_VERSION)
        attempt = 0
        while True:
            try:
                served = client.api_group_versions(GROUP)
                break
            except Exception as e:
                if attempt >= retries:
                    logger.warning(
                        "discovery of /apis/%s failed after %d attempts "
                        "(%s); assuming %s",
                        GROUP, attempt + 1, e, DEFAULT_VERSION,
                    )
                    return cls(DEFAULT_VERSION)
                attempt += 1
                time.sleep(retry_delay)
        return cls._pick(served)

    @classmethod
    def try_discover(cls, client: KubeClient | None) -> "ResourceApi | None":
        """Discovery with NO fallback: a positive answer or None.

        For the NotFound-triggered re-discovery paths, where the fallback
        semantics of ``discover`` would be actively harmful — a transient
        discovery failure must not masquerade as "the server moved to
        v1alpha3" and re-target a correctly-negotiated driver onto a
        dialect the server never served."""
        if client is None:
            return None
        try:
            served = client.api_group_versions(GROUP)
        except Exception as e:
            logger.warning("re-discovery of /apis/%s failed (%s)", GROUP, e)
            return None
        for v in SUPPORTED_VERSIONS:
            if v in served:
                return cls(v)
        return None

    @classmethod
    def _pick(cls, served: list) -> "ResourceApi":
        for v in SUPPORTED_VERSIONS:
            if v in served:
                api = cls(v)
                logger.info(
                    "resource.k8s.io served versions %s; speaking %s",
                    served, api.api_version,
                )
                return api
        logger.warning(
            "server serves resource.k8s.io versions %s, none of which this "
            "driver supports (%s); assuming %s",
            served, SUPPORTED_VERSIONS, DEFAULT_VERSION,
        )
        return cls(DEFAULT_VERSION)

    # -- ResourceSlice conversion ------------------------------------------

    def slice_to_wire(self, obj: dict) -> dict:
        """Canonical slice -> the served dialect.

        v1beta1 IS the canonical shape, so only the apiVersion is
        stamped; v1alpha3 additionally unwraps device capacities to bare
        quantity strings (v1alpha3 types.go:220); v1beta2 removes the
        ``basic`` device wrapper (attributes/capacity/consumesCounters
        inline on the Device).
        """
        out = dict(obj)
        out["apiVersion"] = self.api_version
        if self.version == "v1alpha3":
            out["spec"] = _map_device_capacity(obj.get("spec", {}), _unwrap)
        elif self.version in _FLAT_DEVICE_VERSIONS:
            out["spec"] = _map_devices(obj.get("spec", {}), _flatten_device)
        return out

    def slice_from_wire(self, obj: dict) -> dict:
        """Served dialect -> canonical. Tolerant of every dialect's shape
        (idempotent on already-canonical objects), so fakes and mixed
        transcripts need no special-casing."""
        out = dict(obj)
        out["apiVersion"] = f"{GROUP}/{CANONICAL_VERSION}"
        spec = _map_devices(obj.get("spec", {}), _nest_device)
        out["spec"] = _map_device_capacity(spec, _wrap)
        return out

    # -- ResourceClaim / DeviceClass conversion ----------------------------

    def claim_to_wire(self, obj: dict) -> dict:
        """Canonical claim -> the served dialect. v1alpha3/v1beta1 share
        the claim structure (restamp only); v1beta2 nests each request's
        payload under ``exactly`` (ExactDeviceRequest), the shape that
        makes room for prioritized-list requests."""
        out = dict(obj)
        out["apiVersion"] = self.api_version
        if self.version in _FLAT_DEVICE_VERSIONS:
            out["spec"] = _map_requests(obj.get("spec"), _wrap_exactly)
        return out

    def class_to_wire(self, obj: dict) -> dict:
        """DeviceClass is structurally identical across all three
        dialects; restamp the apiVersion only."""
        out = dict(obj)
        out["apiVersion"] = self.api_version
        return out

    def claim_from_wire(self, obj: dict) -> dict:
        """Wire claim -> canonical: flatten v1beta2 ``exactly`` wrappers
        (tolerant; ``firstAvailable`` prioritized lists pass through
        untouched — no older dialect can express them)."""
        out = dict(obj)
        out["apiVersion"] = f"{GROUP}/{CANONICAL_VERSION}"
        out["spec"] = _map_requests(obj.get("spec"), _unwrap_exactly)
        return out


def _wrap(value) -> dict:
    """Bare quantity -> DeviceCapacity. Idempotent on wrapped values."""
    if isinstance(value, dict):
        return value
    return {"value": str(value)}


def _unwrap(value):
    """DeviceCapacity -> bare quantity string. Idempotent on bare values."""
    if isinstance(value, dict):
        return value.get("value", "")
    return value


def _flatten_device(dev: dict) -> dict:
    """Canonical device -> v1beta2: hoist the ``basic`` payload onto the
    Device itself. Idempotent on already-flat devices."""
    basic = dev.get("basic")
    if not isinstance(basic, dict):
        return dev
    out = {k: v for k, v in dev.items() if k != "basic"}
    out.update(basic)
    return out


_BASIC_FIELDS = ("attributes", "capacity", "consumesCounters")


def _nest_device(dev: dict) -> dict:
    """v1beta2 device -> canonical: re-nest the payload under ``basic``.
    Idempotent on devices that already carry the wrapper."""
    if "basic" in dev or not any(f in dev for f in _BASIC_FIELDS):
        return dev
    out = {k: v for k, v in dev.items() if k not in _BASIC_FIELDS}
    out["basic"] = {f: dev[f] for f in _BASIC_FIELDS if f in dev}
    return out


def _map_devices(spec: dict, fn) -> dict:
    devices = spec.get("devices")
    if not devices or not isinstance(devices, list):
        return spec
    new_devices = [fn(d) if isinstance(d, dict) else d for d in devices]
    if new_devices == devices:
        return spec
    out = dict(spec)
    out["devices"] = new_devices
    return out


def _wrap_exactly(req: dict) -> dict:
    """Canonical flat request -> v1beta2 {name, exactly: {...}}.
    Requests already in v1beta2 form (exactly/firstAvailable) pass
    through."""
    if "exactly" in req or "firstAvailable" in req:
        return req
    payload = {k: v for k, v in req.items() if k != "name"}
    out = {"name": req.get("name", "")}
    if payload:
        out["exactly"] = payload
    return out


def _unwrap_exactly(req: dict) -> dict:
    """v1beta2 {name, exactly: {...}} -> canonical flat request."""
    exactly = req.get("exactly")
    if not isinstance(exactly, dict):
        return req
    out = {k: v for k, v in req.items() if k != "exactly"}
    out.update(exactly)
    return out


def _map_requests(spec, fn) -> dict:
    spec = spec if isinstance(spec, dict) else {}
    devices = spec.get("devices")
    if not isinstance(devices, dict):
        return spec
    requests = devices.get("requests")
    if not requests or not isinstance(requests, list):
        return spec
    new_requests = [fn(r) if isinstance(r, dict) else r for r in requests]
    if new_requests == requests:
        return spec
    out = dict(spec)
    out["devices"] = dict(devices)
    out["devices"]["requests"] = new_requests
    return out


def _map_device_capacity(spec: dict, fn) -> dict:
    """Rewrite every ``devices[].basic.capacity`` value through ``fn``,
    copying only the paths touched (slices are shared with callers)."""
    devices = spec.get("devices")
    if not devices:
        return spec
    new_devices = []
    changed = False
    for dev in devices:
        basic = dev.get("basic") or {}
        cap = basic.get("capacity")
        if not cap:
            new_devices.append(dev)
            continue
        new_cap = {k: fn(v) for k, v in cap.items()}
        if new_cap == cap:
            new_devices.append(dev)
            continue
        changed = True
        new_basic = dict(basic)
        new_basic["capacity"] = new_cap
        new_dev = dict(dev)
        new_dev["basic"] = new_basic
        new_devices.append(new_dev)
    if not changed:
        return spec
    out = dict(spec)
    out["devices"] = new_devices
    return out
