#!/bin/sh
# Regenerate protobuf message code. grpc_tools is not installed, so only
# message classes are generated; the gRPC service wiring is hand-written in
# k8s_dra_driver_tpu/plugin/grpc_services.py against these messages.
set -e
cd "$(dirname "$0")"
protoc --python_out=. dra_v1alpha4.proto pluginregistration_v1.proto
echo "generated: dra_v1alpha4_pb2.py pluginregistration_v1_pb2.py"
