"""Minimal typed Kubernetes client: interface + in-memory fake + REST impl.

The reference leans on client-go + informers (pkg/flags/kubeclient.go:92-106,
cmd/nvidia-dra-controller/imex.go:233-287 in lengrongfu/k8s-dra-driver). No
Kubernetes client library is available here, so this package provides the
three pieces the driver actually needs, dict-native (k8s wire shape):

- ``KubeClient``     — get/list/create/update/delete/watch on any resource
- ``FakeKubeClient`` — in-memory store with resourceVersions and watch
  streams; the hermetic test seam the reference lacked (SURVEY.md §4)
- ``RealKubeClient`` — thin REST client (in-cluster service account or
  kubeconfig), stdlib http only

Objects are plain dicts; callers address resources with a ``GVR``
(group/version + plural), e.g. ``GVR("resource.k8s.io/v1alpha3",
"resourceslices")``.
"""

from __future__ import annotations

import abc
import copy
import dataclasses
import http.client
import itertools
import json
import logging
import os
import queue
import re
import ssl
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from typing import Callable, Iterator, Optional

from ..utils import faults
from ..utils.backoff import Backoff, TokenBucket, full_jitter
from .errors import (
    AlreadyExistsError,
    ApiError,
    ConflictError,
    NotFoundError,
)

logger = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class GVR:
    """GroupVersionResource: addresses a resource collection.

    ``api_version`` is "v1" for core or "group/version" otherwise;
    ``resource`` is the lowercase plural ("resourceslices").
    """

    api_version: str
    resource: str
    namespaced: bool = False

    @property
    def path_prefix(self) -> str:
        if "/" in self.api_version:
            return f"/apis/{self.api_version}"
        return f"/api/{self.api_version}"


# The resources this driver touches.
RESOURCE_SLICES = GVR("resource.k8s.io/v1alpha3", "resourceslices")
RESOURCE_CLAIMS = GVR("resource.k8s.io/v1alpha3", "resourceclaims", namespaced=True)
DEVICE_CLASSES = GVR("resource.k8s.io/v1alpha3", "deviceclasses")
NODES = GVR("v1", "nodes")
PODS = GVR("v1", "pods", namespaced=True)
EVENTS = GVR("v1", "events", namespaced=True)


def parse_label_selector(selector: str | None) -> dict[str, str]:
    """Parse "k=v,k2=v2" equality selectors (the only form we emit).

    Unsupported operators (!=, in, notin) raise rather than being silently
    mangled into their inverse — real API servers would honour them, and a
    fake that inverts their meaning is worse than one that refuses.
    """
    if not selector:
        return {}
    out = {}
    for part in selector.split(","):
        part = part.strip()
        if not part:
            continue
        if "!=" in part or re.search(r"\b(in|notin)\b", part):
            raise ValueError(
                f"unsupported label selector operator in {part!r}; "
                "only equality and existence are implemented"
            )
        if "=" in part:
            k, _, v = part.partition("=")
            out[k.strip()] = v.strip().lstrip("=")
        else:
            out[part] = None  # existence check
    return out


def matches_labels(obj: dict, selector: str | None) -> bool:
    wanted = parse_label_selector(selector)
    if not wanted:
        return True
    labels = (obj.get("metadata") or {}).get("labels") or {}
    for k, v in wanted.items():
        if k not in labels:
            return False
        if v is not None and labels[k] != v:
            return False
    return True


@dataclasses.dataclass
class WatchEvent:
    type: str  # ADDED | MODIFIED | DELETED | ERROR
    object: dict


class Watch:
    """A cancellable stream of WatchEvents."""

    def __init__(self):
        self._q: "queue.Queue[Optional[WatchEvent]]" = queue.Queue()
        self._stopped = threading.Event()
        # Optional teardown hook (e.g. closing a streaming HTTP response
        # so a blocked reader thread unblocks immediately).
        self._on_stop: Optional[Callable[[], None]] = None

    def stop(self) -> None:
        if not self._stopped.is_set():
            self._stopped.set()
            self._q.put(None)
            if self._on_stop is not None:
                try:
                    self._on_stop()
                except Exception:
                    pass

    @property
    def stopped(self) -> bool:
        return self._stopped.is_set()

    def _emit(self, ev: WatchEvent) -> None:
        if not self._stopped.is_set():
            self._q.put(ev)

    def events(self, timeout: Optional[float] = None) -> Iterator[WatchEvent]:
        """Yield events until stopped; with a timeout, returns when idle."""
        while not self._stopped.is_set():
            try:
                ev = self._q.get(timeout=timeout)
            except queue.Empty:
                return
            if ev is None:
                return
            yield ev


class KubeClient(abc.ABC):
    """The API-server seam (role of client-go clientsets)."""

    @abc.abstractmethod
    def get(self, gvr: GVR, name: str, namespace: str = "") -> dict: ...

    @abc.abstractmethod
    def list(
        self,
        gvr: GVR,
        namespace: str = "",
        label_selector: str | None = None,
    ) -> list[dict]: ...

    @abc.abstractmethod
    def create(self, gvr: GVR, obj: dict, namespace: str = "") -> dict: ...

    @abc.abstractmethod
    def update(self, gvr: GVR, obj: dict, namespace: str = "") -> dict: ...

    @abc.abstractmethod
    def delete(self, gvr: GVR, name: str, namespace: str = "") -> None: ...

    @abc.abstractmethod
    def watch(
        self,
        gvr: GVR,
        namespace: str = "",
        label_selector: str | None = None,
    ) -> Watch: ...

    # -- conveniences shared by impls --------------------------------------

    def list_meta(
        self,
        gvr: GVR,
        namespace: str = "",
        label_selector: str | None = None,
    ) -> list[tuple[str, str]]:
        """(name, resourceVersion) pairs for a collection — the cheap
        change-detection probe behind the allocator's incremental
        inventory index: comparing signatures per solve must not pay for
        deep-copying 10k device specs. Default: derived from ``list()``
        (full cost); ``FakeKubeClient`` overrides with a copy-free scan."""
        out = []
        for obj in self.list(gvr, namespace, label_selector):
            md = obj.get("metadata") or {}
            out.append((md.get("name", ""), md.get("resourceVersion", "")))
        return out

    def api_group_versions(self, group: str) -> list[str]:
        """Versions the server serves for an API group, preferred first
        (k8s group discovery, GET ``/apis/<group>``). Empty when the group
        is not served. Default: unknown — callers fall back to their
        pinned default version."""
        return []

    def close(self) -> None:
        """Release client resources (stop watches, join poll threads).

        Default no-op: the fake client's watches are push-driven and own no
        threads. ``RealKubeClient`` overrides this.
        """

    def apply(self, gvr: GVR, obj: dict, namespace: str = "") -> dict:
        """Create-or-update by name (server-side-apply-lite)."""
        name = obj["metadata"]["name"]
        try:
            existing = self.get(gvr, name, namespace)
        except NotFoundError:
            return self.create(gvr, obj, namespace)
        merged = copy.deepcopy(obj)
        merged["metadata"]["resourceVersion"] = existing["metadata"].get(
            "resourceVersion", ""
        )
        return self.update(gvr, merged, namespace)


# ---------------------------------------------------------------------------
# Fake
# ---------------------------------------------------------------------------


class FakeKubeClient(KubeClient):
    """In-memory API server with resourceVersion + watch semantics."""

    def __init__(self):
        self._lock = threading.RLock()
        # (gvr.resource, namespace, name) -> object
        self._store: dict[tuple[str, str, str], dict] = {}
        self._rv = itertools.count(1)
        # (gvr.resource) -> list of (namespace-filter, selector, Watch)
        self._watches: list[tuple[str, str, Optional[str], Watch]] = []
        # Optional fault injection: callable(verb, gvr, name) -> Exception|None
        self.fault_injector: Optional[Callable[[str, GVR, str], Optional[Exception]]] = None
        # group -> served versions (preferred first). Tests shrink this to
        # impersonate one cluster generation: a 1.31 server is
        # {"resource.k8s.io": ["v1alpha3"]}, a 1.32+ one ["v1beta1"].
        # Requests addressed to an unserved group version 404, as the real
        # API server's would.
        self.served_api_versions: dict[str, list[str]] = {
            "resource.k8s.io": ["v1beta1", "v1alpha3"],
        }
        # Apply upstream structural validation (kube/schema.py) to every
        # resource.k8s.io write, the way a real apiserver would (422).
        # The hermetic answer to "FakeKubeClient happily stores shapes a
        # real cluster rejects". Off-switch for tests that deliberately
        # store minimal stubs.
        self.validate_schemas = True

    # -- helpers -----------------------------------------------------------

    def _key(self, gvr: GVR, namespace: str, name: str):
        return (gvr.resource, namespace if gvr.namespaced else "", name)

    def _maybe_fault(self, verb: str, gvr: GVR, name: str):
        # Chaos seam: the global fault registry fires before the per-client
        # injector, so env-armed schedules reach the fake API server too.
        faults.fire(f"kube.{verb}")
        if "/" in gvr.api_version:
            group, _, version = gvr.api_version.partition("/")
            served = self.served_api_versions.get(group)
            if served is not None and version not in served:
                raise NotFoundError(
                    f"the server could not find the requested resource "
                    f"({gvr.api_version} {gvr.resource}; served: {served})"
                )
        if self.fault_injector is not None:
            err = self.fault_injector(verb, gvr, name)
            if err is not None:
                raise err

    def _maybe_validate(self, gvr: GVR, obj: dict):
        if not self.validate_schemas:
            return
        if not gvr.api_version.startswith("resource.k8s.io/"):
            return
        from .errors import InvalidError
        from .schema import SchemaError, validate_for_resource

        try:
            # Dispatch on the collection, as the real apiserver does — an
            # object omitting 'kind' must not bypass validation.
            validate_for_resource(gvr.resource, obj)
        except SchemaError as e:
            raise InvalidError(str(e)) from e

    def _notify(self, gvr: GVR, ev_type: str, obj: dict):
        ns = (obj.get("metadata") or {}).get("namespace", "")
        for res, wns, selector, w in list(self._watches):
            if res != gvr.resource or w.stopped:
                continue
            if gvr.namespaced and wns and wns != ns:
                continue
            if not matches_labels(obj, selector):
                continue
            w._emit(WatchEvent(ev_type, copy.deepcopy(obj)))

    # -- KubeClient --------------------------------------------------------

    def get(self, gvr: GVR, name: str, namespace: str = "") -> dict:
        self._maybe_fault("get", gvr, name)
        with self._lock:
            obj = self._store.get(self._key(gvr, namespace, name))
            if obj is None:
                raise NotFoundError(f"{gvr.resource}/{name} not found")
            return copy.deepcopy(obj)

    def list(
        self,
        gvr: GVR,
        namespace: str = "",
        label_selector: str | None = None,
    ) -> list[dict]:
        self._maybe_fault("list", gvr, "")
        with self._lock:
            out = []
            for (res, ns, _), obj in sorted(self._store.items()):
                if res != gvr.resource:
                    continue
                if gvr.namespaced and namespace and ns != namespace:
                    continue
                if not matches_labels(obj, label_selector):
                    continue
                out.append(copy.deepcopy(obj))
            return out

    def create(self, gvr: GVR, obj: dict, namespace: str = "") -> dict:
        name = obj["metadata"]["name"]
        self._maybe_fault("create", gvr, name)
        self._maybe_validate(gvr, obj)
        with self._lock:
            key = self._key(gvr, namespace or obj["metadata"].get("namespace", ""), name)
            if key in self._store:
                raise AlreadyExistsError(f"{gvr.resource}/{name} already exists")
            stored = copy.deepcopy(obj)
            md = stored.setdefault("metadata", {})
            md["resourceVersion"] = str(next(self._rv))
            md.setdefault("uid", f"uid-{md['resourceVersion']}")
            if gvr.namespaced:
                md.setdefault("namespace", namespace)
            self._store[key] = stored
            self._notify(gvr, "ADDED", stored)
            return copy.deepcopy(stored)

    def update(self, gvr: GVR, obj: dict, namespace: str = "") -> dict:
        name = obj["metadata"]["name"]
        self._maybe_fault("update", gvr, name)
        self._maybe_validate(gvr, obj)
        with self._lock:
            key = self._key(gvr, namespace or obj["metadata"].get("namespace", ""), name)
            existing = self._store.get(key)
            if existing is None:
                raise NotFoundError(f"{gvr.resource}/{name} not found")
            rv = obj["metadata"].get("resourceVersion", "")
            if rv and rv != existing["metadata"]["resourceVersion"]:
                raise ConflictError(
                    f"{gvr.resource}/{name}: resourceVersion {rv} != "
                    f"{existing['metadata']['resourceVersion']}"
                )
            stored = copy.deepcopy(obj)
            stored["metadata"]["resourceVersion"] = str(next(self._rv))
            stored["metadata"].setdefault("uid", existing["metadata"].get("uid"))
            self._store[key] = stored
            self._notify(gvr, "MODIFIED", stored)
            return copy.deepcopy(stored)

    def delete(self, gvr: GVR, name: str, namespace: str = "") -> None:
        self._maybe_fault("delete", gvr, name)
        with self._lock:
            key = self._key(gvr, namespace, name)
            obj = self._store.pop(key, None)
            if obj is None:
                raise NotFoundError(f"{gvr.resource}/{name} not found")
            self._notify(gvr, "DELETED", obj)

    def list_meta(
        self,
        gvr: GVR,
        namespace: str = "",
        label_selector: str | None = None,
    ) -> list[tuple[str, str]]:
        # Same filters and fault site as list(), but no deep copies: the
        # point of the probe is to be cheap at 10k-device inventories.
        self._maybe_fault("list", gvr, "")
        with self._lock:
            out = []
            for (res, ns, _), obj in sorted(self._store.items()):
                if res != gvr.resource:
                    continue
                if gvr.namespaced and namespace and ns != namespace:
                    continue
                if not matches_labels(obj, label_selector):
                    continue
                md = obj.get("metadata") or {}
                out.append((md.get("name", ""), md.get("resourceVersion", "")))
            return out

    def watch(
        self,
        gvr: GVR,
        namespace: str = "",
        label_selector: str | None = None,
    ) -> Watch:
        self._maybe_fault("watch", gvr, "")
        w = Watch()
        with self._lock:
            # Seed with current state (informer-style list+watch).
            for obj in self.list(gvr, namespace, label_selector):
                w._emit(WatchEvent("ADDED", obj))
            self._watches.append((gvr.resource, namespace, label_selector, w))
        return w

    def api_group_versions(self, group: str) -> list[str]:
        return list(self.served_api_versions.get(group, []))


# ---------------------------------------------------------------------------
# Real REST client
# ---------------------------------------------------------------------------

SERVICE_ACCOUNT_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"


class _RelistNeeded(Exception):
    """Internal: the watch history was compacted (410 Gone) — resume
    requires a fresh list."""


@dataclasses.dataclass
class ExecAuthConfig:
    """users[].user.exec from a kubeconfig: an external credential plugin
    (gcloud's gke-gcloud-auth-plugin, aws-iam-authenticator, ...). The
    client-go ExecCredential protocol: run the command, read an
    ExecCredential JSON from stdout, use status.token or the client
    cert/key it returns."""

    command: str
    args: list = dataclasses.field(default_factory=list)
    env: dict = dataclasses.field(default_factory=dict)
    api_version: str = "client.authentication.k8s.io/v1"

    def run(self) -> dict:
        """Execute the plugin; returns the ExecCredential ``status``."""
        import subprocess

        env = dict(os.environ)
        env.update(self.env)
        # The protocol's handshake: tell the plugin which apiVersion we
        # speak and that no interactive terminal is available.
        env["KUBERNETES_EXEC_INFO"] = json.dumps({
            "kind": "ExecCredential",
            "apiVersion": self.api_version,
            "spec": {"interactive": False},
        })
        out = subprocess.run(
            [self.command, *self.args],
            env=env, capture_output=True, text=True, timeout=60,
        )
        if out.returncode != 0:
            raise RuntimeError(
                f"exec credential plugin {self.command!r} failed "
                f"(rc={out.returncode}): {out.stderr.strip()[:500]}"
            )
        try:
            cred = json.loads(out.stdout)
        except ValueError as e:
            raise RuntimeError(
                f"exec credential plugin {self.command!r} printed "
                "non-JSON output"
            ) from e
        if cred.get("kind") != "ExecCredential":
            raise RuntimeError(
                f"exec credential plugin {self.command!r} returned kind "
                f"{cred.get('kind')!r}, want ExecCredential"
            )
        return cred.get("status") or {}


def _b64_pem(data: str) -> str:
    import base64

    return base64.b64decode(data).decode()


@dataclasses.dataclass
class RestConfig:
    host: str
    token: str = ""
    ca_file: str = ""
    ca_data: str = ""            # PEM (kubeconfig certificate-authority-data)
    insecure: bool = False
    client_cert_file: str = ""
    client_key_file: str = ""
    client_cert_data: str = ""   # PEM (kubeconfig client-certificate-data)
    client_key_data: str = ""    # PEM (kubeconfig client-key-data)
    exec_auth: Optional[ExecAuthConfig] = None

    @classmethod
    def in_cluster(cls) -> "RestConfig":
        """In-cluster config from the mounted service account
        (role of rest.InClusterConfig, pkg/flags/kubeclient.go:80-84)."""
        host = os.environ.get("KUBERNETES_SERVICE_HOST", "kubernetes.default.svc")
        port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
        with open(os.path.join(SERVICE_ACCOUNT_DIR, "token")) as f:
            token = f.read().strip()
        return cls(
            host=f"https://{host}:{port}",
            token=token,
            ca_file=os.path.join(SERVICE_ACCOUNT_DIR, "ca.crt"),
        )

    @classmethod
    def from_kubeconfig(cls, path: str = "") -> "RestConfig":
        """Kubeconfig loader (current-context; role of clientcmd,
        pkg/flags/kubeclient.go:85-89). Understands every auth shape the
        clusters this repo's own scripts create actually emit: bearer
        tokens, client cert/key as files OR inline base64 ``*-data``
        (kind writes these), and exec credential plugins (GKE)."""
        import yaml

        path = path or os.environ.get(
            "KUBECONFIG", os.path.expanduser("~/.kube/config")
        )
        with open(path) as f:
            cfg = yaml.safe_load(f)
        ctx_name = cfg.get("current-context", "")
        ctx = next(
            c["context"] for c in cfg["contexts"] if c["name"] == ctx_name
        )
        cluster = next(
            c["cluster"] for c in cfg["clusters"] if c["name"] == ctx["cluster"]
        )
        user = next(
            u["user"] for u in cfg["users"] if u["name"] == ctx["user"]
        )
        exec_auth = None
        if "exec" in user:
            ex = user["exec"] or {}
            exec_auth = ExecAuthConfig(
                command=ex.get("command", ""),
                args=list(ex.get("args") or []),
                env={
                    e["name"]: e["value"] for e in (ex.get("env") or [])
                },
                api_version=ex.get(
                    "apiVersion", "client.authentication.k8s.io/v1"
                ),
            )
        return cls(
            host=cluster["server"],
            token=user.get("token", ""),
            ca_file=cluster.get("certificate-authority", ""),
            ca_data=_b64_pem(cluster.get("certificate-authority-data", "")),
            insecure=bool(cluster.get("insecure-skip-tls-verify", False)),
            client_cert_file=user.get("client-certificate", ""),
            client_key_file=user.get("client-key", ""),
            client_cert_data=_b64_pem(user.get("client-certificate-data", "")),
            client_key_data=_b64_pem(user.get("client-key-data", "")),
            exec_auth=exec_auth,
        )

    @classmethod
    def auto(cls) -> "RestConfig":
        if os.path.exists(os.path.join(SERVICE_ACCOUNT_DIR, "token")):
            return cls.in_cluster()
        return cls.from_kubeconfig()


class RealKubeClient(KubeClient):
    """REST client over stdlib urllib; JSON wire format.

    Watches stream over chunked ``?watch=true`` HTTP (the informer
    pattern, imex.go:233-287): list to seed, then consume newline-
    delimited watch events with resourceVersion resume, bookmark
    handling, and relist-on-410. ``watch_mode="poll"`` keeps the old
    list-diff poller as a fallback for API servers without watch
    support. All verbs pass a client-side QPS/burst token bucket
    (client-go flowcontrol analog, pkg/flags/kubeclient.go:49-64 —
    same defaults: QPS 5, burst 10; qps<=0 disables).
    """

    def __init__(
        self,
        config: Optional[RestConfig] = None,
        poll_interval: float = 10.0,
        qps: float = 5.0,
        burst: int = 10,
        watch_mode: str = "stream",
        list_page_size: int = 500,
        overload_retries: int = 4,
        registry=None,
    ):
        if watch_mode not in ("stream", "poll"):
            raise ValueError(
                f"watch_mode must be 'stream' or 'poll', got {watch_mode!r}"
            )
        # API-traffic metrics (client-go rest_client_requests_total analog);
        # a throwaway registry when none is given keeps call sites branchless.
        from ..utils.metrics import Counter, Registry

        reg = registry if registry is not None else Registry()
        self._m_requests = Counter(
            "tpu_dra_kube_api_requests_total",
            "Kubernetes API requests by verb and outcome code",
            reg,
        )
        self._m_retries = Counter(
            "tpu_dra_kube_api_retries_total",
            "Kubernetes API retries by trigger (overload code, reauth, "
            "watch reconnect)",
            reg,
        )
        self.config = config or RestConfig.auto()
        self.poll_interval = poll_interval
        self.watch_mode = watch_mode
        # Chunked lists (limit/continue, the informer pager's chunk size —
        # client-go's default is 500); 0 fetches whole collections at once.
        self.list_page_size = list_page_size
        # How many times a verb retries a 429/503 before surfacing it.
        self.overload_retries = overload_retries
        self._limiter = TokenBucket(qps=qps, burst=burst)
        self._auth_lock = threading.Lock()
        self._exec_expiry: Optional[float] = None  # epoch seconds, or None
        self._cred_files: list[str] = []  # materialized cert/key temp files
        if self.config.exec_auth is not None:
            self._refresh_exec_credentials()
        self._ssl_ctx = self._make_ssl_ctx()
        self._watch_threads: list[threading.Thread] = []
        self._watches: list[Watch] = []

    def close(self) -> None:
        """Stop every watch this client started and join the poll threads.

        Idempotent. Without this, an orphaned poller keeps hitting the (by
        then dead) API server and logging failures for the life of the
        process — the round-2 advisor caught exactly that in the test suite.
        """
        for w in self._watches:
            w.stop()
        for t in self._watch_threads:
            t.join(timeout=5)
        self._watches.clear()
        self._watch_threads.clear()
        for path in self._cred_files:
            try:
                os.unlink(path)
            except OSError:
                pass
        self._cred_files.clear()

    def __enter__(self) -> "RealKubeClient":
        return self

    def __exit__(self, *exc: object) -> None:
        self.close()

    def _make_ssl_ctx(self) -> Optional[ssl.SSLContext]:
        if not self.config.host.startswith("https"):
            return None
        if self.config.insecure:
            ctx = ssl.create_default_context()
            ctx.check_hostname = False
            ctx.verify_mode = ssl.CERT_NONE
        elif self.config.ca_data:
            ctx = ssl.create_default_context(cadata=self.config.ca_data)
        elif self.config.ca_file:
            ctx = ssl.create_default_context(cafile=self.config.ca_file)
        else:
            ctx = ssl.create_default_context()
        cert_file, key_file = self._client_chain_files()
        if cert_file:
            # mTLS: the client certificate IS the identity on kind/GKE
            # admin kubeconfigs (clientcmd analog: kubeclient.go:85-89).
            ctx.load_cert_chain(cert_file, key_file or None)
        return ctx

    def _client_chain_files(self) -> tuple[str, str]:
        """Client cert/key as file paths. Inline ``*-data`` PEM (what kind
        writes, and what exec plugins return) is materialized into 0600
        temp files — the ssl module loads chains from files only. Files
        from a previous materialization are removed first: load_cert_chain
        copies them into the context, so a superseded pair is pure leakage
        (one rotated key pair per exec refresh, forever)."""
        cfg = self.config
        for path in self._cred_files:
            try:
                os.unlink(path)
            except OSError:
                pass
        self._cred_files.clear()
        if cfg.client_cert_data:
            import tempfile

            def _write(pem: str, suffix: str) -> str:
                fd, path = tempfile.mkstemp(prefix="kubecred-", suffix=suffix)
                os.write(fd, pem.encode())
                os.close(fd)
                os.chmod(path, 0o600)
                self._cred_files.append(path)
                return path

            cert = _write(cfg.client_cert_data, ".crt")
            key = (
                _write(cfg.client_key_data, ".key")
                if cfg.client_key_data else ""
            )
            return cert, key
        return cfg.client_cert_file, cfg.client_key_file

    # -- exec credential plugins -------------------------------------------

    def _refresh_exec_credentials(self) -> None:
        """Run the kubeconfig's exec plugin and absorb its ExecCredential:
        bearer token and/or client cert rotation."""
        status = self.config.exec_auth.run()
        if status.get("token"):
            self.config.token = status["token"]
        if status.get("clientCertificateData"):
            self.config.client_cert_data = status["clientCertificateData"]
            self.config.client_key_data = status.get("clientKeyData", "")
        exp = status.get("expirationTimestamp")
        self._exec_expiry = None
        if exp:
            import datetime

            try:
                self._exec_expiry = datetime.datetime.fromisoformat(
                    exp.replace("Z", "+00:00")
                ).timestamp()
            except ValueError:
                logger.warning(
                    "exec plugin returned unparseable expirationTimestamp "
                    "%r; credentials will not auto-refresh", exp,
                )

    def _maybe_refresh_exec(self) -> None:
        """Re-run the exec plugin shortly before its credential expires
        (client-go refreshes on expiry too; without this, long-lived
        watches outlive a GKE token within the hour).

        A FAILED refresh must not abort the caller's verb: the refresh
        fires 60s early precisely so the cached token is still good, so
        log, defer the next attempt (no once-per-request plugin stalls
        under the auth lock), and proceed with what we have. If the
        cached token really is dead, the 401 path below forces the issue.
        """
        if self.config.exec_auth is None or self._exec_expiry is None:
            return
        if time.time() <= self._exec_expiry - 60:
            return
        with self._auth_lock:
            if time.time() <= self._exec_expiry - 60:
                return
            try:
                self._refresh_exec_credentials()
                self._ssl_ctx = self._make_ssl_ctx()
            except Exception as e:
                logger.warning(
                    "exec credential refresh failed (%s); keeping cached "
                    "credentials and retrying in 30s", e,
                )
                self._exec_expiry = time.time() + 90  # next try in ~30s

    def _force_refresh_exec(self) -> None:
        """401-triggered re-exec (client-go re-runs the plugin on
        Unauthorized): the only refresh path when the plugin never
        returns an expirationTimestamp. Failures propagate — with the
        server rejecting the cached token, there is nothing to fall
        back to."""
        with self._auth_lock:
            self._refresh_exec_credentials()
            self._ssl_ctx = self._make_ssl_ctx()

    def _url(self, gvr: GVR, namespace: str, name: str = "", query: dict | None = None) -> str:
        parts = [self.config.host.rstrip("/"), gvr.path_prefix.lstrip("/")]
        if gvr.namespaced and namespace:
            parts += ["namespaces", namespace]
        parts.append(gvr.resource)
        if name:
            parts.append(name)
        url = "/".join(parts)
        if query:
            url += "?" + urllib.parse.urlencode(query)
        return url

    def _request(self, method: str, url: str, body: dict | None = None,
                 accept: str | None = None) -> dict:
        """One API verb, with overload retries: 429/503 responses are
        retried after the server's Retry-After (priority-and-fairness load
        shedding tells clients exactly when to come back; ignoring it turns
        one overloaded relist into a retry storm). Bounded — the error
        surfaces after ``overload_retries`` attempts."""
        attempts = 0
        reauthed = False
        while True:
            try:
                out = self._request_once(method, url, body, accept=accept)
                self._m_requests.inc(verb=method, code="2xx")
                return out
            except ApiError as e:
                self._m_requests.inc(verb=method, code=str(e.code))
                if (
                    e.code == 401
                    and self.config.exec_auth is not None
                    and not reauthed
                ):
                    # Token died without (or despite) an expiry hint:
                    # re-exec the plugin once and retry (client-go's
                    # Unauthorized handling).
                    reauthed = True
                    logger.warning(
                        "%s %s got 401; re-running exec credential plugin",
                        method, url.split("?")[0],
                    )
                    self._force_refresh_exec()
                    self._m_retries.inc(reason="reauth")
                    continue
                if (
                    e.code not in (429, 503)
                    or attempts >= self.overload_retries
                ):
                    raise
                attempts += 1
                self._m_retries.inc(reason=str(e.code))
                if e.retry_after is not None:
                    # Server-directed pacing is honored exactly.
                    delay = e.retry_after
                else:
                    # Client-derived delays get full jitter so a fleet of
                    # plugins hit by one overload wave decorrelates
                    # instead of retrying in lockstep.
                    delay = full_jitter(min(0.5 * (2 ** attempts), 10.0))
                delay = min(delay, 30.0)
                logger.warning(
                    "%s %s got %d (attempt %d/%d); retrying in %.1fs",
                    method, url.split("?")[0], e.code,
                    attempts, self.overload_retries, delay,
                )
                time.sleep(delay)

    def _request_once(self, method: str, url: str, body: dict | None = None,
                      accept: str | None = None) -> dict:
        self._maybe_refresh_exec()
        self._limiter.acquire()
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(url, data=data, method=method)
        req.add_header("Accept", accept or "application/json")
        if data is not None:
            req.add_header("Content-Type", "application/json")
        if self.config.token:
            req.add_header("Authorization", f"Bearer {self.config.token}")
        try:
            with urllib.request.urlopen(req, context=self._ssl_ctx, timeout=30) as resp:
                payload = resp.read()
                return json.loads(payload) if payload else {}
        except urllib.error.HTTPError as e:
            try:
                msg = e.read().decode(errors="replace")
            except (OSError, http.client.HTTPException):
                # The server reset (ConnectionResetError) or truncated
                # (IncompleteRead) the connection while we drained the
                # error body; the status code alone still types the error —
                # surfacing the read failure here would turn a clean 404
                # into an untyped crash.
                msg = ""
            if e.code == 404:
                raise NotFoundError(msg) from e
            if e.code == 409:
                # The API server uses 409 for both AlreadyExists (duplicate
                # create) and Conflict (stale resourceVersion); disambiguate
                # on the Status reason so fake and real clients agree.
                reason = ""
                try:
                    reason = json.loads(msg).get("reason", "")
                except ValueError:
                    pass
                if reason == "AlreadyExists":
                    raise AlreadyExistsError(msg) from e
                raise ConflictError(msg) from e
            retry_after = None
            raw = e.headers.get("Retry-After", "") if e.headers else ""
            if raw:
                try:
                    retry_after = float(raw)
                except ValueError:
                    pass  # HTTP-date form: fall back to client pacing
            raise ApiError(msg, code=e.code, retry_after=retry_after) from e

    def get(self, gvr: GVR, name: str, namespace: str = "") -> dict:
        # Chaos sites fire on the LOGICAL verb (kube.get/list/create/...)
        # in both the real and fake clients, so an env-armed drill spec
        # behaves identically against either — never on the HTTP method,
        # which would silently rename kube.update to kube.put here.
        faults.fire("kube.get")
        return self._request("GET", self._url(gvr, namespace, name))

    def api_group_versions(self, group: str) -> list[str]:
        """Group discovery (GET /apis/<group>): served versions, the
        server's preferredVersion first. Empty when the group is absent.
        Deliberately skips the overload-retry loop: re-discovery runs from
        latency-sensitive recovery paths (under the plugin's claim lock),
        and a failed discovery is itself recoverable — fail fast."""
        try:
            payload = self._request_once(
                "GET", f"{self.config.host.rstrip('/')}/apis/{group}"
            )
        except NotFoundError:
            return []
        preferred = (payload.get("preferredVersion") or {}).get("version", "")
        versions = [
            v.get("version", "")
            for v in payload.get("versions", [])
            if v.get("version")
        ]
        if preferred in versions:
            versions.remove(preferred)
            versions.insert(0, preferred)
        return versions

    def _list_raw(
        self,
        gvr: GVR,
        namespace: str = "",
        label_selector: str | None = None,
    ) -> dict:
        """Full list response (items + list metadata.resourceVersion),
        assembled from limit/continue chunks (the informer pager: one giant
        list of hundreds of slices is exactly what falls over first at the
        64-chip scale the allocator handles; chunking bounds each response).
        The apiserver serves every chunk from the first chunk's snapshot,
        so the assembled list is consistent and the final page's
        resourceVersion is the resume point."""
        base: dict = {}
        if label_selector:
            base["labelSelector"] = label_selector
        if self.list_page_size > 0:
            base["limit"] = str(self.list_page_size)
        items: list[dict] = []
        cont = ""
        while True:
            q = dict(base)
            if cont:
                q["continue"] = cont
            try:
                out = self._request(
                    "GET", self._url(gvr, namespace, query=q or None)
                )
            except ApiError as e:
                if e.code == 410 and cont:
                    # Continue token outlived the etcd compaction window
                    # (slow page sequence, e.g. under 429 throttling). The
                    # pager contract: restart as one unpaged list —
                    # partial pages are from a dead snapshot and must be
                    # discarded, not stitched.
                    logger.warning(
                        "continue token for %s expired; retrying as one "
                        "unpaged list", gvr.resource,
                    )
                    q = {k: v for k, v in base.items() if k != "limit"}
                    return self._request(
                        "GET", self._url(gvr, namespace, query=q or None)
                    )
                raise
            items.extend(out.get("items", []))
            cont = (out.get("metadata") or {}).get("continue", "")
            if not cont:
                break
        out["items"] = items
        return out

    def list(
        self,
        gvr: GVR,
        namespace: str = "",
        label_selector: str | None = None,
    ) -> list[dict]:
        faults.fire("kube.list")
        return self._list_raw(gvr, namespace, label_selector).get("items", [])

    # Content negotiation for metadata-only lists: the apiserver
    # transcodes any resource list to meta.k8s.io PartialObjectMetadata
    # when asked — names + resourceVersions without the (large) specs.
    # The trailing plain type is the fallback for servers/proxies that
    # ignore the negotiation: they return full objects, which the item
    # loop below handles identically (metadata is metadata either way).
    _META_ACCEPT = (
        "application/json;as=PartialObjectMetadataList;"
        "g=meta.k8s.io;v=v1,application/json"
    )

    def list_meta(
        self,
        gvr: GVR,
        namespace: str = "",
        label_selector: str | None = None,
    ) -> list[tuple[str, str]]:
        """Change-detection probe for the allocator's incremental index:
        a metadata-only list (PartialObjectMetadataList), so polling for
        slice deltas does not re-download 10k device specs per solve.
        Any failure falls back to the base full-list derivation — the
        probe must never be less available than list() itself."""
        faults.fire("kube.list")
        query: dict = {}
        if label_selector:
            query["labelSelector"] = label_selector
        try:
            out = self._request(
                "GET", self._url(gvr, namespace, query=query or None),
                accept=self._META_ACCEPT,
            )
            return [
                ((item.get("metadata") or {}).get("name", ""),
                 (item.get("metadata") or {}).get("resourceVersion", ""))
                for item in out.get("items", [])
            ]
        except ApiError:
            return super().list_meta(gvr, namespace, label_selector)

    def create(self, gvr: GVR, obj: dict, namespace: str = "") -> dict:
        faults.fire("kube.create")
        return self._request("POST", self._url(gvr, namespace), obj)

    def update(self, gvr: GVR, obj: dict, namespace: str = "") -> dict:
        faults.fire("kube.update")
        return self._request(
            "PUT", self._url(gvr, namespace, obj["metadata"]["name"]), obj
        )

    def delete(self, gvr: GVR, name: str, namespace: str = "") -> None:
        faults.fire("kube.delete")
        self._request("DELETE", self._url(gvr, namespace, name))

    def watch(
        self,
        gvr: GVR,
        namespace: str = "",
        label_selector: str | None = None,
    ) -> Watch:
        faults.fire("kube.watch")
        if self.watch_mode == "stream":
            return self._watch_stream(gvr, namespace, label_selector)
        return self._watch_poll(gvr, namespace, label_selector)

    # -- streaming watch ---------------------------------------------------

    def _relist(self, gvr, namespace, label_selector, known, w):
        """List, diff against ``known`` (name -> resourceVersion), emit
        the delta, and return the list resourceVersion to resume from.

        Used both to seed a fresh watch (known={}) and to recover from a
        410 Gone (the server compacted history past our resumeRV): the
        informer relist — consumers see a consistent event stream either
        way.
        """
        out = self._list_raw(gvr, namespace, label_selector)
        seen: dict[str, str] = {}
        for obj in out.get("items", []):
            name = obj["metadata"]["name"]
            rv = obj["metadata"].get("resourceVersion", "")
            seen[name] = rv
            if name not in known:
                w._emit(WatchEvent("ADDED", obj))
            elif known[name] != rv:
                w._emit(WatchEvent("MODIFIED", obj))
        for name in set(known) - set(seen):
            w._emit(WatchEvent(
                "DELETED", {"metadata": {"name": name, "namespace": namespace}}
            ))
        known.clear()
        known.update(seen)
        list_rv = (out.get("metadata") or {}).get("resourceVersion", "")
        if not list_rv and seen:
            # Servers always set list RV; belt-and-braces fallback. RVs are
            # opaque per the API contract — only compare ones that look
            # numeric (every real apiserver's are), and when none do,
            # return "" so the next connect watches from "current" instead
            # of poisoning the loop with a ValueError (which the outer
            # watch loop would treat as a stream failure, relisting
            # forever).
            numeric = [v for v in seen.values() if v and v.isdigit()]
            list_rv = max(numeric, key=int) if numeric else ""
        return list_rv

    def _watch_stream(self, gvr, namespace, label_selector) -> Watch:
        w = Watch()

        def _stream():
            known: dict[str, str] = {}
            rv = ""
            backoff = Backoff(initial=0.2, cap=max(self.poll_interval, 1.0),
                              jitter=True)
            while not w.stopped:
                try:
                    if not rv:
                        rv = self._relist(gvr, namespace, label_selector, known, w)
                    rv = self._consume_stream(
                        gvr, namespace, label_selector, rv, known, w
                    )
                    backoff.reset()
                except _RelistNeeded:
                    rv = ""          # 410: resume via fresh list
                    backoff.reset()
                except Exception as e:
                    if w.stopped:
                        break
                    delay = backoff.next_delay()
                    self._m_retries.inc(reason="watch-reconnect")
                    logger.warning(
                        "watch stream %s failed (%s); reconnecting in %.1fs",
                        gvr.resource, e, delay,
                    )
                    w._stopped.wait(delay)

        t = threading.Thread(
            target=_stream, daemon=True, name=f"watch-{gvr.resource}"
        )
        t.start()
        self._watch_threads.append(t)
        self._watches.append(w)
        return w

    def _consume_stream(self, gvr, namespace, label_selector, rv, known, w):
        """One chunked ``?watch=true`` connection: emit events until the
        server closes it (timeoutSeconds) or an error ends it. Returns
        the resourceVersion to resume from; raises _RelistNeeded on 410.
        """
        query = {
            "watch": "true",
            "allowWatchBookmarks": "true",
            "resourceVersion": rv,
            # Server closes the stream after this long; we then resume
            # from the last seen RV (a cheap request, not a relist).
            "timeoutSeconds": "300",
        }
        if label_selector:
            query["labelSelector"] = label_selector
        url = self._url(gvr, namespace, query=query)
        self._maybe_refresh_exec()
        self._limiter.acquire()
        if w.stopped:
            return rv
        req = urllib.request.Request(url, method="GET")
        req.add_header("Accept", "application/json")
        if self.config.token:
            req.add_header("Authorization", f"Bearer {self.config.token}")
        try:
            resp = urllib.request.urlopen(req, context=self._ssl_ctx, timeout=330)
        except urllib.error.HTTPError as e:
            if e.code == 410:
                raise _RelistNeeded() from e
            raise
        with resp:
            self._set_live_response(w, resp)
            for line in resp:
                if w.stopped:
                    break
                line = line.strip()
                if not line:
                    continue
                try:
                    ev = json.loads(line)
                except ValueError:
                    logger.warning(
                        "watch %s: undecodable event line", gvr.resource
                    )
                    continue
                ev_type = ev.get("type", "")
                obj = ev.get("object") or {}
                if ev_type == "BOOKMARK":
                    rv = (obj.get("metadata") or {}).get("resourceVersion", rv)
                    continue
                if ev_type == "ERROR":
                    if obj.get("code") == 410:
                        raise _RelistNeeded()
                    raise ApiError(
                        f"watch error event: {obj.get('message', obj)}",
                        code=obj.get("code", 500),
                    )
                name = (obj.get("metadata") or {}).get("name", "")
                rv = (obj.get("metadata") or {}).get("resourceVersion", rv)
                if ev_type == "DELETED":
                    known.pop(name, None)
                elif name:
                    known[name] = rv
                w._emit(WatchEvent(ev_type, obj))
        return rv

    @staticmethod
    def _set_live_response(w: Watch, resp) -> None:
        """Point the watch's stop-hook at the live HTTP connection so
        ``stop()`` can sever it out from under a blocked reader.

        Must be a socket ``shutdown()``, not ``resp.close()``: close
        acquires the BufferedReader lock the blocked ``readline`` is
        holding — a deadlock. shutdown() is safe cross-thread and wakes
        the reader with EOF; the reader thread then closes the response
        itself.
        """
        import socket as _socket

        def _sever():
            # resp.fp.raw._sock is CPython's layering; reach it via getattr
            # so other interpreters degrade observably instead of silently
            # leaving the reader blocked until the socket timeout.
            raw = getattr(getattr(resp, "fp", None), "raw", None)
            sock = getattr(raw, "_sock", None)
            if sock is not None:
                try:
                    sock.shutdown(_socket.SHUT_RDWR)
                except Exception:
                    pass
                return
            if raw is not None:
                # SocketIO itself: close() on the raw layer does not take
                # the BufferedReader lock, so it cannot deadlock the way
                # resp.close() would.
                logger.debug(
                    "watch stop: no ._sock on %r; closing raw IO instead",
                    type(raw).__name__,
                )
                try:
                    raw.close()
                except Exception:
                    pass
                return
            logger.debug(
                "watch stop: no severable socket on %r; reader unblocks "
                "at the socket timeout", type(resp).__name__,
            )

        w._on_stop = _sever
        # stop() may have run between connect and hook installation — it
        # would have severed nothing; sever here so the reader never
        # blocks on a connection nobody can cancel.
        if w.stopped:
            _sever()

    # -- poll fallback -----------------------------------------------------

    def _watch_poll(self, gvr, namespace, label_selector) -> Watch:
        w = Watch()

        def _poll():
            known: dict[str, str] = {}  # name -> resourceVersion
            backoff = Backoff(initial=self.poll_interval,
                              cap=max(60.0, self.poll_interval), jitter=True)
            while not w.stopped:
                try:
                    items = self.list(gvr, namespace, label_selector)
                    backoff.reset()
                except Exception as e:  # transient API failures: back off
                    delay = backoff.next_delay()
                    logger.warning(
                        "watch poll %s failed (%s); retrying in %.1fs",
                        gvr.resource, e, delay,
                    )
                    w._stopped.wait(delay)
                    continue  # backoff IS the retry delay; skip the
                    # steady-state poll sleep at the loop bottom
                seen = {}
                for obj in items:
                    name = obj["metadata"]["name"]
                    rv = obj["metadata"].get("resourceVersion", "")
                    seen[name] = rv
                    if name not in known:
                        w._emit(WatchEvent("ADDED", obj))
                    elif known[name] != rv:
                        w._emit(WatchEvent("MODIFIED", obj))
                for name in set(known) - set(seen):
                    w._emit(
                        WatchEvent(
                            "DELETED",
                            {"metadata": {"name": name, "namespace": namespace}},
                        )
                    )
                known.clear()
                known.update(seen)
                w._stopped.wait(self.poll_interval)

        t = threading.Thread(target=_poll, daemon=True, name=f"watch-{gvr.resource}")
        t.start()
        self._watch_threads.append(t)
        self._watches.append(w)
        return w
