"""Shared CLI plumbing for the plugin and controller entrypoints.

Role of the reference's pkg/flags (lengrongfu/k8s-dra-driver,
pkg/flags/{kubeclient,logging}.go): the env-mirrored flag helpers, kube
client bootstrap, and signal wiring both binaries share.
"""

from __future__ import annotations

import os
import signal
import threading


def env(name: str, default: str = "") -> str:
    return os.environ.get(name, default)


def make_kube_client(kubeconfig: str = "", qps: float = 5.0, burst: int = 10,
                     registry=None):
    """In-cluster config unless a kubeconfig is given
    (NewClientSets analog, pkg/flags/kubeclient.go:70-106; QPS/burst
    defaults mirror kubeclient.go:49-64). ``registry`` receives the
    client's API-request/retry counters when given."""
    from ..kube.client import RealKubeClient, RestConfig

    cfg = (
        RestConfig.from_kubeconfig(kubeconfig)
        if kubeconfig
        else RestConfig.auto()
    )
    return RealKubeClient(cfg, qps=qps, burst=burst, registry=registry)


def add_kube_client_flags(parser) -> None:
    """--kube-api-qps/--kube-api-burst with env mirrors (the reference's
    kube-client flag block, pkg/flags/kubeclient.go:40-68)."""
    parser.add_argument(
        "--kube-api-qps",
        type=float,
        default=float(env("KUBE_API_QPS", "5")),
        help="client-side QPS limit toward the API server (<=0 disables)",
    )
    parser.add_argument(
        "--kube-api-burst",
        type=int,
        default=int(env("KUBE_API_BURST", "10")),
        help="client-side burst allowance toward the API server",
    )


def install_signal_stop() -> threading.Event:
    """SIGINT/SIGTERM → Event (signal loop analog, plugin main.go:177-205)."""
    stop = threading.Event()

    def handle(signum, frame):
        import logging

        logging.getLogger(__name__).info(
            "received signal %d; shutting down", signum
        )
        stop.set()

    signal.signal(signal.SIGINT, handle)
    signal.signal(signal.SIGTERM, handle)
    return stop
