"""Shared CLI plumbing for the plugin and controller entrypoints.

Role of the reference's pkg/flags (lengrongfu/k8s-dra-driver,
pkg/flags/{kubeclient,logging}.go): the env-mirrored flag helpers, kube
client bootstrap, and signal wiring both binaries share.
"""

from __future__ import annotations

import os
import signal
import threading


def env(name: str, default: str = "") -> str:
    return os.environ.get(name, default)


def make_kube_client(kubeconfig: str = ""):
    """In-cluster config unless a kubeconfig is given
    (NewClientSets analog, pkg/flags/kubeclient.go:70-106)."""
    from ..kube.client import RealKubeClient, RestConfig

    cfg = (
        RestConfig.from_kubeconfig(kubeconfig)
        if kubeconfig
        else RestConfig.auto()
    )
    return RealKubeClient(cfg)


def install_signal_stop() -> threading.Event:
    """SIGINT/SIGTERM → Event (signal loop analog, plugin main.go:177-205)."""
    stop = threading.Event()

    def handle(signum, frame):
        import logging

        logging.getLogger(__name__).info(
            "received signal %d; shutting down", signum
        )
        stop.set()

    signal.signal(signal.SIGINT, handle)
    signal.signal(signal.SIGTERM, handle)
    return stop
