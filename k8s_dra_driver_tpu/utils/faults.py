"""Deterministic fault injection: named sites, seeded schedules, no-op off.

The chaos seam the driver's robustness story runs on. Production code is
instrumented with *named sites* — ``faults.fire("checkpoint.write")`` at the
top of the checkpoint writer, ``faults.fire("kube.get")`` in the fake API
server, and so on — and each site is a single attribute check while the
registry is disarmed (the default), so the hooks are free in production.

Tests (and operators reproducing a failure) arm a :class:`FaultPlan`:

    plan = FaultPlan()
    plan.fail("kube.update", ApiError("apiserver blackout", code=503),
              times=5)
    plan.crash("checkpoint.write", on_call=1)
    plan.call("cdi.claim-write", lambda: lib.unplug_chip(1))
    with faults.armed(plan):
        ...drive the system...

Rules are matched per-site on the 1-based hit count, so a schedule is fully
deterministic given the same interleaving; :meth:`FaultPlan.seeded` derives
a randomized-but-reproducible schedule from an integer seed for the long
chaos soak tests. ``arm_from_env()`` lets a flag/env arm simple plans on a
real binary (``TPU_DRA_FAULTS="checkpoint.write@2=oserror,kube.get=api503"``)
— unset, it does nothing, which is the production state.

Site naming convention: ``<component>.<operation>``. The canonical
registry of instrumented sites is :data:`ALL_SITES` (grouped by family:
``kube.*``, ``chiplib.*``, ``checkpoint.*``, ``cdi.*``, ``sharing.*``
and ``rebalance.*`` for the dynamic-sharing state/resize path, the
model-side ``train.*`` family — ``train.step`` fires at the top of every
elastic train step, ``train.reshard`` at the top of every gang resize —
``gateway.*`` for the fleet serving gateway's route/drain/scale
transitions, and ``defrag.*`` for the defrag executor's
intent-write/drain/replace/admit orchestration steps).
Seeded schedules should draw their site lists from it via
:func:`sites_in` so new families are automatically soak-covered.
"""

from __future__ import annotations

import contextlib
import dataclasses
import logging
import os
import random
import threading
from typing import Callable, Optional

logger = logging.getLogger(__name__)

# Canonical registry of instrumented fault sites — the seeded-schedule
# site list. Every site name fired in production code must be listed
# here (tests/test_faults.py cross-checks the source tree), so a chaos
# soak drawing from a family prefix cannot silently miss a site.
ALL_SITES = (
    # Kubernetes API round-trips (kube/client.py).
    "kube.get",
    "kube.list",
    "kube.create",
    "kube.update",
    "kube.delete",
    "kube.watch",
    # Chip library hardware probes (tpulib/chiplib.py).
    "chiplib.enumerate",
    "chiplib.create-channel",
    # Prepared-claim checkpoint store (plugin/checkpoint.py).
    "checkpoint.read",
    "checkpoint.write",
    # CDI spec writes (cdi/spec.py).
    "cdi.base-write",
    "cdi.claim-write",
    # Durable sharing state (plugin/sharing.py): every acquire/release/
    # limits-meta rewrite funnels through the state store's put/clear.
    "sharing.state-write",
    # Dynamic-sharing rebalance path: the hitless session limits
    # re-render (plugin/sharing.py ProcessShareSession.resize) and the
    # workload shim's re-apply of a new limits generation
    # (parallel/shim.py poll_sharing_update).
    "rebalance.session-resize",
    "rebalance.shim-apply",
    # Model-side training loop (parallel/elastic.py): injectable like the
    # driver sites, so chaos schedules can unplug a chip mid-step or
    # crash mid-reshard.
    "train.step",
    "train.reshard",
    # Fleet serving gateway (serving_gateway/gateway.py): the three
    # state transitions of the cluster-level request path — dispatch
    # routing, replica drain, and autoscaler apply.
    "gateway.route",
    "gateway.drain",
    "gateway.scale",
    # Defrag execution (kube/defrag_executor.py): the orchestration
    # steps of a live migration plan — the per-plan intent checkpoint,
    # then per migration the serving drain, the blocker re-place, and
    # finally the stuck-claim admit. A crash at any of them must leave
    # state the executor's restart recovery converges (forward or back).
    "defrag.intent-write",
    "defrag.drain",
    "defrag.replace",
    "defrag.admit",
)


def sites_in(*families: str) -> list[str]:
    """Registered sites under the given family prefixes (e.g.
    ``sites_in("kube.", "train.")``) — the building block for seeded-
    schedule site lists."""
    return [s for s in ALL_SITES if s.startswith(families)]


class FaultError(RuntimeError):
    """Generic injected failure (used when a schedule needs *an* error and
    the site's callers only care that one surfaced)."""


class CrashPoint(BaseException):
    """Simulated hard crash (SIGKILL/OOM analog).

    Deliberately a ``BaseException``: rollback/except-Exception recovery
    paths must NOT observe it — a real SIGKILL runs none of them. Harness
    code catches it at the top level and rebuilds the component from its
    on-disk state, the way a restarted pod would.
    """


@dataclasses.dataclass
class FaultRule:
    """One scheduled behavior at a site.

    ``on_calls`` is a set of 1-based per-site hit indices (None = every
    hit); ``times`` bounds total firings. Exactly one of ``exc`` (an
    exception instance or zero-arg factory) or ``action`` (a callable run
    in-line at the site, e.g. "unplug chip 1 now") is set.
    """

    site: str
    exc: Optional[object] = None
    action: Optional[Callable[[], None]] = None
    on_calls: Optional[frozenset[int]] = None
    times: Optional[int] = None
    fired: int = 0

    def wants(self, hit: int) -> bool:
        if self.times is not None and self.fired >= self.times:
            return False
        return self.on_calls is None or hit in self.on_calls

    def make_exc(self) -> Optional[BaseException]:
        if self.exc is None:
            return None
        return self.exc() if callable(self.exc) else self.exc


class FaultPlan:
    """A deterministic schedule of rules, keyed by site name."""

    def __init__(self):
        self.rules: list[FaultRule] = []

    def _add(self, rule: FaultRule) -> "FaultPlan":
        self.rules.append(rule)
        return self

    def fail(self, site: str, exc, on_calls=None,
             times: Optional[int] = None) -> "FaultPlan":
        """Raise ``exc`` at ``site`` (every hit, or the given 1-based
        call indices, at most ``times`` total)."""
        return self._add(FaultRule(
            site=site, exc=exc,
            on_calls=frozenset(on_calls) if on_calls else None, times=times,
        ))

    def crash(self, site: str, on_call: int = 1) -> "FaultPlan":
        """Simulate a hard crash at the ``on_call``-th hit of ``site``."""
        return self._add(FaultRule(
            site=site, exc=CrashPoint(f"simulated crash at {site}"),
            on_calls=frozenset({on_call}), times=1,
        ))

    def call(self, site: str, action: Callable[[], None], on_calls=None,
             times: Optional[int] = 1) -> "FaultPlan":
        """Run ``action`` when ``site`` is hit (then continue normally) —
        the hook for 'unplug the chip exactly here'."""
        return self._add(FaultRule(
            site=site, action=action,
            on_calls=frozenset(on_calls) if on_calls else None, times=times,
        ))

    @classmethod
    def seeded(cls, seed: int, sites: list[str], exc_factory=None,
               rounds: int = 8, fail_rate: float = 0.3,
               max_call: int = 6) -> "FaultPlan":
        """Reproducible random schedule over ``sites``: ``rounds`` draws,
        each failing a random site at a random upcoming call index with
        probability ``fail_rate``. Same seed → same schedule."""
        rng = random.Random(seed)
        plan = cls()
        exc_factory = exc_factory or (lambda s: FaultError(f"chaos@{s}"))
        for _ in range(rounds):
            if rng.random() >= fail_rate:
                continue
            site = rng.choice(sites)
            plan.fail(site, exc_factory(site),
                      on_calls={rng.randint(1, max_call)}, times=1)
        return plan


class FaultRegistry:
    """Process-wide arm point. Disarmed, ``fire()`` is one attr check."""

    def __init__(self):
        self.armed = False
        self._plan: Optional[FaultPlan] = None
        self._hits: dict[str, int] = {}
        self._lock = threading.Lock()

    def arm(self, plan: FaultPlan) -> None:
        with self._lock:
            self._plan = plan
            self._hits = {}
            self.armed = True

    def disarm(self) -> None:
        with self._lock:
            self.armed = False
            self._plan = None
            self._hits = {}

    def hits(self, site: str) -> int:
        """How many times ``site`` fired while armed (test observability)."""
        with self._lock:
            return self._hits.get(site, 0)

    def fire(self, site: str) -> None:
        """Hit ``site``: count it, run any matching action, raise any
        matching exception. No-op when disarmed."""
        if not self.armed:
            return
        with self._lock:
            plan = self._plan
            if plan is None:
                return
            hit = self._hits.get(site, 0) + 1
            self._hits[site] = hit
            exc: Optional[BaseException] = None
            action: Optional[Callable[[], None]] = None
            for rule in plan.rules:
                if rule.site != site or not rule.wants(hit):
                    continue
                rule.fired += 1
                if rule.action is not None:
                    action = rule.action
                else:
                    exc = rule.make_exc()
                break
        # Outside the lock: actions/exceptions may re-enter other sites.
        if action is not None:
            logger.info("fault site %s (hit %d): running injected action",
                        site, hit)
            action()
        if exc is not None:
            logger.info("fault site %s (hit %d): raising %r", site, hit, exc)
            raise exc


REGISTRY = FaultRegistry()


def fire(site: str) -> None:
    """Module-level hook production code calls at each named site."""
    if REGISTRY.armed:
        REGISTRY.fire(site)


def arm(plan: FaultPlan) -> None:
    REGISTRY.arm(plan)


def disarm() -> None:
    REGISTRY.disarm()


@contextlib.contextmanager
def armed(plan: FaultPlan):
    """Arm for the duration of a with-block; always disarms."""
    REGISTRY.arm(plan)
    try:
        yield REGISTRY
    finally:
        REGISTRY.disarm()


# Named exception kinds arm_from_env understands. API errors are built
# lazily so importing this module never drags the kube package in.
def _env_exc(kind: str, site: str):
    kind = kind.strip().lower()
    if kind == "crash":
        return CrashPoint(f"TPU_DRA_FAULTS crash at {site}")
    if kind == "oserror":
        return OSError(f"TPU_DRA_FAULTS injected OSError at {site}")
    if kind.startswith("api"):
        from ..kube.errors import ApiError

        try:
            code = int(kind[3:] or 500)
        except ValueError:
            code = 500
        return ApiError(f"TPU_DRA_FAULTS injected {code} at {site}",
                        code=code)
    return FaultError(f"TPU_DRA_FAULTS injected fault at {site}")


def arm_from_env(env_var: str = "TPU_DRA_FAULTS") -> bool:
    """Arm a plan described by ``env_var`` (the flag/env arm point both
    binaries call at startup). Format: comma-separated ``site[@call]=kind``
    where kind ∈ {fault, oserror, crash, api<code>}. Unset/empty → no-op
    (production). Returns True when a plan was armed."""
    spec = os.environ.get(env_var, "").strip()
    if not spec:
        return False
    plan = FaultPlan()
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        site_part, _, kind = part.partition("=")
        site, _, call_s = site_part.partition("@")
        on_calls = None
        if call_s:
            try:
                on_calls = {int(call_s)}
            except ValueError:
                logger.warning("TPU_DRA_FAULTS: bad call index in %r", part)
                continue
        plan.fail(site.strip(), _env_exc(kind or "fault", site.strip()),
                  on_calls=on_calls, times=1)
    if not plan.rules:
        return False
    logger.warning(
        "FAULT INJECTION ARMED from %s: %d rule(s) — this is a chaos/"
        "debug configuration, never production", env_var, len(plan.rules),
    )
    arm(plan)
    return True
