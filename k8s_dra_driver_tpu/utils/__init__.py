"""Shared utilities: atomic fs writes, logging, metrics."""

from .fs import atomic_write_json
from .logging import setup_logging
from .metrics import Counter, Gauge, Histogram, MetricsServer, Registry

__all__ = [
    "atomic_write_json",
    "setup_logging",
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsServer",
    "Registry",
]
