"""Claim-lifecycle tracing: stdlib-only spans with claim-UID correlation.

The production DRA debugging question is "which claim, on which node,
failed at which stage, and why" — a question Prometheus counters cannot
answer because they aggregate away the claim. This module gives every
kubelet RPC a root span and every stage underneath it (claim fetch, device
allocation, CDI render, checkpoint write) a child span, all carrying the
claim UID, so one trace shows the full NodePrepareResources decomposition.

Design constraints, in order:

- **stdlib only** (no opentelemetry in the image): ``contextvars`` carries
  the current span, a bounded ring buffer holds finished traces, and JSONL
  is the export format (served by ``MetricsServer`` at ``/debug/traces``).
- **Zero plumbing for leaf modules**: ``child_span()`` parents from the
  contextvar, so ``cdi/spec.py`` or ``plugin/checkpoint.py`` never see a
  Tracer object — outside a traced request they get a no-op span.
- **Cross-signal correlation**: ``current_span()`` is read by
  ``utils.logging.JsonFormatter`` so every log line emitted inside a span
  carries the trace/span/claim ids; metrics observe ``Span.duration`` so
  histograms and traces time the same interval.

Thread propagation follows the ``contextvars`` contract: a thread started
with ``contextvars.copy_context().run`` (or any executor that copies
context) sees the caller's current span and parents correctly.
"""

from __future__ import annotations

import collections
import itertools
import json
import threading
import time
from contextvars import ContextVar
from typing import Any, Optional

# The tag key that correlates spans, logs, and Kubernetes Events.
CLAIM_UID_TAG = "claim_uid"

_current_span: ContextVar[Optional["Span"]] = ContextVar(
    "tpu_dra_current_span", default=None
)


def current_span() -> Optional["Span"]:
    """The innermost active span in this context, or None."""
    return _current_span.get()


def child_span(name: str, **tags: Any) -> "Span":
    """A child of the current span — or a no-op span when nothing is
    being traced. The plumbing-free entry point for leaf modules: the CDI
    renderer and checkpoint store call this and inherit the RPC's trace
    automatically, without ever holding a Tracer reference."""
    parent = _current_span.get()
    if parent is None or parent.tracer is None:
        return Span(None, name, tags=tags)
    return parent.tracer.span(name, tags=tags)


class Span:
    """One timed, tagged operation. Context manager; never raises from
    tracing itself. A span with ``tracer=None`` is a no-op that still
    measures duration (so callers can log latency uniformly)."""

    __slots__ = (
        "tracer", "name", "trace_id", "span_id", "parent_id",
        "tags", "status", "error", "start", "duration",
        "_t0", "_token",
    )

    def __init__(
        self,
        tracer: Optional["Tracer"],
        name: str,
        parent: Optional["Span"] = None,
        tags: Optional[dict] = None,
    ):
        self.tracer = tracer
        self.name = name
        self.tags: dict[str, Any] = dict(tags or {})
        if parent is not None:
            self.trace_id = parent.trace_id
            self.parent_id = parent.span_id
            # Claim-UID correlation: children inherit the claim id so every
            # span of a prepare carries it, not just the one that set it.
            if CLAIM_UID_TAG in parent.tags:
                self.tags.setdefault(CLAIM_UID_TAG, parent.tags[CLAIM_UID_TAG])
        else:
            self.trace_id = tracer._new_id() if tracer else ""
            self.parent_id = ""
        self.span_id = tracer._new_id() if tracer else ""
        self.status = "ok"
        self.error = ""
        self.start = 0.0
        self.duration = 0.0
        self._t0 = 0.0
        self._token = None

    # -- tagging -----------------------------------------------------------

    def set_tag(self, key: str, value: Any) -> "Span":
        self.tags[key] = value
        return self

    def set_error(self, message: str) -> "Span":
        self.status = "error"
        self.error = message
        return self

    @property
    def claim_uid(self) -> str:
        return str(self.tags.get(CLAIM_UID_TAG, ""))

    # -- lifecycle ---------------------------------------------------------

    def __enter__(self) -> "Span":
        self.start = time.time()
        self._t0 = time.monotonic()
        self._token = _current_span.set(self)
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        self.duration = time.monotonic() - self._t0
        if exc_type is not None and self.status == "ok":
            self.set_error(f"{exc_type.__name__}: {exc}")
        if self._token is not None:
            try:
                _current_span.reset(self._token)
            except ValueError:
                # Exited in a different context than it was entered in
                # (cross-thread misuse); clear rather than crash the caller.
                _current_span.set(None)
            self._token = None
        if self.tracer is not None:
            self.tracer._finish(self)
        return False  # never swallow the caller's exception

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "traceId": self.trace_id,
            "spanId": self.span_id,
            "parentId": self.parent_id,
            "start": round(self.start, 6),
            "duration": round(self.duration, 6),
            "status": self.status,
            "error": self.error,
            "tags": dict(self.tags),
        }


class Tracer:
    """Span factory + bounded ring buffer of finished traces.

    A *trace* is the set of spans sharing a trace id; it is sealed (moved
    into the ring buffer) when its root span finishes. The buffer keeps the
    most recent ``max_traces`` traces; older ones are evicted — this is a
    flight recorder, not a telemetry pipeline.
    """

    # Spans accumulated for roots that never finish (a wedged RPC) must not
    # grow without bound; the oldest open trace is dropped past this.
    MAX_OPEN_TRACES = 256

    def __init__(self, max_traces: int = 256):
        self._lock = threading.Lock()
        self._ids = itertools.count(1)
        self._open: "collections.OrderedDict[str, list[dict]]" = (
            collections.OrderedDict()
        )
        self._traces: "collections.deque[dict]" = collections.deque(
            maxlen=max_traces
        )

    def _new_id(self) -> str:
        with self._lock:
            return f"{next(self._ids):08x}"

    def span(self, name: str, claim_uid: str = "",
             tags: Optional[dict] = None, **extra: Any) -> Span:
        """Start a span. Parents from the context's current span when one
        is active (even one belonging to another Tracer — the root's
        tracer owns the trace); otherwise this span is a trace root.
        ``tags`` and keyword extras merge into one FLAT tag dict — the
        /debug/traces schema has no nesting."""
        parent = _current_span.get()
        all_tags = dict(tags or {})
        all_tags.update(extra)
        if claim_uid:
            all_tags[CLAIM_UID_TAG] = claim_uid
        if parent is not None and parent.tracer is not None:
            return Span(parent.tracer, name, parent=parent, tags=all_tags)
        return Span(self, name, tags=all_tags)

    def _finish(self, span: Span) -> None:
        with self._lock:
            bucket = self._open.setdefault(span.trace_id, [])
            bucket.append(span.to_dict())
            if span.parent_id == "":
                spans = self._open.pop(span.trace_id)
                spans.sort(key=lambda s: (s["start"], s["spanId"]))
                self._traces.append(
                    {
                        "traceId": span.trace_id,
                        "root": span.name,
                        "claimUid": span.claim_uid,
                        "duration": round(span.duration, 6),
                        # A DRA RPC succeeds even when a claim inside it
                        # fails (errors are in-band); the trace summary
                        # surfaces any erroring stage, not just the root.
                        "status": (
                            "error"
                            if any(s["status"] == "error" for s in spans)
                            else span.status
                        ),
                        "spans": spans,
                    }
                )
            while len(self._open) > self.MAX_OPEN_TRACES:
                self._open.popitem(last=False)

    # -- export ------------------------------------------------------------

    def traces(self) -> list[dict]:
        """Finished traces, oldest first."""
        with self._lock:
            return list(self._traces)

    def find_trace(self, claim_uid: str) -> Optional[dict]:
        """Most recent finished trace whose root carries this claim UID."""
        with self._lock:
            for trace in reversed(self._traces):
                if trace["claimUid"] == claim_uid or any(
                    s["tags"].get(CLAIM_UID_TAG) == claim_uid
                    for s in trace["spans"]
                ):
                    return trace
        return None

    def find_trace_by_tag(self, key: str, value) -> Optional[dict]:
        """Most recent finished trace with any span tagged ``key=value``
        — the generalization of :meth:`find_trace` the serving gateway
        uses to join a request's submit span on its gateway id."""
        with self._lock:
            for trace in reversed(self._traces):
                if any(s["tags"].get(key) == value
                       for s in trace["spans"]):
                    return trace
        return None

    def export_jsonl(self) -> str:
        """One JSON object per line per finished trace (the
        ``/debug/traces`` wire format)."""
        out = [json.dumps(t, sort_keys=True) for t in self.traces()]
        return "\n".join(out) + ("\n" if out else "")

    def clear(self) -> None:
        with self._lock:
            self._traces.clear()
            self._open.clear()
