"""Filesystem helpers shared by the CDI writer, checkpoint, and state stores."""

from __future__ import annotations

import json
import os
import tempfile


def atomic_write_json(path: str, obj: dict, indent: int | None = 2) -> None:
    """Write JSON via tempfile + rename so readers never see a torn file
    (the property kubelet's checkpoint store provides in the reference)."""
    d = os.path.dirname(path) or "."
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(obj, f, indent=indent, sort_keys=True)
            f.write("\n")
            f.flush()
            # Durability, not just atomicity: without the fsync a power loss
            # after rename can surface an empty/truncated checkpoint, which
            # read() treats as corruption and wedges the plugin.
            os.fsync(f.fileno())
        os.rename(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    # Best-effort directory fsync: the rename is already committed, so a
    # failure here (fd exhaustion, EIO) must not make callers treat a
    # successful write as failed and roll back real state.
    try:
        dirfd = os.open(d, os.O_RDONLY)
        try:
            os.fsync(dirfd)
        finally:
            os.close(dirfd)
    except OSError:
        pass
