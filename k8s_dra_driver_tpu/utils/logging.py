"""Logging setup: klog-style text or structured JSON.

Role of the reference's logging flags bridge (lengrongfu/k8s-dra-driver,
pkg/flags/logging.go:38-88), which wires k8s logsapi's JSON-format option
into the CLI. Here: stdlib logging with an optional JSON formatter.

The JSON formatter is the correlation seam of the observability layer:
``extra={...}`` structured fields are merged into the line, and when a
tracing span is active (utils/tracing.py) the line carries its
``traceId``/``spanId`` and claim UID — so logs, traces, metrics, and
Kubernetes Events all key on the same claim UID.
"""

from __future__ import annotations

import json
import logging
import os
import sys
import time

# Attributes every LogRecord carries (computed from a dummy record so the
# set tracks the running Python version, e.g. 3.12's taskName); anything
# else on the record arrived via ``extra={...}`` and belongs in the line.
_RESERVED_RECORD_ATTRS = frozenset(
    logging.LogRecord("", 0, "", 0, "", (), None).__dict__
) | {"message", "asctime", "taskName"}


class JsonFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        out = {
            "ts": round(time.time(), 3),
            "level": record.levelname.lower(),
            "logger": record.name,
            "msg": record.getMessage(),
        }
        # Span correlation: any line logged inside a traced operation
        # carries the ids that find its trace in /debug/traces.
        from .tracing import current_span

        span = current_span()
        if span is not None and span.trace_id:
            out["traceId"] = span.trace_id
            out["spanId"] = span.span_id
            if span.claim_uid:
                out["claimUid"] = span.claim_uid
        for key, value in record.__dict__.items():
            if key in _RESERVED_RECORD_ATTRS or key.startswith("_"):
                continue
            out.setdefault(key, value)
        if record.exc_info:
            out["exc"] = self.formatException(record.exc_info)
        return json.dumps(out, default=repr)


def setup_logging(level: str | None = None,
                  json_format: bool | None = None) -> None:
    """Install the root handler.

    ``None`` arguments fall back to the ``TPU_DRA_LOG_LEVEL`` /
    ``TPU_DRA_LOG_FORMAT`` (``json``|``text``) environment overrides — the
    seam that lets a DaemonSet flip to JSON/debug by editing pod env
    without changing the container args. An explicit argument (the CLI
    flag path) always wins over the environment.
    """
    if level is None or level == "":
        level = os.environ.get("TPU_DRA_LOG_LEVEL") or "INFO"
    if json_format is None:
        json_format = (
            os.environ.get("TPU_DRA_LOG_FORMAT", "").strip().lower() == "json"
        )
    handler = logging.StreamHandler(sys.stderr)
    if json_format:
        handler.setFormatter(JsonFormatter())
    else:
        handler.setFormatter(
            logging.Formatter(
                "%(asctime)s %(levelname).1s %(name)s: %(message)s",
                datefmt="%H:%M:%S",
            )
        )
    root = logging.getLogger()
    root.handlers[:] = [handler]
    root.setLevel(getattr(logging, level.upper(), logging.INFO))
