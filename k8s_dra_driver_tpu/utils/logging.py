"""Logging setup: klog-style text or structured JSON.

Role of the reference's logging flags bridge (lengrongfu/k8s-dra-driver,
pkg/flags/logging.go:38-88), which wires k8s logsapi's JSON-format option
into the CLI. Here: stdlib logging with an optional JSON formatter.
"""

from __future__ import annotations

import json
import logging
import sys
import time


class JsonFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        out = {
            "ts": round(time.time(), 3),
            "level": record.levelname.lower(),
            "logger": record.name,
            "msg": record.getMessage(),
        }
        if record.exc_info:
            out["exc"] = self.formatException(record.exc_info)
        return json.dumps(out)


def setup_logging(level: str = "INFO", json_format: bool = False) -> None:
    handler = logging.StreamHandler(sys.stderr)
    if json_format:
        handler.setFormatter(JsonFormatter())
    else:
        handler.setFormatter(
            logging.Formatter(
                "%(asctime)s %(levelname).1s %(name)s: %(message)s",
                datefmt="%H:%M:%S",
            )
        )
    root = logging.getLogger()
    root.handlers[:] = [handler]
    root.setLevel(getattr(logging, level.upper(), logging.INFO))
