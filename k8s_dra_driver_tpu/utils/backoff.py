"""Exponential backoff and client-side rate limiting.

Role of the reference's client-go flowcontrol: the QPS/burst token
bucket every API-server client carries (lengrongfu/k8s-dra-driver,
pkg/flags/kubeclient.go:49-64 — defaults QPS 5, burst 10) and the
transient-error retry delay its controllers use
(cmd/nvidia-dra-controller/imex.go:143-162). Pure stdlib, thread-safe.
"""

from __future__ import annotations

import random
import threading
import time


def full_jitter(delay: float, rng: random.Random | None = None) -> float:
    """AWS-style full jitter: uniform in [0, delay].

    The point is decorrelation: a node-wide apiserver blip makes every
    plugin's retry timer start at the same instant, and undithered
    exponential delays keep them in lockstep — each retry wave arrives as
    one thundering herd. Spreading each client uniformly over its window
    converts the spike into a flat trickle at the same average rate.
    """
    return (rng or _module_rng).uniform(0.0, delay)


_module_rng = random.Random()


class TokenBucket:
    """Blocking QPS/burst limiter (client-go flowcontrol analog).

    ``acquire()`` takes one token, sleeping until one accrues. Tokens
    refill continuously at ``qps`` up to ``burst``. A non-positive
    ``qps`` disables limiting entirely.
    """

    def __init__(self, qps: float = 5.0, burst: int = 10):
        if burst < 1:
            raise ValueError(f"burst must be >= 1, got {burst}")
        self.qps = qps
        self.burst = burst
        self._tokens = float(burst)
        self._last = time.monotonic()
        self._lock = threading.Lock()

    def _refill(self, now: float) -> None:
        self._tokens = min(
            float(self.burst), self._tokens + (now - self._last) * self.qps
        )
        self._last = now

    def try_acquire(self) -> bool:
        """Non-blocking: take a token if one is available."""
        if self.qps <= 0:
            return True
        with self._lock:
            self._refill(time.monotonic())
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                return True
            return False

    def acquire(self) -> float:
        """Take a token, blocking as needed; returns seconds slept."""
        if self.qps <= 0:
            return 0.0
        slept = 0.0
        while True:
            with self._lock:
                self._refill(time.monotonic())
                if self._tokens >= 1.0:
                    self._tokens -= 1.0
                    return slept
                wait = (1.0 - self._tokens) / self.qps
            time.sleep(wait)
            slept += wait


class Backoff:
    """Exponential backoff with a cap; reset on success.

    The controller's transient-error retry (imex.go:143-162 waits a flat
    minute; exponential-with-cap subsumes that: short first retries for
    blips, the cap for real outages).

    ``jitter=True`` applies full jitter to each returned delay (the
    exponential base still grows deterministically, so ``current`` and the
    cap behave identically); pass ``rng`` to make jittered sequences
    reproducible in tests.
    """

    def __init__(
        self,
        initial: float = 1.0,
        cap: float = 60.0,
        factor: float = 2.0,
        jitter: bool = False,
        rng: random.Random | None = None,
    ):
        self.initial = initial
        self.cap = cap
        self.factor = factor
        self.jitter = jitter
        self._rng = rng
        self._current = 0.0

    def next_delay(self) -> float:
        """The delay to wait after one more consecutive failure."""
        if self._current <= 0:
            self._current = self.initial
        else:
            self._current = min(self.cap, self._current * self.factor)
        if self.jitter:
            return full_jitter(self._current, self._rng)
        return self._current

    def reset(self) -> None:
        self._current = 0.0

    @property
    def current(self) -> float:
        return self._current
