"""Prometheus-format metrics + debug HTTP endpoint.

Role of the reference's controller observability (lengrongfu/k8s-dra-driver,
cmd/nvidia-dra-controller/main.go:194-241: prometheus handler + pprof mux) —
extended to the node plugin too, which in the reference exposes no metrics
at all (SURVEY.md §5 gap). stdlib-only: a tiny registry rendering the
Prometheus text exposition format, served by http.server.
"""

from __future__ import annotations

import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional


class Counter:
    def __init__(self, name: str, help_: str, registry: "Registry"):
        self.name = name
        self.help = help_
        self._values: dict[tuple, float] = {}
        self._lock = threading.Lock()
        registry._register(self)

    def inc(self, amount: float = 1.0, **labels) -> None:
        key = tuple(sorted(labels.items()))
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def render(self) -> list[str]:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} counter"]
        with self._lock:
            for key, val in sorted(self._values.items()):
                out.append(f"{self.name}{_labels(key)} {_num(val)}")
        return out


class Gauge:
    def __init__(self, name: str, help_: str, registry: "Registry"):
        self.name = name
        self.help = help_
        self._values: dict[tuple, float] = {}
        self._lock = threading.Lock()
        registry._register(self)

    def set(self, value: float, **labels) -> None:
        key = tuple(sorted(labels.items()))
        with self._lock:
            self._values[key] = value

    def render(self) -> list[str]:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} gauge"]
        with self._lock:
            for key, val in sorted(self._values.items()):
                out.append(f"{self.name}{_labels(key)} {_num(val)}")
        return out


class Histogram:
    """Fixed-bucket histogram (claim-prepare latencies etc.)."""

    DEFAULT_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10)

    def __init__(self, name: str, help_: str, registry: "Registry",
                 buckets=DEFAULT_BUCKETS):
        self.name = name
        self.help = help_
        self.buckets = tuple(sorted(buckets))
        self._counts = [0] * (len(self.buckets) + 1)
        self._sum = 0.0
        self._n = 0
        self._lock = threading.Lock()
        registry._register(self)

    def observe(self, value: float) -> None:
        with self._lock:
            self._sum += value
            self._n += 1
            for i, b in enumerate(self.buckets):
                if value <= b:
                    self._counts[i] += 1
                    return
            self._counts[-1] += 1

    def time(self):
        """Context manager: observe elapsed seconds."""
        hist = self

        class _Timer:
            def __enter__(self):
                self.t0 = time.monotonic()
                return self

            def __exit__(self, *exc):
                hist.observe(time.monotonic() - self.t0)

        return _Timer()

    def render(self) -> list[str]:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} histogram"]
        with self._lock:
            cum = 0
            for i, b in enumerate(self.buckets):
                cum += self._counts[i]
                out.append(f'{self.name}_bucket{{le="{_num(b)}"}} {cum}')
            cum += self._counts[-1]
            out.append(f'{self.name}_bucket{{le="+Inf"}} {cum}')
            out.append(f"{self.name}_sum {_num(self._sum)}")
            out.append(f"{self.name}_count {self._n}")
        return out


def _labels(key: tuple) -> str:
    if not key:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in key)
    return "{" + inner + "}"


def _num(v: float) -> str:
    return str(int(v)) if float(v).is_integer() else repr(float(v))


class Registry:
    def __init__(self):
        self._metrics: list = []
        self._lock = threading.Lock()

    def _register(self, metric) -> None:
        with self._lock:
            self._metrics.append(metric)

    def render(self) -> str:
        lines: list[str] = []
        with self._lock:
            for m in self._metrics:
                lines.extend(m.render())
        return "\n".join(lines) + "\n"


def _dump_stacks() -> str:
    """All thread stacks (pprof goroutine-profile analog)."""
    import sys
    import traceback

    names = {t.ident: t.name for t in threading.enumerate()}
    out = []
    for ident, frame in sys._current_frames().items():
        out.append(f"--- thread {ident} ({names.get(ident, '?')}) ---")
        out.extend(line.rstrip() for line in traceback.format_stack(frame))
    return "\n".join(out) + "\n"


def _sample_profile(seconds: float, hz: float = 100.0) -> str:
    """Statistical CPU profile: sample every thread's stack for `seconds`,
    report the hottest aggregated stacks (pprof CPU-profile analog —
    cProfile only sees its own thread, so sampling is the stdlib way to
    profile a multithreaded server in place)."""
    import sys
    import traceback
    from collections import Counter as _Counter

    period = 1.0 / hz
    counts: _Counter = _Counter()
    deadline = time.monotonic() + min(seconds, 60.0)
    n = 0
    me = threading.get_ident()
    while time.monotonic() < deadline:
        for ident, frame in sys._current_frames().items():
            if ident == me:
                continue
            stack = tuple(
                f"{fs.filename.rsplit('/', 1)[-1]}:{fs.lineno}:{fs.name}"
                for fs in traceback.extract_stack(frame)[-8:]
            )
            counts[stack] += 1
        n += 1
        time.sleep(period)
    out = [f"# {n} samples at {hz:g} Hz over {seconds:g}s", ""]
    for stack, c in counts.most_common(30):
        out.append(f"{c} samples ({100.0 * c / max(n, 1):.1f}%):")
        out.extend(f"    {line}" for line in stack)
        out.append("")
    return "\n".join(out) + "\n"


class MetricsServer:
    """/metrics + /healthz + /version + /debug/{stacks,profile} on a
    background HTTP server (SetupHTTPEndpoint analog, main.go:194-241,
    incl. the pprof mux at main.go:216-224)."""

    def __init__(self, registry: Registry, host: str = "0.0.0.0", port: int = 0):
        self.registry = registry
        registry_ref = registry
        health = self._health = {"ok": True}

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):
                status = 200
                if self.path == "/metrics":
                    body = registry_ref.render().encode()
                    ctype = "text/plain; version=0.0.4"
                elif self.path == "/healthz":
                    body = (b"ok" if health["ok"] else b"unhealthy")
                    status = 200 if health["ok"] else 503
                    ctype = "text/plain"
                elif self.path == "/version":
                    from ..version import version_string

                    body = (version_string() + "\n").encode()
                    ctype = "text/plain"
                elif self.path == "/debug/stacks":
                    body = _dump_stacks().encode()
                    ctype = "text/plain"
                elif self.path.startswith("/debug/profile"):
                    from urllib.parse import parse_qs, urlparse

                    q = parse_qs(urlparse(self.path).query)
                    try:
                        secs = float(q.get("seconds", ["2"])[0])
                    except ValueError:
                        body = b"bad seconds parameter\n"
                        status = 400
                        ctype = "text/plain"
                    else:
                        # NaN fails both bounds checks and lands on 2s.
                        if not (0.0 <= secs <= 60.0):
                            secs = min(max(secs, 0.0), 60.0) if secs == secs else 2.0
                        body = _sample_profile(secs).encode()
                        ctype = "text/plain"
                else:
                    body = b"not found"
                    status = 404
                    ctype = "text/plain"
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):
                pass  # quiet; structured logs carry the signal

        self._server = ThreadingHTTPServer((host, port), Handler)
        self.port = self._server.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True, name="metrics-http"
        )
        self._thread.start()

    def set_healthy(self, ok: bool) -> None:
        self._health["ok"] = ok

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
