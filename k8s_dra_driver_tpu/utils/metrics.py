"""Prometheus-format metrics + debug HTTP endpoint.

Role of the reference's controller observability (lengrongfu/k8s-dra-driver,
cmd/nvidia-dra-controller/main.go:194-241: prometheus handler + pprof mux) —
extended to the node plugin too, which in the reference exposes no metrics
at all (SURVEY.md §5 gap). stdlib-only: a tiny registry rendering the
Prometheus text exposition format, served by http.server.

Conventions enforced here (and by ``tools/lint.py`` / ``make
verify-metrics``): metric names must match the exposition-format name
grammar, first-party metrics carry the ``tpu_dra_`` prefix and a unit
suffix, label values are escaped per the text-format spec, and non-finite
values render as ``+Inf``/``-Inf``/``NaN`` (``repr(inf)`` is not parseable
by Prometheus). Renamed metrics keep their old name rendering for one
release via ``Registry.alias`` with a ``(deprecated)`` HELP marker.
"""

from __future__ import annotations

import logging
import math
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional

# Prometheus text-exposition grammars (data model spec): metric names admit
# colons (recording rules); label names do not.
_METRIC_NAME_RE = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*\Z")
_LABEL_NAME_RE = re.compile(r"[a-zA-Z_][a-zA-Z0-9_]*\Z")


def _validate_metric_name(name: str) -> str:
    if not _METRIC_NAME_RE.match(name):
        raise ValueError(
            f"invalid metric name {name!r}: must match "
            "[a-zA-Z_:][a-zA-Z0-9_:]*"
        )
    return name


def _validate_label_names(labels: dict) -> None:
    for k in labels:
        if not _LABEL_NAME_RE.match(k):
            raise ValueError(
                f"invalid label name {k!r}: must match "
                "[a-zA-Z_][a-zA-Z0-9_]*"
            )


class Counter:
    def __init__(self, name: str, help_: str, registry: "Registry"):
        self.name = _validate_metric_name(name)
        self.help = help_
        self.type = "counter"
        self._values: dict[tuple, float] = {}
        self._lock = threading.Lock()
        registry._register(self)

    def inc(self, amount: float = 1.0, **labels) -> None:
        _validate_label_names(labels)
        key = tuple(sorted(labels.items()))
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def value(self, **labels) -> float:
        key = tuple(sorted(labels.items()))
        with self._lock:
            return self._values.get(key, 0.0)

    def render(self) -> list[str]:
        return self.render_as(self.name, self.help)

    def render_as(self, name: str, help_: str) -> list[str]:
        out = [f"# HELP {name} {help_}", f"# TYPE {name} counter"]
        with self._lock:
            for key, val in sorted(self._values.items()):
                out.append(f"{name}{_labels(key)} {_num(val)}")
        return out


class Gauge:
    def __init__(self, name: str, help_: str, registry: "Registry"):
        self.name = _validate_metric_name(name)
        self.help = help_
        self.type = "gauge"
        self._values: dict[tuple, float] = {}
        self._lock = threading.Lock()
        registry._register(self)

    def set(self, value: float, **labels) -> None:
        _validate_label_names(labels)
        key = tuple(sorted(labels.items()))
        with self._lock:
            self._values[key] = value

    def remove(self, **labels) -> None:
        """Drop one labeled series entirely. For label sets scoped to a
        finite-lifetime object (a claim UID): zeroing such a series
        keeps it in every future scrape forever — unbounded cardinality
        over churn — while removal is the standard end-of-life."""
        key = tuple(sorted(labels.items()))
        with self._lock:
            self._values.pop(key, None)

    def value(self, **labels) -> float:
        key = tuple(sorted(labels.items()))
        with self._lock:
            return self._values.get(key, 0.0)

    def render(self) -> list[str]:
        return self.render_as(self.name, self.help)

    def render_as(self, name: str, help_: str) -> list[str]:
        out = [f"# HELP {name} {help_}", f"# TYPE {name} gauge"]
        with self._lock:
            for key, val in sorted(self._values.items()):
                out.append(f"{name}{_labels(key)} {_num(val)}")
        return out


class Histogram:
    """Fixed-bucket histogram (claim-prepare latencies etc.). Optionally
    labeled: ``observe(v, phase="admit")`` keeps an independent bucket
    series per label set, rendered with ``le`` appended last — how the
    serving tick profiler keeps one ``tpu_dra_srv_tick_phase_seconds``
    family across its ``{component, phase}`` enum instead of a family
    per phase. Label-less use renders exactly as before (including the
    zeroed series when nothing was observed yet)."""

    DEFAULT_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10)

    def __init__(self, name: str, help_: str, registry: "Registry",
                 buckets=DEFAULT_BUCKETS):
        self.name = _validate_metric_name(name)
        self.help = help_
        self.type = "histogram"
        self.buckets = tuple(sorted(buckets))
        # key -> [per-bucket counts (+overflow), sum, n]
        self._series: dict[tuple, list] = {}
        self._lock = threading.Lock()
        registry._register(self)

    def _cell(self, labels: dict) -> list:
        """Caller must hold the lock."""
        _validate_label_names(labels)
        if "le" in labels:
            raise ValueError(
                "label name 'le' is reserved for histogram buckets"
            )
        key = tuple(sorted(labels.items()))
        cell = self._series.get(key)
        if cell is None:
            cell = self._series[key] = [
                [0] * (len(self.buckets) + 1), 0.0, 0
            ]
        return cell

    def observe(self, value: float, **labels) -> None:
        with self._lock:
            cell = self._cell(labels)
            cell[1] += value
            cell[2] += 1
            for i, b in enumerate(self.buckets):
                if value <= b:
                    cell[0][i] += 1
                    return
            cell[0][-1] += 1

    def zero(self, **labels) -> None:
        """Materialize an all-zero series for a label set — the explicit-
        zeros convention for labeled histograms (an unscraped enum cell
        must read 0, not be absent)."""
        with self._lock:
            self._cell(labels)

    def time(self):
        """Context manager: observe elapsed seconds (label-less)."""
        hist = self

        class _Timer:
            def __enter__(self):
                self.t0 = time.monotonic()
                return self

            def __exit__(self, *exc):
                hist.observe(time.monotonic() - self.t0)

        return _Timer()

    def summary(self, **labels) -> tuple[int, float]:
        """(count, sum) — the scalar view snapshot/doctor reports use.
        With labels: that series only; without: aggregated over all."""
        with self._lock:
            if labels:
                key = tuple(sorted(labels.items()))
                cell = self._series.get(key)
                return (cell[2], cell[1]) if cell else (0, 0.0)
            n = sum(c[2] for c in self._series.values())
            total = sum(c[1] for c in self._series.values())
            return n, total

    def render(self) -> list[str]:
        return self.render_as(self.name, self.help)

    def render_as(self, name: str, help_: str) -> list[str]:
        out = [f"# HELP {name} {help_}", f"# TYPE {name} histogram"]
        with self._lock:
            series = self._series or {
                (): [[0] * (len(self.buckets) + 1), 0.0, 0]
            }
            for key, (counts, total, n) in sorted(series.items()):
                cum = 0
                for i, b in enumerate(self.buckets):
                    cum += counts[i]
                    out.append(
                        f"{name}_bucket{_bucket_labels(key, _num(b))} {cum}"
                    )
                cum += counts[-1]
                out.append(
                    f'{name}_bucket{_bucket_labels(key, "+Inf")} {cum}'
                )
                out.append(f"{name}_sum{_labels(key)} {_num(total)}")
                out.append(f"{name}_count{_labels(key)} {n}")
        return out


def _escape_label_value(v) -> str:
    """Text-format label-value escaping: backslash, double-quote and
    newline must be escaped or the scrape line is unparseable."""
    return (
        str(v)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _labels(key: tuple) -> str:
    if not key:
        return ""
    inner = ",".join(f'{k}="{_escape_label_value(v)}"' for k, v in key)
    return "{" + inner + "}"


def _bucket_labels(key: tuple, le: str) -> str:
    """Histogram bucket label block: the series labels with ``le``
    appended last (``le`` is reserved by the text format, never a
    user label — _validate_label_names accepts it, so the histogram
    label path must not be handed an ``le`` of its own)."""
    inner = ",".join(
        f'{k}="{_escape_label_value(v)}"' for k, v in key
    )
    return "{" + (inner + "," if inner else "") + f'le="{le}"' + "}"


def _num(v: float) -> str:
    f = float(v)
    if math.isinf(f):
        return "+Inf" if f > 0 else "-Inf"
    if math.isnan(f):
        return "NaN"
    return str(int(f)) if f.is_integer() else repr(f)


class _DeprecatedAlias:
    """Renders a metric once more under its pre-rename name, HELP-marked
    deprecated, so dashboards survive one release of the rename
    (docs/migration.md records the mapping)."""

    def __init__(self, old_name: str, metric):
        self.name = _validate_metric_name(old_name)
        self.metric = metric

    def render(self) -> list[str]:
        return self.metric.render_as(
            self.name,
            f"{self.metric.help} (deprecated; renamed to {self.metric.name})",
        )


class Registry:
    def __init__(self):
        self._metrics: list = []
        self._names: set[str] = set()
        self._lock = threading.Lock()
        self._render_hooks: list[Callable] = []

    def _register(self, metric) -> None:
        with self._lock:
            if metric.name in self._names:
                raise ValueError(f"duplicate metric name {metric.name!r}")
            self._names.add(metric.name)
            self._metrics.append(metric)

    def alias(self, old_name: str, metric) -> None:
        """Keep ``old_name`` rendering (deprecated) for a renamed metric."""
        self._register(_DeprecatedAlias(old_name, metric))

    def add_render_hook(self, hook: Callable) -> None:
        """Run ``hook()`` before every render. The seam for metrics that
        integrate over time (usage allocated-seconds): values must be
        brought current at the scrape instant, not at the last event.
        Hooks run OUTSIDE the registry lock (they set gauges/counters,
        which register nothing) and a raising hook is swallowed — a
        broken integrator must not take /metrics down with it."""
        with self._lock:
            self._render_hooks.append(hook)

    def render(self) -> str:
        with self._lock:
            hooks = list(self._render_hooks)
        for hook in hooks:
            try:
                hook()
            except Exception:
                logging.getLogger(__name__).exception("render hook failed")
        lines: list[str] = []
        with self._lock:
            for m in self._metrics:
                lines.extend(m.render())
        return "\n".join(lines) + "\n"


def _dump_stacks() -> str:
    """All thread stacks (pprof goroutine-profile analog)."""
    import sys
    import traceback

    names = {t.ident: t.name for t in threading.enumerate()}
    out = []
    for ident, frame in sys._current_frames().items():
        out.append(f"--- thread {ident} ({names.get(ident, '?')}) ---")
        out.extend(line.rstrip() for line in traceback.format_stack(frame))
    return "\n".join(out) + "\n"


def _sample_profile(seconds: float, hz: float = 100.0) -> str:
    """Statistical CPU profile: sample every thread's stack for `seconds`,
    report the hottest aggregated stacks (pprof CPU-profile analog —
    cProfile only sees its own thread, so sampling is the stdlib way to
    profile a multithreaded server in place)."""
    import sys
    import traceback
    from collections import Counter as _Counter

    period = 1.0 / hz
    counts: _Counter = _Counter()
    deadline = time.monotonic() + min(seconds, 60.0)
    n = 0
    me = threading.get_ident()
    while time.monotonic() < deadline:
        for ident, frame in sys._current_frames().items():
            if ident == me:
                continue
            stack = tuple(
                f"{fs.filename.rsplit('/', 1)[-1]}:{fs.lineno}:{fs.name}"
                for fs in traceback.extract_stack(frame)[-8:]
            )
            counts[stack] += 1
        n += 1
        time.sleep(period)
    out = [f"# {n} samples at {hz:g} Hz over {seconds:g}s", ""]
    for stack, c in counts.most_common(30):
        out.append(f"{c} samples ({100.0 * c / max(n, 1):.1f}%):")
        out.extend(f"    {line}" for line in stack)
        out.append("")
    return "\n".join(out) + "\n"


class MetricsServer:
    """/metrics + /healthz + /readyz + /version + /debug/{stacks,profile,
    traces} on a background HTTP server (SetupHTTPEndpoint analog,
    main.go:194-241, incl. the pprof mux at main.go:216-224).

    ``/healthz`` is liveness: the process flag flipped by ``set_healthy``.
    ``/readyz`` is readiness: every CRITICAL check registered with
    ``add_readiness_check`` must pass (the DaemonSet/Deployment
    readinessProbe target — a plugin whose gRPC socket is down or whose
    checkpoint dir is read-only must stop advertising ready, not die).
    Checks registered with ``critical=False`` distinguish DEGRADED from
    dead: when only those fail, /readyz stays 200 but its body ends in
    ``degraded`` and the failing checks are marked ``[~]`` — an apiserver
    outage must not make kubelet abandon a plugin that is still serving
    prepares from checkpointed state.
    ``/debug/traces`` streams the tracer's finished claim traces as JSONL.
    ``/debug/usage`` serves the utilization accountant's JSON snapshot
    when a provider was registered with ``set_usage_provider`` (404
    otherwise). ``/debug/allocations`` streams the allocator's solve
    decisions (candidate funnels, terminal reasons) as JSONL when a
    provider was registered with ``set_allocations_provider`` (404
    otherwise). ``/debug/defrag`` serves the defrag planner's JSON plan
    buffer when a provider was registered with ``set_defrag_provider``
    (404 otherwise). ``/debug/rebalance`` serves the dynamic-sharing
    rebalancer's decision ring + per-claim share view when a provider
    was registered with ``set_rebalance_provider`` (404 otherwise).
    ``/debug/gateway`` serves the fleet serving gateway's snapshot
    (replicas, queues, event ring) when a provider was registered with
    ``set_gateway_provider`` (404 otherwise).
    ``/debug/compute`` serves the compute telemetry's snapshot (compile
    ledger, per-program rooflines, HBM decomposition, collective
    accounting) when a provider was registered with
    ``set_compute_provider`` (404 otherwise).
    ``/debug/requests`` streams the serving telemetry's sealed request
    timelines as JSONL when a provider was registered with
    ``set_requests_provider`` (404 otherwise); ``?view=ticks`` /
    ``exemplars`` / ``slo`` select the tick-profile, violation-exemplar,
    and fleet-SLO-summary views, and an unknown view is a 400.
    All routes are GET-only; other methods get ``405``
    with an ``Allow: GET`` header — the scrape surface mutates nothing.
    """

    def __init__(self, registry: Registry, host: str = "0.0.0.0",
                 port: int = 0, tracer=None):
        self.registry = registry
        self.tracer = tracer
        self.usage_provider: Optional[Callable] = None
        self.allocations_provider: Optional[Callable] = None
        self.defrag_provider: Optional[Callable] = None
        self.rebalance_provider: Optional[Callable] = None
        self.gateway_provider: Optional[Callable] = None
        self.requests_provider: Optional[Callable] = None
        self.kv_provider: Optional[Callable] = None
        self.residency_provider: Optional[Callable] = None
        self.compute_provider: Optional[Callable] = None
        # The JSON debug surfaces share one handler block: path ->
        # (provider attribute, not-enabled message). /debug/allocations
        # stays separate (the provider returns pre-rendered JSONL).
        self._json_debug_routes = {
            "/debug/usage": (
                "usage_provider", "usage accounting not enabled"),
            "/debug/defrag": (
                "defrag_provider", "defrag planning not enabled"),
            "/debug/rebalance": (
                "rebalance_provider",
                "dynamic-sharing rebalancer not enabled"),
            "/debug/gateway": (
                "gateway_provider", "serving gateway not enabled"),
            "/debug/kv": (
                "kv_provider", "kv telemetry not enabled"),
            "/debug/residency": (
                "residency_provider", "residency index not enabled"),
            "/debug/compute": (
                "compute_provider", "compute telemetry not enabled"),
        }
        registry_ref = registry
        health = self._health = {"ok": True}
        self._ready_checks: dict[str, Callable] = {}
        self._ready_lock = threading.Lock()
        server_ref = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):
                status, ctype, body = self._resolve()
                self._reply(status, ctype, body, include_body=True)

            def do_HEAD(self):
                # Same status line + headers as the GET would produce,
                # no body (RFC 9110) — HEAD-probing health checkers keep
                # working.
                status, ctype, body = self._resolve(head=True)
                self._reply(status, ctype, body, include_body=False)

            def _resolve(self, head=False):
                status = 200
                if self.path == "/metrics":
                    body = registry_ref.render().encode()
                    ctype = "text/plain; version=0.0.4"
                elif self.path in server_ref._json_debug_routes:
                    attr, missing = server_ref._json_debug_routes[
                        self.path
                    ]
                    provider = getattr(server_ref, attr)
                    if provider is None:
                        body = (missing + "\n").encode()
                        status = 404
                        ctype = "text/plain"
                    else:
                        import json as _json

                        try:
                            body = (
                                _json.dumps(provider(), sort_keys=True)
                                + "\n"
                            ).encode()
                            ctype = "application/json"
                        except Exception as e:
                            what = self.path.rsplit("/", 1)[-1]
                            body = (
                                f"{what} snapshot failed: {e}\n"
                            ).encode()
                            status = 500
                            ctype = "text/plain"
                elif self.path == "/debug/allocations":
                    provider = server_ref.allocations_provider
                    if provider is None:
                        body = b"allocation explainability not enabled\n"
                        status = 404
                        ctype = "text/plain"
                    else:
                        try:
                            body = provider().encode()
                            ctype = "application/x-ndjson"
                        except Exception as e:
                            body = (
                                f"allocations snapshot failed: {e}\n"
                            ).encode()
                            status = 500
                            ctype = "text/plain"
                elif self.path == "/healthz":
                    body = (b"ok" if health["ok"] else b"unhealthy")
                    status = 200 if health["ok"] else 503
                    ctype = "text/plain"
                elif self.path == "/readyz":
                    body, status = server_ref._render_readiness()
                    ctype = "text/plain"
                elif self.path == "/version":
                    from ..version import version_string

                    body = (version_string() + "\n").encode()
                    ctype = "text/plain"
                elif self.path == "/debug/traces":
                    if server_ref.tracer is None:
                        body = b"tracing not enabled\n"
                        status = 404
                        ctype = "text/plain"
                    else:
                        body = server_ref.tracer.export_jsonl().encode()
                        ctype = "application/x-ndjson"
                elif self.path.split("?", 1)[0] == "/debug/requests":
                    provider = server_ref.requests_provider
                    if provider is None:
                        body = b"request tracing not enabled\n"
                        status = 404
                        ctype = "text/plain"
                    else:
                        from urllib.parse import parse_qs, urlparse

                        q = parse_qs(urlparse(self.path).query)
                        view = q.get("view", [""])[0]
                        try:
                            body = provider(view).encode()
                            ctype = "application/x-ndjson"
                        except ValueError as e:
                            body = (str(e) + "\n").encode()
                            status = 400
                            ctype = "text/plain"
                        except Exception as e:
                            body = (
                                f"requests snapshot failed: {e}\n"
                            ).encode()
                            status = 500
                            ctype = "text/plain"
                elif self.path == "/debug/stacks":
                    body = _dump_stacks().encode()
                    ctype = "text/plain"
                elif self.path.startswith("/debug/profile"):
                    from urllib.parse import parse_qs, urlparse

                    q = parse_qs(urlparse(self.path).query)
                    try:
                        secs = float(q.get("seconds", ["2"])[0])
                    except ValueError:
                        body = b"bad seconds parameter\n"
                        status = 400
                        ctype = "text/plain"
                    else:
                        # NaN fails both bounds checks and lands on 2s.
                        if not (0.0 <= secs <= 60.0):
                            secs = min(max(secs, 0.0), 60.0) if secs == secs else 2.0
                        # A HEAD probe must not pin a handler thread on
                        # seconds of stack sampling just to drop the body.
                        body = b"" if head else _sample_profile(secs).encode()
                        ctype = "text/plain"
                else:
                    body = b"not found"
                    status = 404
                    ctype = "text/plain"
                return status, ctype, body

            def _reply(self, status, ctype, body, include_body):
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                if include_body:
                    self.wfile.write(body)

            def _method_not_allowed(self):
                body = b"method not allowed; this surface is GET-only\n"
                self.send_response(405)
                self.send_header("Allow", "GET, HEAD")
                self.send_header("Content-Type", "text/plain")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            # The debug surface is read-only by contract; a mutating
            # method is a caller bug (or a probe misconfiguration) and
            # must say so rather than fall into BaseHTTPRequestHandler's
            # 501. HEAD is a read and is served above.
            do_POST = _method_not_allowed
            do_PUT = _method_not_allowed
            do_DELETE = _method_not_allowed
            do_PATCH = _method_not_allowed

            def log_message(self, *args):
                pass  # quiet; structured logs carry the signal

        self._server = ThreadingHTTPServer((host, port), Handler)
        self.port = self._server.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True, name="metrics-http"
        )
        self._thread.start()

    def set_healthy(self, ok: bool) -> None:
        self._health["ok"] = ok

    def set_usage_provider(self, provider: Callable) -> None:
        """Serve ``provider()`` (a JSON-serializable dict) at
        ``/debug/usage``. Safe to call after ``start()``."""
        self.usage_provider = provider

    def set_allocations_provider(self, provider: Callable) -> None:
        """Serve ``provider()`` (a JSONL string, e.g.
        ``ReferenceAllocator.export_allocations_jsonl``) at
        ``/debug/allocations``. Safe to call after ``start()``."""
        self.allocations_provider = provider

    def set_defrag_provider(self, provider: Callable) -> None:
        """Serve ``provider()`` (a JSON-serializable dict, e.g.
        ``DefragPlanner.export_json``) at ``/debug/defrag``. Safe to
        call after ``start()``."""
        self.defrag_provider = provider

    def set_rebalance_provider(self, provider: Callable) -> None:
        """Serve ``provider()`` (a JSON-serializable dict, e.g.
        ``Rebalancer.snapshot``) at ``/debug/rebalance``. Safe to call
        after ``start()``."""
        self.rebalance_provider = provider

    def set_gateway_provider(self, provider: Callable) -> None:
        """Serve ``provider()`` (a JSON-serializable dict, e.g.
        ``ServingGateway.snapshot``) at ``/debug/gateway``. Safe to
        call after ``start()``."""
        self.gateway_provider = provider

    def set_kv_provider(self, provider: Callable) -> None:
        """Serve ``provider()`` (a JSON-serializable dict, e.g.
        ``DecodeEngine.kv_debug``) at ``/debug/kv``. Safe to call
        after ``start()``."""
        self.kv_provider = provider

    def set_residency_provider(self, provider: Callable) -> None:
        """Serve ``provider()`` (a JSON-serializable dict, e.g.
        ``ResidencyIndex.snapshot``) at ``/debug/residency``. Safe to
        call after ``start()``."""
        self.residency_provider = provider

    def set_compute_provider(self, provider: Callable) -> None:
        """Serve ``provider()`` (a JSON-serializable dict, e.g.
        ``ComputeTelemetry.compute_debug``) at ``/debug/compute``. Safe
        to call after ``start()``."""
        self.compute_provider = provider

    def set_requests_provider(self, provider: Callable) -> None:
        """Serve ``provider(view)`` (a JSONL string, e.g.
        ``ServingTelemetry.export_requests``) at ``/debug/requests``;
        ``view`` is the ``?view=`` query value ("" for the default
        timeline ring) and a ``ValueError`` from the provider renders
        as a 400. Safe to call after ``start()``."""
        self.requests_provider = provider

    def add_readiness_check(self, name: str, check: Callable,
                            critical: bool = True) -> None:
        """Register a readiness check. ``check()`` returns ``(ok, detail)``
        (a bare bool is accepted). A check that raises reads as not-ready
        with the exception as the detail — readiness must fail closed.
        ``critical=False`` checks only downgrade /readyz to ``degraded``
        (still 200) when failing. Safe to call after ``start()`` (late
        registration during wiring)."""
        with self._ready_lock:
            self._ready_checks[name] = (check, critical)

    def _render_readiness(self) -> tuple[bytes, int]:
        lines = []
        all_ok = self._health["ok"]
        degraded = False
        if not self._health["ok"]:
            lines.append("[-] healthz: unhealthy")
        with self._ready_lock:
            checks = sorted(self._ready_checks.items())
        for name, (check, critical) in checks:
            try:
                result = check()
            except Exception as e:
                result = (False, f"check raised: {e}")
            if isinstance(result, tuple):
                ok, detail = result
            else:
                ok, detail = bool(result), ""
            if not ok:
                if critical:
                    all_ok = False
                else:
                    degraded = True
            mark = "+" if ok else ("-" if critical else "~")
            lines.append(f"[{mark}] {name}" + (f": {detail}" if detail else ""))
        if not all_ok:
            lines.append("not ready")
        elif degraded:
            lines.append("degraded")
        else:
            lines.append("ready")
        return ("\n".join(lines) + "\n").encode(), (200 if all_ok else 503)

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
