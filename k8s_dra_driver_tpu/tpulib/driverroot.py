"""Driver-root resolution: locate TPU runtime files under a configured root.

Role of the reference's root type (lengrongfu/k8s-dra-driver,
cmd/nvidia-dra-plugin/root.go:25-107): the driver's files may live on the
host filesystem (mounted into the plugin container) rather than in the
plugin's image, so a root is a prefix under which a fixed list of
well-known directories is searched for each driver file, chasing symlinks
WITHIN the root (chroot-style); a root containing a dev/ directory is a
"dev root" usable for device nodes (root.go:64-81).

Two paths describe the same directory: ``root`` is where the plugin
CONTAINER sees the mount (where the search runs), ``host_root`` is the
HOST path of that directory (what goes into CDI ``hostPath`` fields, which
the container runtime resolves in the host mount namespace). The reference
keeps the same split via NVIDIA_DRIVER_ROOT vs its in-container mount.

TPU equivalents of (libnvidia-ml.so.1, nvidia-smi):

- ``libtpu.so`` — the TPU runtime library. JAX/XLA load it from
  ``TPU_LIBRARY_PATH`` when set, so once found the prepare path mounts it
  into workload containers and points the env at it (the analog of
  nvcdi's driver-library mounts).
- ``tpu-info`` — the diagnostic CLI shipped with recent libtpu wheels
  (nvidia-smi analog); surfaced in startup logs for debugging.
"""

from __future__ import annotations

import dataclasses
import glob
import os

# Well-known library directories, relative to the root. The plain system
# paths mirror root.go:30-37; the site-packages globs cover libtpu wheels
# (the common install on GKE TPU node images and dev hosts).
LIBRARY_SEARCH_PATHS = [
    "usr/lib64",
    "usr/lib/x86_64-linux-gnu",
    "usr/lib/aarch64-linux-gnu",
    "lib64",
    "lib/x86_64-linux-gnu",
    "lib/aarch64-linux-gnu",
    "usr/local/lib",
    "lib/libtpu",
    "usr/lib/python3*/site-packages/libtpu",
    "usr/local/lib/python3*/site-packages/libtpu",
    "opt/*/lib/python3*/site-packages/libtpu",
    "home/*/.local/lib/python3*/site-packages/libtpu",
]

# Binary search directories (root.go:49-55 analog).
BINARY_SEARCH_PATHS = [
    "usr/bin",
    "usr/sbin",
    "bin",
    "sbin",
    "usr/local/bin",
]


class DriverRootError(FileNotFoundError):
    """A driver file was not found under the root."""


@dataclasses.dataclass(frozen=True)
class DriverRoot:
    """A filesystem prefix containing the TPU driver installation.

    ``root`` — the prefix as visible to THIS process (the plugin
    container's mount of the host directory).
    ``host_root`` — the same directory's path on the host; defaults to
    ``root`` (correct when running unconfined on the host itself).
    """

    root: str = "/"
    host_root: str | None = None

    # -- symlink handling --------------------------------------------------

    def _resolve_link(self, path: str, max_hops: int = 16) -> str:
        """Chase symlinks chroot-style: the link target — absolute, or
        relative with ``..`` chains — is interpreted as if the root were
        ``/``. posixpath.normpath clamps ``/../`` at ``/``, so a target
        like ``../../../../usr/lib/libtpu.so`` cannot escape into the
        plugin container's own filesystem (and then be emitted as a bogus
        CDI hostPath)."""
        # Virtual (in-root) view of the path.
        v = "/" + os.path.relpath(path, self.root)
        for _ in range(max_hops):
            real = os.path.join(self.root, v.lstrip("/"))
            if not os.path.islink(real):
                return real
            target = os.readlink(real)
            if not os.path.isabs(target):
                target = os.path.join(os.path.dirname(v), target)
            v = os.path.normpath(target)
        raise DriverRootError(f"symlink loop resolving {path!r}")

    # -- layered search (findFile analog, root.go:84-107) ------------------

    def find_file(self, name: str, search_in: list[str]) -> str:
        """Search the root itself plus each listed directory (glob
        patterns allowed) for `name`; resolve symlinks; return the first
        regular file found (container-visible path)."""
        for rel in ["", *search_in]:
            pattern = os.path.join(self.root, rel, name)
            for candidate in sorted(glob.glob(pattern)):
                try:
                    resolved = self._resolve_link(candidate)
                except DriverRootError:
                    continue
                if os.path.isfile(resolved):
                    return resolved
        raise DriverRootError(
            f"{name!r} not found under driver root {self.root!r}"
        )

    def find_library(self, name: str = "libtpu.so") -> str:
        return self.find_file(name, LIBRARY_SEARCH_PATHS)

    def find_binary(self, name: str = "tpu-info") -> str:
        return self.find_file(name, BINARY_SEARCH_PATHS)

    # -- container -> host translation -------------------------------------

    def to_host_path(self, path: str) -> str:
        """Translate a path found under ``root`` into the host mount
        namespace, where the container runtime resolves CDI hostPaths."""
        hroot = self.host_root if self.host_root is not None else self.root
        rel = os.path.relpath(path, self.root)
        if rel.startswith(".."):
            raise DriverRootError(
                f"{path!r} is not under driver root {self.root!r}"
            )
        return os.path.normpath(os.path.join(hroot, rel))

    # -- dev root (root.go:64-81 analog) -----------------------------------

    def is_dev_root(self) -> bool:
        return os.path.isdir(os.path.join(self.root, "dev"))

    def dev_root(self) -> str:
        """The dev root associated with this root: itself if it contains a
        dev/ directory, else the container's own /."""
        return self.root if self.is_dev_root() else "/"

    # -- workload wiring ---------------------------------------------------

    def libtpu_or_none(self) -> str | None:
        try:
            return self.find_library()
        except DriverRootError:
            return None
