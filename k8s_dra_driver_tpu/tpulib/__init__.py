"""tpulib: TPU chip discovery and device modeling (role of the reference's
nvlib.go + deviceinfo.go + allocatable.go, see SURVEY.md §2)."""

from .chiplib import (  # noqa: F401
    HEALTH_DEGRADED,
    HEALTH_GONE,
    HEALTH_HEALTHY,
    ICI_CHANNEL_COUNT,
    ChipLib,
    ChipLibConfig,
    FakeChipLib,
    HealthStatus,
    RealChipLib,
    SHARING_EXCLUSIVE,
    SHARING_PROCESS_SHARED,
    SHARING_TIME_SHARED,
)
from .deviceinfo import (  # noqa: F401
    AllocatableDevice,
    AllocatableDevices,
    ChipDeviceType,
    ChipInfo,
    IciChannelDeviceType,
    IciChannelInfo,
    TensorCoreDeviceType,
    TensorCoreInfo,
    counter_sets,
)
from .topology import (  # noqa: F401
    GENERATIONS,
    Coord,
    MeshShape,
    enumerate_submeshes,
    is_contiguous_submesh,
)
