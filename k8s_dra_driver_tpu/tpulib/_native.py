"""ctypes loader for the native discovery shim (see native/tpu_discovery.cpp).

Builds on demand with the in-tree Makefile if the shared object is missing
(g++ is part of the toolchain; pybind11 is not, hence ctypes).  All callers
must tolerate ``available == False`` — the pure-Python sysfs fallback in
``chiplib.RealChipLib`` has identical semantics.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess

logger = logging.getLogger(__name__)

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "..", "native")
_SO_PATH = os.path.abspath(os.path.join(_NATIVE_DIR, "libtpudiscovery.so"))


class NativeShim:
    def __init__(self, lib: ctypes.CDLL | None):
        self._lib = lib
        self.available = lib is not None
        if lib is not None:
            lib.tpud_count_accel.argtypes = [ctypes.c_char_p]
            lib.tpud_count_accel.restype = ctypes.c_int
            lib.tpud_chip_meta.argtypes = [
                ctypes.c_char_p,
                ctypes.c_int,
                ctypes.c_char_p,
                ctypes.c_int,
            ]
            lib.tpud_chip_meta.restype = ctypes.c_int
            lib.tpud_mknod_char.argtypes = [
                ctypes.c_char_p,
                ctypes.c_int,
                ctypes.c_int,
                ctypes.c_int,
            ]
            lib.tpud_mknod_char.restype = ctypes.c_int
            lib.tpud_read_file.argtypes = [
                ctypes.c_char_p,
                ctypes.c_char_p,
                ctypes.c_int,
            ]
            lib.tpud_read_file.restype = ctypes.c_int
            lib.tpud_vfio_groups.argtypes = [
                ctypes.c_char_p,
                ctypes.c_char_p,
                ctypes.c_char_p,
                ctypes.c_int,
            ]
            lib.tpud_vfio_groups.restype = ctypes.c_int
            lib.tpud_watch_devdir.argtypes = [ctypes.c_char_p, ctypes.c_int]
            lib.tpud_watch_devdir.restype = ctypes.c_int

    def count_accel(self, dev_root: str) -> int:
        return self._lib.tpud_count_accel(dev_root.encode())

    def chip_meta(self, sysfs_root: str, index: int) -> dict[str, str]:
        buf = ctypes.create_string_buffer(4096)
        n = self._lib.tpud_chip_meta(sysfs_root.encode(), index, buf, len(buf))
        if n < 0:
            return {}
        meta = {}
        for line in buf.value.decode().splitlines():
            if "=" in line:
                k, v = line.split("=", 1)
                meta[k] = v
        return meta

    def mknod_char(self, path: str, major: int, minor: int, mode: int) -> None:
        rc = self._lib.tpud_mknod_char(path.encode(), major, minor, mode)
        if rc != 0:
            raise OSError(-rc, os.strerror(-rc), path)

    def read_file(self, path: str) -> str:
        buf = ctypes.create_string_buffer(4096)
        n = self._lib.tpud_read_file(path.encode(), buf, len(buf))
        if n < 0:
            raise OSError(-n, os.strerror(-n), path)
        return buf.value.decode()

    def vfio_groups(self, dev_root: str, sysfs_root: str) -> dict[int, str]:
        """{group number: pci address} for every /dev/vfio group node."""
        buf = ctypes.create_string_buffer(65536)
        n = self._lib.tpud_vfio_groups(
            dev_root.encode(), sysfs_root.encode(), buf, len(buf)
        )
        if n < 0:
            return {}
        groups: dict[int, str] = {}
        for line in buf.value.decode().splitlines():
            fields = dict(
                f.split("=", 1) for f in line.split(" ") if "=" in f
            )
            if "group" in fields:
                groups[int(fields["group"])] = fields.get("pci", "")
        return groups

    def watch_devdir(self, dev_root: str, timeout_ms: int) -> bool:
        """Block until a device node changes under {dev_root}/dev (inotify);
        False on timeout. Raises when the directory cannot be watched."""
        rc = self._lib.tpud_watch_devdir(dev_root.encode(), timeout_ms)
        if rc < 0:
            raise OSError(-rc, os.strerror(-rc), dev_root)
        return rc > 0


def _build() -> bool:
    try:
        subprocess.run(
            ["make", "-s", "-C", _NATIVE_DIR],
            check=True,
            capture_output=True,
            timeout=120,
        )
        return True
    except Exception as e:  # toolchain absent or build failure: fall back
        logger.warning("native shim build failed: %s", e)
        return False


def load(allow_build: bool = True) -> NativeShim:
    if not os.path.exists(_SO_PATH) and allow_build:
        _build()
    if os.path.exists(_SO_PATH):
        try:
            return NativeShim(ctypes.CDLL(_SO_PATH))
        except OSError as e:
            logger.warning("failed to load native shim: %s", e)
    return NativeShim(None)
