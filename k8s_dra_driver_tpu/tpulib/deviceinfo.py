"""Allocatable-device model and ResourceSlice attribute rendering.

TPU-native analog of the reference's deviceinfo.go + allocatable.go
(lengrongfu/k8s-dra-driver, cmd/nvidia-dra-plugin/deviceinfo.go:30-217,
allocatable.go:25-108): three device kinds form a tagged union —

- ``ChipInfo``        — a whole TPU chip            (reference: GpuInfo)
- ``TensorCoreInfo``  — a sub-chip core partition   (reference: MigDeviceInfo)
- ``IciChannelInfo``  — an interconnect channel     (reference: ImexChannelInfo)

Each renders itself to a ``resource.k8s.io`` Device (plain dict in k8s wire
shape) with topology-first attributes so the stock scheduler's CEL /
matchAttribute machinery can express things the reference could not, e.g.
"4 chips forming a contiguous 2x2 sub-mesh on one host".
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

from .topology import GENERATIONS, Coord, MeshShape

# Device type tags (reference: cmd/nvidia-dra-plugin/types.go:19-24).
ChipDeviceType = "chip"
TensorCoreDeviceType = "tensorcore"
IciChannelDeviceType = "ici"
UnknownDeviceType = "unknown"

ATTR_PREFIX = "tpu.google.com"


def _attr(value: Any) -> dict[str, Any]:
    """Wrap a value in the DRA DeviceAttribute union shape."""
    if isinstance(value, bool):
        return {"bool": value}
    if isinstance(value, int):
        return {"int": value}
    if isinstance(value, str):
        # Version-ish strings go in the version slot, everything else string.
        return {"string": value}
    raise TypeError(f"unsupported attribute type: {type(value)!r}")


def _version_attr(value: str) -> dict[str, Any]:
    return {"version": value}


@dataclasses.dataclass
class ChipInfo:
    """A whole TPU chip (reference GpuInfo, deviceinfo.go:30-43)."""

    index: int                      # host-local chip index (device ordinal)
    uuid: str                       # stable id, e.g. "TPU-<serial>"
    generation: str                 # "v4" | "v5e" | "v5p" | "v6e" | ...
    device_paths: list[str]         # e.g. ["/dev/accel0"] or vfio group nodes
    hbm_bytes: int
    cores: int                      # TensorCores on this chip
    coord: Coord                    # ICI coordinates within the slice
    slice_id: str                   # pod-slice identity, e.g. "v5p-16-abcd"
    slice_topology: MeshShape       # physical shape of the owning slice
    host_id: int                    # worker index within the slice
    hosts_per_slice: int
    pci_address: str = ""
    numa_node: int = -1
    driver_version: str = "0.0.0"   # libtpu version
    firmware_version: str = "0.0.0"
    # False when the chip library could not ground the coordinate in
    # runtime metadata (chiplib.RealChipLib.enumerate_chips contract) —
    # the contiguity tile attributes are then withheld so a scheduler
    # never gang-allocates on made-up adjacency.
    coords_reliable: bool = True
    # Health flags stamped by DeviceState from the chip library's health
    # poll (chiplib.HealthStatus). Published as the tpu.google.com/healthy
    # attribute so CEL selectors can require healthy chips; ``gone`` chips
    # never render at all (DeviceState drops them from allocatable).
    healthy: bool = True
    health_reason: str = ""

    def canonical_name(self) -> str:
        return f"tpu-{self.index}"

    def canonical_index(self) -> str:
        return str(self.index)

    def uuids(self) -> list[str]:
        return [self.uuid]

    def submesh_tile_id(self, tx: int, ty: int, tz: int = 1) -> str:
        """Identity of the axis-aligned (tx, ty, tz) tile this chip's
        coordinate falls in, scoped to the slice.

        Published as an attribute so a stock scheduler can enforce ICI
        contiguity with nothing but ``matchAttribute``: every chip with the
        same tile id is, by construction, part of one contiguous sub-mesh
        (the TPU analog of MIG placement constraints,
        demo/specs/quickstart/gpu-test4.yaml:42-44). Aligned tiles partition
        the slice, so tile-equality claims can never straddle a gap.
        """
        c = self.coord
        return (
            f"{self.slice_id}:{tx}x{ty}x{tz}:"
            f"{c.x // tx}-{c.y // ty}-{c.z // tz}"
        )

    def get_device(self) -> dict[str, Any]:
        """Render as a resource.k8s.io Device (deviceinfo.go:98-140 analog)."""
        spec = GENERATIONS.get(self.generation)
        peak_flops = int(spec.peak_bf16_flops) if spec else 0
        dev = {
            "name": self.canonical_name(),
            "basic": {
                "attributes": {
                    "type": _attr(ChipDeviceType),
                    "uuid": _attr(self.uuid),
                    "index": _attr(self.index),
                    "generation": _attr(self.generation),
                    "cores": _attr(self.cores),
                    "iciX": _attr(self.coord.x),
                    "iciY": _attr(self.coord.y),
                    "iciZ": _attr(self.coord.z),
                    "coord": _attr(str(self.coord)),
                    "sliceId": _attr(self.slice_id),
                    "sliceTopology": _attr(str(self.slice_topology)),
                    "hostId": _attr(self.host_id),
                    "hostsPerSlice": _attr(self.hosts_per_slice),
                    "pcieAddress": _attr(self.pci_address),
                    "numaNode": _attr(self.numa_node),
                    "healthy": _attr(self.healthy),
                    "driverVersion": _version_attr(self.driver_version),
                    "firmwareVersion": _version_attr(self.firmware_version),
                },
                "capacity": {
                    "hbm": {"value": str(self.hbm_bytes)},
                    "tensorcores": {"value": str(self.cores)},
                    "peakBf16Flops": {"value": str(peak_flops)},
                },
            },
        }
        if self.coords_reliable:
            attrs = dev["basic"]["attributes"]
            attrs["submesh2x2Id"] = _attr(self.submesh_tile_id(2, 2, 1))
            attrs["submesh4x4Id"] = _attr(self.submesh_tile_id(4, 4, 1))
        if self.cores >= 2:
            # A whole-chip claim drains the chip's counter set, so the
            # scheduler cannot also hand out this chip's TensorCore
            # partitions (and vice versa). The reference encodes the same
            # exclusivity via MIG memory-slice capacities
            # (deviceinfo.go:184-198).
            dev["basic"]["consumesCounters"] = [
                {
                    "counterSet": f"chip-{self.index}-counters",
                    "counters": {
                        "cores": {"value": str(self.cores)},
                        "hbm": {"value": str(self.hbm_bytes)},
                    },
                }
            ]
        return dev


@dataclasses.dataclass(frozen=True)
class PartitionProfile:
    """A sub-chip partition SHAPE (role of the reference's MIG profile
    records, nvlib.go:244-295): how many cores one instance consumes,
    what fraction of the chip's HBM it takes, and which placement start
    positions it may occupy. The placement/counter machinery is profile-
    generic even though current TPU generations ship only the single
    whole-core profile — a future asymmetric profile (e.g. one core with
    half the chip's HBM) is a table entry, not a code change.
    """

    name: str                       # e.g. "1c"; "1c.halfhbm"; "2c"
    cores: int = 1                  # cores consumed per instance
    # HBM consumed as a fraction (num, den) of the parent chip's HBM;
    # None = proportional to cores/total_cores.
    hbm_fraction: Optional[tuple[int, int]] = None

    def placements(self, total_cores: int) -> list[int]:
        """Valid start cores for this profile on a chip with
        ``total_cores`` (aligned, non-overlapping — MIG placement sets)."""
        if self.cores > total_cores:
            return []
        return list(range(0, total_cores - self.cores + 1, self.cores))

    def hbm_share(self, parent_hbm: int, total_cores: int) -> int:
        if self.hbm_fraction is not None:
            num, den = self.hbm_fraction
            return parent_hbm * num // den
        return parent_hbm * self.cores // max(total_cores, 1)


# The single-core profile every multi-core generation supports (v4/v5p
# chips run two independent TensorCore programs when not fused in
# megacore mode).
ONE_CORE_PROFILE = PartitionProfile(name="1c", cores=1)


def partition_profiles(generation: str) -> list[PartitionProfile]:
    """Profiles a generation supports (reference: the per-arch MIG
    profile enumeration, nvlib.go:244-295). One table entry today;
    the seam future profiles plug into."""
    spec = GENERATIONS.get(generation)
    if spec is None or not spec.partitionable:
        return []
    return [ONE_CORE_PROFILE]


@dataclasses.dataclass
class TensorCoreInfo:
    """A sub-chip TensorCore partition (reference MigDeviceInfo,
    deviceinfo.go:45-56).

    Where MIG slices a GPU into profiles with memory slices, TPU sub-chip
    partitioning hands out placements of a ``PartitionProfile`` on a
    multi-core chip. Each partition is advertised as a first-class device
    that consumes its profile's share of the parent chip's counters, so
    the scheduler can never double-book a chip as both whole and
    partitioned, nor overlap two placements.
    """

    parent: ChipInfo
    core_index: int                 # placement start core within the chip
    profile: PartitionProfile = ONE_CORE_PROFILE

    @property
    def uuid(self) -> str:
        # Profile-qualified so placements of different profiles at the
        # same start core never collide; "1c" keeps the historical form.
        # Parse these back with ``chip_uuid_of_device_uuid`` — never with
        # ad-hoc string splitting.
        if self.profile.name == "1c":
            return f"{self.parent.uuid}-core-{self.core_index}"
        return (
            f"{self.parent.uuid}-{self.profile.name}-{self.core_index}"
        )

    def spanned_cores(self) -> list[int]:
        """The physical core indices this placement occupies."""
        return list(
            range(self.core_index, self.core_index + self.profile.cores)
        )

    def canonical_name(self) -> str:
        # reference: fmt "gpu-%d-mig-%d-%d-%d" deviceinfo.go:80-88. The
        # 1c profile keeps the historical "tpu-N-core-M" names; other
        # profiles carry their profile name MIG-style.
        if self.profile.name == "1c":
            return f"tpu-{self.parent.index}-core-{self.core_index}"
        return (
            f"tpu-{self.parent.index}-{self.profile.name}-{self.core_index}"
        )

    def canonical_index(self) -> str:
        return f"{self.parent.index}:{self.core_index}"

    def uuids(self) -> list[str]:
        return [self.uuid]

    def get_device(self) -> dict[str, Any]:
        total = max(self.parent.cores, 1)
        hbm_share = self.profile.hbm_share(self.parent.hbm_bytes, total)
        spec = GENERATIONS.get(self.parent.generation)
        flops_share = (
            int(spec.peak_bf16_flops) * self.profile.cores // total
            if spec else 0
        )
        dev = {
            "name": self.canonical_name(),
            "basic": {
                "attributes": {
                    "type": _attr(TensorCoreDeviceType),
                    "uuid": _attr(self.uuid),
                    "parentUuid": _attr(self.parent.uuid),
                    "parentIndex": _attr(self.parent.index),
                    "index": _attr(self.core_index),
                    "profile": _attr(self.profile.name),
                    "profileCores": _attr(self.profile.cores),
                    "generation": _attr(self.parent.generation),
                    "coord": _attr(str(self.parent.coord)),
                    "sliceId": _attr(self.parent.slice_id),
                    "hostId": _attr(self.parent.host_id),
                    # A partition is only as healthy as its parent chip.
                    "healthy": _attr(self.parent.healthy),
                    "driverVersion": _version_attr(self.parent.driver_version),
                },
                "capacity": {
                    "hbm": {"value": str(hbm_share)},
                    "tensorcores": {"value": str(self.profile.cores)},
                    "peakBf16Flops": {"value": str(flops_share)},
                },
            },
        }
        # consumesCounters ties every partition of one chip together so
        # the scheduler cannot double-book a chip as both whole and
        # partitioned, nor overlap placements (role of MIG memory-slice
        # capacities, deviceinfo.go:184-198).
        dev["basic"]["consumesCounters"] = [
            {
                "counterSet": f"chip-{self.parent.index}-counters",
                "counters": {
                    "cores": {"value": str(self.profile.cores)},
                    "hbm": {"value": str(hbm_share)},
                },
            }
        ]
        return dev


@dataclasses.dataclass
class IciChannelInfo:
    """A cross-host interconnect channel (reference ImexChannelInfo,
    deviceinfo.go:58-61).

    IMEX channels gate NVLink cross-node memory export; the TPU analog is a
    claimable channel on a slice's ICI/DCN domain.  Workloads that want
    cross-host collectives claim one channel per pod from the slice's domain
    pool; preparation materialises the common launch environment (coordinator
    address, megascale ids) that makes jax.distributed over ICI/DCN work.
    """

    channel: int
    slice_id: str = ""

    def canonical_name(self) -> str:
        return f"ici-channel-{self.channel}"

    NAME_PREFIX = "ici-channel-"

    def uuids(self) -> list[str]:
        return [f"ici-channel-{self.channel}"]

    def get_device(self) -> dict[str, Any]:
        return {
            "name": self.canonical_name(),
            "basic": {
                "attributes": {
                    "type": _attr(IciChannelDeviceType),
                    "channel": _attr(self.channel),
                    "sliceId": _attr(self.slice_id),
                },
            },
        }


@dataclasses.dataclass
class AllocatableDevice:
    """Tagged union over the three device kinds (allocatable.go:27-31)."""

    chip: Optional[ChipInfo] = None
    tensorcore: Optional[TensorCoreInfo] = None
    ici_channel: Optional[IciChannelInfo] = None

    def type(self) -> str:
        if self.chip is not None:
            return ChipDeviceType
        if self.tensorcore is not None:
            return TensorCoreDeviceType
        if self.ici_channel is not None:
            return IciChannelDeviceType
        return UnknownDeviceType

    @property
    def impl(self):
        return self.chip or self.tensorcore or self.ici_channel

    def canonical_name(self) -> str:
        return self.impl.canonical_name()

    def get_device(self) -> dict[str, Any]:
        return self.impl.get_device()


# name -> AllocatableDevice (reference: AllocatableDevices map, allocatable.go:25)
AllocatableDevices = dict[str, AllocatableDevice]


def is_ici_channel_device_name(name: str) -> bool:
    """Whether a device (or allocation-result) name is an ICI channel —
    IciChannelInfo.canonical_name's form. The one classifier; callers
    must not match the prefix themselves, and must never classify by
    POOL name: node pools are named after operator-controlled node
    names, which may themselves start with "ici-"."""
    return name.startswith(IciChannelInfo.NAME_PREFIX)


def chip_uuid_of_device_uuid(device_uuid: str) -> str:
    """The chip uuid any device uuid belongs to. Chip uuids are
    ``TPU-<serial>`` with a hyphen-free serial; partition uuids append
    ``-core-<i>`` (1c profile) or ``-<profile>-<i>`` (TensorCoreInfo.uuid
    above) — so the chip is always the first two hyphen tokens. The one
    parser for that format; callers must not re-implement the split."""
    return "-".join(device_uuid.split("-")[:2])


def chip_uuids(devices: AllocatableDevices) -> list[str]:
    return sorted(
        d.chip.uuid for d in devices.values() if d.chip is not None
    )


def counter_sets(devices: AllocatableDevices) -> list[dict[str, Any]]:
    """SharedCounter sets for partitionable chips (one per multi-core chip)."""
    out = []
    for d in devices.values():
        if d.chip is None or d.chip.cores < 2:
            continue
        out.append(
            {
                "name": f"chip-{d.chip.index}-counters",
                "counters": {
                    "cores": {"value": str(d.chip.cores)},
                    "hbm": {"value": str(d.chip.hbm_bytes)},
                },
            }
        )
    return out
