"""TPU generation specs and ICI topology math.

This is the TPU-native replacement for the reference's GPU architecture /
CUDA-compute-capability attribute surface (nvlib.go:202-313 in
lengrongfu/k8s-dra-driver): instead of `architecture` + `cudaComputeCapability`
we model the things a scheduler (and a JAX workload) actually needs on TPU —
generation, cores per chip, HBM, peak FLOPs, and the chip's coordinates in the
ICI mesh, so that multi-chip claims can demand *contiguous sub-meshes* via
attribute selectors (the capability the reference deliberately skipped for
dynamic MIG, device_state.go:512).
"""

from __future__ import annotations

import dataclasses
import itertools
from typing import Iterator


@dataclasses.dataclass(frozen=True)
class GenerationSpec:
    """Static per-generation hardware description."""

    name: str                   # "v4", "v5e", "v5p", "v6e"
    cores_per_chip: int         # TensorCores per chip
    hbm_bytes: int
    # Peak dense bf16 FLOP/s per chip (both cores). Used by the workload layer
    # for MFU accounting and published as a capacity so schedulers can reason
    # about "how much compute" a claim grants.
    peak_bf16_flops: float
    # ICI mesh dimensionality: v4/v5p are 3D tori, v5e/v6e are 2D meshes.
    ici_dims: int
    # Whether two cores can be addressed as independent sub-chip devices
    # ("megacore" generations fuse them; pre-v4 and v5e expose one core/chip).
    partitionable: bool


GENERATIONS: dict[str, GenerationSpec] = {
    "v2": GenerationSpec("v2", 2, 8 << 30, 45e12, 2, True),
    "v3": GenerationSpec("v3", 2, 16 << 30, 123e12, 2, True),
    "v4": GenerationSpec("v4", 2, 32 << 30, 275e12, 3, True),
    "v5e": GenerationSpec("v5e", 1, 16 << 30, 197e12, 2, False),
    "v5p": GenerationSpec("v5p", 2, 95 << 30, 459e12, 3, True),
    "v6e": GenerationSpec("v6e", 1, 32 << 30, 918e12, 2, False),
}


@dataclasses.dataclass(frozen=True, order=True)
class Coord:
    """Chip coordinate in the ICI mesh (z is 0 for 2D generations)."""

    x: int
    y: int
    z: int = 0

    def as_tuple(self) -> tuple[int, int, int]:
        return (self.x, self.y, self.z)

    def __str__(self) -> str:  # "1,2,0"
        return f"{self.x},{self.y},{self.z}"

    @classmethod
    def parse(cls, s: str) -> "Coord":
        parts = [int(p) for p in s.split(",")]
        while len(parts) < 3:
            parts.append(0)
        return cls(*parts[:3])


@dataclasses.dataclass(frozen=True)
class MeshShape:
    """Physical topology of a slice, e.g. 2x2x1 (v5e-4) or 4x4x4 (v5p-128)."""

    x: int
    y: int
    z: int = 1

    def __str__(self) -> str:
        return f"{self.x}x{self.y}x{self.z}"

    @classmethod
    def parse(cls, s: str) -> "MeshShape":
        parts = [int(p) for p in s.lower().split("x")]
        while len(parts) < 3:
            parts.append(1)
        return cls(*parts[:3])

    @property
    def num_chips(self) -> int:
        return self.x * self.y * self.z

    def coords(self) -> Iterator[Coord]:
        for x, y, z in itertools.product(
            range(self.x), range(self.y), range(self.z)
        ):
            yield Coord(x, y, z)

    def coord_at(self, index: int) -> Coord:
        """The index'th coordinate in ``coords()`` order (x outermost, z
        fastest) without materialising the iterator. This linearisation IS
        the coordinate contract: device index n on a host maps to the n'th
        cell of the host's chip block."""
        if not 0 <= index < self.num_chips:
            raise IndexError(f"index {index} outside {self}")
        yz = self.y * self.z
        return Coord(index // yz, (index % yz) // self.z, index % self.z)

    def index_of(self, c: Coord) -> int:
        """Inverse of ``coord_at``."""
        return (c.x * self.y + c.y) * self.z + c.z

    def divides(self, other: "MeshShape") -> bool:
        """True iff this shape tiles ``other`` exactly along every axis."""
        return (
            other.x % self.x == 0
            and other.y % self.y == 0
            and other.z % self.z == 0
        )

    def contains(self, c: Coord) -> bool:
        return 0 <= c.x < self.x and 0 <= c.y < self.y and 0 <= c.z < self.z


def is_contiguous_submesh(coords: list[Coord]) -> bool:
    """True iff `coords` form a dense axis-aligned box in the ICI mesh.

    This is the predicate behind gang allocation of multi-chip claims: a claim
    for N chips is only useful if the chips are an unbroken sub-mesh, because
    XLA's collective performance model assumes torus/mesh neighbours.  The
    scheduler enforces it via matchAttribute on the submesh id we publish; this
    helper is what the fake/real chiplibs and tests use to validate that.
    """
    if not coords:
        return False
    if len(set(coords)) != len(coords):
        return False
    xs = [c.x for c in coords]
    ys = [c.y for c in coords]
    zs = [c.z for c in coords]
    dims = (
        max(xs) - min(xs) + 1,
        max(ys) - min(ys) + 1,
        max(zs) - min(zs) + 1,
    )
    return dims[0] * dims[1] * dims[2] == len(coords)


def enumerate_submeshes(
    shape: MeshShape, sub: MeshShape
) -> Iterator[tuple[Coord, list[Coord]]]:
    """Yield (origin, member coords) for every axis-aligned `sub` box in `shape`.

    Used by the plugin to publish "submesh" attributes (the TPU analog of MIG
    placement enumeration, nvlib.go:244-295: for every profile, every placement
    it fits is advertised so the scheduler can pick a non-overlapping one).
    """
    for ox in range(shape.x - sub.x + 1):
        for oy in range(shape.y - sub.y + 1):
            for oz in range(shape.z - sub.z + 1):
                origin = Coord(ox, oy, oz)
                members = [
                    Coord(ox + dx, oy + dy, oz + dz)
                    for dx, dy, dz in itertools.product(
                        range(sub.x), range(sub.y), range(sub.z)
                    )
                ]
                yield origin, members


def box_shapes(volume: int, within: MeshShape) -> list[tuple[int, int, int]]:
    """Every axis-aligned box (dx, dy, dz) of exactly ``volume`` cells
    that fits inside ``within``, most-cubical first.

    This is the gang-shape enumeration behind the allocator's placement
    scorer and the defrag planner: a claim for N chips is satisfiable by
    any dense N-cell box, and trying compact shapes first keeps the ICI
    hop diameter (and therefore collective latency) low.
    """
    out = []
    for dx in range(1, min(volume, within.x) + 1):
        if volume % dx:
            continue
        rem = volume // dx
        for dy in range(1, min(rem, within.y) + 1):
            if rem % dy:
                continue
            dz = rem // dy
            if dz <= within.z:
                out.append((dx, dy, dz))
    out.sort(key=lambda d: (max(d) - min(d), d))
    return out


def free_components(free: set[tuple[int, int, int]]) -> list[set[tuple[int, int, int]]]:
    """Connected components of the free cell set under ICI adjacency
    (6-neighbour). The component a placement lands in is the scorer's
    best-fit unit: packing a gang into the smallest component that still
    fits it preserves the larger components for future large gangs."""
    seen: set[tuple[int, int, int]] = set()
    comps = []
    for start in free:
        if start in seen:
            continue
        comp = set()
        stack = [start]
        seen.add(start)
        while stack:
            x, y, z = stack.pop()
            comp.add((x, y, z))
            for nb in ((x + 1, y, z), (x - 1, y, z), (x, y + 1, z),
                       (x, y - 1, z), (x, y, z + 1), (x, y, z - 1)):
                if nb in free and nb not in seen:
                    seen.add(nb)
                    stack.append(nb)
        comps.append(comp)
    return comps


def largest_free_submesh(
    shape: MeshShape, free: set[tuple[int, int, int]]
) -> int:
    """Volume of the largest fully-free axis-aligned box in ``shape``.

    The fleet fragmentation metric: under churn this number decays even
    while total free capacity stays flat, and it bounds the largest gang
    claim that can still be satisfied without defragmentation. Uses a 3D
    prefix sum so every (dims, origin) probe is O(1); total cost is
    O(|dims| * |origins|), fine for per-slice meshes.
    """
    if not free:
        return 0
    nx, ny, nz = shape.x, shape.y, shape.z
    # p[x][y][z] = free cells in the [0,x) x [0,y) x [0,z) prefix box.
    p = [[[0] * (nz + 1) for _ in range(ny + 1)] for _ in range(nx + 1)]
    for x in range(nx):
        for y in range(ny):
            row = p[x + 1][y + 1]
            prow = p[x][y + 1]
            srow = p[x + 1][y]
            drow = p[x][y]
            for z in range(nz):
                row[z + 1] = (
                    (1 if (x, y, z) in free else 0)
                    + prow[z + 1] + srow[z + 1] + row[z]
                    - drow[z + 1] - prow[z] - srow[z] + drow[z]
                )

    def box_free(ox, oy, oz, dx, dy, dz) -> bool:
        x1, y1, z1 = ox + dx, oy + dy, oz + dz
        total = (
            p[x1][y1][z1] - p[ox][y1][z1] - p[x1][oy][z1] - p[x1][y1][oz]
            + p[ox][oy][z1] + p[ox][y1][oz] + p[x1][oy][oz] - p[ox][oy][oz]
        )
        return total == dx * dy * dz

    best = 1  # free is non-empty, so a 1-cell box always exists
    for dx in range(1, nx + 1):
        for dy in range(1, ny + 1):
            for dz in range(1, nz + 1):
                vol = dx * dy * dz
                if vol <= best or vol > len(free):
                    continue
                hit = False
                for ox in range(nx - dx + 1):
                    for oy in range(ny - dy + 1):
                        for oz in range(nz - dz + 1):
                            if box_free(ox, oy, oz, dx, dy, dz):
                                best = vol
                                hit = True
                                break
                        if hit:
                            break
                    if hit:
                        break
    return best


def default_slice_shapes(generation: str, num_chips: int) -> MeshShape:
    """Best-effort physical shape for a slice of `num_chips` chips."""
    spec = GENERATIONS.get(generation, GENERATIONS["v4"])
    if spec.ici_dims == 2:
        # Square-ish 2D mesh.
        x = 1
        for cand in range(1, int(num_chips**0.5) + 1):
            if num_chips % cand == 0:
                x = cand
        return MeshShape(x, num_chips // x, 1)
    # 3D torus: cube-ish factorisation.
    best = (1, 1, num_chips)
    for x in range(1, num_chips + 1):
        if num_chips % x:
            continue
        rem = num_chips // x
        for y in range(1, rem + 1):
            if rem % y:
                continue
            z = rem // y
            cand = tuple(sorted((x, y, z)))
            if max(cand) - min(cand) < max(best) - min(best):
                best = cand
    return MeshShape(*best)
