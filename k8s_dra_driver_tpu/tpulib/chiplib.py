"""Chip enumeration library: the hardware seam of the driver.

TPU-native analog of the reference's deviceLib (lengrongfu/k8s-dra-driver,
cmd/nvidia-dra-plugin/nvlib.go:40-46, :111-200): where the reference wraps
NVML via cgo to enumerate GPUs/MIG devices/IMEX channels, we enumerate TPU
chips from ``/dev/accel*`` + sysfs (optionally accelerated by the C++ shim in
``k8s_dra_driver_tpu/native``) and synthesise TensorCore partitions and ICI
channels from generation/topology metadata.

Unlike the reference — whose only backend is real hardware, making its test
story "run the demo on GPUs" (SURVEY.md §4) — the backend here is an abstract
interface with a first-class ``FakeChipLib``, so every layer above (device
state, CDI, gRPC plugin, controller) is testable hermetically.
"""

from __future__ import annotations

import abc
import dataclasses
import glob
import hashlib
import logging
import os
import re
import stat
import threading
import time
from typing import Optional

from .deviceinfo import (
    AllocatableDevice,
    AllocatableDevices,
    ChipInfo,
    IciChannelInfo,
    TensorCoreInfo,
)
from .topology import GENERATIONS, Coord, MeshShape, default_slice_shapes

logger = logging.getLogger(__name__)


def _safe_int(value, default: int) -> int:
    """Tolerant int parse for sysfs values (kernel files can hold garbage)."""
    try:
        return int(str(value).strip())
    except (TypeError, ValueError):
        return default


def _hostpath(root: str, rel: str) -> str:
    """Join a host-root prefix with a relative path; root='/' must yield
    absolute paths, not cwd-relative ones."""
    return os.path.join(root.rstrip("/") or "/", rel)


# Accelerator-type prefixes as they appear in TPU_ACCELERATOR_TYPE; GKE uses
# "v5litepod-16" for v5e and "v5p-8" for v5p.
_GENERATION_ALIASES = {
    "v5litepod": "v5e",
    "v5lite": "v5e",
    "v5": "v5p",
}


def normalize_generation(gen: str) -> str:
    gen = gen.strip().lower()
    if gen in GENERATIONS:
        return gen
    return _GENERATION_ALIASES.get(gen, "v4")

# Mirror of the reference's IMEX channel capacity constants
# (cmd/nvidia-dra-plugin/nvlib.go:441-444): how many interconnect channels a
# single driver instance will advertise.
ICI_CHANNEL_COUNT = 2048

# Chip health states. The reference has no health model at all — an NVML
# device that wedges after startup stays advertised forever; here health is
# a first-class output of the chip library, consumed by DeviceState.
HEALTH_HEALTHY = "healthy"
HEALTH_DEGRADED = "degraded"   # present but erroring; drain, don't allocate
HEALTH_GONE = "gone"           # device node vanished (unplug, vfio rebind)


@dataclasses.dataclass(frozen=True)
class HealthStatus:
    """Point-in-time health of one chip.

    ``since`` is the epoch timestamp of the OBSERVATION (the poll that
    produced this status), not of the underlying hardware event — the
    library has no better clock for that.
    """

    state: str = HEALTH_HEALTHY
    reason: str = ""
    since: float = 0.0

    def is_healthy(self) -> bool:
        return self.state == HEALTH_HEALTHY

    def is_gone(self) -> bool:
        return self.state == HEALTH_GONE

# Sharing modes for a chip runtime (role of NVML compute modes,
# nvlib.go:541-558).
SHARING_EXCLUSIVE = "exclusive"
SHARING_TIME_SHARED = "time-shared"
SHARING_PROCESS_SHARED = "process-shared"


def derive_host_block(
    topology: MeshShape, n_per_host: int
) -> Optional[MeshShape]:
    """Most compact (bx,by,bz) with bx*by*bz == n_per_host tiling the
    topology: minimal z extent first (real multi-host blocks are flat:
    v4/v5p hosts own 2x2x1), then most square in x/y. Shared by the real
    and fake backends so both speak the same coordinate contract."""
    best = None
    for bx in range(1, topology.x + 1):
        if topology.x % bx or n_per_host % bx:
            continue
        for by in range(1, topology.y + 1):
            if topology.y % by or (n_per_host // bx) % by:
                continue
            bz = n_per_host // (bx * by)
            if bz > topology.z or topology.z % bz:
                continue
            key = (bz, abs(bx - by), bx + by + bz)
            if best is None or key < best[0]:
                best = (key, MeshShape(bx, by, bz))
    return best[1] if best else None


@dataclasses.dataclass
class ChipLibConfig:
    """Host-side knobs (role of driver-root flags, main.go:73-123)."""

    dev_root: str = "/"
    sysfs_root: str = "/sys"
    # Metadata overrides; on real hosts these come from the TPU runtime env
    # (GKE sets TPU_* env on node pools) or the C++ shim's sysfs probe.
    generation: Optional[str] = None
    slice_id: Optional[str] = None
    slice_topology: Optional[str] = None
    host_id: int = 0
    hosts_per_slice: int = 1
    # Coordinate-grid metadata (TPU_CHIPS_PER_HOST_BOUNDS /
    # TPU_HOST_BOUNDS mirrors), e.g. "2,2,1". See enumerate_chips for the
    # mapping contract.
    chips_per_host_bounds: Optional[str] = None
    host_bounds: Optional[str] = None


class ChipLib(abc.ABC):
    """Interface mirrored from deviceLib (nvlib.go:40-46)."""

    @abc.abstractmethod
    def init(self) -> None: ...

    @abc.abstractmethod
    def shutdown(self) -> None: ...

    @abc.abstractmethod
    def enumerate_chips(self) -> list[ChipInfo]: ...

    def enumerate_all_possible_devices(
        self, device_classes: set[str],
        chips: Optional[list[ChipInfo]] = None,
    ) -> AllocatableDevices:
        """Enumerate chips + core partitions + ICI channels
        (enumerateAllPossibleDevices, nvlib.go:111-136). Pass ``chips``
        (e.g. from :meth:`snapshot`) to build from an existing probe
        instead of re-walking the hardware."""
        devices: AllocatableDevices = {}
        if chips is None:
            chips = self.enumerate_chips()
        if "chip" in device_classes or "tensorcore" in device_classes:
            for chip in chips:
                if "chip" in device_classes:
                    d = AllocatableDevice(chip=chip)
                    devices[d.canonical_name()] = d
                if "tensorcore" in device_classes:
                    for tc in self.enumerate_core_partitions(chip):
                        d = AllocatableDevice(tensorcore=tc)
                        devices[d.canonical_name()] = d
        if "ici" in device_classes:
            slice_id = chips[0].slice_id if chips else ""
            for ch in self.enumerate_ici_channels(slice_id):
                d = AllocatableDevice(ici_channel=ch)
                devices[d.canonical_name()] = d
        return devices

    def enumerate_core_partitions(self, chip: ChipInfo) -> list[TensorCoreInfo]:
        """Sub-chip partitions for a chip: every placement of every
        profile the generation supports (role of MIG profile/placement
        enumeration, nvlib.go:244-295). Counter consumption keeps
        overlapping placements and whole-chip claims mutually exclusive.
        """
        from .deviceinfo import partition_profiles

        if chip.cores < 2:
            return []
        return [
            TensorCoreInfo(parent=chip, core_index=start, profile=prof)
            for prof in partition_profiles(chip.generation)
            for start in prof.placements(chip.cores)
        ]

    def enumerate_ici_channels(
        self, slice_id: str = ""
    ) -> list[IciChannelInfo]:
        """All possible interconnect channels (enumerateImexChannels,
        nvlib.go:182-200; count hardcoded like nvlib.go:441-444)."""
        return [
            IciChannelInfo(channel=i, slice_id=slice_id)
            for i in range(ICI_CHANNEL_COUNT)
        ]

    def worker_hostnames(self) -> list[str]:
        """Hostnames of all workers in this host's slice, worker-id order.

        Ground truth for the cross-host launch env an ICI-channel prepare
        injects (cdi.spec.ici_channel_launch_env): worker 0 hosts the
        jax.distributed coordinator. Empty when the platform metadata does
        not carry hostnames (single-host, or bare-metal without the GKE
        TPU env) — preparation then omits the coordinator env and the
        workload falls back to its own bootstrap.
        """
        return []

    def wait_device_event(self, timeout_s: float) -> bool:
        """Block until the device inventory MAY have changed (chip
        hot-plug, vfio rebind), or the timeout lapses; returns True when an
        event woke the wait. The driver's republish loop sleeps here; a
        False return still triggers a periodic re-enumeration, so backends
        without an event source (this default) just pace the resync.
        """
        time.sleep(timeout_s)
        return False

    def chip_health(
        self, chips: Optional[list[ChipInfo]] = None
    ) -> dict[str, HealthStatus]:
        """uuid → HealthStatus for every chip this backend knows about.

        ``chips`` lets the caller supply an enumeration it already has
        (hardware probes are not free; see :meth:`snapshot`). MAY include
        chips ``enumerate_chips`` no longer returns (reported ``gone``
        with a reason) when the backend remembers them; callers
        additionally diff against their own previous view, so a backend
        without memory (this default: everything visible is healthy) still
        yields correct gone-detection one layer up (DeviceState).
        """
        now = time.time()
        if chips is None:
            chips = self.enumerate_chips()
        return {
            c.uuid: HealthStatus(HEALTH_HEALTHY, since=now) for c in chips
        }

    def snapshot(self) -> tuple[list[ChipInfo], dict[str, HealthStatus]]:
        """ONE probe yielding (chips, health) observed at the same
        instant — the device-watch loop's per-tick read. The default
        enumerates once and derives health from that enumeration, so a
        refresh never walks the hardware twice (the probe runs under the
        DeviceState lock that Prepare RPCs also take)."""
        chips = self.enumerate_chips()
        return chips, self.chip_health(chips)

    # --- side-effecting operations used at Prepare time -------------------

    @abc.abstractmethod
    def set_sharing_mode(self, chip_uuids: list[str], mode: str) -> None:
        """Set the chip runtime sharing mode (role of setComputeMode /
        setTimeSlice exec'ing nvidia-smi, nvlib.go:521-558)."""

    @abc.abstractmethod
    def create_ici_channel_device(self, channel: int) -> str:
        """Materialise the per-channel device node (role of
        createImexChannelDevice's mknod, nvlib.go:490-519). Returns path."""


# ---------------------------------------------------------------------------
# Fake backend (the testing seam the reference lacked — SURVEY.md §4)
# ---------------------------------------------------------------------------


class FakeChipLib(ChipLib):
    """In-memory chip backend with a configurable slice topology.

    Fully scriptable fault controls (the hermetic half of the health
    subsystem): ``wedge_chip`` marks a chip degraded in place,
    ``unplug_chip`` removes its device node from enumeration and reports
    it gone, ``restore_chip`` undoes either, and ``set_flap`` flips a chip
    between present and gone on a deterministic schedule driven by the
    health-poll count (never wall time, so chaos tests replay exactly).
    """

    def __init__(
        self,
        generation: str = "v5p",
        topology: str = "2x2x1",
        host_id: int = 0,
        hosts_per_slice: int = 1,
        slice_id: str = "",
        chips_per_host: Optional[int] = None,
        hostnames: Optional[list[str]] = None,
    ):
        self.generation = generation
        self.topology = MeshShape.parse(topology)
        self.host_id = host_id
        self.hosts_per_slice = hosts_per_slice
        self.hostnames = list(hostnames) if hostnames else []
        self.slice_id = slice_id or f"{generation}-{self.topology}-fake"
        self.chips_per_host = (
            chips_per_host
            if chips_per_host is not None
            else self.topology.num_chips // hosts_per_slice
        )
        self.initialized = False
        # Side-effect journals for test assertions.
        self.sharing_modes: dict[str, str] = {}
        self.created_channels: list[int] = []
        # Tests set() this to wake a driver watch loop immediately (the
        # fake's stand-in for an inotify device event).
        self.device_event = threading.Event()
        # Fault state, keyed by host-local chip index.
        self._wedged: dict[int, str] = {}      # index -> reason
        self._unplugged: dict[int, str] = {}   # index -> reason
        self._flaps: dict[int, int] = {}       # index -> period (in polls)
        self.health_polls = 0                  # deterministic flap clock

    # -- fault controls ----------------------------------------------------

    def wedge_chip(self, index: int, reason: str = "wedged") -> None:
        """Chip stays enumerated but reports degraded (hung runtime)."""
        self._wedged[index] = reason
        self.device_event.set()

    def unplug_chip(self, index: int, reason: str = "unplugged") -> None:
        """Chip's device node disappears: gone from enumeration + health."""
        self._unplugged[index] = reason
        self.device_event.set()

    def restore_chip(self, index: int) -> None:
        """Undo wedge/unplug/flap for one chip."""
        self._wedged.pop(index, None)
        self._unplugged.pop(index, None)
        self._flaps.pop(index, None)
        self.device_event.set()

    def set_flap(self, index: int, period: int = 2) -> None:
        """Flap a chip on a schedule: present for ``period`` health polls,
        gone for the next ``period``, repeating. Deterministic — driven by
        the poll count, not time."""
        if period < 1:
            raise ValueError(f"flap period must be >= 1, got {period}")
        self._flaps[index] = period
        self.device_event.set()

    def _flapped_out(self, index: int) -> bool:
        period = self._flaps.get(index)
        if period is None:
            return False
        return (self.health_polls // period) % 2 == 1

    def init(self) -> None:
        self.initialized = True

    def shutdown(self) -> None:
        self.initialized = False

    def _host_coords(self) -> list[Coord]:
        """This host's chip coordinates under the same block contract the
        real backend derives from grid metadata (RealChipLib.
        enumerate_chips): host_id indexes a host grid of compact per-host
        blocks. Falls back to host-major linear slicing when the chip
        count doesn't tile the topology (deliberately odd test setups)."""
        block = derive_host_block(self.topology, self.chips_per_host)
        if block is not None:
            host_grid = MeshShape(
                self.topology.x // block.x,
                self.topology.y // block.y,
                self.topology.z // block.z,
            )
            if self.host_id < host_grid.num_chips:
                hc = host_grid.coord_at(self.host_id)
                return [
                    Coord(
                        hc.x * block.x + block.coord_at(i).x,
                        hc.y * block.y + block.coord_at(i).y,
                        hc.z * block.z + block.coord_at(i).z,
                    )
                    for i in range(self.chips_per_host)
                ]
        all_coords = list(self.topology.coords())
        lo = self.host_id * self.chips_per_host
        return all_coords[lo:lo + self.chips_per_host]

    def _all_chips(self) -> list[ChipInfo]:
        """Every chip of this host's block, ignoring fault state (the
        ground truth unplug/flap subtract from)."""
        spec = GENERATIONS[self.generation]
        chips = []
        for local_idx, coord in enumerate(self._host_coords()):
            serial = hashlib.sha256(
                f"{self.slice_id}/{coord}".encode()
            ).hexdigest()[:12]
            chips.append(
                ChipInfo(
                    index=local_idx,
                    uuid=f"TPU-{serial}",
                    generation=self.generation,
                    device_paths=[f"/dev/accel{local_idx}"],
                    hbm_bytes=spec.hbm_bytes,
                    cores=spec.cores_per_chip,
                    coord=coord,
                    slice_id=self.slice_id,
                    slice_topology=self.topology,
                    host_id=self.host_id,
                    hosts_per_slice=self.hosts_per_slice,
                    pci_address=f"0000:{local_idx:02x}:00.0",
                    numa_node=local_idx % 2,
                    driver_version="1.0.0",
                    firmware_version="1.0.0",
                )
            )
        return chips

    def enumerate_chips(self) -> list[ChipInfo]:
        from ..utils import faults

        faults.fire("chiplib.enumerate")
        return [
            c for c in self._all_chips()
            if c.index not in self._unplugged
            and not self._flapped_out(c.index)
        ]

    def chip_health(
        self, chips: Optional[list[ChipInfo]] = None
    ) -> dict[str, HealthStatus]:
        """Scripted health: unplugged/flapped-out chips report gone (with
        the injected reason), wedged ones degraded, the rest healthy. Each
        call advances the deterministic flap clock by one poll. ``chips``
        is ignored — the fake's ground truth is its own fault state, and
        health must cover unplugged chips a caller's enumeration lacks."""
        self.health_polls += 1
        now = time.time()
        out: dict[str, HealthStatus] = {}
        for c in self._all_chips():
            if c.index in self._unplugged:
                out[c.uuid] = HealthStatus(
                    HEALTH_GONE, self._unplugged[c.index], now
                )
            elif self._flapped_out(c.index):
                out[c.uuid] = HealthStatus(
                    HEALTH_GONE,
                    f"flapping (period {self._flaps[c.index]} polls)", now,
                )
            elif c.index in self._wedged:
                out[c.uuid] = HealthStatus(
                    HEALTH_DEGRADED, self._wedged[c.index], now
                )
            else:
                out[c.uuid] = HealthStatus(HEALTH_HEALTHY, since=now)
        return out

    def snapshot(self) -> tuple[list[ChipInfo], dict[str, HealthStatus]]:
        """Health FIRST (advancing the flap clock), then enumeration, so
        both halves observe the same deterministic tick — the base
        default would enumerate at the pre-advance tick."""
        health = self.chip_health()
        return self.enumerate_chips(), health

    def set_sharing_mode(self, chip_uuids: list[str], mode: str) -> None:
        for u in chip_uuids:
            self.sharing_modes[u] = mode

    def create_ici_channel_device(self, channel: int) -> str:
        from ..utils import faults

        faults.fire("chiplib.create-channel")
        self.created_channels.append(channel)
        return f"/dev/tpu-ici-channels/channel{channel}"

    def worker_hostnames(self) -> list[str]:
        return list(self.hostnames)

    def wait_device_event(self, timeout_s: float) -> bool:
        if self.device_event.wait(timeout_s):
            self.device_event.clear()
            return True
        return False


# ---------------------------------------------------------------------------
# Real backend: /dev/accel* + sysfs probing (C++ shim with Python fallback)
# ---------------------------------------------------------------------------

ICI_CHANNEL_DIR = "dev/tpu-ici-channels"


class RealChipLib(ChipLib):
    """Probes the host for TPU chips.

    Discovery sources, in order (mirrors the reference's layered root
    resolution, cmd/nvidia-dra-plugin/root.go:29-81):

    1. The native C++ shim (``libtpudiscovery.so``), which walks
       ``/sys/class/accel`` / ``/sys/bus/pci`` and reads vendor/device ids,
       NUMA nodes, and PCI addresses without spawning processes.
    2. A pure-Python sysfs/glob fallback with identical semantics, used when
       the shim is not built (e.g. unit tests on dev machines).
    3. TPU runtime environment metadata for slice identity/topology —
       the variables the GKE TPU node pools export (``TPU_WORKER_ID``,
       ``TPU_ACCELERATOR_TYPE``, ``TPU_TOPOLOGY``, ``TPU_WORKER_HOSTNAMES``)
       — overridable via ``ChipLibConfig``.
    """

    # PCI vendor id for Google; TPU device ids per generation.
    GOOGLE_PCI_VENDOR = "0x1ae0"
    PCI_DEVICE_GENERATIONS = {
        "0x0027": "v2",
        "0x0056": "v3",
        "0x005e": "v4",
        "0x0063": "v5e",
        "0x0062": "v5p",
        "0x006f": "v6e",
    }

    def __init__(self, config: Optional[ChipLibConfig] = None):
        self.config = config or ChipLibConfig()
        self.initialized = False
        self._native = None
        # Health-probe memory: chips seen by the last enumeration (so a
        # vanished device node can be reported gone, not just absent) and
        # the last libtpu/sysfs error-counter sample per chip.
        self._known_chips: dict[str, ChipInfo] = {}
        self._last_errors: dict[str, int] = {}

    def init(self) -> None:
        from . import _native

        # Building at plugin startup is opt-in: container images ship the .so
        # prebuilt, and the package dir may be read-only at runtime.
        allow_build = os.environ.get("TPU_DRA_BUILD_NATIVE", "") == "1"
        self._native = _native.load(allow_build=allow_build)
        self.initialized = True

    def shutdown(self) -> None:
        self.initialized = False

    # -- metadata ----------------------------------------------------------

    def _env(self, name: str, default: str = "") -> str:
        return os.environ.get(name, default)

    def _detect_generation(self, pci_device_id: str) -> str:
        if self.config.generation:
            return normalize_generation(self.config.generation)
        accel = self._env("TPU_ACCELERATOR_TYPE")  # e.g. "v5p-16", "v5litepod-8"
        if accel:
            return normalize_generation(accel.split("-")[0])
        return self.PCI_DEVICE_GENERATIONS.get(pci_device_id, "v4")

    def _slice_metadata(self, generation: str, n_local: int):
        slice_id = self.config.slice_id or self._env(
            "TPU_SLICE_ID", self._env("MEGASCALE_SLICE_ID", "")
        )
        topo_s = self.config.slice_topology or self._env("TPU_TOPOLOGY", "")
        host_id = self.config.host_id or _safe_int(
            self._env("TPU_WORKER_ID", "0"), 0
        )
        hostnames = self.worker_hostnames()
        hosts = (
            self.config.hosts_per_slice
            if self.config.hosts_per_slice > 1
            else (len(hostnames) if hostnames else 1)
        )
        if topo_s:
            topology = MeshShape.parse(topo_s)
        else:
            topology = default_slice_shapes(generation, n_local * hosts)
        if not slice_id:
            slice_id = f"{generation}-{topology}-{os.uname().nodename}"
        return slice_id, topology, host_id, hosts

    @staticmethod
    def _parse_bounds(s: str) -> Optional[MeshShape]:
        """TPU bounds env format: comma-separated ("2,2,1"); tolerate the
        x-separated topology form too. Non-positive axes are malformed
        metadata (they'd divide by zero downstream): treated as absent."""
        s = s.strip()
        if not s:
            return None
        try:
            shape = MeshShape.parse(s.replace(",", "x"))
        except ValueError:
            return None
        if shape.x < 1 or shape.y < 1 or shape.z < 1:
            return None
        return shape

    def _grid_metadata(
        self, topology: MeshShape, hosts: int
    ) -> Optional[tuple[MeshShape, MeshShape, bool]]:
        """(per-host chip bounds, host grid, grounded) from runtime metadata.

        Sources: ``TPU_CHIPS_PER_HOST_BOUNDS`` and ``TPU_HOST_BOUNDS`` (the
        variables libtpu itself consumes), overridable via ChipLibConfig.
        When only one is present the other derives from the slice topology;
        when neither is, a compact per-host block is derived from topology ÷
        hosts (the 2x2x1 block of real v4/v5p hosts falls out naturally).
        ``grounded`` is True only when the mapping needs no guessing — a
        single-host slice, or explicit bounds metadata; multi-host blocks
        DERIVED by heuristic stay usable for coordinates but are flagged so
        contiguity attributes are withheld.
        Returns None — caller falls back to positional coords — if the
        metadata is inconsistent (bounds don't tile the topology, or the
        grids disagree with the host count)."""
        bounds = (
            self._parse_bounds(self.config.chips_per_host_bounds or "")
            or self._parse_bounds(self._env("TPU_CHIPS_PER_HOST_BOUNDS"))
        )
        host_grid = (
            self._parse_bounds(self.config.host_bounds or "")
            or self._parse_bounds(self._env("TPU_HOST_BOUNDS"))
        )
        grounded = hosts == 1 or bounds is not None or host_grid is not None
        if bounds is None and host_grid is not None:
            if not host_grid.divides(topology):
                return None
            bounds = MeshShape(
                topology.x // host_grid.x,
                topology.y // host_grid.y,
                topology.z // host_grid.z,
            )
        if bounds is None:
            bounds = self._derive_compact_bounds(
                topology, max(topology.num_chips // max(hosts, 1), 1)
            )
            if bounds is None:
                return None
        if not bounds.divides(topology):
            logger.warning(
                "chip bounds %s do not tile slice topology %s; "
                "falling back to positional coordinates", bounds, topology,
            )
            return None
        derived_grid = MeshShape(
            topology.x // bounds.x, topology.y // bounds.y,
            topology.z // bounds.z,
        )
        if host_grid is None:
            host_grid = derived_grid
        elif host_grid != derived_grid:
            logger.warning(
                "host bounds %s inconsistent with topology %s / chip "
                "bounds %s; falling back to positional coordinates",
                host_grid, topology, bounds,
            )
            return None
        if hosts > 1 and host_grid.num_chips != hosts:
            logger.warning(
                "host grid %s holds %d hosts but the slice reports %d; "
                "falling back to positional coordinates",
                host_grid, host_grid.num_chips, hosts,
            )
            return None
        return bounds, host_grid, grounded

    @staticmethod
    def _derive_compact_bounds(
        topology: MeshShape, n_per_host: int
    ) -> Optional[MeshShape]:
        return derive_host_block(topology, n_per_host)

    # -- device probing ----------------------------------------------------

    def _probe_accel_nodes(self) -> list[tuple[int, str, str, dict]]:
        """Find (index, path, kind, meta) for TPU device nodes.

        kind is "accel" for /dev/accel* char devices (meta read from sysfs
        here, once) or "vfio" for /dev/vfio/* group nodes (v5p+ GKE hosts;
        meta carries the iommu-derived PCI address).
        """
        nodes = []
        for path in sorted(glob.glob(_hostpath(self.config.dev_root, "dev/accel[0-9]*"))):
            m = re.search(r"accel(\d+)$", path)
            if not m:
                continue
            try:
                st = os.stat(path)
            except OSError:
                continue
            if stat.S_ISCHR(st.st_mode):
                index = int(m.group(1))
                nodes.append(
                    (index, path, "accel", self._sysfs_chip_meta(index))
                )
        if not nodes:
            nodes = self._probe_vfio_nodes()
        return nodes

    def _probe_vfio_nodes(self) -> list[tuple[int, str, str, dict]]:
        """vfio group nodes, ordered by metadata rather than glob luck.

        A vfio group number carries no chip identity; the stable order is
        the PCI address of the group's device (resolved via
        /sys/kernel/iommu_groups/<g>/devices). Chip indices then come from
        ``TPU_VISIBLE_CHIPS`` when the runtime published it, else from the
        PCI-ordered position."""
        # One native call resolves every group's PCI identity (the batch
        # enumeration role go-nvml's VisitDevices plays); the per-group
        # Python walk remains the fallback.
        native_groups: dict[int, str] = {}
        if self._native is not None and self._native.available:
            native_groups = self._native.vfio_groups(
                self.config.dev_root, self.config.sysfs_root
            )
        entries = []  # (sort key, group path)
        for path in glob.glob(
            _hostpath(self.config.dev_root, "dev/vfio/[0-9]*")
        ):
            group = os.path.basename(path)
            pci = (
                native_groups.get(_safe_int(group, -1))
                or self._vfio_pci_address(group)
            )
            # PCI addresses sort correctly as strings within one domain;
            # fall back to the numeric group id when sysfs is stripped.
            entries.append(((pci or "~", int(group)), path))
        entries.sort()
        visible = [
            _safe_int(v, -1)
            for v in self._env("TPU_VISIBLE_CHIPS").split(",")
            if v.strip()
        ]
        usable = (
            len(visible) == len(entries)
            and all(v >= 0 for v in visible)
            and len(set(visible)) == len(visible)  # dupes would collapse
        )                                          # two chips into one name
        if visible and not usable:
            logger.warning(
                "TPU_VISIBLE_CHIPS %r unusable for %d vfio nodes; "
                "using PCI-ordered indices", visible, len(entries),
            )
        nodes = []
        for pos, ((pci, _), path) in enumerate(entries):
            meta = {"pci_address": pci} if pci != "~" else {}
            nodes.append((visible[pos] if usable else pos, path, "vfio", meta))
        return nodes

    def _vfio_pci_address(self, group: str) -> str:
        devdir = _hostpath(
            self.config.sysfs_root, f"kernel/iommu_groups/{group}/devices"
        )
        try:
            devs = sorted(os.listdir(devdir))
        except OSError:
            return ""
        return devs[0] if devs else ""

    def _sysfs_chip_meta(self, index: int) -> dict[str, str]:
        """Read PCI metadata for accel device `index` from sysfs."""
        if self._native is not None and self._native.available:
            meta = self._native.chip_meta(self.config.sysfs_root, index)
            if meta:
                return meta
        base = f"{self.config.sysfs_root}/class/accel/accel{index}/device"
        meta = {}
        for key in ("vendor", "device", "numa_node"):
            try:
                with open(f"{base}/{key}") as f:
                    meta[key] = f.read().strip()
            except OSError:
                pass
        try:
            meta["pci_address"] = os.path.basename(os.readlink(base))
        except OSError:
            meta["pci_address"] = ""
        return meta

    def enumerate_chips(self) -> list[ChipInfo]:
        """Probe device nodes and derive each chip's mesh coordinate.

        Coordinate contract (the ground truth behind ``coord``,
        ``iciX/Y/Z`` and the ``submesh{2x2,4x4}Id`` contiguity attributes;
        reference discipline: attributes come from the device library's
        metadata, not position — nvlib.go:202-313):

        1. The slice topology T comes from ``TPU_TOPOLOGY``; the per-host
           chip block B from ``TPU_CHIPS_PER_HOST_BOUNDS`` and the host
           grid H from ``TPU_HOST_BOUNDS`` (libtpu's own variables, with
           ChipLibConfig overrides). Each may be derived from the others
           (T = H∘B elementwise).
        2. Host w (``TPU_WORKER_ID``) owns the block of chips whose origin
           is ``H.coord_at(w) * B`` — the same x-outermost/z-fastest
           linearisation ``MeshShape.coords`` uses everywhere.
        3. Device index n (the accelN minor, or the vfio chip index from
           ``TPU_VISIBLE_CHIPS``/PCI order) sits at ``B.coord_at(n)``
           WITHIN the block: global = origin + local. Index-keyed, not
           ordinal-keyed — a host with a missing/hidden chip still
           publishes true coordinates for the rest (round-2 verdict:
           positional gpos published confidently wrong contiguity on any
           non-host-major or heterogeneous layout).
        4. If the grids are absent or inconsistent, fall back to the
           positional mapping and SKIP publishing submesh tile attributes
           (deviceinfo withholds them when ``coords_reliable`` is False),
           so a scheduler can never gang-allocate on made-up contiguity.
        """
        from ..utils import faults

        faults.fire("chiplib.enumerate")
        nodes = self._probe_accel_nodes()
        # Reject foreign accel-class devices (other vendors' NPUs also appear
        # as /dev/accelN): keep a node only if its sysfs vendor is Google or
        # vendor metadata is unavailable (vfio nodes, stripped sysfs).
        kept = []
        for index, path, kind, meta in nodes:
            if kind == "accel":
                vendor = meta.get("vendor", "")
                if vendor and vendor != self.GOOGLE_PCI_VENDOR:
                    logger.info("skipping non-TPU accel device %s (vendor %s)",
                                path, vendor)
                    continue
            kept.append((index, path, kind, meta))
        nodes = kept
        if not nodes:
            logger.warning("no TPU device nodes found under %s", self.config.dev_root)
            return []
        generation = self._detect_generation(nodes[0][3].get("device", ""))
        spec = GENERATIONS.get(generation, GENERATIONS["v4"])
        slice_id, topology, host_id, hosts = self._slice_metadata(
            generation, len(nodes)
        )
        grids = self._grid_metadata(topology, hosts)
        origin = None
        grounded = False
        if grids is not None:
            bounds, host_grid, grounded = grids
            if host_id < host_grid.num_chips:
                hc = host_grid.coord_at(host_id)
                origin = Coord(
                    hc.x * bounds.x, hc.y * bounds.y, hc.z * bounds.z
                )
            else:
                logger.warning(
                    "host id %d outside host grid %s; falling back to "
                    "positional coordinates", host_id, host_grid,
                )
        all_coords = list(topology.coords())
        chips = []
        for local_idx, (index, path, kind, meta) in enumerate(nodes):
            indexed = origin is not None and 0 <= index < bounds.num_chips
            coords_reliable = indexed and grounded
            if indexed:
                local = bounds.coord_at(index)
                coord = Coord(
                    origin.x + local.x, origin.y + local.y,
                    origin.z + local.z,
                )
            else:
                # Positional fallback: ordinal within this host's nodes.
                gpos = host_id * len(nodes) + local_idx
                coord = (
                    all_coords[gpos] if gpos < len(all_coords)
                    else Coord(0, 0, 0)
                )
            uid_src = meta.get("pci_address") or f"{slice_id}/{index}"
            serial = hashlib.sha256(uid_src.encode()).hexdigest()[:12]
            chips.append(
                ChipInfo(
                    index=index,
                    uuid=f"TPU-{serial}",
                    generation=generation,
                    device_paths=[path],
                    hbm_bytes=spec.hbm_bytes,
                    cores=spec.cores_per_chip,
                    coord=coord,
                    slice_id=slice_id,
                    slice_topology=topology,
                    host_id=host_id,
                    hosts_per_slice=hosts,
                    pci_address=meta.get("pci_address", ""),
                    numa_node=_safe_int(meta.get("numa_node"), -1),
                    driver_version=self._libtpu_version(),
                    coords_reliable=coords_reliable,
                )
            )
        return chips

    def _libtpu_version(self) -> str:
        try:
            import importlib.metadata as md

            return md.version("libtpu")
        except Exception:
            return "0.0.0"

    # -- side effects ------------------------------------------------------

    def set_sharing_mode(self, chip_uuids: list[str], mode: str) -> None:
        """Record the requested per-chip sharing mode.

        The TPU runtime has no persistent on-device mode like NVML compute
        modes; sharing is realised at Prepare time through the env/flags the
        CDI spec injects (TPU_PROCESS_BOUNDS, multi-process flags — see
        plugin/sharing.py).  We persist the requested mode in a small state
        dir so that concurrent claims on one chip can be validated against it
        (role of nvidia-smi -c, nvlib.go:541-558).
        """
        state_dir = _hostpath(self.config.dev_root, "var/run/tpu-dra")
        os.makedirs(state_dir, exist_ok=True)
        for u in chip_uuids:
            with open(os.path.join(state_dir, f"{u}.mode"), "w") as f:
                f.write(mode)

    def create_ici_channel_device(self, channel: int) -> str:
        """mknod the per-channel device (createImexChannelDevice,
        nvlib.go:490-519)."""
        from ..utils import faults

        faults.fire("chiplib.create-channel")
        dirpath = _hostpath(self.config.dev_root, ICI_CHANNEL_DIR)
        os.makedirs(dirpath, exist_ok=True)
        path = os.path.join(dirpath, f"channel{channel}")
        if os.path.exists(path):
            return path
        major = self._ici_major()
        if self._native is not None and self._native.available:
            self._native.mknod_char(path, major, channel, 0o666)
        else:
            os.mknod(path, 0o666 | stat.S_IFCHR, os.makedev(major, channel))
            os.chmod(path, 0o666)
        return path

    def worker_hostnames(self) -> list[str]:
        """Slice worker hostnames from the platform env (GKE TPU node pools
        export TPU_WORKER_HOSTNAMES in worker-id order)."""
        raw = self._env("TPU_WORKER_HOSTNAMES", "")
        return [h.strip() for h in raw.split(",") if h.strip()]

    def wait_device_event(self, timeout_s: float) -> bool:
        """inotify on {dev_root}/dev (+ /dev/vfio) via the native shim —
        wakes the driver's republish loop the moment a chip node appears
        or disappears. Falls back to plain pacing (periodic resync still
        re-enumerates) when the shim or the watch is unavailable."""
        if self._native is not None and self._native.available:
            try:
                return self._native.watch_devdir(
                    self.config.dev_root, int(timeout_s * 1000)
                )
            except OSError as e:
                logger.debug("device watch unavailable: %s", e)
        return super().wait_device_event(timeout_s)

    # -- health probing ----------------------------------------------------

    def chip_health(
        self, chips: Optional[list[ChipInfo]] = None
    ) -> dict[str, HealthStatus]:
        """Poll health for every chip this host has ever enumerated.

        ``chips`` skips the enumeration when the caller (snapshot) just
        did one — a full sysfs walk is not free and this path runs under
        the DeviceState lock. Two signals, mirroring what a TPU host
        actually exposes:

        - **presence**: the chip must still enumerate AND its device node
          must still stat — a vfio rebind or PCIe dropout reads ``gone``;
        - **error counters**: per-chip error counts from sysfs (the files
          libtpu's own health monitor reads); a counter that ADVANCED
          since the previous poll reads ``degraded`` — absolute values are
          meaningless across reboots, deltas are the signal.

        Chips remembered from earlier polls keep reporting ``gone`` until
        they re-enumerate, so one missed poll can never silently drop a
        failure the slice publisher should be reacting to.
        """
        now = time.time()
        if chips is None:
            chips = self.enumerate_chips()
        current = {c.uuid: c for c in chips}
        self._known_chips.update(current)
        out: dict[str, HealthStatus] = {}
        for uuid, chip in self._known_chips.items():
            if uuid not in current:
                out[uuid] = HealthStatus(
                    HEALTH_GONE, "chip no longer enumerable", now
                )
                continue
            missing = [
                p for p in chip.device_paths if not os.path.exists(p)
            ]
            if missing:
                out[uuid] = HealthStatus(
                    HEALTH_GONE, f"device node missing: {missing[0]}", now
                )
                continue
            errs = self._error_counter(chip.index)
            if errs is not None:
                last = self._last_errors.get(uuid)
                self._last_errors[uuid] = errs
                if last is not None and errs > last:
                    out[uuid] = HealthStatus(
                        HEALTH_DEGRADED,
                        f"error counter advanced {last} -> {errs}", now,
                    )
                    continue
            out[uuid] = HealthStatus(HEALTH_HEALTHY, since=now)
        return out

    def _error_counter(self, index: int) -> Optional[int]:
        """Summed per-chip error counters from sysfs, or None when the
        host exposes none (older driver stacks): absence must read as
        'no signal', never as 'zero errors observed'."""
        devdir = f"{self.config.sysfs_root}/class/accel/accel{index}/device"
        total: Optional[int] = None
        for name in ("tpu_error_count", "errors", "ae_count"):
            try:
                with open(os.path.join(devdir, name)) as f:
                    v = _safe_int(f.read(), -1)
            except OSError:
                continue
            if v >= 0:
                total = (total or 0) + v
        return total

    def _ici_major(self) -> int:
        """Device major for ICI channel nodes from /proc/devices
        (role of nvlib.go:446-488)."""
        proc = _hostpath(self.config.dev_root, "proc/devices")
        try:
            with open(proc) as f:
                for line in f:
                    parts = line.split()
                    if len(parts) == 2 and parts[1] in (
                        "tpu-ici",
                        "vfio",
                        "accel",
                    ):
                        return int(parts[0])
        except OSError:
            pass
        return 511  # dynamic-major fallback
