"""Chip enumeration library: the hardware seam of the driver.

TPU-native analog of the reference's deviceLib (lengrongfu/k8s-dra-driver,
cmd/nvidia-dra-plugin/nvlib.go:40-46, :111-200): where the reference wraps
NVML via cgo to enumerate GPUs/MIG devices/IMEX channels, we enumerate TPU
chips from ``/dev/accel*`` + sysfs (optionally accelerated by the C++ shim in
``k8s_dra_driver_tpu/native``) and synthesise TensorCore partitions and ICI
channels from generation/topology metadata.

Unlike the reference — whose only backend is real hardware, making its test
story "run the demo on GPUs" (SURVEY.md §4) — the backend here is an abstract
interface with a first-class ``FakeChipLib``, so every layer above (device
state, CDI, gRPC plugin, controller) is testable hermetically.
"""

from __future__ import annotations

import abc
import dataclasses
import glob
import hashlib
import logging
import os
import re
import stat
from typing import Optional

from .deviceinfo import (
    AllocatableDevice,
    AllocatableDevices,
    ChipInfo,
    IciChannelInfo,
    TensorCoreInfo,
)
from .topology import GENERATIONS, Coord, MeshShape, default_slice_shapes

logger = logging.getLogger(__name__)


def _safe_int(value, default: int) -> int:
    """Tolerant int parse for sysfs values (kernel files can hold garbage)."""
    try:
        return int(str(value).strip())
    except (TypeError, ValueError):
        return default


def _hostpath(root: str, rel: str) -> str:
    """Join a host-root prefix with a relative path; root='/' must yield
    absolute paths, not cwd-relative ones."""
    return os.path.join(root.rstrip("/") or "/", rel)


# Accelerator-type prefixes as they appear in TPU_ACCELERATOR_TYPE; GKE uses
# "v5litepod-16" for v5e and "v5p-8" for v5p.
_GENERATION_ALIASES = {
    "v5litepod": "v5e",
    "v5lite": "v5e",
    "v5": "v5p",
}


def normalize_generation(gen: str) -> str:
    gen = gen.strip().lower()
    if gen in GENERATIONS:
        return gen
    return _GENERATION_ALIASES.get(gen, "v4")

# Mirror of the reference's IMEX channel capacity constants
# (cmd/nvidia-dra-plugin/nvlib.go:441-444): how many interconnect channels a
# single driver instance will advertise.
ICI_CHANNEL_COUNT = 2048

# Sharing modes for a chip runtime (role of NVML compute modes,
# nvlib.go:541-558).
SHARING_EXCLUSIVE = "exclusive"
SHARING_TIME_SHARED = "time-shared"
SHARING_PROCESS_SHARED = "process-shared"


@dataclasses.dataclass
class ChipLibConfig:
    """Host-side knobs (role of driver-root flags, main.go:73-123)."""

    dev_root: str = "/"
    sysfs_root: str = "/sys"
    # Metadata overrides; on real hosts these come from the TPU runtime env
    # (GKE sets TPU_* env on node pools) or the C++ shim's sysfs probe.
    generation: Optional[str] = None
    slice_id: Optional[str] = None
    slice_topology: Optional[str] = None
    host_id: int = 0
    hosts_per_slice: int = 1


class ChipLib(abc.ABC):
    """Interface mirrored from deviceLib (nvlib.go:40-46)."""

    @abc.abstractmethod
    def init(self) -> None: ...

    @abc.abstractmethod
    def shutdown(self) -> None: ...

    @abc.abstractmethod
    def enumerate_chips(self) -> list[ChipInfo]: ...

    def enumerate_all_possible_devices(
        self, device_classes: set[str]
    ) -> AllocatableDevices:
        """Enumerate chips + core partitions + ICI channels
        (enumerateAllPossibleDevices, nvlib.go:111-136)."""
        devices: AllocatableDevices = {}
        chips = self.enumerate_chips()
        if "chip" in device_classes or "tensorcore" in device_classes:
            for chip in chips:
                if "chip" in device_classes:
                    d = AllocatableDevice(chip=chip)
                    devices[d.canonical_name()] = d
                if "tensorcore" in device_classes:
                    for tc in self.enumerate_core_partitions(chip):
                        d = AllocatableDevice(tensorcore=tc)
                        devices[d.canonical_name()] = d
        if "ici" in device_classes:
            slice_id = chips[0].slice_id if chips else ""
            for ch in self.enumerate_ici_channels(slice_id):
                d = AllocatableDevice(ici_channel=ch)
                devices[d.canonical_name()] = d
        return devices

    def enumerate_core_partitions(self, chip: ChipInfo) -> list[TensorCoreInfo]:
        """Sub-chip partitions for a chip (role of MIG profile/placement
        enumeration, nvlib.go:244-295)."""
        spec = GENERATIONS.get(chip.generation)
        if spec is None or not spec.partitionable or chip.cores < 2:
            return []
        return [
            TensorCoreInfo(parent=chip, core_index=i) for i in range(chip.cores)
        ]

    def enumerate_ici_channels(
        self, slice_id: str = ""
    ) -> list[IciChannelInfo]:
        """All possible interconnect channels (enumerateImexChannels,
        nvlib.go:182-200; count hardcoded like nvlib.go:441-444)."""
        return [
            IciChannelInfo(channel=i, slice_id=slice_id)
            for i in range(ICI_CHANNEL_COUNT)
        ]

    # --- side-effecting operations used at Prepare time -------------------

    @abc.abstractmethod
    def set_sharing_mode(self, chip_uuids: list[str], mode: str) -> None:
        """Set the chip runtime sharing mode (role of setComputeMode /
        setTimeSlice exec'ing nvidia-smi, nvlib.go:521-558)."""

    @abc.abstractmethod
    def create_ici_channel_device(self, channel: int) -> str:
        """Materialise the per-channel device node (role of
        createImexChannelDevice's mknod, nvlib.go:490-519). Returns path."""


# ---------------------------------------------------------------------------
# Fake backend (the testing seam the reference lacked — SURVEY.md §4)
# ---------------------------------------------------------------------------


class FakeChipLib(ChipLib):
    """In-memory chip backend with a configurable slice topology."""

    def __init__(
        self,
        generation: str = "v5p",
        topology: str = "2x2x1",
        host_id: int = 0,
        hosts_per_slice: int = 1,
        slice_id: str = "",
        chips_per_host: Optional[int] = None,
    ):
        self.generation = generation
        self.topology = MeshShape.parse(topology)
        self.host_id = host_id
        self.hosts_per_slice = hosts_per_slice
        self.slice_id = slice_id or f"{generation}-{self.topology}-fake"
        self.chips_per_host = (
            chips_per_host
            if chips_per_host is not None
            else self.topology.num_chips // hosts_per_slice
        )
        self.initialized = False
        # Side-effect journals for test assertions.
        self.sharing_modes: dict[str, str] = {}
        self.created_channels: list[int] = []

    def init(self) -> None:
        self.initialized = True

    def shutdown(self) -> None:
        self.initialized = False

    def enumerate_chips(self) -> list[ChipInfo]:
        spec = GENERATIONS[self.generation]
        all_coords = list(self.topology.coords())
        lo = self.host_id * self.chips_per_host
        hi = lo + self.chips_per_host
        chips = []
        for local_idx, coord in enumerate(all_coords[lo:hi]):
            serial = hashlib.sha256(
                f"{self.slice_id}/{coord}".encode()
            ).hexdigest()[:12]
            chips.append(
                ChipInfo(
                    index=local_idx,
                    uuid=f"TPU-{serial}",
                    generation=self.generation,
                    device_paths=[f"/dev/accel{local_idx}"],
                    hbm_bytes=spec.hbm_bytes,
                    cores=spec.cores_per_chip,
                    coord=coord,
                    slice_id=self.slice_id,
                    slice_topology=self.topology,
                    host_id=self.host_id,
                    hosts_per_slice=self.hosts_per_slice,
                    pci_address=f"0000:{local_idx:02x}:00.0",
                    numa_node=local_idx % 2,
                    driver_version="1.0.0",
                    firmware_version="1.0.0",
                )
            )
        return chips

    def set_sharing_mode(self, chip_uuids: list[str], mode: str) -> None:
        for u in chip_uuids:
            self.sharing_modes[u] = mode

    def create_ici_channel_device(self, channel: int) -> str:
        self.created_channels.append(channel)
        return f"/dev/tpu-ici-channels/channel{channel}"


# ---------------------------------------------------------------------------
# Real backend: /dev/accel* + sysfs probing (C++ shim with Python fallback)
# ---------------------------------------------------------------------------

ICI_CHANNEL_DIR = "dev/tpu-ici-channels"


class RealChipLib(ChipLib):
    """Probes the host for TPU chips.

    Discovery sources, in order (mirrors the reference's layered root
    resolution, cmd/nvidia-dra-plugin/root.go:29-81):

    1. The native C++ shim (``libtpudiscovery.so``), which walks
       ``/sys/class/accel`` / ``/sys/bus/pci`` and reads vendor/device ids,
       NUMA nodes, and PCI addresses without spawning processes.
    2. A pure-Python sysfs/glob fallback with identical semantics, used when
       the shim is not built (e.g. unit tests on dev machines).
    3. TPU runtime environment metadata for slice identity/topology —
       the variables the GKE TPU node pools export (``TPU_WORKER_ID``,
       ``TPU_ACCELERATOR_TYPE``, ``TPU_TOPOLOGY``, ``TPU_WORKER_HOSTNAMES``)
       — overridable via ``ChipLibConfig``.
    """

    # PCI vendor id for Google; TPU device ids per generation.
    GOOGLE_PCI_VENDOR = "0x1ae0"
    PCI_DEVICE_GENERATIONS = {
        "0x0027": "v2",
        "0x0056": "v3",
        "0x005e": "v4",
        "0x0063": "v5e",
        "0x0062": "v5p",
        "0x006f": "v6e",
    }

    def __init__(self, config: Optional[ChipLibConfig] = None):
        self.config = config or ChipLibConfig()
        self.initialized = False
        self._native = None

    def init(self) -> None:
        from . import _native

        # Building at plugin startup is opt-in: container images ship the .so
        # prebuilt, and the package dir may be read-only at runtime.
        allow_build = os.environ.get("TPU_DRA_BUILD_NATIVE", "") == "1"
        self._native = _native.load(allow_build=allow_build)
        self.initialized = True

    def shutdown(self) -> None:
        self.initialized = False

    # -- metadata ----------------------------------------------------------

    def _env(self, name: str, default: str = "") -> str:
        return os.environ.get(name, default)

    def _detect_generation(self, pci_device_id: str) -> str:
        if self.config.generation:
            return normalize_generation(self.config.generation)
        accel = self._env("TPU_ACCELERATOR_TYPE")  # e.g. "v5p-16", "v5litepod-8"
        if accel:
            return normalize_generation(accel.split("-")[0])
        return self.PCI_DEVICE_GENERATIONS.get(pci_device_id, "v4")

    def _slice_metadata(self, generation: str, n_local: int):
        slice_id = self.config.slice_id or self._env(
            "TPU_SLICE_ID", self._env("MEGASCALE_SLICE_ID", "")
        )
        topo_s = self.config.slice_topology or self._env("TPU_TOPOLOGY", "")
        host_id = self.config.host_id or _safe_int(
            self._env("TPU_WORKER_ID", "0"), 0
        )
        hostnames = self._env("TPU_WORKER_HOSTNAMES", "")
        hosts = (
            self.config.hosts_per_slice
            if self.config.hosts_per_slice > 1
            else (len(hostnames.split(",")) if hostnames else 1)
        )
        if topo_s:
            topology = MeshShape.parse(topo_s)
        else:
            topology = default_slice_shapes(generation, n_local * hosts)
        if not slice_id:
            slice_id = f"{generation}-{topology}-{os.uname().nodename}"
        return slice_id, topology, host_id, hosts

    # -- device probing ----------------------------------------------------

    def _probe_accel_nodes(self) -> list[tuple[int, str, str]]:
        """Find (index, path, kind) for TPU device nodes.

        kind is "accel" for /dev/accel* char devices (sysfs metadata
        available) or "vfio" for /dev/vfio/* group nodes (v5p+ GKE hosts;
        no accel-class sysfs entry, so metadata comes from env only).
        """
        nodes = []
        for path in sorted(glob.glob(_hostpath(self.config.dev_root, "dev/accel[0-9]*"))):
            m = re.search(r"accel(\d+)$", path)
            if not m:
                continue
            try:
                st = os.stat(path)
            except OSError:
                continue
            if stat.S_ISCHR(st.st_mode):
                nodes.append((int(m.group(1)), path, "accel"))
        if not nodes:
            vfio_paths = sorted(
                glob.glob(_hostpath(self.config.dev_root, "dev/vfio/[0-9]*"))
            )
            for local_idx, path in enumerate(vfio_paths):
                nodes.append((local_idx, path, "vfio"))
        return nodes

    def _sysfs_chip_meta(self, index: int) -> dict[str, str]:
        """Read PCI metadata for accel device `index` from sysfs."""
        if self._native is not None and self._native.available:
            meta = self._native.chip_meta(self.config.sysfs_root, index)
            if meta:
                return meta
        base = f"{self.config.sysfs_root}/class/accel/accel{index}/device"
        meta = {}
        for key in ("vendor", "device", "numa_node"):
            try:
                with open(f"{base}/{key}") as f:
                    meta[key] = f.read().strip()
            except OSError:
                pass
        try:
            meta["pci_address"] = os.path.basename(os.readlink(base))
        except OSError:
            meta["pci_address"] = ""
        return meta

    def enumerate_chips(self) -> list[ChipInfo]:
        nodes = self._probe_accel_nodes()
        # Reject foreign accel-class devices (other vendors' NPUs also appear
        # as /dev/accelN): keep a node only if its sysfs vendor is Google or
        # vendor metadata is unavailable (vfio nodes, stripped sysfs).
        kept = []
        for index, path, kind in nodes:
            if kind == "accel":
                vendor = self._sysfs_chip_meta(index).get("vendor", "")
                if vendor and vendor != self.GOOGLE_PCI_VENDOR:
                    logger.info("skipping non-TPU accel device %s (vendor %s)",
                                path, vendor)
                    continue
            kept.append((index, path, kind))
        nodes = kept
        if not nodes:
            logger.warning("no TPU device nodes found under %s", self.config.dev_root)
            return []
        first_meta = (
            self._sysfs_chip_meta(nodes[0][0]) if nodes[0][2] == "accel" else {}
        )
        generation = self._detect_generation(first_meta.get("device", ""))
        spec = GENERATIONS.get(generation, GENERATIONS["v4"])
        slice_id, topology, host_id, hosts = self._slice_metadata(
            generation, len(nodes)
        )
        all_coords = list(topology.coords())
        chips = []
        for local_idx, (index, path, kind) in enumerate(nodes):
            meta = self._sysfs_chip_meta(index) if kind == "accel" else {}
            # Global position = host offset + local ordinal.
            gpos = host_id * len(nodes) + local_idx
            coord = all_coords[gpos] if gpos < len(all_coords) else Coord(0, 0, 0)
            uid_src = meta.get("pci_address") or f"{slice_id}/{index}"
            serial = hashlib.sha256(uid_src.encode()).hexdigest()[:12]
            chips.append(
                ChipInfo(
                    index=index,
                    uuid=f"TPU-{serial}",
                    generation=generation,
                    device_paths=[path],
                    hbm_bytes=spec.hbm_bytes,
                    cores=spec.cores_per_chip,
                    coord=coord,
                    slice_id=slice_id,
                    slice_topology=topology,
                    host_id=host_id,
                    hosts_per_slice=hosts,
                    pci_address=meta.get("pci_address", ""),
                    numa_node=_safe_int(meta.get("numa_node"), -1),
                    driver_version=self._libtpu_version(),
                )
            )
        return chips

    def _libtpu_version(self) -> str:
        try:
            import importlib.metadata as md

            return md.version("libtpu")
        except Exception:
            return "0.0.0"

    # -- side effects ------------------------------------------------------

    def set_sharing_mode(self, chip_uuids: list[str], mode: str) -> None:
        """Record the requested per-chip sharing mode.

        The TPU runtime has no persistent on-device mode like NVML compute
        modes; sharing is realised at Prepare time through the env/flags the
        CDI spec injects (TPU_PROCESS_BOUNDS, multi-process flags — see
        plugin/sharing.py).  We persist the requested mode in a small state
        dir so that concurrent claims on one chip can be validated against it
        (role of nvidia-smi -c, nvlib.go:541-558).
        """
        state_dir = _hostpath(self.config.dev_root, "var/run/tpu-dra")
        os.makedirs(state_dir, exist_ok=True)
        for u in chip_uuids:
            with open(os.path.join(state_dir, f"{u}.mode"), "w") as f:
                f.write(mode)

    def create_ici_channel_device(self, channel: int) -> str:
        """mknod the per-channel device (createImexChannelDevice,
        nvlib.go:490-519)."""
        dirpath = _hostpath(self.config.dev_root, ICI_CHANNEL_DIR)
        os.makedirs(dirpath, exist_ok=True)
        path = os.path.join(dirpath, f"channel{channel}")
        if os.path.exists(path):
            return path
        major = self._ici_major()
        if self._native is not None and self._native.available:
            self._native.mknod_char(path, major, channel, 0o666)
        else:
            os.mknod(path, 0o666 | stat.S_IFCHR, os.makedev(major, channel))
            os.chmod(path, 0o666)
        return path

    def _ici_major(self) -> int:
        """Device major for ICI channel nodes from /proc/devices
        (role of nvlib.go:446-488)."""
        proc = _hostpath(self.config.dev_root, "proc/devices")
        try:
            with open(proc) as f:
                for line in f:
                    parts = line.split()
                    if len(parts) == 2 and parts[1] in (
                        "tpu-ici",
                        "vfio",
                        "accel",
                    ):
                        return int(parts[0])
        except OSError:
            pass
        return 511  # dynamic-major fallback
