"""Parallelism: meshes, sharding rules, ring attention, distributed init.

The bootstrap/shim surface (distributed, shim) is importable WITHOUT jax:
a jax-less container (e.g. the driver image running a claim-plumbing
check) can still call ``initialize_distributed()`` to apply the sharing
env. The mesh/ring/pipeline/sharding surface requires jax and is simply
absent when it is not installed.
"""

from .distributed import coordinator_from_env, initialize_distributed
from .shim import SharingRuntime, apply_sharing_env, timeshare_lease

__all__ = [
    "coordinator_from_env",
    "initialize_distributed",
    "SharingRuntime",
    "apply_sharing_env",
    "timeshare_lease",
]

try:
    from .mesh import (
        AXES,
        MeshConfig,
        auto_mesh_config,
        build_mesh,
        host_mesh_shape,
        mesh_from_env,
    )
    from .pipeline import pipeline, stage_params
    from .ring import ring_attention, ulysses_attention
    from .sharding import (
        DEFAULT_RULES,
        batch_sharding,
        named_sharding,
        shard_pytree,
        spec_for,
    )
except ImportError:  # pragma: no cover - exercised via the jax-less demo
    pass
else:
    __all__ += [
        "AXES",
        "MeshConfig",
        "auto_mesh_config",
        "build_mesh",
        "mesh_from_env",
        "host_mesh_shape",
        "pipeline",
        "stage_params",
        "ring_attention",
        "ulysses_attention",
        "DEFAULT_RULES",
        "spec_for",
        "named_sharding",
        "shard_pytree",
        "batch_sharding",
    ]
