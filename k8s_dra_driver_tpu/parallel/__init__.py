"""Parallelism: meshes, sharding rules, ring attention, distributed init."""

from .distributed import coordinator_from_env, initialize_distributed
from .mesh import (
    AXES,
    MeshConfig,
    auto_mesh_config,
    build_mesh,
    host_mesh_shape,
    mesh_from_env,
)
from .pipeline import pipeline, stage_params
from .ring import ring_attention, ulysses_attention
from .shim import SharingRuntime, apply_sharing_env, timeshare_lease
from .sharding import (
    DEFAULT_RULES,
    batch_sharding,
    named_sharding,
    shard_pytree,
    spec_for,
)

__all__ = [
    "AXES",
    "MeshConfig",
    "auto_mesh_config",
    "build_mesh",
    "mesh_from_env",
    "host_mesh_shape",
    "pipeline",
    "stage_params",
    "ring_attention",
    "ulysses_attention",
    "coordinator_from_env",
    "initialize_distributed",
    "SharingRuntime",
    "apply_sharing_env",
    "timeshare_lease",
    "DEFAULT_RULES",
    "spec_for",
    "named_sharding",
    "shard_pytree",
    "batch_sharding",
]
