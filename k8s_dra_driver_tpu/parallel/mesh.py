"""Device-mesh construction from DRA-injected topology.

This is the workload side of the driver contract: the node plugin injects
``TPU_VISIBLE_CHIPS`` / ``TPU_TOPOLOGY`` / ``TPU_WORKER_ID`` (cdi/spec.py),
the cluster controller's ICI channel prepare adds coordinator env, and this
module turns that into a ``jax.sharding.Mesh`` whose axis layout matches the
physical ICI topology — so XLA's collectives ride ICI neighbours instead of
arbitrary device orderings.

Axis convention (outer → inner):
``("data", "fsdp", "pipe", "expert", "sequence", "tensor")``.
- ``tensor``  — innermost, mapped onto directly-connected chips: per-op
  all-reduces must be the cheapest collective.
- ``sequence`` — ring/all-to-all sequence parallelism for long context.
- ``expert``  — MoE expert parallelism; dispatch/combine all-to-alls.
- ``pipe``    — pipeline stages; neighbour-only activation transfers.
- ``fsdp``    — parameter sharding; all-gathers overlap with compute.
- ``data``    — pure data parallel, outermost (can span DCN between slices).
"""

from __future__ import annotations

import dataclasses
import math
import os
from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh

AXES = ("data", "fsdp", "pipe", "expert", "sequence", "tensor")


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Logical parallelism degrees. Product must equal the device count."""

    data: int = 1
    fsdp: int = 1
    pipe: int = 1
    expert: int = 1
    sequence: int = 1
    tensor: int = 1

    @property
    def shape(self) -> tuple[int, int, int, int, int, int]:
        return (self.data, self.fsdp, self.pipe, self.expert,
                self.sequence, self.tensor)

    @property
    def num_devices(self) -> int:
        return math.prod(self.shape)

    def __str__(self) -> str:
        return "x".join(
            f"{a}={d}" for a, d in zip(AXES, self.shape) if d > 1
        ) or "single"


def auto_mesh_config(
    n_devices: int,
    *,
    model_needs_tensor: int = 1,
    long_context: bool = False,
) -> MeshConfig:
    """Reasonable default factorization for ``n_devices``.

    Heuristic from the scaling playbook: give the model its required tensor
    degree, spend the next factor on sequence if long-context, and the rest
    on fsdp (which subsumes data parallel at these scales).
    """
    if n_devices % model_needs_tensor:
        raise ValueError(
            f"{n_devices} devices not divisible by tensor={model_needs_tensor}"
        )
    rest = n_devices // model_needs_tensor
    sequence = 1
    if long_context and rest % 2 == 0:
        sequence = min(rest, 4)
        while rest % sequence:
            sequence //= 2
        rest //= sequence
    return MeshConfig(
        data=1, fsdp=rest, sequence=sequence, tensor=model_needs_tensor
    )


def build_mesh(
    config: Optional[MeshConfig] = None,
    devices: Optional[list] = None,
) -> Mesh:
    """Create a Mesh over ``devices`` (default: all).

    Devices are ordered by (slice, host, local index) before reshaping so
    the innermost mesh axes land on intra-host / ICI-adjacent chips. JAX's
    own device order already follows physical topology on TPU; we keep it
    and only reshape.
    """
    devices = list(devices if devices is not None else jax.devices())
    if config is None:
        config = auto_mesh_config(len(devices))
    if config.num_devices != len(devices):
        raise ValueError(
            f"mesh {config} needs {config.num_devices} devices, "
            f"have {len(devices)}"
        )
    arr = np.array(devices).reshape(config.shape)
    return Mesh(arr, AXES)


def mesh_from_env(config: Optional[MeshConfig] = None) -> Mesh:
    """Build the mesh inside a DRA-prepared container.

    Honors the env the driver injected: if ``TPU_VISIBLE_CHIPS`` restricted
    the chip set, jax.devices() already reflects it; multi-host jobs call
    ``initialize_distributed`` (distributed.py) first.
    """
    return build_mesh(config)


def host_mesh_shape() -> tuple[int, ...]:
    """Physical bounds of this host's chips from driver-injected env."""
    bounds = os.environ.get("TPU_CHIPS_PER_HOST_BOUNDS", "")
    if not bounds:
        return (len(jax.local_devices()),)
    return tuple(int(x) for x in bounds.split(","))
