"""Device-mesh construction from DRA-injected topology.

This is the workload side of the driver contract: the node plugin injects
``TPU_VISIBLE_CHIPS`` / ``TPU_TOPOLOGY`` / ``TPU_WORKER_ID`` (cdi/spec.py),
the cluster controller's ICI channel prepare adds coordinator env, and this
module turns that into a ``jax.sharding.Mesh`` whose axis layout matches the
physical ICI topology — so XLA's collectives ride ICI neighbours instead of
arbitrary device orderings.

Axis convention (outer → inner):
``("data", "fsdp", "pipe", "expert", "sequence", "tensor")``.
- ``tensor``  — innermost, mapped onto directly-connected chips: per-op
  all-reduces must be the cheapest collective.
- ``sequence`` — ring/all-to-all sequence parallelism for long context.
- ``expert``  — MoE expert parallelism; dispatch/combine all-to-alls.
- ``pipe``    — pipeline stages; neighbour-only activation transfers.
- ``fsdp``    — parameter sharding; all-gathers overlap with compute.
- ``data``    — pure data parallel, outermost (can span DCN between slices).
"""

from __future__ import annotations

import dataclasses
import math
import os
from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh

AXES = ("data", "fsdp", "pipe", "expert", "sequence", "tensor")


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Logical parallelism degrees. Product must equal the device count."""

    data: int = 1
    fsdp: int = 1
    pipe: int = 1
    expert: int = 1
    sequence: int = 1
    tensor: int = 1

    @property
    def shape(self) -> tuple[int, int, int, int, int, int]:
        return (self.data, self.fsdp, self.pipe, self.expert,
                self.sequence, self.tensor)

    @property
    def num_devices(self) -> int:
        return math.prod(self.shape)

    def __str__(self) -> str:
        return "x".join(
            f"{a}={d}" for a, d in zip(AXES, self.shape) if d > 1
        ) or "single"

    @property
    def model_degrees(self) -> int:
        """Product of the degrees fixed by the MODEL, not the fleet:
        tensor/sequence/expert/pipe are architecture choices (kv-head
        divisibility, expert count, stage splits) that an elastic resize
        must not change. Only data x fsdp — pure replication/param
        sharding — can absorb device-count changes."""
        return self.pipe * self.expert * self.sequence * self.tensor

    def resize(self, n_devices: int) -> "MeshConfig":
        """Refactor this config for ``n_devices``, preserving the
        model-mandated degrees and collapsing data/fsdp into one degree.

        The elastic seam: when a gang shrinks (chip died) or grows (spare
        admitted), the tensor/sequence/expert/pipe degrees carry over
        unchanged — resharding must not alter the model's parallelism
        contract mid-run — and the combined data x fsdp product collapses
        to a single degree. WHICH axis carries it follows the source
        config's character: an fsdp-sharded config (fsdp > 1) stays fsdp
        — it shards params because they don't fit replicated, and a
        resize must not blow HBM — while a pure data-parallel config
        collapses into ``data``, keeping the parameter replication that
        makes the NEXT shrink live-reshardable (a gang that grew into
        fsdp sharding would lose unreplicated shards with the next dead
        chip and be forced through the cold checkpoint path). Raises
        ``ValueError`` when ``n_devices`` cannot hold the preserved
        degrees; callers wanting "largest valid sub-mesh" semantics
        should round down first (see ``elastic.largest_usable_count``).
        """
        fixed = self.model_degrees
        if n_devices <= 0:
            raise ValueError(f"cannot resize mesh to {n_devices} devices")
        if n_devices % fixed:
            raise ValueError(
                f"{n_devices} devices cannot hold the preserved degrees "
                f"of {self} (pipe*expert*sequence*tensor={fixed}); "
                f"use a multiple of {fixed}"
            )
        rest = n_devices // fixed
        if self.fsdp > 1:
            return dataclasses.replace(self, data=1, fsdp=rest)
        return dataclasses.replace(self, data=rest, fsdp=1)


def auto_mesh_config(
    n_devices: int,
    *,
    model_needs_tensor: int = 1,
    long_context: bool = False,
) -> MeshConfig:
    """Reasonable default factorization for ``n_devices``.

    Heuristic from the scaling playbook: give the model its required tensor
    degree, spend the next factor on sequence if long-context, and the rest
    on fsdp (which subsumes data parallel at these scales).
    """
    if n_devices < 1:
        raise ValueError(f"need at least one device, got {n_devices}")
    if model_needs_tensor < 1:
        raise ValueError(
            f"tensor degree must be >= 1, got {model_needs_tensor}"
        )
    if model_needs_tensor > n_devices:
        # Distinct from mere indivisibility: no factorization exists at
        # ANY device multiple — the model demands more tensor-parallel
        # peers than the allocation holds.
        raise ValueError(
            f"model needs tensor={model_needs_tensor} but only "
            f"{n_devices} device(s) are available; allocate at least "
            f"{model_needs_tensor} devices or lower the tensor degree"
        )
    if n_devices % model_needs_tensor:
        raise ValueError(
            f"{n_devices} devices not divisible by tensor={model_needs_tensor}"
        )
    rest = n_devices // model_needs_tensor
    sequence = 1
    if long_context and rest % 2 == 0:
        sequence = min(rest, 4)
        while rest % sequence:
            sequence //= 2
        rest //= sequence
    return MeshConfig(
        data=1, fsdp=rest, sequence=sequence, tensor=model_needs_tensor
    )


def build_mesh(
    config: Optional[MeshConfig] = None,
    devices: Optional[list] = None,
) -> Mesh:
    """Create a Mesh over ``devices`` (default: all).

    Devices are ordered by (slice, host, local index) before reshaping so
    the innermost mesh axes land on intra-host / ICI-adjacent chips. JAX's
    own device order already follows physical topology on TPU; we keep it
    and only reshape.
    """
    devices = list(devices if devices is not None else jax.devices())
    if config is None:
        config = auto_mesh_config(len(devices))
    if config.num_devices != len(devices):
        raise ValueError(
            f"mesh {config} needs {config.num_devices} devices, "
            f"have {len(devices)}"
        )
    arr = np.array(devices).reshape(config.shape)
    return Mesh(arr, AXES)


def mesh_from_env(config: Optional[MeshConfig] = None) -> Mesh:
    """Build the mesh inside a DRA-prepared container.

    Honors the env the driver injected: if ``TPU_VISIBLE_CHIPS`` restricted
    the chip set, jax.devices() already reflects it; multi-host jobs call
    ``initialize_distributed`` (distributed.py) first.
    """
    return build_mesh(config)


def host_mesh_shape() -> tuple[int, ...]:
    """Physical bounds of this host's chips from driver-injected env."""
    bounds = os.environ.get("TPU_CHIPS_PER_HOST_BOUNDS", "")
    if not bounds:
        return (len(jax.local_devices()),)
    return tuple(int(x) for x in bounds.split(","))
