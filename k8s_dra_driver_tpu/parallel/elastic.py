"""Elastic resize coordinator: reshape the mesh, reshard the live state.

The workload half of the gang-resize story (the plugin half is
``plugin/driver.py``'s elastic coordinator). When chip health shrinks a
gang claim — or a restored spare grows it back — the driver emits a typed
``GangResize`` message; this module consumes the surviving device set and
keeps training alive:

1. pick the **largest valid sub-mesh** of the survivors (the model's
   tensor/sequence/expert/pipe degrees are preserved — ``MeshConfig.
   resize`` — and the global batch must still divide the data axes; the
   remainder is idled, not used);
2. **reshard the live TrainState in place** — params and optimizer
   moments move device-to-device with ``jax.device_put`` from the old
   mesh's shardings to the new mesh's (the Flex-MIG reshard-on-resize
   discipline: no checkpoint round-trip on the hot path). The cold
   fallback — ``models/checkpoint.restore_template`` + restore — runs
   ONLY when the surviving devices cannot cover the state (some shard's
   every replica lived on lost chips);
3. rebuild the jitted train step for the new mesh and resume — the step
   counter and optimizer state carry over, so the loss trajectory
   continues where it left off.

Fault sites: ``train.step`` fires at the top of every train step and
``train.reshard`` at the top of every resize, so the chaos harness can
land a chip-unplug (or a crash) exactly mid-step / mid-reshard the same
way it does for ``kube.*``/``chiplib.*`` sites.
"""

from __future__ import annotations

import dataclasses
import logging
import time
from typing import Any, Optional, Sequence

from ..utils import faults
from ..utils.metrics import Counter, Gauge, Histogram, Registry

logger = logging.getLogger(__name__)

RESHARD_LIVE = "live"
RESHARD_COLD = "cold"


class ElasticResizeError(RuntimeError):
    """A resize that cannot proceed: no valid sub-mesh exists for the
    surviving devices, or the cold fallback has no checkpoint to restore
    from. Training state is left untouched — the caller may retry with a
    different device set (or after saving a checkpoint)."""


def largest_usable_count(
    n_available: int, config, global_batch: Optional[int] = None
) -> int:
    """Largest device count ``<= n_available`` that yields a valid mesh.

    Valid means: the preserved model degrees (``config.model_degrees``)
    divide it, and — when ``global_batch`` is given — the resulting
    data x fsdp product still divides the batch (the train step shards
    batches over ``("data", "fsdp")``; a dp degree that does not divide
    the batch cannot run). Returns 0 when no count works.
    """
    fixed = config.model_degrees
    n = (n_available // fixed) * fixed
    while n >= fixed:
        dp = n // fixed
        if global_batch is None or global_batch % dp == 0:
            return n
        n -= fixed
    return 0


def state_covered(state: Any, available) -> bool:
    """Can ``available`` devices reconstruct every shard of ``state``?

    For each leaf, group the sharding's device→index map by index: every
    distinct shard must have at least one replica on an available device.
    Data-parallel replication makes shrink coverable (the surviving
    replica holds a full copy); a pure-fsdp layout is NOT covered when
    any of its devices is lost — that is exactly the cold-restore case.
    """
    import jax  # noqa: F401  (lazy: keep module importable early)

    avail = set(available)
    for leaf in jax.tree.leaves(state):
        sharding = getattr(leaf, "sharding", None)
        if sharding is None:
            continue
        replicas: dict[tuple, bool] = {}
        for dev, idx in sharding.devices_indices_map(leaf.shape).items():
            key = tuple(
                (s.start, s.stop, s.step) if isinstance(s, slice) else s
                for s in idx
            )
            replicas[key] = replicas.get(key, False) or dev in avail
        if not all(replicas.values()):
            return False
    return True


@dataclasses.dataclass(frozen=True)
class ResizeEvent:
    """Workload-side record of one completed resize (the resize trace)."""

    direction: str                 # "shrink" | "grow" | "reshape"
    path: str                      # "live" | "cold"
    reason: str
    step: int                      # TrainState.step AFTER the resize
    old_mesh: str                  # str(MeshConfig) before
    new_mesh: str                  # str(MeshConfig) after
    n_old: int
    n_used: int
    n_idled: int
    duration_seconds: float

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


class ElasticTrainer:
    """Owns the mesh, the TrainState, and the jitted step — and survives
    the device set changing underneath them.

    ``devices`` is the gang's initial jax device list; ``resize()``
    takes the post-resize device list (survivors, or survivors + spares)
    in allocation order. ``global_batch`` pins the batch geometry so a
    resize never lands on a mesh the batch cannot shard over.
    """

    def __init__(
        self,
        config,
        optimizer,
        devices: Sequence,
        *,
        mesh_config=None,
        global_batch: Optional[int] = None,
        checkpoint_dir: Optional[str] = None,
        checkpoint_every: int = 0,
        use_ring: bool = False,
        remat: bool = True,
        seed: int = 0,
        registry: Optional[Registry] = None,
    ):
        from ..models.train import init_train_state, make_train_step
        from .mesh import auto_mesh_config, build_mesh

        self.config = config
        self.optimizer = optimizer
        self.use_ring = use_ring
        self.remat = remat
        self.global_batch = global_batch
        self.checkpoint_dir = checkpoint_dir
        self.checkpoint_every = checkpoint_every
        self.devices = list(devices)
        self.idled: list = []
        self.mesh_config = mesh_config or auto_mesh_config(len(self.devices))
        if self.mesh_config.num_devices != len(self.devices):
            raise ValueError(
                f"mesh {self.mesh_config} needs "
                f"{self.mesh_config.num_devices} devices, got "
                f"{len(self.devices)}"
            )
        self.mesh = build_mesh(self.mesh_config, self.devices)
        self.state = init_train_state(
            config, self.mesh, optimizer, seed=seed
        )
        self._step_fn = make_train_step(
            config, self.mesh, optimizer, use_ring=use_ring, remat=remat
        )
        self.resize_trace: list[ResizeEvent] = []

        reg = registry if registry is not None else Registry()
        self._m_reshards = Counter(
            "tpu_dra_elastic_reshards_total",
            "Live-state reshards by direction, path (live/cold) and "
            "outcome",
            reg,
        )
        self._m_reshard_seconds = Histogram(
            "tpu_dra_elastic_reshard_seconds",
            "End-to-end resize latency: sub-mesh choice, state reshard, "
            "and train-step rebuild",
            reg,
        )
        self._m_devices = Gauge(
            "tpu_dra_elastic_devices",
            "Devices in the current elastic gang by role (used/idled)",
            reg,
        )
        self._set_device_gauges()

    def _set_device_gauges(self) -> None:
        self._m_devices.set(len(self.devices), role="used")
        self._m_devices.set(len(self.idled), role="idled")

    # -- training ----------------------------------------------------------

    @property
    def step_count(self) -> int:
        return int(self.state.step)

    def step(self, tokens) -> float:
        """One train step; returns the loss. Instrumented as the
        ``train.step`` fault site so chaos schedules can unplug a chip
        (or crash) exactly mid-training."""
        faults.fire("train.step")
        self.state, loss = self._step_fn(self.state, tokens)
        if (
            self.checkpoint_every
            and self.checkpoint_dir
            and self.step_count % self.checkpoint_every == 0
        ):
            self.save()
        return float(loss)

    def save(self) -> None:
        if not self.checkpoint_dir:
            raise ElasticResizeError(
                "no checkpoint_dir configured; cannot save"
            )
        from ..models.checkpoint import save_checkpoint

        save_checkpoint(self.checkpoint_dir, self.state,
                        step=self.step_count)

    # -- resize ------------------------------------------------------------

    def relocate(self, devices: Sequence, *, reason: str = "") -> ResizeEvent:
        """Defrag-migration resize: move the gang onto ``devices``
        WITHOUT shrinking the mesh. A relocation trades placement for
        placement — the defrag executor promises loss continuity, so a
        destination that would silently idle part of the mesh (or force
        a smaller sub-mesh) is refused up front with
        :class:`ElasticResizeError` instead of degrading training.
        Otherwise delegates to :meth:`resize` with the old devices
        marked still-alive — a migration is a planned move, not a
        failure, so the live state reshards device-to-device onto the
        destination (never a checkpoint restore) and the step/loss
        continuity guarantees apply unchanged."""
        usable = largest_usable_count(
            len(devices), self.mesh_config, self.global_batch
        )
        if usable < len(self.devices):
            raise ElasticResizeError(
                f"relocation target of {len(devices)} device(s) cannot "
                f"host the current {len(self.devices)}-device mesh "
                f"(largest valid sub-mesh: {usable}) — a defrag move "
                "must not shrink the gang"
            )
        return self.resize(devices, reason=reason or "defrag relocation",
                           sources_alive=True)

    def resize(self, devices: Sequence, *, reason: str = "",
               sources_alive: bool = False) -> ResizeEvent:
        """Reshape the mesh onto ``devices`` and reshard the live state.

        ``devices`` is the post-resize gang (survivors first is not
        required — devices already in the old mesh are preferred for the
        sub-mesh so transfers stay local). Devices beyond the largest
        valid sub-mesh are idled, not dropped: they remain in the gang
        and re-enter the mesh on the next grow.

        ``sources_alive`` (the :meth:`relocate` path) declares that
        devices LEAVING the gang still hold readable HBM — a planned
        migration, not a chip loss — so the live state reshards from
        them instead of falling back to a checkpoint restore.
        """
        t0 = time.monotonic()
        faults.fire("train.reshard")
        from ..models.train import make_train_step, reshard_train_state
        from .mesh import build_mesh

        devices = list(devices)
        old_devices = list(self.devices)
        old_config = self.mesh_config
        n_old = len(old_devices)
        usable = largest_usable_count(
            len(devices), old_config, self.global_batch
        )
        if usable == 0:
            self._m_reshards.inc(
                direction="unknown", path="none", outcome="no-valid-mesh"
            )
            raise ElasticResizeError(
                f"no valid sub-mesh for {len(devices)} device(s): the "
                f"preserved degrees of {old_config} need multiples of "
                f"{old_config.model_degrees}"
                + (
                    f" that divide global batch {self.global_batch}"
                    if self.global_batch else ""
                )
            )
        # Prefer devices the old mesh already used (their shards are in
        # place), then spares — stable within each class so the driver's
        # allocation order is respected.
        old_set = set(old_devices)
        ordered = (
            [d for d in devices if d in old_set]
            + [d for d in devices if d not in old_set]
        )
        used, idled = ordered[:usable], ordered[usable:]
        new_config = old_config.resize(usable)
        new_mesh = build_mesh(new_config, used)
        direction = (
            "grow" if len(devices) > n_old
            else "shrink" if len(devices) < n_old
            else "reshape"
        )

        # Sources readable for a live reshard: old-mesh devices that are
        # still part of the gang. A device absent from ``devices``
        # vanished with its HBM — its shards only survive as replicas —
        # UNLESS the caller vouches the sources are alive (a planned
        # relocation reads every old shard device-to-device).
        available = old_set if sources_alive else old_set & set(devices)
        path = RESHARD_LIVE
        new_state = None
        if state_covered(self.state, available):
            try:
                new_state = reshard_train_state(self.state, new_mesh)
            except Exception:
                logger.exception(
                    "live reshard failed; falling back to checkpoint "
                    "restore"
                )
                path = RESHARD_COLD
        else:
            logger.warning(
                "surviving devices cannot cover the live state "
                "(unreplicated shards on lost devices); cold-restoring "
                "from checkpoint"
            )
            path = RESHARD_COLD
        if new_state is None:
            try:
                new_state = self._cold_restore(new_mesh)
            except Exception:
                # ANY restore failure counts (MeshShapeMismatchError,
                # orbax I/O errors, ...) — dashboards alerting on error
                # outcomes must see exactly these.
                self._m_reshards.inc(
                    direction=direction, path=RESHARD_COLD,
                    outcome="error",
                )
                raise
        self._step_fn = make_train_step(
            self.config, new_mesh, self.optimizer,
            use_ring=self.use_ring, remat=self.remat,
        )
        self.state = new_state
        self.mesh = new_mesh
        self.mesh_config = new_config
        self.devices = used
        self.idled = idled
        self._set_device_gauges()

        event = ResizeEvent(
            direction=direction,
            path=path,
            reason=reason,
            step=self.step_count,
            old_mesh=str(old_config),
            new_mesh=str(new_config),
            n_old=n_old,
            n_used=len(used),
            n_idled=len(idled),
            duration_seconds=time.monotonic() - t0,
        )
        self.resize_trace.append(event)
        self._m_reshards.inc(
            direction=direction, path=path, outcome="ok"
        )
        self._m_reshard_seconds.observe(event.duration_seconds)
        logger.info(
            "elastic resize (%s, %s): %s -> %s on %d device(s) "
            "(%d idled) at step %d in %.3fs — %s",
            direction, path, event.old_mesh, event.new_mesh,
            event.n_used, event.n_idled, event.step,
            event.duration_seconds, reason or "no reason given",
        )
        return event

    def _cold_restore(self, new_mesh):
        """The fallback when live shards are unrecoverable: restore the
        latest checkpoint resharded onto the new mesh. Loses the steps
        since the last save — which is why it is never taken while a
        live reshard can work."""
        if not self.checkpoint_dir:
            raise ElasticResizeError(
                "surviving devices cannot cover the live state and no "
                "checkpoint_dir is configured — training state is lost"
            )
        from ..models.checkpoint import (
            latest_step,
            restore_checkpoint,
            restore_template,
        )

        if latest_step(self.checkpoint_dir) is None:
            raise ElasticResizeError(
                "surviving devices cannot cover the live state and no "
                f"checkpoint exists under {self.checkpoint_dir}"
            )
        template = restore_template(self.state, new_mesh)
        return restore_checkpoint(self.checkpoint_dir, template)
