"""Sharding rules: logical axis names → mesh partition specs.

The tpu-idiomatic way to scale (scaling-book recipe): annotate arrays with
*logical* axes, map logical → mesh axes in one table, and let pjit/XLA
insert the collectives. Changing the parallelism strategy is then a table
edit, not a model edit.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# Logical axis → mesh axis (None = replicated). The model layer tags params
# and activations with the left-hand names.
DEFAULT_RULES: dict[str, Optional[str | tuple[str, ...]]] = {
    "batch": ("data", "fsdp"),    # data-parallel batch split
    "seq": "sequence",            # sequence parallelism (ring attention)
    "embed": None,                # model dim of activations: replicated
    "vocab": "tensor",
    "embed_fsdp": "fsdp",         # param model-dim rows: fsdp-sharded
    "heads": "tensor",            # attention heads: tensor parallel
    "kv_heads": "tensor",
    "mlp": "tensor",              # mlp hidden: tensor parallel
    "head_dim": None,
    "layers": None,
}


def spec_for(*logical_axes: Optional[str], rules=None) -> P:
    """PartitionSpec for an array whose dims carry these logical names."""
    rules = rules or DEFAULT_RULES
    parts = []
    for name in logical_axes:
        if name is None:
            parts.append(None)
        else:
            parts.append(rules.get(name))
    # Trailing Nones are implicit.
    while parts and parts[-1] is None:
        parts.pop()
    return P(*parts)


def named_sharding(mesh: Mesh, *logical_axes, rules=None) -> NamedSharding:
    return NamedSharding(mesh, spec_for(*logical_axes, rules=rules))


def shard_pytree(tree: Any, mesh: Mesh, spec_tree: Any) -> Any:
    """Device-put a pytree with per-leaf PartitionSpecs."""
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        tree,
        spec_tree,
    )


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Input batch: split over (data, fsdp) — every chip sees distinct rows."""
    return named_sharding(mesh, "batch", "seq")
