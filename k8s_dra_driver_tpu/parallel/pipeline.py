"""Pipeline parallelism: GPipe microbatch schedule over the "pipe" axis.

TPU-first shape: stages are devices along the mesh's "pipe" axis, stage
weights are the layer stack reshaped [n_stages, L/n_stages, ...] and
sharded on the leading axis, and activations move stage-to-stage with
``ppermute`` — a neighbour transfer that rides one ICI hop per step. The
schedule is plain GPipe: microbatch j enters stage p at step p + j, so a
run of M microbatches over P stages takes M + P - 1 steps with a bubble
fraction of (P-1)/(M+P-1). Everything is a static-shape ``fori_loop``
(lowered to scan), so the whole pipeline jits, shards, and reverse-mode
differentiates without a custom VJP — the backward replays the schedule
in reverse through the transposed ppermutes.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P


def stage_params(layer_stack: Any, n_stages: int) -> Any:
    """Reshape a layer-stacked pytree [L, ...] → [n_stages, L/n_stages, ...]
    so the leading axis can shard over "pipe"."""
    def split(a):
        l = a.shape[0]
        assert l % n_stages == 0, (
            f"{l} layers do not split over {n_stages} pipeline stages"
        )
        return a.reshape(n_stages, l // n_stages, *a.shape[1:])
    return jax.tree_util.tree_map(split, layer_stack)


def pipeline(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    staged: Any,                      # [n_stages, L/P, ...] pytree
    x: jax.Array,                     # [B, ...]
    *,
    mesh: Mesh,
    n_microbatches: int,
    axis_name: str = "pipe",
    batch_axes: Optional[tuple] = ("data", "fsdp"),
) -> jax.Array:
    """Run ``stage_fn`` (same-shape activation transform, e.g. a scan over
    this stage's transformer layers) as a P-stage pipeline. Returns the
    transformed batch."""
    b = x.shape[0]
    m = n_microbatches
    assert b % m == 0, f"batch {b} not divisible into {m} microbatches"
    xs = x.reshape(m, b // m, *x.shape[1:])

    def local(staged_local, xs_local):
        idx = jax.lax.axis_index(axis_name)
        p = jax.lax.psum(1, axis_name)
        me = jax.tree_util.tree_map(lambda a: a[0], staged_local)
        shift = [(i, (i + 1) % p) for i in range(p)]

        def step(t, carry):
            buf, outs = carry
            # Stage 0 draws microbatch t from the input queue; later
            # stages consume what the previous stage handed over.
            inp = jnp.where(idx == 0, xs_local[jnp.clip(t, 0, m - 1)], buf)
            y = stage_fn(me, inp)
            # The last stage finishes microbatch t - (P-1) at step t.
            j = t - (p - 1)
            write = jnp.logical_and(idx == p - 1, j >= 0)
            outs = jnp.where(
                write, outs.at[jnp.clip(j, 0, m - 1)].set(y), outs
            )
            buf = jax.lax.ppermute(y, axis_name, shift)
            return buf, outs

        buf = jnp.zeros_like(xs_local[0])
        outs = jnp.zeros_like(xs_local)
        _, outs = jax.lax.fori_loop(0, m + p - 1, step, (buf, outs))
        # Results live on the last stage; replicate along the pipe axis so
        # the out_spec needn't special-case it.
        return jax.lax.psum(
            jnp.where(idx == p - 1, outs, jnp.zeros_like(outs)), axis_name
        )

    spec_params = jax.tree_util.tree_map(
        lambda a: P(axis_name, *([None] * (a.ndim - 1))), staged
    )
    mb_spec = P(None, batch_axes, *([None] * (x.ndim - 1)))
    fn = shard_map(
        local,
        mesh=mesh,
        in_specs=(spec_params, mb_spec),
        out_specs=mb_spec,
        check_vma=False,
    )
    out = fn(staged, xs)
    return out.reshape(b, *x.shape[1:])
