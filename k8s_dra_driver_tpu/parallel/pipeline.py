"""Pipeline parallelism: GPipe microbatch schedule over the "pipe" axis.

TPU-first shape: stages are devices along the mesh's "pipe" axis, stage
weights are the layer stack reshaped [n_stages, L/n_stages, ...] and
sharded on the leading axis, and activations move stage-to-stage with
``ppermute`` — a neighbour transfer that rides one ICI hop per step. The
schedule is plain GPipe: microbatch j enters stage p at step p + j, so a
run of M microbatches over P stages takes M + P - 1 steps with a bubble
fraction of (P-1)/(M+P-1). Everything is a static-shape ``fori_loop``
(lowered to scan), so the whole pipeline jits, shards, and reverse-mode
differentiates without a custom VJP — the backward replays the schedule
in reverse through the transposed ppermutes.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from .compat import shard_map_compat as _shard_map_compat


def stage_params(layer_stack: Any, n_stages: int) -> Any:
    """Reshape a layer-stacked pytree [L, ...] → [n_stages, L/n_stages, ...]
    so the leading axis can shard over "pipe"."""
    def split(a):
        l = a.shape[0]
        assert l % n_stages == 0, (
            f"{l} layers do not split over {n_stages} pipeline stages"
        )
        return a.reshape(n_stages, l // n_stages, *a.shape[1:])
    return jax.tree_util.tree_map(split, layer_stack)


def pipeline(
    stage_fn: Callable[[Any, Any], Any],
    staged: Any,                      # [n_stages, L/P, ...] pytree
    x: Any,                           # [B, ...] array or pytree of them
    *,
    mesh: Mesh,
    n_microbatches: int,
    axis_name: str = "pipe",
    batch_axes: Optional[tuple] = ("data", "fsdp"),
    manual_only: bool = True,
) -> Any:
    """Run ``stage_fn`` (same-structure activation transform, e.g. a scan
    over this stage's transformer layers) as a P-stage pipeline. Returns
    the transformed batch.

    ``x`` may be a pytree of same-leading-dim arrays (e.g. hidden states
    plus an auxiliary-loss channel); every leaf rides the same GPipe
    schedule and ppermute hops. With ``manual_only=False`` the shard_map
    is manual ONLY over the pipe + batch axes and leaves every other
    mesh axis (tensor, expert, sequence) automatic, so ``stage_fn`` may
    contain ordinary GSPMD sharding constraints — that is how pp
    composes with tp/ep in a single step.
    """
    tree_map = jax.tree_util.tree_map
    leaves = jax.tree_util.tree_leaves(x)
    b = leaves[0].shape[0]
    m = n_microbatches
    assert b % m == 0, f"batch {b} not divisible into {m} microbatches"
    xs = tree_map(lambda a: a.reshape(m, b // m, *a.shape[1:]), x)

    def local(staged_local, xs_local):
        idx = jax.lax.axis_index(axis_name)
        p = jax.lax.psum(1, axis_name)
        me = tree_map(lambda a: a[0], staged_local)
        shift = [(i, (i + 1) % p) for i in range(p)]

        def step(t, carry):
            buf, outs = carry
            # Stage 0 draws microbatch t from the input queue; later
            # stages consume what the previous stage handed over.
            tt = jnp.clip(t, 0, m - 1)
            inp = tree_map(
                lambda q, bu: jnp.where(idx == 0, q[tt], bu), xs_local, buf
            )
            y = stage_fn(me, inp)
            # The last stage finishes microbatch t - (P-1) at step t.
            j = t - (p - 1)
            write = jnp.logical_and(idx == p - 1, j >= 0)
            jc = jnp.clip(j, 0, m - 1)
            outs = tree_map(
                lambda o, yy: jnp.where(write, o.at[jc].set(yy), o),
                outs, y,
            )
            buf = tree_map(
                lambda yy: jax.lax.ppermute(yy, axis_name, shift), y
            )
            return buf, outs

        buf = tree_map(lambda q: jnp.zeros_like(q[0]), xs_local)
        outs = tree_map(jnp.zeros_like, xs_local)
        _, outs = jax.lax.fori_loop(0, m + p - 1, step, (buf, outs))
        # Results live on the last stage; replicate along the pipe axis so
        # the out_spec needn't special-case it.
        return tree_map(
            lambda o: jax.lax.psum(
                jnp.where(idx == p - 1, o, jnp.zeros_like(o)), axis_name
            ),
            outs,
        )

    spec_params = tree_map(
        lambda a: P(axis_name, *([None] * (a.ndim - 1))), staged
    )
    # xs leaves are [m, b/m, ...]: microbatch dim unsharded, batch dim
    # over the batch axes, trailing dims replicated.
    mb_spec = tree_map(
        lambda a: P(None, batch_axes, *([None] * (a.ndim - 2))), xs
    )
    kwargs = {}
    if not manual_only:
        manual = {axis_name} | (
            set(batch_axes or ()) & set(mesh.axis_names)
        )
        kwargs["axis_names"] = frozenset(manual)
    fn = _shard_map_compat(
        local,
        mesh=mesh,
        in_specs=(spec_params, mb_spec),
        out_specs=mb_spec,
        check_vma=False,
        **kwargs,
    )
    out = fn(staged, xs)
    return tree_map(
        lambda o, orig: o.reshape(b, *orig.shape[1:]), out, x
    )
