"""Host-side ICI/DCN collective accounting — the emission layer of the
compute-plane telemetry (models/compute_telemetry.py).

Every collective site in the codebase (parallel/ring.py's permutes and
attention rings, the MoE expert-parallel ring/psum combine, the elastic
``reshard_train_state`` device_puts) calls :func:`emit` with an
*analytic* byte volume derived from static shapes. The call happens in
host Python — at trace time for sites inside jitted/shard_mapped bodies,
at call time for host-level sites like the reshard — so the accounting
never adds an op to a compiled program and can never perturb tokens,
tick counts, or the compile-once invariant. With no ledger installed,
:func:`emit` is a single list-truthiness check: the zero-cost contract
``make computesmoke`` enforces.

Accounting convention (pinned by tests/test_compute_telemetry.py): a
record's ``bytes`` is the total fabric traffic of one logical invocation
summed over every participating shard, under the standard ring
algorithms —

- permute (``ppermute``/ring hop): each of the ``n`` shards sends its
  whole local payload once → ``n * payload``.
- all_gather (tiled): ``n - 1`` ring steps, one chunk per shard per
  step → ``n * (n - 1) * local_chunk``.
- all_to_all: each shard keeps 1/n of its buffer and sends the rest →
  ``(n - 1) * local_buffer``.
- all_reduce (psum/pmean): reduce-scatter + all-gather →
  ``2 * (n - 1) * payload``.

Sites inside a jitted program fire once per *trace* (per program build),
not per executed step — the record is the per-invocation volume of the
traced program; multiply by the program's step counters for cumulative
traffic. Eager calls and host-level sites fire per call.
"""

from __future__ import annotations

import math
import threading

MEDIUM_ICI = "ici"   # in-mesh collective fabric
MEDIUM_DCN = "dcn"   # cross-slice / host-mediated transfers (device_put)

# Installed CollectiveLedgers. Module-level on purpose: the collective
# sites (ring.py, moe.py, train.py) must not need a handle threaded
# through every model call; attaching telemetry installs a ledger here.
_LEDGERS: list["CollectiveLedger"] = []
_LOCK = threading.Lock()


def payload_bytes(shape, dtype) -> int:
    """Bytes of one array payload from its static shape + dtype (works
    on tracers — only ``shape``/``dtype.itemsize`` are read)."""
    return int(math.prod(shape)) * int(dtype.itemsize)


def permute_bytes(payload: int, n: int) -> int:
    """One ring hop: every shard ships its local payload. A ring of one
    is a self-permute — no fabric traffic."""
    return n * payload if n > 1 else 0


def all_gather_bytes(local_chunk: int, n: int) -> int:
    """Tiled all-gather via the ring algorithm."""
    return n * (n - 1) * local_chunk


def all_to_all_bytes(local_buffer: int, n: int) -> int:
    """Each shard sends (n-1)/n of its local buffer."""
    return (n - 1) * local_buffer


def all_reduce_bytes(payload: int, n: int) -> int:
    """psum/pmean as reduce-scatter + all-gather."""
    return 2 * (n - 1) * payload


def emit(site: str, medium: str, nbytes: int, invocations: int = 1) -> None:
    """Record ``nbytes`` of fabric traffic for ``site``. No-op (one
    truthiness check) unless a ledger is installed."""
    if not _LEDGERS:
        return
    with _LOCK:
        for ledger in _LEDGERS:
            ledger.record(site, medium, nbytes, invocations)


class CollectiveLedger:
    """Plain-int per-(site, medium) byte/invocation counters.

    The hot-path half of the collective accounting: sites write here
    (host-side, via :func:`emit`), and the exporter half
    (:class:`CollectiveMetrics`, synced from ComputeTelemetry's render
    hook) publishes deltas at scrape time only."""

    def __init__(self):
        # (site, medium) -> [bytes, invocations]
        self.sites: dict[tuple[str, str], list[int]] = {}

    def record(self, site: str, medium: str, nbytes: int,
               invocations: int = 1) -> None:
        cell = self.sites.setdefault((site, medium), [0, 0])
        cell[0] += int(nbytes)
        cell[1] += int(invocations)

    def install(self) -> None:
        with _LOCK:
            if self not in _LEDGERS:
                _LEDGERS.append(self)

    def uninstall(self) -> None:
        with _LOCK:
            if self in _LEDGERS:
                _LEDGERS.remove(self)

    def snapshot(self) -> list[dict]:
        """JSON-clean rows, sorted for stable rendering."""
        return [
            {"site": site, "medium": medium,
             "bytes": cell[0], "invocations": cell[1]}
            for (site, medium), cell in sorted(self.sites.items())
        ]


class CollectiveMetrics:
    """The exported ``tpu_dra_compute_collective_*`` series.

    Declared here (not in compute_telemetry.py) so the family's two
    owners match its two halves: this module owns the collective
    vocabulary, compute_telemetry.py owns the rest of the
    ``tpu_dra_compute_*`` catalog — the same two-owner split
    tools/lint.py TPM05 pins for ``tpu_dra_kv_``."""

    def __init__(self, registry):
        from ..utils.metrics import Counter

        self._published: dict[tuple, int] = {}
        self._c_bytes = Counter(
            "tpu_dra_compute_collective_bytes_total",
            "Analytic fabric traffic per collective site (bytes summed "
            "over participating shards; jitted sites account once per "
            "program build — see parallel/collectives.py).",
            registry,
        )
        self._c_invocations = Counter(
            "tpu_dra_compute_collective_invocations_total",
            "Collective-site invocations (traces for jitted sites, "
            "calls for eager/host-level sites like train.reshard).",
            registry,
        )

    def sync(self, ledger: CollectiveLedger) -> None:
        for (site, medium), (nbytes, invocations) in ledger.sites.items():
            for counter, current in (
                (self._c_bytes, nbytes),
                (self._c_invocations, invocations),
            ):
                key = (counter.name, site, medium)
                delta = current - self._published.get(key, 0)
                if delta > 0:
                    counter.inc(delta, site=site, medium=medium)
                self._published[key] = current
