"""Workload-side sharing shim: makes the driver's sharing env REAL.

The node plugin's sharing managers (plugin/sharing.py) inject a
claim-level envelope — ``TPU_DRA_SHARING``, ``TPU_DRA_MAX_PROCESSES``,
``TPU_DRA_HBM_LIMIT_BYTES``, ``TPU_DRA_TIMESHARE_QUANTUM``, a shared
coordination dir — the per-PROCESS consequences of which only the
workload process itself can apply (which slot am I, which chips do I
see, when may I touch the device). This module is that consumer,
invoked automatically by ``initialize_distributed`` or directly by an
entrypoint.

Reference behavior bar: GPU time-slicing / MPS actually change device
behavior (lengrongfu/k8s-dra-driver, cmd/nvidia-dra-plugin/
sharing.go:103-122 and :185-344). On TPU there is no on-device knob and
no control daemon; the real mechanisms are

- **process-shared**: libtpu/XLA env — a unique process slot (flock'd
  file in the shared dir, so two processes can never claim the same
  slot), a per-slot ``TPU_VISIBLE_CHIPS`` partition when the claim's
  chips divide across processes, and the HBM budget applied through
  ``XLA_PYTHON_CLIENT_MEM_FRACTION`` (the allocator fraction JAX
  honors) computed from the driver-injected limit and chip HBM size.
- **time-shared**: cooperative gating — ``timeshare_lease()`` holds an
  exclusive flock on the claim's shared lock file while the process
  runs device work; the quantum hint bounds the advisory lease length.
"""

from __future__ import annotations

import contextlib
import dataclasses
import errno
import fcntl
import json
import logging
import os
from typing import IO, Iterator, MutableMapping, Optional

logger = logging.getLogger(__name__)

# The generation-stamped limits document the node plugin renders into
# the claim's shared dir (plugin/sharing.py LIMITS_FILE) and the marker
# env var recording the last generation THIS process applied.
_LIMITS_FILE = "limits.json"
_GENERATION_MARKER = "TPU_DRA_SHIM_GENERATION"
# Set when the OPERATOR pre-set XLA_PYTHON_CLIENT_MEM_FRACTION in the
# pod spec: an explicit operator override outranks the driver's derived
# fraction, at startup and across every later rebalance generation.
_FRACTION_PINNED_MARKER = "TPU_DRA_MEM_FRACTION_PINNED"

# Quantum hint level (TPU_DRA_TIMESHARE_QUANTUM, api/v1alpha1/sharing.py
# INTERVALS) → advisory lease seconds.
_QUANTUM_SECONDS = {0: 1.0, 1: 0.1, 2: 1.0, 3: 10.0}


class SharingRuntimeError(RuntimeError):
    pass


# The process's applied decision (default-environ path). Holding it here
# keeps the slot flock alive for the process lifetime — a dropped
# SharingRuntime releases its slot.
_active: Optional["SharingRuntime"] = None

# Marker the shim leaves in the env so a second invocation (entrypoint
# calls apply_sharing_env, then initialize_distributed calls it again)
# can't burn a second slot or re-partition the already-halved chip list.
_APPLIED_MARKER = "TPU_DRA_SHIM_APPLIED"


@dataclasses.dataclass
class SharingRuntime:
    """What the shim decided for THIS process."""

    mode: str
    slot: int = -1
    max_processes: int = 1
    visible_chips: Optional[str] = None
    mem_fraction: Optional[float] = None
    quantum_seconds: Optional[float] = None
    # The slot lock must live as long as the process; dropping the
    # runtime object releases the slot.
    _slot_lock: Optional[IO[str]] = None

    def release(self) -> None:
        if self._slot_lock is not None:
            self._slot_lock.close()
            self._slot_lock = None


def _acquire_slot(shared_dir: str, max_processes: int) -> tuple[int, IO[str]]:
    """First free slot in [0, max_processes): an exclusive flock on
    slot-N.lock. The lock dies with the process, so a crashed worker's
    slot frees itself — no daemon, no leases to expire (the property MPS
    gets from its control daemon, sharing.go:185-344)."""
    os.makedirs(shared_dir, exist_ok=True)
    for i in range(max_processes):
        f = open(os.path.join(shared_dir, f"slot-{i}.lock"), "a+")
        try:
            fcntl.flock(f, fcntl.LOCK_EX | fcntl.LOCK_NB)
            return i, f
        except OSError as e:
            f.close()
            if e.errno not in (errno.EAGAIN, errno.EACCES):
                raise
    raise SharingRuntimeError(
        f"all {max_processes} process slots of shared claim are busy "
        f"(dir {shared_dir})"
    )


def _partition_visible_chips(
    visible: str, slot: int, max_processes: int
) -> Optional[str]:
    """Slot's share of the claim's chips, when they divide evenly; None
    leaves the claim-level visibility untouched (all processes share all
    chips and the HBM fraction is the budget)."""
    chips = [c.strip() for c in visible.split(",") if c.strip()]
    if not chips or len(chips) % max_processes != 0:
        return None
    per = len(chips) // max_processes
    return ",".join(chips[slot * per:(slot + 1) * per])


def apply_sharing_env(
    environ: Optional[MutableMapping[str, str]] = None,
) -> Optional[SharingRuntime]:
    """Apply the driver's sharing envelope to this process.

    Mutates ``environ`` (default ``os.environ``) BEFORE the TPU runtime
    initializes — call it ahead of the first jax import/device touch
    (``initialize_distributed`` does). Returns the decision record, or
    None when the claim is exclusive (no envelope present).
    """
    global _active
    env = environ if environ is not None else os.environ
    mode = env.get("TPU_DRA_SHARING", "")
    if not mode:
        return None
    if env.get(_APPLIED_MARKER):
        # Idempotent: the first application's decision stands.
        return _active if environ is None else None

    if mode == "process-shared":
        max_p = max(int(env.get("TPU_DRA_MAX_PROCESSES", "1") or 1), 1)
        shared_dir = env.get("TPU_DRA_SHARED_DIR", "")
        slot, lock = (-1, None)
        if shared_dir:
            # Acquire even when maxProcesses == 1: that's the case where
            # a second process sneaking in MUST be refused.
            slot, lock = _acquire_slot(shared_dir, max_p)
        rt = SharingRuntime(
            mode=mode, slot=slot, max_processes=max_p, _slot_lock=lock
        )
        if slot >= 0:
            env.setdefault("TPU_DRA_PROCESS_SLOT", str(slot))
            part = _partition_visible_chips(
                env.get("TPU_VISIBLE_CHIPS", ""), slot, max_p
            )
            if part is not None:
                env["TPU_VISIBLE_CHIPS"] = part
                rt.visible_chips = part
        limit = int(env.get("TPU_DRA_HBM_LIMIT_BYTES", "0") or 0)
        hbm = int(env.get("TPU_DRA_CHIP_HBM_BYTES", "0") or 0)
        derived = (f"{min(limit / hbm, 1.0):.4f}"
                   if limit > 0 and hbm > 0 else None)
        preset = env.get("XLA_PYTHON_CLIENT_MEM_FRACTION")
        if preset is not None and preset != derived:
            # A fraction that does NOT match the value the driver would
            # derive from its own injected budget is an OPERATOR
            # override (the CDI claim spec injects the derived value
            # verbatim, so the driver's own injection compares equal):
            # pin it, so neither this setup nor any later rebalance
            # generation clobbers it.
            env[_FRACTION_PINNED_MARKER] = "1"
        if derived is not None:
            env.setdefault("XLA_PYTHON_CLIENT_MEM_FRACTION", derived)
            rt.mem_fraction = float(env["XLA_PYTHON_CLIENT_MEM_FRACTION"])
        logger.info(
            "process-shared claim: slot %d/%d, visible=%s, mem_fraction=%s",
            slot, max_p, rt.visible_chips or "(claim-wide)",
            rt.mem_fraction,
        )
        env[_APPLIED_MARKER] = "1"
        # A rebalance may have moved the claim's limits since the claim
        # spec env above was rendered; the session's limits file is the
        # fresher truth, so a process starting mid-rebalance begins on
        # the current generation instead of the prepare-time one.
        update = poll_sharing_update(env)
        if update is not None and update.mem_fraction is not None:
            rt.mem_fraction = update.mem_fraction
        if environ is None:
            _active = rt
        return rt

    if mode == "time-shared":
        level = int(env.get("TPU_DRA_TIMESHARE_QUANTUM", "0") or 0)
        rt = SharingRuntime(
            mode=mode,
            quantum_seconds=_QUANTUM_SECONDS.get(level, 1.0),
        )
        logger.info(
            "time-shared claim: quantum level %d (%.1fs advisory lease); "
            "gate device work with timeshare_lease()",
            level, rt.quantum_seconds,
        )
        env[_APPLIED_MARKER] = "1"
        if environ is None:
            _active = rt
        return rt

    logger.warning("unknown TPU_DRA_SHARING mode %r ignored", mode)
    return None


@dataclasses.dataclass
class SharingUpdate:
    """A newly observed limits generation, already applied to the env."""

    generation: int
    tensorcore_percent: Optional[int] = None
    hbm_limit_bytes: Optional[int] = None
    mem_fraction: Optional[float] = None


def poll_sharing_update(
    environ: Optional[MutableMapping[str, str]] = None,
) -> Optional[SharingUpdate]:
    """Observe the claim's limits file and re-apply a newer generation.

    The node plugin's rebalancer resizes a process-shared claim's limits
    by re-rendering ``limits.json`` in the shared dir with a bumped
    ``generation`` (plugin/sharing.py ``ProcessShareSession.resize``).
    This is the workload half of that contract: call it at a SAFE STEP
    BOUNDARY (between training steps, between serving batches — anywhere
    the process can tolerate its allocator budget changing) and, when it
    returns an update, re-apply what the env now says (a changed
    ``XLA_PYTHON_CLIENT_MEM_FRACTION`` only binds a freshly initialized
    client; a running program keeps its allocation until the workload
    rebuilds it, which is exactly why the boundary is the caller's).

    Returns None when there is nothing new (no envelope, no file, or the
    generation was already applied) — so a loop can call it every step
    for free. Idempotent per generation via the ``TPU_DRA_SHIM_GENERATION``
    marker.
    """
    env = environ if environ is not None else os.environ
    if env.get("TPU_DRA_SHARING", "") != "process-shared":
        return None
    shared_dir = env.get("TPU_DRA_SHARED_DIR", "")
    if not shared_dir:
        return None
    try:
        with open(os.path.join(shared_dir, _LIMITS_FILE)) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        # No file yet (pre-rebalancer plugin) or a torn read the atomic
        # writer makes impossible in practice: nothing to apply.
        return None
    try:
        generation = int(doc.get("generation", 0))
    except (TypeError, ValueError):
        return None
    applied = int(env.get(_GENERATION_MARKER, "0") or 0)
    if generation <= applied:
        return None
    from ..utils import faults

    faults.fire("rebalance.shim-apply")
    update = SharingUpdate(generation=generation)
    pinned = env.get(_FRACTION_PINNED_MARKER) == "1"
    limit = doc.get("hbmLimitBytes")
    chip_hbm = doc.get("chipHbmBytes") or int(
        env.get("TPU_DRA_CHIP_HBM_BYTES", "0") or 0
    )
    if limit:
        update.hbm_limit_bytes = int(limit)
        env["TPU_DRA_HBM_LIMIT_BYTES"] = str(int(limit))
        if chip_hbm and not pinned:
            frac = min(int(limit) / int(chip_hbm), 1.0)
            env["XLA_PYTHON_CLIENT_MEM_FRACTION"] = f"{frac:.4f}"
            env["TPU_DRA_CHIP_HBM_BYTES"] = str(int(chip_hbm))
            update.mem_fraction = frac
    else:
        # A null limit is a CLEAR (e.g. a rollback restoring an
        # uncapped claim), not "nothing to say": leaving the aborted
        # cap in the env would enforce limits the checkpoint no longer
        # grants.
        env.pop("TPU_DRA_HBM_LIMIT_BYTES", None)
        if not pinned:
            env.pop("XLA_PYTHON_CLIENT_MEM_FRACTION", None)
    pct = doc.get("tensorcorePercent")
    if pct is not None:
        update.tensorcore_percent = int(pct)
        env["TPU_DRA_ACTIVE_CORE_PERCENTAGE"] = str(int(pct))
    else:
        env.pop("TPU_DRA_ACTIVE_CORE_PERCENTAGE", None)
    env[_GENERATION_MARKER] = str(generation)
    logger.info(
        "sharing limits generation %d applied: tensorcore=%s%%, "
        "mem_fraction=%s",
        generation, update.tensorcore_percent, update.mem_fraction,
    )
    return update


def report_usage(
    busy_fraction: float,
    hbm_fraction: Optional[float] = None,
    environ: Optional[MutableMapping[str, str]] = None,
) -> bool:
    """Publish this process's recent device utilization into the shared
    dir — the demand signal the node-side rebalancer reads
    (plugin/rebalancer.py ``FileDemandSource``). ``busy_fraction`` is
    how much of the process's CURRENT grant it actually used over the
    last window (0..1): ~1.0 means pressure (wants more), ~0.0 means
    idle (can donate). Optional ``hbm_fraction`` is the analogous HBM
    signal. Free no-op off process-shared claims, so library code can
    call it unconditionally next to its step loop. Returns True when a
    sample was written."""
    env = environ if environ is not None else os.environ
    if env.get("TPU_DRA_SHARING", "") != "process-shared":
        return False
    shared_dir = env.get("TPU_DRA_SHARED_DIR", "")
    if not shared_dir:
        return False
    import time

    slot = env.get("TPU_DRA_PROCESS_SLOT", "0")
    doc: dict = {"ts": time.time(), "busy": float(busy_fraction)}
    if hbm_fraction is not None:
        doc["hbm"] = float(hbm_fraction)
    try:
        from ..utils.fs import atomic_write_json

        atomic_write_json(
            os.path.join(shared_dir, f"usage-slot-{slot}.json"), doc,
            indent=None,
        )
    except OSError as e:
        logger.warning("usage report failed: %s", e)
        return False
    return True


@contextlib.contextmanager
def timeshare_lease(
    environ: Optional[MutableMapping[str, str]] = None,
) -> Iterator[None]:
    """Exclusive device lease for a time-shared claim.

    Wrap each chunk of device work (a training step, an inference batch):
    the lease flocks ONE LOCK FILE PER CHIP (``TPU_DRA_CHIP_UUIDS``) in
    the node-global rendezvous dir, always in sorted order (no
    deadlocks). Per-chip locks mean claims with overlapping but unequal
    chip sets contend exactly on the chips they share — which IS the
    time-slicing. Holding a lease much longer than the operator-chosen
    quantum is logged, since co-tenants are starving meanwhile. On an
    exclusive claim (no envelope) this is a free no-op, so library code
    can use it unconditionally.
    """
    import time

    env = environ if environ is not None else os.environ
    if env.get("TPU_DRA_SHARING", "") != "time-shared":
        yield
        return
    shared_dir = env.get("TPU_DRA_SHARED_DIR", "")
    if not shared_dir:
        logger.warning(
            "time-shared claim without TPU_DRA_SHARED_DIR; lease is a no-op"
        )
        yield
        return
    os.makedirs(shared_dir, exist_ok=True)
    names = sorted(
        u.strip() for u in env.get("TPU_DRA_CHIP_UUIDS", "").split(",")
        if u.strip()
    ) or ["timeshare"]
    level = int(env.get("TPU_DRA_TIMESHARE_QUANTUM", "0") or 0)
    quantum = _QUANTUM_SECONDS.get(level, 1.0)
    files = []
    try:
        for name in names:
            f = open(os.path.join(shared_dir, f"{name}.lock"), "a+")
            files.append(f)
            fcntl.flock(f, fcntl.LOCK_EX)
        start = time.monotonic()
        yield
        held = time.monotonic() - start
        if held > 2 * quantum:
            logger.warning(
                "time-share lease held %.2fs, over the %.1fs quantum — "
                "co-tenant processes were starved; shorten device-work "
                "chunks or raise the claim's interval", held, quantum,
            )
    finally:
        for f in reversed(files):
            try:
                fcntl.flock(f, fcntl.LOCK_UN)
            finally:
                f.close()
