"""Workload-side sharing shim: makes the driver's sharing env REAL.

The node plugin's sharing managers (plugin/sharing.py) inject a
claim-level envelope — ``TPU_DRA_SHARING``, ``TPU_DRA_MAX_PROCESSES``,
``TPU_DRA_HBM_LIMIT_BYTES``, ``TPU_DRA_TIMESHARE_QUANTUM``, a shared
coordination dir — the per-PROCESS consequences of which only the
workload process itself can apply (which slot am I, which chips do I
see, when may I touch the device). This module is that consumer,
invoked automatically by ``initialize_distributed`` or directly by an
entrypoint.

Reference behavior bar: GPU time-slicing / MPS actually change device
behavior (lengrongfu/k8s-dra-driver, cmd/nvidia-dra-plugin/
sharing.go:103-122 and :185-344). On TPU there is no on-device knob and
no control daemon; the real mechanisms are

- **process-shared**: libtpu/XLA env — a unique process slot (flock'd
  file in the shared dir, so two processes can never claim the same
  slot), a per-slot ``TPU_VISIBLE_CHIPS`` partition when the claim's
  chips divide across processes, and the HBM budget applied through
  ``XLA_PYTHON_CLIENT_MEM_FRACTION`` (the allocator fraction JAX
  honors) computed from the driver-injected limit and chip HBM size.
- **time-shared**: cooperative gating — ``timeshare_lease()`` holds an
  exclusive flock on the claim's shared lock file while the process
  runs device work; the quantum hint bounds the advisory lease length.
"""

from __future__ import annotations

import contextlib
import dataclasses
import errno
import fcntl
import logging
import os
from typing import IO, Iterator, MutableMapping, Optional

logger = logging.getLogger(__name__)

# Quantum hint level (TPU_DRA_TIMESHARE_QUANTUM, api/v1alpha1/sharing.py
# INTERVALS) → advisory lease seconds.
_QUANTUM_SECONDS = {0: 1.0, 1: 0.1, 2: 1.0, 3: 10.0}


class SharingRuntimeError(RuntimeError):
    pass


# The process's applied decision (default-environ path). Holding it here
# keeps the slot flock alive for the process lifetime — a dropped
# SharingRuntime releases its slot.
_active: Optional["SharingRuntime"] = None

# Marker the shim leaves in the env so a second invocation (entrypoint
# calls apply_sharing_env, then initialize_distributed calls it again)
# can't burn a second slot or re-partition the already-halved chip list.
_APPLIED_MARKER = "TPU_DRA_SHIM_APPLIED"


@dataclasses.dataclass
class SharingRuntime:
    """What the shim decided for THIS process."""

    mode: str
    slot: int = -1
    max_processes: int = 1
    visible_chips: Optional[str] = None
    mem_fraction: Optional[float] = None
    quantum_seconds: Optional[float] = None
    # The slot lock must live as long as the process; dropping the
    # runtime object releases the slot.
    _slot_lock: Optional[IO[str]] = None

    def release(self) -> None:
        if self._slot_lock is not None:
            self._slot_lock.close()
            self._slot_lock = None


def _acquire_slot(shared_dir: str, max_processes: int) -> tuple[int, IO[str]]:
    """First free slot in [0, max_processes): an exclusive flock on
    slot-N.lock. The lock dies with the process, so a crashed worker's
    slot frees itself — no daemon, no leases to expire (the property MPS
    gets from its control daemon, sharing.go:185-344)."""
    os.makedirs(shared_dir, exist_ok=True)
    for i in range(max_processes):
        f = open(os.path.join(shared_dir, f"slot-{i}.lock"), "a+")
        try:
            fcntl.flock(f, fcntl.LOCK_EX | fcntl.LOCK_NB)
            return i, f
        except OSError as e:
            f.close()
            if e.errno not in (errno.EAGAIN, errno.EACCES):
                raise
    raise SharingRuntimeError(
        f"all {max_processes} process slots of shared claim are busy "
        f"(dir {shared_dir})"
    )


def _partition_visible_chips(
    visible: str, slot: int, max_processes: int
) -> Optional[str]:
    """Slot's share of the claim's chips, when they divide evenly; None
    leaves the claim-level visibility untouched (all processes share all
    chips and the HBM fraction is the budget)."""
    chips = [c.strip() for c in visible.split(",") if c.strip()]
    if not chips or len(chips) % max_processes != 0:
        return None
    per = len(chips) // max_processes
    return ",".join(chips[slot * per:(slot + 1) * per])


def apply_sharing_env(
    environ: Optional[MutableMapping[str, str]] = None,
) -> Optional[SharingRuntime]:
    """Apply the driver's sharing envelope to this process.

    Mutates ``environ`` (default ``os.environ``) BEFORE the TPU runtime
    initializes — call it ahead of the first jax import/device touch
    (``initialize_distributed`` does). Returns the decision record, or
    None when the claim is exclusive (no envelope present).
    """
    global _active
    env = environ if environ is not None else os.environ
    mode = env.get("TPU_DRA_SHARING", "")
    if not mode:
        return None
    if env.get(_APPLIED_MARKER):
        # Idempotent: the first application's decision stands.
        return _active if environ is None else None

    if mode == "process-shared":
        max_p = max(int(env.get("TPU_DRA_MAX_PROCESSES", "1") or 1), 1)
        shared_dir = env.get("TPU_DRA_SHARED_DIR", "")
        slot, lock = (-1, None)
        if shared_dir:
            # Acquire even when maxProcesses == 1: that's the case where
            # a second process sneaking in MUST be refused.
            slot, lock = _acquire_slot(shared_dir, max_p)
        rt = SharingRuntime(
            mode=mode, slot=slot, max_processes=max_p, _slot_lock=lock
        )
        if slot >= 0:
            env.setdefault("TPU_DRA_PROCESS_SLOT", str(slot))
            part = _partition_visible_chips(
                env.get("TPU_VISIBLE_CHIPS", ""), slot, max_p
            )
            if part is not None:
                env["TPU_VISIBLE_CHIPS"] = part
                rt.visible_chips = part
        limit = int(env.get("TPU_DRA_HBM_LIMIT_BYTES", "0") or 0)
        hbm = int(env.get("TPU_DRA_CHIP_HBM_BYTES", "0") or 0)
        if limit > 0 and hbm > 0:
            frac = min(limit / hbm, 1.0)
            env.setdefault("XLA_PYTHON_CLIENT_MEM_FRACTION", f"{frac:.4f}")
            rt.mem_fraction = float(env["XLA_PYTHON_CLIENT_MEM_FRACTION"])
        logger.info(
            "process-shared claim: slot %d/%d, visible=%s, mem_fraction=%s",
            slot, max_p, rt.visible_chips or "(claim-wide)",
            rt.mem_fraction,
        )
        env[_APPLIED_MARKER] = "1"
        if environ is None:
            _active = rt
        return rt

    if mode == "time-shared":
        level = int(env.get("TPU_DRA_TIMESHARE_QUANTUM", "0") or 0)
        rt = SharingRuntime(
            mode=mode,
            quantum_seconds=_QUANTUM_SECONDS.get(level, 1.0),
        )
        logger.info(
            "time-shared claim: quantum level %d (%.1fs advisory lease); "
            "gate device work with timeshare_lease()",
            level, rt.quantum_seconds,
        )
        env[_APPLIED_MARKER] = "1"
        if environ is None:
            _active = rt
        return rt

    logger.warning("unknown TPU_DRA_SHARING mode %r ignored", mode)
    return None


@contextlib.contextmanager
def timeshare_lease(
    environ: Optional[MutableMapping[str, str]] = None,
) -> Iterator[None]:
    """Exclusive device lease for a time-shared claim.

    Wrap each chunk of device work (a training step, an inference batch):
    the lease flocks ONE LOCK FILE PER CHIP (``TPU_DRA_CHIP_UUIDS``) in
    the node-global rendezvous dir, always in sorted order (no
    deadlocks). Per-chip locks mean claims with overlapping but unequal
    chip sets contend exactly on the chips they share — which IS the
    time-slicing. Holding a lease much longer than the operator-chosen
    quantum is logged, since co-tenants are starving meanwhile. On an
    exclusive claim (no envelope) this is a free no-op, so library code
    can use it unconditionally.
    """
    import time

    env = environ if environ is not None else os.environ
    if env.get("TPU_DRA_SHARING", "") != "time-shared":
        yield
        return
    shared_dir = env.get("TPU_DRA_SHARED_DIR", "")
    if not shared_dir:
        logger.warning(
            "time-shared claim without TPU_DRA_SHARED_DIR; lease is a no-op"
        )
        yield
        return
    os.makedirs(shared_dir, exist_ok=True)
    names = sorted(
        u.strip() for u in env.get("TPU_DRA_CHIP_UUIDS", "").split(",")
        if u.strip()
    ) or ["timeshare"]
    level = int(env.get("TPU_DRA_TIMESHARE_QUANTUM", "0") or 0)
    quantum = _QUANTUM_SECONDS.get(level, 1.0)
    files = []
    try:
        for name in names:
            f = open(os.path.join(shared_dir, f"{name}.lock"), "a+")
            files.append(f)
            fcntl.flock(f, fcntl.LOCK_EX)
        start = time.monotonic()
        yield
        held = time.monotonic() - start
        if held > 2 * quantum:
            logger.warning(
                "time-share lease held %.2fs, over the %.1fs quantum — "
                "co-tenant processes were starved; shorten device-work "
                "chunks or raise the claim's interval", held, quantum,
            )
    finally:
        for f in reversed(files):
            try:
                fcntl.flock(f, fcntl.LOCK_UN)
            finally:
                f.close()
