"""Multi-host initialization from DRA-injected environment.

The consumer side of the driver's ICI-channel prepare: the cluster
controller publishes per-slice channel pools (controller/slice_manager.py),
the node plugin injects slice/worker env (cdi/spec.py), and a pod entrypoint
calls ``initialize_distributed()`` before building a mesh. Maps onto
``jax.distributed.initialize``, which wires the cross-host coordination the
reference's world relies on NCCL/IMEX for — on TPU the data plane is ICI/DCN
driven by XLA collectives, so all that's needed is coordinator bootstrap.
"""

from __future__ import annotations

import logging
import os
from typing import Optional

logger = logging.getLogger(__name__)


def coordinator_from_env() -> Optional[str]:
    """Coordinator address for jax.distributed.

    Priority: explicit TPU_DRA_COORDINATOR (set by the channel prepare),
    then the GKE-style TPU_WORKER_HOSTNAMES list (worker 0 coordinates).
    """
    addr = os.environ.get("TPU_DRA_COORDINATOR", "")
    if addr:
        return addr
    hostnames = os.environ.get("TPU_WORKER_HOSTNAMES", "")
    if hostnames:
        first = hostnames.split(",")[0].strip()
        port = os.environ.get("TPU_DRA_COORDINATOR_PORT", "8476")
        return f"{first}:{port}"
    return None


def initialize_distributed(
    coordinator: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> bool:
    """Initialize jax.distributed from env; no-op for single-host jobs.

    Returns True if distributed mode was initialized.
    """
    # Sharing first: the shim must adjust TPU_VISIBLE_CHIPS /
    # XLA_PYTHON_CLIENT_MEM_FRACTION before jax initializes a backend.
    from .shim import apply_sharing_env

    apply_sharing_env()

    try:
        import jax
    except ImportError:
        # A jax-less container (e.g. the driver image running a claim
        # plumbing check) still gets the sharing env applied above; there
        # is no backend to wire, so this is a clean single-process no-op.
        logger.info("jax not importable; sharing env applied, "
                    "skipping jax.distributed")
        return False

    coordinator = coordinator or coordinator_from_env()
    if num_processes is None:
        hosts = os.environ.get("TPU_WORKER_HOSTNAMES", "")
        num_processes = len(hosts.split(",")) if hosts else 1
    if process_id is None:
        process_id = int(os.environ.get("TPU_WORKER_ID", "0") or 0)
    if coordinator is None or num_processes <= 1:
        logger.info("single-host job; skipping jax.distributed")
        return False
    # Multi-process on the CPU backend needs a collectives transport; gloo
    # is the in-tree one. Harmless on TPU (only make_cpu_client reads it);
    # guarded because the option is version-dependent.
    try:
        if not jax.config.jax_cpu_collectives_implementation:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:  # pragma: no cover - older jax without the option
        pass
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )
    logger.info(
        "jax.distributed up: %d processes, this is %d, coordinator %s",
        num_processes, process_id, coordinator,
    )
    return True
