"""Ring attention: sequence parallelism over the "sequence" mesh axis.

Long-context support: each device holds one sequence shard of Q/K/V; K/V
blocks rotate around the ring via ``ppermute`` (XLA lowers this onto ICI
neighbour links on TPU) while each device accumulates blockwise attention
with the online-softmax recurrence — so memory per device is O(S/n) with no
materialized [S, S] scores, and the N-1 hops hide behind the per-step
attention compute.

Also provides the Ulysses-style alternative (`all_to_all` heads↔sequence):
cheaper for many-head models on all-to-all-friendly topologies; ring wins on
plain ICI tori at long S.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from jax import shard_map

NEG_INF = -1e30


def _block_attend(q, k, v, scale, q_offset, kv_offset, causal):
    """One blockwise attention step in f32: returns (scores-max m, denom l,
    unnormalized out) for the online-softmax merge.

    q: [B, H, Sq, D]; k,v: [B, H, Skv, D]. Offsets are the global sequence
    positions of element 0, used for causal masking across shards.
    """
    s = jnp.einsum(
        "bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    if causal:
        sq, skv = q.shape[-2], k.shape[-2]
        qpos = q_offset + jnp.arange(sq)[:, None]
        kpos = kv_offset + jnp.arange(skv)[None, :]
        mask = kpos <= qpos
        s = jnp.where(mask, s, NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)            # [B,H,Sq,1]
    # Fully-masked rows: m = NEG_INF; zero their contribution.
    p = jnp.exp(s - jax.lax.stop_gradient(m))
    if causal:
        p = jnp.where(mask, p, 0.0)
        m = jnp.where(m <= NEG_INF, NEG_INF, m)
    l = jnp.sum(p, axis=-1, keepdims=True)            # [B,H,Sq,1]
    o = jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32))
    return m, l, o


def _merge(m1, l1, o1, m2, l2, o2):
    """Merge two online-softmax partials."""
    m = jnp.maximum(m1, m2)
    a1 = jnp.exp(m1 - m)
    a2 = jnp.exp(m2 - m)
    return m, l1 * a1 + l2 * a2, o1 * a1 + o2 * a2


def _ring_attention_local(
    q, k, v, *, axis_name: str, scale: float, causal: bool
):
    """Per-shard body (runs inside shard_map). q,k,v: [B, H, S_local, D]."""
    n = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    s_local = q.shape[-2]
    q32 = q.astype(jnp.float32)
    q_offset = idx * s_local

    m = jnp.full(q.shape[:-1] + (1,), NEG_INF, jnp.float32)
    l = jnp.zeros_like(m)
    o = jnp.zeros(q.shape, jnp.float32)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(t, carry):
        m, l, o, k_cur, v_cur = carry
        # After t hops, we hold the block originally on device (idx - t).
        kv_idx = (idx - t) % n
        m2, l2, o2 = _block_attend(
            q32, k_cur.astype(jnp.float32), v_cur.astype(jnp.float32),
            scale, q_offset, kv_idx * s_local, causal,
        )
        m, l, o = _merge(m, l, o, m2, l2, o2)
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return m, l, o, k_nxt, v_nxt

    m, l, o, _, _ = jax.lax.fori_loop(0, n, step, (m, l, o, k, v))
    return (o / jnp.maximum(l, 1e-30)).astype(q.dtype)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    *,
    axis_name: str = "sequence",
    causal: bool = True,
    scale: Optional[float] = None,
    batch_axes: tuple = ("data", "fsdp"),
    head_axis: Optional[str] = "tensor",
) -> jax.Array:
    """Sequence-parallel attention. q,k,v: [B, H, S, D] sharded with S over
    ``axis_name`` (and optionally B over batch axes / H over tensor)."""
    d = q.shape[-1]
    scale = scale if scale is not None else d ** -0.5
    if k.shape[1] != q.shape[1]:  # GQA: replicate kv heads first
        reps = q.shape[1] // k.shape[1]
        k = jnp.repeat(k, reps, axis=1)
        v = jnp.repeat(v, reps, axis=1)
    spec = P(batch_axes, head_axis, axis_name, None)
    fn = shard_map(
        functools.partial(
            _ring_attention_local,
            axis_name=axis_name,
            scale=scale,
            causal=causal,
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    *,
    axis_name: str = "sequence",
    causal: bool = True,
    scale: Optional[float] = None,
    batch_axes: tuple = ("data", "fsdp"),
    attn_fn=None,
) -> jax.Array:
    """All-to-all sequence parallelism (DeepSpeed-Ulysses style): exchange
    sequence shards for head shards, run full-sequence attention locally on
    H/n heads, exchange back. Requires H % n == 0."""
    from ..ops.attention import attention_reference

    d = q.shape[-1]
    scale = scale if scale is not None else d ** -0.5
    if k.shape[1] != q.shape[1]:
        reps = q.shape[1] // k.shape[1]
        k = jnp.repeat(k, reps, axis=1)
        v = jnp.repeat(v, reps, axis=1)
    attn = attn_fn or (
        lambda q_, k_, v_: attention_reference(q_, k_, v_, causal, scale)
    )

    def local(q, k, v):
        # [B, H, S/n, D] → all-to-all → [B, H/n, S, D]
        def a2a(x, split_axis, concat_axis):
            return jax.lax.all_to_all(
                x, axis_name, split_axis=split_axis,
                concat_axis=concat_axis, tiled=True,
            )

        qh = a2a(q, 1, 2)
        kh = a2a(k, 1, 2)
        vh = a2a(v, 1, 2)
        oh = attn(qh, kh, vh)
        return a2a(oh, 2, 1)

    spec = P(batch_axes, None, axis_name, None)
    fn = shard_map(
        local,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)
