"""Ring attention: sequence parallelism over the "sequence" mesh axis.

Long-context support: each device holds one sequence shard of Q/K/V; K/V
blocks rotate around the ring via ``ppermute`` (XLA lowers this onto ICI
neighbour links on TPU) while each device accumulates blockwise attention
with the online-softmax recurrence — so memory per device is O(S/n) with no
materialized [S, S] scores, and the N-1 hops hide behind the per-step
attention compute.

Also provides the Ulysses-style alternative (`all_to_all` heads↔sequence):
cheaper for many-head models on all-to-all-friendly topologies; ring wins on
plain ICI tori at long S.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from . import collectives
from .compat import shard_map_compat as _shard_map_compat

NEG_INF = -1e30


def _block_attend(q, k, v, scale, q_offset, kv_offset, causal):
    """One blockwise attention step in f32: returns (scores-max m, denom l,
    unnormalized out) for the online-softmax merge.

    q: [B, H, Sq, D]; k,v: [B, H, Skv, D]. Offsets are the global sequence
    positions of element 0, used for causal masking across shards.
    """
    s = jnp.einsum(
        "bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    if causal:
        sq, skv = q.shape[-2], k.shape[-2]
        qpos = q_offset + jnp.arange(sq)[:, None]
        kpos = kv_offset + jnp.arange(skv)[None, :]
        mask = kpos <= qpos
        s = jnp.where(mask, s, NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)            # [B,H,Sq,1]
    # Fully-masked rows: m = NEG_INF; zero their contribution.
    p = jnp.exp(s - jax.lax.stop_gradient(m))
    if causal:
        p = jnp.where(mask, p, 0.0)
        m = jnp.where(m <= NEG_INF, NEG_INF, m)
    l = jnp.sum(p, axis=-1, keepdims=True)            # [B,H,Sq,1]
    o = jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32))
    return m, l, o


def _merge(m1, l1, o1, m2, l2, o2):
    """Merge two online-softmax partials."""
    m = jnp.maximum(m1, m2)
    a1 = jnp.exp(m1 - m)
    a2 = jnp.exp(m2 - m)
    return m, l1 * a1 + l2 * a2, o1 * a1 + o2 * a2


# ---------------------------------------------------------------------------
# Flash ring: per-hop Pallas flash kernels + lse-based merge, custom VJP.
#
# The XLA einsum path below ("xla" impl) materializes [Sq, Skv] scores per
# hop; this path instead runs the flash kernel on each (q-shard, kv-shard)
# pair, so per-hop memory stays O(block) and the MXU sees the same kernels
# as single-chip attention. Three hop classes under causal masking: the
# diagonal hop runs the causal kernel, hops holding earlier kv run the
# unmasked kernel, and hops holding later kv skip compute entirely (the
# rotation still happens — the ring must keep turning). K/V rotate at
# their NATIVE GQA head count (no repeat), dividing ICI traffic by the
# group size versus the XLA path.
#
# Differentiation: per-hop VJPs would need d/d(lse) terms the flash
# backward doesn't produce, so the WHOLE ring gets one custom VJP (the
# ring-attention recipe): forward saves (q, k, v, global out, global lse);
# backward rides the ring again, calling the flash backward kernels with
# the GLOBAL lse/out per hop — dq accumulates at home, dk/dv accumulate
# on carriers that rotate alongside their kv shard and arrive home after
# n hops. dk/dv rotate in f32 (n-term accumulation in bf16 would drift).
# ---------------------------------------------------------------------------


def _hop_class(kv_idx, idx, causal):
    """0 = diagonal (causal kernel), 1 = fully visible, 2 = skip."""
    if not causal:
        return jnp.int32(1)
    return jnp.where(
        kv_idx == idx, jnp.int32(0),
        jnp.where(kv_idx < idx, jnp.int32(1), jnp.int32(2)),
    )


def _ring_flash_fwd_loop(q, k, v, axis_name, scale, causal, interpret):
    from ..ops.attention import NEG_INF as _NI
    from ..ops.attention import _flash_attention_pallas

    n = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]
    b, h, sq, d = q.shape

    from ..ops.attention import attention_blocks

    bq, bk, _, _ = attention_blocks()  # honor the swept/env-set config

    def attend(kc, vc, causal_hop):
        out, lse = _flash_attention_pallas(
            q, kc, vc, causal_hop, scale, block_q=bq, block_k=bk,
            interpret=interpret, return_lse=True,
        )
        return out.astype(jnp.float32), lse[..., None]  # [B,H,Sq,D], [B,H,Sq,1]

    def skip(kc, vc):
        return (
            jnp.zeros(q.shape, jnp.float32),
            jnp.full((b, h, sq, 1), _NI, jnp.float32),
        )

    def step(t, carry):
        out_acc, lse_acc, k_cur, v_cur = carry
        kv_idx = (idx - t) % n
        out_hop, lse_hop = jax.lax.switch(
            _hop_class(kv_idx, idx, causal),
            [
                lambda kc, vc: attend(kc, vc, True),
                lambda kc, vc: attend(kc, vc, False),
                skip,
            ],
            k_cur, v_cur,
        )
        # lse-weighted merge of normalized partials; a skipped hop (lse =
        # NEG_INF) contributes weight-0 zeros.
        lse_new = jnp.logaddexp(lse_acc, lse_hop)
        out_acc = (
            out_acc * jnp.exp(lse_acc - lse_new)
            + out_hop * jnp.exp(lse_hop - lse_new)
        )
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return out_acc, lse_new, k_nxt, v_nxt

    out = jnp.zeros(q.shape, jnp.float32)
    lse = jnp.full((b, h, sq, 1), _NI, jnp.float32)
    out, lse, _, _ = jax.lax.fori_loop(0, n, step, (out, lse, k, v))
    return out.astype(q.dtype), lse[..., 0]  # lse: [B, H, Sq]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _ring_flash(q, k, v, axis_name, scale, causal, interpret):
    out, _ = _ring_flash_fwd_loop(q, k, v, axis_name, scale, causal, interpret)
    return out


def _ring_flash_vjp_fwd(q, k, v, axis_name, scale, causal, interpret):
    out, lse = _ring_flash_fwd_loop(
        q, k, v, axis_name, scale, causal, interpret
    )
    return out, (q, k, v, out, lse)


def _ring_flash_vjp_bwd(axis_name, scale, causal, interpret, res, g):
    from ..ops.attention import _flash_attention_bwd_pallas

    q, k, v, out, lse = res
    n = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]

    from ..ops.attention import attention_blocks

    bq, bk, bbq, bbk = attention_blocks()

    def grads(kc, vc, causal_hop):
        dq_h, dk_h, dv_h = _flash_attention_bwd_pallas(
            q, kc.astype(q.dtype), vc.astype(q.dtype), out, lse, g,
            causal_hop, scale,
            block_q=bbq or bq, block_k=bbk or bk, interpret=interpret,
        )
        return (
            dq_h.astype(jnp.float32),
            dk_h.astype(jnp.float32),
            dv_h.astype(jnp.float32),
        )

    def skip(kc, vc):
        return (
            jnp.zeros(q.shape, jnp.float32),
            jnp.zeros(kc.shape, jnp.float32),
            jnp.zeros(vc.shape, jnp.float32),
        )

    def step(t, carry):
        dq_acc, dk_acc, dv_acc, k_cur, v_cur = carry
        kv_idx = (idx - t) % n
        dq_h, dk_h, dv_h = jax.lax.switch(
            _hop_class(kv_idx, idx, causal),
            [
                lambda kc, vc: grads(kc, vc, True),
                lambda kc, vc: grads(kc, vc, False),
                skip,
            ],
            k_cur, v_cur,
        )
        dq_acc = dq_acc + dq_h
        # dk/dv accumulators rotate WITH their kv shard; after n hops each
        # arrives back at the shard's home device with every q-shard's
        # contribution folded in.
        dk_acc = jax.lax.ppermute(dk_acc + dk_h, axis_name, perm)
        dv_acc = jax.lax.ppermute(dv_acc + dv_h, axis_name, perm)
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return dq_acc, dk_acc, dv_acc, k_nxt, v_nxt

    dq = jnp.zeros(q.shape, jnp.float32)
    dk = jnp.zeros(k.shape, jnp.float32)
    dv = jnp.zeros(v.shape, jnp.float32)
    dq, dk, dv, _, _ = jax.lax.fori_loop(0, n, step, (dq, dk, dv, k, v))
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_ring_flash.defvjp(_ring_flash_vjp_fwd, _ring_flash_vjp_bwd)


def _ring_attention_local(
    q, k, v, *, axis_name: str, scale: float, causal: bool
):
    """Per-shard body (runs inside shard_map). q,k,v: [B, H, S_local, D]."""
    n = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    s_local = q.shape[-2]
    q32 = q.astype(jnp.float32)
    q_offset = idx * s_local

    m = jnp.full(q.shape[:-1] + (1,), NEG_INF, jnp.float32)
    l = jnp.zeros_like(m)
    o = jnp.zeros(q.shape, jnp.float32)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(t, carry):
        m, l, o, k_cur, v_cur = carry
        # After t hops, we hold the block originally on device (idx - t).
        kv_idx = (idx - t) % n
        m2, l2, o2 = _block_attend(
            q32, k_cur.astype(jnp.float32), v_cur.astype(jnp.float32),
            scale, q_offset, kv_idx * s_local, causal,
        )
        m, l, o = _merge(m, l, o, m2, l2, o2)
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return m, l, o, k_nxt, v_nxt

    m, l, o, _, _ = jax.lax.fori_loop(0, n, step, (m, l, o, k, v))
    return (o / jnp.maximum(l, 1e-30)).astype(q.dtype)


def _emit_ring_attention_kv(k, v, n_seq: int) -> None:
    """Forward-ring accounting: k/v are the GLOBAL arrays at the head
    counts that actually rotate (native GQA on the flash path, repeated
    on the xla path). Each of the n hops moves every shard's local k/v
    chunk — global k+v bytes per hop, n_seq hops."""
    if n_seq <= 1:
        return
    collectives.emit(
        "ring_attention.kv", collectives.MEDIUM_ICI,
        n_seq * (
            collectives.payload_bytes(k.shape, k.dtype)
            + collectives.payload_bytes(v.shape, v.dtype)
        ),
    )


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    *,
    axis_name: str = "sequence",
    causal: bool = True,
    scale: Optional[float] = None,
    batch_axes: tuple = ("data", "fsdp"),
    head_axis: Optional[str] = "tensor",
    impl: str = "auto",
) -> jax.Array:
    """Sequence-parallel attention. q,k,v: [B, H, S, D] sharded with S over
    ``axis_name`` (and optionally B over batch axes / H over tensor).

    ``impl``: "flash" runs the Pallas flash kernels per ring hop (GQA kv
    rotates un-repeated, masked hops skip compute — see the flash-ring
    section above); "xla" is the einsum reference; "auto" picks flash on
    TPU (interpret-mode flash elsewhere is kernel-accurate but slow).
    """
    assert impl in ("auto", "flash", "xla"), impl
    from ..ops.attention import attention_impl_label

    d = q.shape[-1]
    scale = scale if scale is not None else d ** -0.5
    on_tpu = jax.default_backend() == "tpu"
    n_seq = mesh.shape[axis_name]
    s_local = q.shape[-2] // max(n_seq, 1)
    # "auto" follows the global attention dispatch (so the documented
    # TPU_DRA_ATTN_IMPL=xla escape hatch covers ring attention too) and
    # requires flash-blockable shard lengths (multiples of 8).
    use_flash = impl == "flash" or (
        impl == "auto"
        and attention_impl_label() == "pallas"
        and s_local % 8 == 0
    )
    if use_flash:
        h, hkv = q.shape[1], k.shape[1]
        tp = mesh.shape[head_axis] if head_axis else 1
        if hkv % max(tp, 1):
            # kv heads don't divide over the tensor axis at native GQA
            # count: repeat by the smallest group divisor that does (full
            # group in the worst case — then it matches the xla path).
            g = h // hkv
            r = next(
                (r for r in range(2, g + 1)
                 if g % r == 0 and (hkv * r) % tp == 0),
                g,
            )
            k = jnp.repeat(k, r, axis=1)
            v = jnp.repeat(v, r, axis=1)
        kv_spec = P(batch_axes, head_axis, axis_name, None)
        _emit_ring_attention_kv(k, v, n_seq)
        fn = _shard_map_compat(
            # custom_vjp nondiff args must stay positional.
            lambda q_, k_, v_: _ring_flash(
                q_, k_, v_, axis_name, scale, causal, not on_tpu
            ),
            mesh=mesh,
            in_specs=(kv_spec, kv_spec, kv_spec),
            out_specs=kv_spec,
            check_vma=False,
        )
        return fn(q, k, v)
    if k.shape[1] != q.shape[1]:  # GQA: replicate kv heads first
        reps = q.shape[1] // k.shape[1]
        k = jnp.repeat(k, reps, axis=1)
        v = jnp.repeat(v, reps, axis=1)
    spec = P(batch_axes, head_axis, axis_name, None)
    _emit_ring_attention_kv(k, v, n_seq)
    fn = _shard_map_compat(
        functools.partial(
            _ring_attention_local,
            axis_name=axis_name,
            scale=scale,
            causal=causal,
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)


# ---------------------------------------------------------------------------
# Ring permute: one rotation of a per-shard buffer along a mesh axis —
# the building block of the overlapped expert all-to-all
# (models/moe._moe_block_dropless_ep_ring). Two implementations:
#
# - "xla": `lax.ppermute` — portable, differentiable, and the one legal
#   under partial-manual shard_maps where other mesh axes stay with
#   GSPMD. XLA's async collective-permute start/done pair lets the
#   transfer overlap independent compute between issue and use — this
#   is the impl that delivers the ring-EP overlap schedule, and the
#   default.
# - "pallas": explicit inter-chip RDMA via `make_async_remote_copy`
#   (the SNIPPETS.md [1]/[2] right-permute pattern): the whole shard
#   moves HBM→HBM in one remote DMA, no XLA collective runtime on the
#   critical path. Legal only when the ring axis is the SOLE nontrivial
#   mesh axis (a pallas_call has no partitioning rule) — the caller
#   gates this, same discipline as the megablox kernel. The LOGICAL
#   device id equals the ring-axis index exactly because every other
#   axis is trivial. HONEST LIMIT: start() and wait() sit in the same
#   kernel, so each call completes its DMA before returning — the
#   transfer CANNOT overlap compute outside the pallas_call. It exists
#   as the measured alternative for runtimes where the XLA collective
#   path underperforms, and as the building block for a future fused
#   hop kernel (grouped matmul between start and wait).
#
# The pallas kernel gets a custom VJP: the cotangent of a rotation is
# the inverse rotation (shift negated).
# ---------------------------------------------------------------------------


def _ring_permute_pallas_call(x, axis_name: str, n: int, shift: int,
                              interpret: bool):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    def kernel(x_ref, o_ref, send_sem, recv_sem):
        me = jax.lax.axis_index(axis_name)
        nbr = jax.lax.rem(me + shift + n, n)
        op = pltpu.make_async_remote_copy(
            src_ref=x_ref,
            dst_ref=o_ref,
            send_sem=send_sem,
            recv_sem=recv_sem,
            device_id=nbr,
            device_id_type=pltpu.DeviceIdType.LOGICAL,
        )
        op.start()
        op.wait()

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=0,
        in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)],
        out_specs=pl.BlockSpec(memory_space=pltpu.ANY),
        scratch_shapes=[pltpu.SemaphoreType.DMA] * 2,
    )
    kwargs = {}
    try:
        # Remote DMA needs a collective id for its barrier semaphore on
        # real TPU; interpret mode ignores compiler params entirely.
        kwargs["compiler_params"] = pltpu.TPUCompilerParams(
            collective_id=0
        )
    except AttributeError:  # pragma: no cover - older pallas
        pass
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        grid_spec=grid_spec,
        interpret=interpret,
        **kwargs,
    )(x)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4))
def _ring_permute_pallas(x, axis_name, n, shift, interpret):
    return _ring_permute_pallas_call(x, axis_name, n, shift, interpret)


def _ring_permute_pallas_fwd(x, axis_name, n, shift, interpret):
    return _ring_permute_pallas_call(x, axis_name, n, shift, interpret), None


def _ring_permute_pallas_bwd(axis_name, n, shift, interpret, _res, g):
    return (_ring_permute_pallas_call(g, axis_name, n, -shift, interpret),)


_ring_permute_pallas.defvjp(_ring_permute_pallas_fwd,
                            _ring_permute_pallas_bwd)


def ring_permute(
    x: jax.Array,
    axis_name: str,
    n: int,
    *,
    shift: int = 1,
    impl: str = "auto",
    interpret: Optional[bool] = None,
    site: str = "ring.permute",
) -> jax.Array:
    """Move shard i's ``x`` to shard (i + shift) mod n along
    ``axis_name`` (call inside a shard_map manual over that axis).

    ``impl``: "xla"/"auto" = ppermute (async collective-permute — the
    overlappable default); "pallas" = the explicit remote-DMA kernel
    (ring axis must be the only nontrivial mesh axis — caller's
    contract — and each call completes its DMA before returning, see
    the section comment).

    ``site`` labels this hop in the collective ledger; callers with a
    named schedule (the MoE EP ring) pass their own.
    """
    assert impl in ("auto", "pallas", "xla"), impl
    # x is the per-shard buffer here (we're inside a shard_map), so one
    # hop ships n * payload across the fabric. Fires at trace time.
    collectives.emit(
        site, collectives.MEDIUM_ICI,
        collectives.permute_bytes(
            collectives.payload_bytes(x.shape, x.dtype), n
        ),
    )
    on_tpu = jax.default_backend() == "tpu"
    if impl == "pallas":
        return _ring_permute_pallas(
            x, axis_name, n, shift % n,
            (not on_tpu) if interpret is None else interpret,
        )
    perm = [(i, (i + shift) % n) for i in range(n)]
    return jax.lax.ppermute(x, axis_name, perm)


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    *,
    axis_name: str = "sequence",
    causal: bool = True,
    scale: Optional[float] = None,
    batch_axes: tuple = ("data", "fsdp"),
    attn_fn=None,
) -> jax.Array:
    """All-to-all sequence parallelism (DeepSpeed-Ulysses style): exchange
    sequence shards for head shards, run full-sequence attention locally on
    H/n heads, exchange back. Requires H % n == 0."""
    from ..ops.attention import flash_attention

    d = q.shape[-1]
    scale = scale if scale is not None else d ** -0.5
    if k.shape[1] != q.shape[1]:
        reps = q.shape[1] // k.shape[1]
        k = jnp.repeat(k, reps, axis=1)
        v = jnp.repeat(v, reps, axis=1)
    # flash_attention dispatches the Pallas kernel on TPU and the XLA
    # reference elsewhere — the local full-sequence attention after the
    # all-to-all gets the same kernels as single-chip attention.
    attn = attn_fn or (
        lambda q_, k_, v_: flash_attention(q_, k_, v_, causal, scale)
    )
    n = mesh.shape[axis_name]
    if n > 1:
        # Four a2as: q, k, v in; output (q-shaped) back out. Local
        # buffer per shard is global/n.
        collectives.emit(
            "ulysses.all_to_all", collectives.MEDIUM_ICI,
            sum(
                collectives.all_to_all_bytes(
                    collectives.payload_bytes(t.shape, t.dtype) // n, n
                )
                for t in (q, k, v, q)
            ),
            invocations=4,
        )

    def local(q, k, v):
        # [B, H, S/n, D] → all-to-all → [B, H/n, S, D]
        def a2a(x, split_axis, concat_axis):
            return jax.lax.all_to_all(
                x, axis_name, split_axis=split_axis,
                concat_axis=concat_axis, tiled=True,
            )

        qh = a2a(q, 1, 2)
        kh = a2a(k, 1, 2)
        vh = a2a(v, 1, 2)
        oh = attn(qh, kh, vh)
        return a2a(oh, 2, 1)

    spec = P(batch_axes, None, axis_name, None)
    fn = _shard_map_compat(
        local,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)
