"""jax API compatibility shims shared by the parallelism modules."""

from __future__ import annotations

try:  # jax >= 0.6 exports shard_map at top level
    from jax import shard_map
except ImportError:  # pragma: no cover - jax 0.4.x
    from jax.experimental.shard_map import shard_map


def shard_map_compat(*args, **kwargs):
    """shard_map across jax versions: the replication-check kwarg was
    renamed check_rep -> check_vma, and older jax spells the manual-axes
    set as its complement ``auto``; translate both."""
    try:
        return shard_map(*args, **kwargs)
    except TypeError:
        kwargs = dict(kwargs)
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        if "axis_names" in kwargs:
            mesh_ = kwargs.get(
                "mesh", args[1] if len(args) > 1 else None
            )
            manual = frozenset(kwargs.pop("axis_names"))
            kwargs["auto"] = frozenset(mesh_.axis_names) - manual
        return shard_map(*args, **kwargs)
