"""CDI spec generation for TPU containers."""

from .spec import (
    CDI_VERSION,
    CDIHandler,
    ContainerEdits,
    chip_visibility_env,
    tensorcore_visibility_env,
)

__all__ = [
    "CDI_VERSION",
    "CDIHandler",
    "ContainerEdits",
    "chip_visibility_env",
    "tensorcore_visibility_env",
]
