"""CDI (Container Device Interface) spec generation for TPU devices.

Role of the reference's CDI handler (lengrongfu/k8s-dra-driver,
cmd/nvidia-dra-plugin/cdi.go:50-298), which delegates to the vendored nvcdi
library to emit GPU device nodes, driver-library mounts and hooks. TPUs need
none of that machinery — a TPU container needs exactly:

1. the chip device nodes (``/dev/accel*`` or ``/dev/vfio/*``),
2. environment telling libtpu which chips to bind and how they're laid out
   (``TPU_VISIBLE_CHIPS``, topology/worker env), and
3. for shared claims, the process-bounds / HBM-limit env the sharing manager
   computed.

So we generate CDI 0.7 specs directly: a **base spec** advertising every
allocatable device (CreateStandardDeviceSpecFile analog, cdi.go:158-227) and
**transient per-claim specs** carrying claim-specific env (CreateClaimSpecFile
analog, cdi.go:229-279). Files are written atomically (tempfile + rename) the
way the CDI cache writer does.
"""

from __future__ import annotations

import dataclasses
import logging
import os
from typing import Any, Optional

from ..tpulib.deviceinfo import AllocatableDevices, ChipInfo, TensorCoreInfo
from ..utils.fs import atomic_write_json as _atomic_write_json
from ..utils.tracing import child_span

logger = logging.getLogger(__name__)

CDI_VERSION = "0.7.0"

# Qualified-name components (cdi.go:36-48 analog):
#   vendor "k8s.tpu.google.com", class "chip" → kind "k8s.tpu.google.com/chip"
DEFAULT_DRIVER_NAME = "tpu.google.com"


@dataclasses.dataclass
class ContainerEdits:
    """A subset of CDI containerEdits we emit."""

    env: dict[str, str] = dataclasses.field(default_factory=dict)
    device_nodes: list[str] = dataclasses.field(default_factory=list)
    mounts: list[dict[str, Any]] = dataclasses.field(default_factory=list)

    def merge(self, other: "ContainerEdits") -> "ContainerEdits":
        env = dict(self.env)
        env.update(other.env)
        return ContainerEdits(
            env=env,
            device_nodes=list(dict.fromkeys(self.device_nodes + other.device_nodes)),
            mounts=self.mounts + other.mounts,
        )

    def to_cdi(self) -> dict[str, Any]:
        out: dict[str, Any] = {}
        if self.env:
            out["env"] = [f"{k}={v}" for k, v in sorted(self.env.items())]
        if self.device_nodes:
            out["deviceNodes"] = [
                {"path": p, "type": "c", "permissions": "rw"}
                for p in self.device_nodes
            ]
        if self.mounts:
            out["mounts"] = self.mounts
        return out


class CDIHandler:
    """Writes/deletes CDI spec files under ``cdi_root``
    (NewCDIHandler analog, cdi.go:68-141)."""

    def __init__(
        self,
        cdi_root: str,
        driver_name: str = DEFAULT_DRIVER_NAME,
        dev_root: str = "/",
        driver_root: str = "/",
        driver_root_ctr_path: Optional[str] = None,
    ):
        self.cdi_root = cdi_root
        self.driver_name = driver_name
        self.vendor = f"k8s.{driver_name}"
        self.device_class = "chip"
        self.claim_class = "claim"
        self.dev_root = dev_root
        # driver_root is the HOST path of the driver installation (what CDI
        # hostPath fields must name); driver_root_ctr_path is where that
        # directory is mounted inside THIS container, i.e. where the search
        # actually runs. They coincide when running on the host.
        self.driver_root = driver_root
        self.driver_root_ctr_path = (
            driver_root_ctr_path if driver_root_ctr_path is not None
            else driver_root
        )
        os.makedirs(cdi_root, exist_ok=True)

    # Stable in-container home for the runtime library mount; JAX loads
    # libtpu from TPU_LIBRARY_PATH when set.
    CONTAINER_LIBTPU = "/usr/lib/tpu/libtpu.so"

    def _libtpu_edits(self) -> ContainerEdits:
        """Driver-library injection (nvcdi driver-mount analog): when the
        configured driver root holds a libtpu.so, mount it read-only into
        workload containers and point TPU_LIBRARY_PATH at it. No-op when
        absent — containers then use their image's own libtpu.

        Probed at every spec WRITE (not cached at startup): claim specs are
        written at prepare time, so a driver installed after plugin startup
        (the usual driver-installer DaemonSet race) is picked up by the
        next claim without a plugin restart."""
        from ..tpulib.driverroot import DriverRoot

        droot = DriverRoot(
            root=self.driver_root_ctr_path, host_root=self.driver_root
        )
        lib = droot.libtpu_or_none()
        if lib is None:
            return ContainerEdits()
        return ContainerEdits(
            env={"TPU_LIBRARY_PATH": self.CONTAINER_LIBTPU},
            mounts=[{
                "hostPath": droot.to_host_path(lib),
                "containerPath": self.CONTAINER_LIBTPU,
                "options": ["ro", "nosuid", "nodev", "bind"],
            }],
        )

    # -- qualified names (cdi.go:286-298 analog) ---------------------------

    def get_standard_device(self, device_name: str) -> str:
        return f"{self.vendor}/{self.device_class}={device_name}"

    def get_claim_device(self, claim_uid: str, device_name: str) -> str:
        return f"{self.vendor}/{self.claim_class}={claim_uid}-{device_name}"

    def _base_spec_path(self) -> str:
        return os.path.join(self.cdi_root, f"{self.vendor}-base.json")

    def _claim_spec_path(self, claim_uid: str) -> str:
        return os.path.join(self.cdi_root, f"{self.vendor}-claim_{claim_uid}.json")

    # -- device edits ------------------------------------------------------

    def _chip_edits(self, chip: ChipInfo) -> ContainerEdits:
        return ContainerEdits(device_nodes=list(chip.device_paths))

    def device_edits(self, device) -> ContainerEdits:
        """Per-device containerEdits for the base spec."""
        if device.chip is not None:
            return self._chip_edits(device.chip)
        if device.tensorcore is not None:
            # A core partition still needs its parent chip's device node;
            # core selection happens via env in the claim spec.
            return self._chip_edits(device.tensorcore.parent)
        if device.ici_channel is not None:
            return ContainerEdits(
                device_nodes=[
                    f"/dev/tpu-ici-channels/channel{device.ici_channel.channel}"
                ]
            )
        return ContainerEdits()

    # -- spec files --------------------------------------------------------

    def create_standard_device_spec_file(self, allocatable: AllocatableDevices) -> str:
        """Base spec with one CDI device per allocatable device
        (cdi.go:158-227 analog).

        The commonEdits guard plays the role of NVIDIA_VISIBLE_DEVICES=void
        (cdi.go:175-180): mark the container as DRA-managed so host tooling
        (and the TPU device-plugin, if both run) knows not to double-inject.
        """
        from ..utils import faults

        faults.fire("cdi.base-write")
        devices = []
        for name, dev in sorted(allocatable.items()):
            edits = self.device_edits(dev)
            devices.append({"name": name, "containerEdits": edits.to_cdi()})
        spec = {
            "cdiVersion": CDI_VERSION,
            "kind": f"{self.vendor}/{self.device_class}",
            "devices": devices,
            "containerEdits": ContainerEdits(
                env={"TPU_DRA_MANAGED": "1"}
            ).merge(self._libtpu_edits()).to_cdi(),
        }
        path = self._base_spec_path()
        _atomic_write_json(path, spec)
        return path

    def create_claim_spec_file(
        self,
        claim_uid: str,
        device_edits: dict[str, ContainerEdits],
        common_env: Optional[dict[str, str]] = None,
    ) -> str:
        """Transient per-claim spec (cdi.go:229-279 analog).

        ``device_edits`` maps device name → claim-specific edits (the env the
        sharing manager / device state computed). ``common_env`` applies to
        every container using any device of the claim (topology env), and is
        merged with the driver-library injection (claims are prepared after
        startup, so this is the injection point that survives the
        driver-installed-late race).
        """
        from ..utils import faults

        faults.fire("cdi.claim-write")
        with child_span("cdi-render", claim_uid=claim_uid) as sp:
            devices = []
            for name, edits in sorted(device_edits.items()):
                devices.append(
                    {
                        "name": f"{claim_uid}-{name}",
                        "containerEdits": edits.to_cdi(),
                    }
                )
            spec = {
                "cdiVersion": CDI_VERSION,
                "kind": f"{self.vendor}/{self.claim_class}",
                "devices": devices,
            }
            common = ContainerEdits(env=dict(common_env or {})).merge(
                self._libtpu_edits()
            ).to_cdi()
            if common:
                spec["containerEdits"] = common
            path = self._claim_spec_path(claim_uid)
            sp.set_tag("path", path).set_tag("devices", len(devices))
            _atomic_write_json(path, spec)
        return path

    def delete_claim_spec_file(self, claim_uid: str) -> None:
        """cdi.go:281-284 analog; missing file is not an error."""
        try:
            os.unlink(self._claim_spec_path(claim_uid))
        except FileNotFoundError:
            pass

    def base_spec_exists(self) -> bool:
        """Whether the standard device spec is on disk (inspection seam:
        the file name is this class's private convention)."""
        return os.path.exists(self._base_spec_path())

    def list_claim_spec_uids(self) -> list[str]:
        """UIDs with transient specs on disk — the orphan-cleanup seam the
        reference left as a TODO (driver.go:154-166)."""
        prefix = f"{self.vendor}-claim_"
        out = []
        for fn in os.listdir(self.cdi_root):
            if fn.startswith(prefix) and fn.endswith(".json"):
                out.append(fn[len(prefix):-len(".json")])
        return sorted(out)


# ---------------------------------------------------------------------------
# TPU workload environment
# ---------------------------------------------------------------------------


def chip_visibility_env(chips: list[ChipInfo]) -> dict[str, str]:
    """Env restricting libtpu to the allocated chips.

    TPU_VISIBLE_CHIPS is the TPU analog of NVIDIA_VISIBLE_DEVICES; the
    topology bounds tell the runtime the shape of the allocated sub-mesh so
    XLA's mesh builder sees the real ICI layout.
    """
    if not chips:
        return {}
    indices = ",".join(str(c.index) for c in sorted(chips, key=lambda c: c.index))
    xs = [c.coord.x for c in chips]
    ys = [c.coord.y for c in chips]
    zs = [c.coord.z for c in chips]
    bounds = (
        f"{max(xs) - min(xs) + 1},{max(ys) - min(ys) + 1},{max(zs) - min(zs) + 1}"
    )
    first = chips[0]
    # Accelerator-type strings count TensorCores, not chips: 4 chips of v5p
    # is "v5p-8" (cores_per_chip=2), while lite generations (v5e/v6e,
    # cores_per_chip=1) count chips. libtpu derives topology from this.
    from ..tpulib.topology import GENERATIONS

    spec = GENERATIONS.get(first.generation)
    n_cores = len(chips) * (spec.cores_per_chip if spec else 1)
    env = {
        "TPU_VISIBLE_CHIPS": indices,
        "TPU_CHIPS_PER_HOST_BOUNDS": bounds,
        "TPU_ACCELERATOR_TYPE": f"{first.generation}-{n_cores}",
        "TPU_SLICE_ID": first.slice_id,
        "TPU_TOPOLOGY": str(first.slice_topology),
        "TPU_WORKER_ID": str(first.host_id),
        "TPU_RUNTIME_METRICS_PORTS": "",
        # Containers must not fall back to GCE metadata probing on bare hosts.
        "TPU_SKIP_MDS_QUERY": "true",
    }
    return env


def claim_visibility_env(
    chips: list[ChipInfo], cores: list[TensorCoreInfo]
) -> dict[str, str]:
    """Visibility env over ALL devices of one claim.

    Computed once per claim (not per config group) so a claim whose
    allocation spans several config groups still presents the full chip set
    to libtpu. Core partitions contribute their parent chips to the chip
    set plus a TPU_VISIBLE_CORES selection.
    """
    by_uuid = {c.uuid: c for c in chips}
    for core in cores:
        by_uuid.setdefault(core.parent.uuid, core.parent)
    env = chip_visibility_env(list(by_uuid.values()))
    if cores:
        # A multi-core partition profile exposes EVERY core it spans.
        pairs = sorted(
            (c.parent.index, core)
            for c in cores
            for core in c.spanned_cores()
        )
        env["TPU_VISIBLE_CORES"] = ",".join(f"{i}:{j}" for i, j in pairs)
        env["TPU_PROCESS_BOUNDS"] = f"1,1,{len(pairs)}"
        env["TPU_MEGACORE"] = "0"  # cores addressed independently, not fused
    return env


def tensorcore_visibility_env(cores: list[TensorCoreInfo]) -> dict[str, str]:
    """Env for sub-chip core-partition claims.

    Core partitions run one process per TensorCore: TPU_PROCESS_BOUNDS
    carves the chip, TPU_VISIBLE_CHIPS binds the parent chip, and the core
    index selects the process slot (the role MIG UUIDs play in the
    reference's claim specs).
    """
    if not cores:
        return {}
    return claim_visibility_env([], cores)


# Default base for per-channel coordinator ports. jax.distributed's
# conventional port is 8476; offsetting by the channel number gives every
# claimed channel on a slice a disjoint rendezvous, the way IMEX channel
# ids partition the cross-node memory domain (imex.go:43-45).
COORDINATOR_BASE_PORT = 8476


def ici_channel_launch_env(
    hostnames: list[str], channel: int, host_id: Optional[int] = None
) -> dict[str, str]:
    """Cross-host launch env for an ICI-channel claim.

    The IciChannelInfo contract (tpulib/deviceinfo.py): preparing a channel
    materialises the common launch environment that makes jax.distributed
    over ICI/DCN work — the consumer is parallel.distributed.
    initialize_distributed, which reads exactly these variables. Worker 0
    hosts the coordinator; the port is derived from the claimed channel so
    concurrent jobs on one slice rendezvous on disjoint ports.

    Empty when the chip library has no hostname ground truth — preparation
    must not invent addresses.
    """
    if not hostnames:
        return {}
    raw = os.environ.get("TPU_DRA_COORDINATOR_BASE_PORT",
                         str(COORDINATOR_BASE_PORT))
    try:
        base = int(raw)
    except ValueError:
        raise ValueError(
            f"invalid TPU_DRA_COORDINATOR_BASE_PORT {raw!r}: must be an "
            f"integer port number"
        ) from None
    port = base + channel
    if not 1 <= port <= 65535:
        raise ValueError(
            f"coordinator port {port} (base {base} + channel {channel}) "
            f"outside 1-65535"
        )
    env = {
        "TPU_WORKER_HOSTNAMES": ",".join(hostnames),
        "TPU_DRA_COORDINATOR": f"{hostnames[0]}:{port}",
    }
    # Channel-only claims carry no chips, so chip_visibility_env never runs
    # for them; the process id still has to reach initialize_distributed or
    # every gang member would boot as process 0.
    if host_id is not None:
        env["TPU_WORKER_ID"] = str(host_id)
    return env
