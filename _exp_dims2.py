import time
import jax, jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
import k8s_dra_driver_tpu.ops.attention as A

def fetch(o):
    leaf = jax.tree_util.tree_leaves(o)[0]
    float(leaf.ravel()[0].astype(jnp.float32))

B, H, HKV, S, D = 8, 32, 8, 2048, 64
useful = 2 * 2 * B * H * S * S * D * 0.5
keys = jax.random.split(jax.random.PRNGKey(0), 40)
qs = [jax.random.normal(keys[i], (B, H, S, D), jnp.bfloat16) for i in range(16)]
kk = jax.random.normal(keys[30], (B, HKV, S, D), jnp.bfloat16)
vv = jax.random.normal(keys[31], (B, HKV, S, D), jnp.bfloat16)
jax.block_until_ready(qs)

def measure(label, fa):
    # distinct pre-staged q per iteration; serialize via tiny scalar dep
    def run(n, off):
        dep = jnp.zeros((), jnp.bfloat16)
        out = None
        t0 = time.perf_counter()
        for i in range(n):
            out = fa(qs[(off + i) % 16] + dep, kk, vv)
            dep = out.ravel()[0] * 0
        fetch(out)
        return time.perf_counter() - t0
    run(2, 0)
    dt = (run(12, 2) - run(3, 14)) / 9
    print(f"{label}: {dt*1e3:.2f} ms ({useful/dt/1e12:.1f} TF/s useful)", flush=True)

fa = jax.jit(lambda q,k,v: A._flash_diff(q, k, v, True, D**-0.5, False, 1024, 1024))
measure("baseline 1024x1024", fa)

orig = pl.pallas_call
def patched(kernel, **kw):
    kw.setdefault("compiler_params", pltpu.CompilerParams(
        dimension_semantics=("parallel", "arbitrary", "arbitrary")))
    return orig(kernel, **kw)
pl.pallas_call = patched
fa2 = jax.jit(lambda q,k,v: A._flash_diff(q, k, v, True, D**-0.5, False, 1024, 1024) * 1.0000001)
measure("dimsem 1024x1024", fa2)
fa3 = jax.jit(lambda q,k,v: A._flash_diff(q, k, v, True, D**-0.5, False, 2048, 512) * 1.0000001)
measure("dimsem 2048x512", fa3)
pl.pallas_call = orig
