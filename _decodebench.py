"""Decode (serving) throughput on the chip: KV-cache autoregressive
tokens/s for the dense Llama presets AND the Mixtral MoE presets (both
families share the cache/decode machinery, models/decode._mlp_or_moe).

Timing: ``generate`` (prefill + N-step while_loop decode) and ``prefill``
alone are each ONE compiled program; their time difference over distinct
prompts is N steady-state decode steps with the tunnel round-trip and
prompt processing cancelled. Decode is HBM-bound — every step streams
all weights except the embedding table, which is only gathered — so the
roofline companion is non_embed_params_bytes / HBM_bandwidth.
Remote compiles are minutes per program — this tool compiles exactly two
(and `enable_compile_cache()` makes later runs of the same shapes load
from the persistent cache instead of recompiling).

Knobs (script mode): TPU_DRA_DECODE_PRESET (e.g. 160m-gqa, 1b, or a
MoE preset like 8x160m), TPU_DRA_DECODE_PROMPT (long-context cache
costs), TPU_DRA_DECODE_QUANT ("int8" = weights, "int8-kv" = KV cache,
"int8,int8-kv" = both), TPU_DRA_DECODE_SERVING=1 (also run the
sustained-traffic continuous-batching bench — requests/s at measured
p99 token latency — plus the shared-prefix profile served cache-on vs
cache-off for the prefix-cache speedup + hit rate). Any decode metric
whose repeat spread exceeds 2% of its mean is flagged (spread_flags) —
the recompile tripwire.
"""
import os
import time

import jax

HBM_BW = 810e9  # v5e


def enable_compile_cache(path: str = "") -> None:
    """Persistent compilation cache: the 1b generate program costs many
    minutes in the remote compiler; cached, it loads in seconds on every
    later run (bench.py calls this so round-over-round benches pay the
    compile once)."""
    jax.config.update(
        "jax_compilation_cache_dir",
        path or os.environ.get("JAX_COMPILATION_CACHE_DIR",
                               os.path.join(os.path.dirname(__file__),
                                            ".jax_cache")),
    )
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 10)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)


def run_decode_bench(
    preset: str = "160m",
    batch: int = 8,
    prompt_len: int = 128,
    n_steps: int = 96,
    quant: bool = False,
    quant_kv: bool = False,
) -> dict:
    """One decode measurement -> a bench.py-style metric dict."""
    from k8s_dra_driver_tpu.models.decode import generate, prefill
    from k8s_dra_driver_tpu.models.llama import PRESETS, init_params
    from k8s_dra_driver_tpu.models.moe import MOE_PRESETS
    from k8s_dra_driver_tpu.models.moe import init_params as moe_init_params
    from k8s_dra_driver_tpu.models.quant import quantize_params

    # Dense and Mixtral families share the cache/decode machinery
    # (models/decode._mlp_or_moe); MoE presets serve through the same
    # tool (e.g. TPU_DRA_DECODE_PRESET=8x160m).
    is_moe = preset in MOE_PRESETS
    if is_moe:
        config = MOE_PRESETS[preset]
        init = moe_init_params
    else:
        config = PRESETS[preset]
        init = init_params
    params = jax.jit(lambda k: init(config, k))(jax.random.PRNGKey(0))
    if quant:
        params = jax.jit(quantize_params)(params)

    # Every timed execution needs its own never-before-dispatched prompt:
    # the remote runtime memoizes identical (program, input) dispatches
    # (see the timing note below), so prompt reuse would time a cache
    # hit. 2 per repeat pair + 2 warmups.
    n_repeats = max(1, int(os.environ.get("TPU_DRA_BENCH_REPEATS", "3")))
    prompts = [
        jax.random.randint(
            jax.random.PRNGKey(10 + i), (batch, prompt_len), 0,
            config.vocab_size,
        )
        for i in range(2 * n_repeats + 2)
    ]
    jax.block_until_ready(prompts)

    # Both programs size their KV cache identically so prefill cost
    # matches and the difference isolates the decode steps. Params are
    # ARGUMENTS, not a closure: closed-over arrays are captured as
    # constants in the lowered program (gigabytes embedded in the HLO),
    # which is what made the 1b generate compile take >15 min remotely.
    gen = jax.jit(
        lambda w, p: generate(w, p, config, n_steps,
                              quantize_cache=quant_kv)
    )
    pre = jax.jit(
        lambda w, p: prefill(w, p, config, prompt_len + n_steps,
                             quantize_cache=quant_kv)
    )

    def run(fn, prompt, out_of):
        t0 = time.perf_counter()
        out = fn(params, prompt)
        float(out_of(out))  # forces execution through remote runtimes
        return time.perf_counter() - t0

    t0 = time.perf_counter()
    run(gen, prompts[-2], lambda o: o[0, -1])
    gen_compile_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    run(pre, prompts[-1], lambda o: o[0][0, 0])
    pre_compile_s = time.perf_counter() - t0

    diffs = sorted(
        run(gen, prompts[2 * i], lambda o: o[0, -1])
        - run(pre, prompts[2 * i + 1], lambda o: o[0][0, 0])
        for i in range(n_repeats)
    )
    step = diffs[len(diffs) // 2] / n_steps  # median
    toks = sorted(batch * n_steps / d for d in diffs)
    spread = (toks[-1] - toks[0]) / 2

    # Embedding rows are gathered, not streamed; everything else (incl.
    # the lm_head matmul) is read in full every step. The cache read
    # grows with the filled length; charge the mean over the span.
    streamed = config.num_params() - config.vocab_size * config.hidden
    w_bytes = 1 if quant else 2  # int8 vs bf16 (scales negligible)
    mean_len = prompt_len + n_steps / 2
    cache_elems = (
        2 * config.n_layers * batch * config.n_kv_heads
        * mean_len * config.head_dim
    )
    c_bytes = 1 if quant_kv else 2
    roofline_s = (streamed * w_bytes + cache_elems * c_bytes) / HBM_BW

    # Cost-model cross-check (models/compute_telemetry.py): the SAME
    # deterministic estimator the serving-path CompileLedger records at
    # build time, evaluated against this bench's measured step. If
    # "predicted vs measured" drifts round-over-round the estimator (or
    # the chip) changed — doctor's mfu-regression check consumes the
    # serving-side twin of this number.
    from k8s_dra_driver_tpu.models.compute_telemetry import (
        device_peaks, estimate_decode_step_cost, roofline,
    )
    pred_flops, pred_bytes = estimate_decode_step_cost(
        config, batch=batch, context=mean_len,
        streamed_bytes=streamed * w_bytes,
        kv_bytes_per_token=(
            2 * config.n_layers * config.n_kv_heads
            * config.head_dim * c_bytes
        ),
    )
    peaks = device_peaks()
    roof = roofline(pred_flops, pred_bytes, step,
                    peaks["peakFlopsPerS"], peaks["peakBytesPerS"])
    cost_model = {
        "predicted_flops": round(pred_flops),
        "predicted_bytes": round(pred_bytes),
        "measured_flops_per_s": round(roof["flopsPerS"]),
        "measured_bytes_per_s": round(roof["bytesPerS"]),
        "mfu": round(roof["mfu"], 5),
        "bound_by": roof["boundBy"],
        "device": peaks["matched"],
    }

    tags = "".join(
        t for t, on in (("-int8", quant), ("-kvq", quant_kv)) if on
    )
    family = "mixtral" if is_moe else "llama3"
    return {
        "metric": f"{family}_{preset}{tags}_decode_toks_b{batch}_p{prompt_len}",
        "value": round(batch / step, 1),
        "unit": "tokens_per_s",
        # Fraction of the HBM roofline achieved (1.0 = bandwidth-bound
        # and perfect); the serving analog of vs_baseline.
        "vs_baseline": round(roofline_s / step, 4),
        # Median-of-n with observed run-to-run spread (tok/s), so the
        # recorded number carries its own noise floor.
        "repeats": n_repeats,
        "spread": round(spread, 1),
        "detail": {
            "step_ms": round(step * 1e3, 3),
            "hbm_roofline_ms": round(roofline_s * 1e3, 3),
            "compile_s": round(gen_compile_s + pre_compile_s, 1),
            "costModel": cost_model,
            **(_moe_decode_detail(config, batch) if is_moe else {}),
        },
    }


def _moe_decode_detail(config, batch) -> dict:
    """Which MoE MLP impl and dispatch pipeline the decode step actually
    runs (auto resolves per geometry — a decode batch routes through the
    fused grouped matmul, not the one-hot einsum)."""
    from k8s_dra_driver_tpu.models.moe import resolve_moe_impl
    from k8s_dra_driver_tpu.ops.moe_dispatch import dispatch_impl_label

    impl = resolve_moe_impl(config, batch)
    out = {"moe_impl": impl}
    if impl == "dropless":
        out["moe_dispatch"] = dispatch_impl_label(
            config.hidden, config.mlp_hidden
        )
    return out


def spread_flags(metrics, rel: float = 0.02) -> list:
    """Flag any ``*_decode_toks_*``, ``*_prefill_toks_*`` or
    ``*_gateway_rps_*`` metric whose repeat spread exceeds ``rel`` of
    its mean — the signature of per-shape recompilation (the BENCH_r05
    125-315 tok/s spreads; for the packed prefill program, a shape leak
    in the ragged lanes) or, for the fleet bench, of routing
    nondeterminism. Mutates the dicts in place (``spread_flag: true``)
    and returns the flagged metric names so bench.py can surface them
    on stderr."""
    flagged = []
    for m in metrics:
        name = m.get("metric", "")
        if ("_decode_toks_" not in name
                and "_prefill_toks_" not in name
                and "_gateway_rps_" not in name):
            continue
        spread = m.get("spread")
        value = m.get("value")
        if spread is None or not value:
            continue
        if spread > rel * value:
            m["spread_flag"] = True
            flagged.append(name)
    return flagged


def _serving_traffic(profile, prompt_lens, n_requests, config, seed):
    """Prompt list for a serving profile.

    - ``mixed``: independent random prompts of rotating lengths (the
      original BENCH continuity series).
    - ``shared-prefix``: 16 fixed system prompts x short random tails —
      the production shape (system prompts, few-shot templates, agent
      loops re-sending history) the prefix cache exists for. Every
      request beyond the first per system prompt can serve its prefix
      from cached blocks.
    """
    import numpy as np

    rng = np.random.RandomState(seed)
    if profile == "mixed":
        return [
            rng.randint(0, config.vocab_size,
                        size=int(prompt_lens[i % len(prompt_lens)])).tolist()
            for i in range(n_requests)
        ]
    if profile == "shared-prefix":
        n_sys = 16
        sys_len = max(prompt_lens)
        tail_len = max(8, min(prompt_lens) // 2)
        systems = [
            rng.randint(0, config.vocab_size, size=sys_len).tolist()
            for _ in range(n_sys)
        ]
        return [
            systems[i % n_sys]
            + rng.randint(0, config.vocab_size, size=tail_len).tolist()
            for i in range(n_requests)
        ]
    raise ValueError(f"unknown serving profile {profile!r}")


def run_serving_bench(
    preset: str = "160m",
    batch_slots: int = 8,
    n_requests: int = 32,
    prompt_lens=(32, 128, 256),
    max_new_tokens: int = 64,
    block_size: int = 64,
    quant: bool = False,
    quant_kv: bool = False,
    seed: int = 0,
    profile: str = "mixed",
    prefix_cache: bool = True,
    overlap: bool = True,
    prefill_chunk: int | None = None,
    prefill_batch: int | None = None,
    burst_size: int | None = None,
    burst_gap_ticks: int = 8,
) -> dict:
    """Sustained traffic through the continuous-batching engine:
    requests/s completed at a measured p99 per-token latency.

    Unlike the steady-state decode number, this measures the whole
    serving loop — chunked prefill interleaving, admissions, block
    churn, prefix-cache hits — under the ``profile``'s traffic shape
    (see ``_serving_traffic``). ``prefix_cache=False`` is the A/B
    baseline for the shared-prefix profile (the cache-disabled engine
    the >= 1.5x req/s acceptance gate compares against).

    TTFT-focused knobs: ``prefill_batch`` sizes the packed prefill
    program (``1`` = the serial one-chunk-per-tick baseline;
    ``run_prefill_bench`` is the dedicated A/B pair). ``burst_size``
    switches arrivals from all-upfront to a burst profile — requests
    arrive ``burst_size`` at a time with ``burst_gap_ticks`` engine
    ticks between bursts, so TTFT measures concurrent same-class
    arrivals contending for prefill lanes (the gateway admission shape)
    instead of one deep queue.
    """
    from k8s_dra_driver_tpu.models.llama import PRESETS, init_params
    from k8s_dra_driver_tpu.models.moe import MOE_PRESETS
    from k8s_dra_driver_tpu.models.moe import init_params as moe_init_params
    from k8s_dra_driver_tpu.models.quant import quantize_params
    from k8s_dra_driver_tpu.models.serving import DecodeEngine

    is_moe = preset in MOE_PRESETS
    config = MOE_PRESETS[preset] if is_moe else PRESETS[preset]
    init = moe_init_params if is_moe else init_params
    params = jax.jit(lambda k: init(config, k))(jax.random.PRNGKey(0))
    if quant:
        params = jax.jit(quantize_params)(params)

    prompts = _serving_traffic(profile, prompt_lens, n_requests, config,
                               seed)
    span = max(len(p) for p in prompts) + max_new_tokens
    # Pool sized so roughly half the requests fit concurrently: block
    # churn and admission control are part of what's being measured.
    num_blocks = max(
        batch_slots * (-(-span // block_size)),
        -(-sum(len(p) + max_new_tokens for p in prompts) // (2 * block_size)),
    )
    if prefill_chunk is None:
        # The chunk is the prefill-savings granularity: a cache hit can
        # only skip whole chunks, so the shared profile keeps chunks at
        # block width (cold system prompts take many ticks, hot tails
        # one); the mixed profile keeps the wide low-overhead default.
        prefill_chunk = (
            max(block_size, 16) if profile == "shared-prefix"
            else min(128, max(len(p) for p in prompts))
        )
    engine = DecodeEngine(
        params, config, batch_slots=batch_slots, num_blocks=num_blocks,
        block_size=block_size, max_seq_len=span,
        prefill_chunk=prefill_chunk, prefill_batch=prefill_batch,
        quantize_cache=quant_kv, prefix_cache=prefix_cache,
        overlap=overlap,
    )
    # Warm the two compiled programs so the timed window measures the
    # serving loop, not the compiler; latency stats reset after.
    from k8s_dra_driver_tpu.models.serving import ServingStats

    engine.submit(prompts[0][: min(len(prompts[0]), prompt_lens[0])],
                  max_new_tokens=2)
    engine.run()
    engine.stats = ServingStats()
    t0 = time.perf_counter()
    if burst_size:
        # Burst arrivals: each burst lands between serving ticks, so
        # TTFT reflects concurrent arrivals racing for prefill lanes.
        for lo in range(0, len(prompts), burst_size):
            for p in prompts[lo:lo + burst_size]:
                engine.submit(p, max_new_tokens=max_new_tokens)
            for _ in range(burst_gap_ticks):
                engine.tick()
    else:
        for p in prompts:
            engine.submit(p, max_new_tokens=max_new_tokens)
    engine.run()
    wall = time.perf_counter() - t0
    engine.assert_no_leaks()
    s = engine.stats
    tags = "".join(
        t for t, on in (("-int8", quant), ("-kvq", quant_kv)) if on
    )
    family = "mixtral" if is_moe else "llama3"
    suffix = "_shared" if profile == "shared-prefix" else ""
    if not prefix_cache:
        suffix += "_nocache"
    return {
        "metric": (
            f"{family}_{preset}{tags}_serving_rps{suffix}_b{batch_slots}"
        ),
        "value": round(n_requests / wall, 2),
        "unit": "requests_per_s",
        # p99 token latency is the SLO leg of "requests/s at fixed p99".
        "vs_baseline": 0.0,
        "detail": {
            "profile": profile,
            "arrival": (
                f"bursts of {burst_size} every {burst_gap_ticks} ticks"
                if burst_size else "upfront"
            ),
            "prefill_batch": engine.prefill_batch,
            "prefill_batch_occupancy": round(
                s.prefill_batch_occupancy(), 4
            ),
            "p99_token_ms": round(s.p99_token_ms(), 2),
            "p50_token_ms": round(s.p50_token_ms(), 2),
            "p50_ttft_ms": round(s.p50_ttft_ms(), 2),
            "p99_ttft_ms": round(s.p99_ttft_ms(), 2),
            "toks_per_s": round(s.tokens_generated / wall, 1),
            # Prefill-vs-decode throughput split: where the wall time's
            # token work went (prefill_toks counts computed prompt
            # tokens; cache hits don't compute, so saved tokens move
            # req/s instead of this number).
            "prefill_toks_per_s": round(s.prefill_tokens / wall, 1),
            "decode_toks_per_s": round(s.tokens_generated / wall, 1),
            # Prefix-cache observability (zeros when disabled).
            "prefix_cache": prefix_cache,
            "prefix_hit_rate": round(s.hit_rate(), 4),
            "prefill_tokens_saved": s.prefix_hit_tokens,
            "cow_recomputes": s.cow_recomputes,
            "queue_depth_mean": round(s.queue_depth_mean(), 2),
            "queue_depth_max": s.queue_depth_max(),
            "overlap": overlap,
            "preemptions": s.preemptions,
            "decode_steps": s.decode_steps,
            "prefill_chunks": s.prefill_chunks,
            "compile_counts": dict(engine.compile_counts),
            "num_blocks": num_blocks,
            "block_size": block_size,
            # The engine's OWN per-program resolution (decode_step +
            # prefill_chunk at their actual traced shapes, mesh-aware) —
            # one source of truth, not a bench-side re-derivation.
            **({"moe_impl": engine.moe_impl} if is_moe else {}),
        },
    }


def run_prefix_cache_bench(
    preset: str = "160m",
    batch_slots: int = 8,
    n_requests: int = 96,
    prompt_lens=(32, 128, 256),
    max_new_tokens: int = 12,
    block_size: int = 64,
    quant: bool = False,
    quant_kv: bool = False,
    seed: int = 0,
) -> dict:
    """The prefix-cache acceptance pair: the shared-prefix profile
    served twice through otherwise identical engines — cache on vs
    cache off — reporting the req/s speedup at the measured p99 token
    latencies plus the hit rate. The BENCH_r06 before/after lives in
    one metric: ``value`` is the cache-on req/s, ``detail.speedup_rps``
    the ratio (acceptance gate: >= 1.5x at equal p99)."""
    base = run_serving_bench(
        preset=preset, batch_slots=batch_slots, n_requests=n_requests,
        prompt_lens=prompt_lens, max_new_tokens=max_new_tokens,
        block_size=block_size, quant=quant, quant_kv=quant_kv, seed=seed,
        profile="shared-prefix", prefix_cache=False,
    )
    hot = run_serving_bench(
        preset=preset, batch_slots=batch_slots, n_requests=n_requests,
        prompt_lens=prompt_lens, max_new_tokens=max_new_tokens,
        block_size=block_size, quant=quant, quant_kv=quant_kv, seed=seed,
        profile="shared-prefix", prefix_cache=True,
    )
    hot["detail"]["speedup_rps"] = round(
        hot["value"] / max(base["value"], 1e-9), 3
    )
    hot["detail"]["rps_cache_off"] = base["value"]
    hot["detail"]["p99_token_ms_cache_off"] = (
        base["detail"]["p99_token_ms"]
    )
    hot["detail"]["p99_ttft_ms_cache_off"] = base["detail"]["p99_ttft_ms"]
    return hot


def run_prefill_bench(
    preset: str = "160m",
    batch_slots: int = 8,
    n_requests: int = 24,
    prompt_len: int = 256,
    prefill_chunk: int = 64,
    prefill_batch: int = 4,
    max_new_tokens: int = 8,
    block_size: int = 64,
    quant: bool = False,
    quant_kv: bool = False,
    seed: int = 0,
) -> dict:
    """The prefill fast-path acceptance pair: a burst of concurrent
    arrivals (all requests land at tick 0 — the gateway admission shape
    TTFT is measured under) served through two otherwise identical
    engines — packed prefill at ``prefill_batch`` lanes vs the serial
    one-chunk-per-tick baseline (``prefill_batch=1``).

    Engines and stats share a VIRTUAL clock advancing one unit per
    tick, so every TTFT percentile is measured in ticks — deterministic
    on a noisy host, and the unit the smoke gate pins (tick-normalized
    TTFT-p99 improvement >= 1.5x at equal-or-better decode-token p99).
    ``value`` is the batched engine's computed-prompt tokens/s over the
    wall clock (``llama3_*_prefill_toks_*`` — the throughput leg, with
    repeat spread as the recompile tripwire for the packed program);
    the TTFT pair lives in detail. The prefix cache is OFF in both
    engines: this bench measures raw prefill compute, and a warm cache
    would zero the very work being timed on repeat runs."""
    from k8s_dra_driver_tpu.models.llama import PRESETS, init_params
    from k8s_dra_driver_tpu.models.moe import MOE_PRESETS
    from k8s_dra_driver_tpu.models.moe import init_params as moe_init_params
    from k8s_dra_driver_tpu.models.quant import quantize_params
    from k8s_dra_driver_tpu.models.serving import (
        DecodeEngine,
        ServingStats,
    )

    is_moe = preset in MOE_PRESETS
    config = MOE_PRESETS[preset] if is_moe else PRESETS[preset]
    init = moe_init_params if is_moe else init_params
    params = jax.jit(lambda k: init(config, k))(jax.random.PRNGKey(0))
    if quant:
        params = jax.jit(quantize_params)(params)

    import numpy as np

    rng = np.random.RandomState(seed)
    prompts = [
        rng.randint(0, config.vocab_size, size=prompt_len).tolist()
        for _ in range(n_requests)
    ]
    span = prompt_len + max_new_tokens
    num_blocks = batch_slots * (-(-span // block_size)) + 2

    def make_engine(pb, clk):
        eng = DecodeEngine(
            params, config, batch_slots=batch_slots,
            num_blocks=num_blocks, block_size=block_size,
            max_seq_len=span, prefill_chunk=prefill_chunk,
            prefill_batch=pb, quantize_cache=quant_kv,
            prefix_cache=False, clock=clk,
        )
        eng.submit(prompts[0][: prefill_chunk // 2], max_new_tokens=2)
        eng.run()
        eng.stats = ServingStats()
        return eng

    def one_run(eng, clock_box):
        for p in prompts:                      # the burst: all at once
            eng.submit(p, max_new_tokens=max_new_tokens)
        t0 = time.perf_counter()
        while not eng.idle:
            eng.tick()
            clock_box[0] += 1.0
        wall = time.perf_counter() - t0
        eng.assert_no_leaks()
        s, eng.stats = eng.stats, ServingStats()
        return {
            "wall": wall,
            "prefill_toks_per_s": s.prefill_tokens / wall,
            "prefill_tokens": s.prefill_tokens,
            "ttft_p50_ticks": s.pctl(s.ttft_s, 0.50),
            "ttft_p99_ticks": s.pctl(s.ttft_s, 0.99),
            "token_p99_ticks": s.pctl(s.token_interval_s, 0.99),
            "occupancy": round(s.prefill_batch_occupancy(), 4),
            "ticks": s.ticks,
            "compile_counts": dict(eng.compile_counts),
        }

    serial_box = [0.0]
    serial = one_run(make_engine(1, lambda: serial_box[0]), serial_box)
    n_repeats = max(1, int(os.environ.get("TPU_DRA_BENCH_REPEATS", "3")))
    batched_box = [0.0]
    eng = make_engine(prefill_batch, lambda: batched_box[0])
    runs = [one_run(eng, batched_box) for _ in range(n_repeats)]
    runs.sort(key=lambda r: r["prefill_toks_per_s"])
    hot = runs[len(runs) // 2]
    spread = (runs[-1]["prefill_toks_per_s"]
              - runs[0]["prefill_toks_per_s"]) / 2
    tags = "".join(
        t for t, on in (("-int8", quant), ("-kvq", quant_kv)) if on
    )
    family = "mixtral" if is_moe else "llama3"
    return {
        "metric": (
            f"{family}_{preset}{tags}_prefill_toks_b{batch_slots}"
            f"_pb{prefill_batch}"
        ),
        "value": round(hot["prefill_toks_per_s"], 1),
        "unit": "tokens_per_s",
        "vs_baseline": 0.0,
        "repeats": n_repeats,
        "spread": round(spread, 1),
        "detail": {
            "prefill_batch": prefill_batch,
            "prompt_len": prompt_len,
            "prefill_chunk": prefill_chunk,
            "n_requests": n_requests,
            "ttft_p50_ticks": hot["ttft_p50_ticks"],
            "ttft_p99_ticks": hot["ttft_p99_ticks"],
            "ttft_p50_ticks_serial": serial["ttft_p50_ticks"],
            "ttft_p99_ticks_serial": serial["ttft_p99_ticks"],
            # The acceptance ratio (gate >= 1.5x in the decode smoke):
            # deterministic — both legs are tick-counted, same seed.
            "ttft_p99_speedup_ticks": round(
                serial["ttft_p99_ticks"] / max(hot["ttft_p99_ticks"], 1e-9),
                3,
            ),
            "token_p99_ticks": hot["token_p99_ticks"],
            "token_p99_ticks_serial": serial["token_p99_ticks"],
            "prefill_batch_occupancy": hot["occupancy"],
            "ticks": hot["ticks"],
            "ticks_serial": serial["ticks"],
            "prefill_toks_per_s_serial": round(
                serial["prefill_toks_per_s"], 1
            ),
            "compile_counts": hot["compile_counts"],
            "compile_counts_serial": serial["compile_counts"],
        },
    }


def run_gateway_bench(
    preset: str = "160m",
    n_replicas: int = 2,
    batch_slots: int = 4,
    n_requests: int = 64,
    n_systems: int = 8,
    system_len: int = 256,
    tail_len: int = 32,
    max_new_tokens: int = 16,
    block_size: int = 64,
    num_blocks: int | None = None,
    quant: bool = False,
    quant_kv: bool = False,
    seed: int = 0,
    repeats: int = 2,
) -> dict:
    """The fleet-gateway acceptance pair: shared-prefix traffic (the
    production shape prefix affinity exists for) served through N
    DecodeEngine replicas twice — prefix-affinity routing vs the
    round-robin baseline — reporting fleet requests/s at the measured
    p99 token latency, the engine-level prefix hit rate, and the shed
    rate. ``value`` is the affinity fleet req/s; the acceptance gate
    (tools/run_gateway_smoke.py, ISSUE 14) is the tick-normalized
    ``speedup_rps_ticks >= 1.3`` at equal-or-lower p99 token latency —
    NOT the host-noise-prone wall-clock ``speedup_rps``, which is
    reported alongside for the headline only.

    Per-replica pools are sized so ONE replica's cache cannot hold
    every system prompt but CAN hold its consistent-hash share: the
    fleet effect being measured is that affinity keeps each replica's
    working set inside its pool while round-robin makes every replica
    churn through all of them.
    """
    import numpy as np

    from k8s_dra_driver_tpu.models.llama import PRESETS, init_params
    from k8s_dra_driver_tpu.models.moe import MOE_PRESETS
    from k8s_dra_driver_tpu.models.moe import init_params as moe_init_params
    from k8s_dra_driver_tpu.models.quant import quantize_params
    from k8s_dra_driver_tpu.models.serving import DecodeEngine
    from k8s_dra_driver_tpu.serving_gateway import (
        AdmissionPolicy,
        Router,
        ServingGateway,
    )

    is_moe = preset in MOE_PRESETS
    config = MOE_PRESETS[preset] if is_moe else PRESETS[preset]
    init = moe_init_params if is_moe else init_params
    params = jax.jit(lambda k: init(config, k))(jax.random.PRNGKey(0))
    if quant:
        params = jax.jit(quantize_params)(params)

    rng = np.random.RandomState(seed)
    systems = [
        rng.randint(0, config.vocab_size, size=system_len).tolist()
        for _ in range(n_systems)
    ]
    prompts = [
        systems[i % n_systems]
        + rng.randint(0, config.vocab_size, size=tail_len).tolist()
        for i in range(n_requests)
    ]
    # Shuffled arrival order: round-robin over an interleaved
    # system sequence would otherwise pin system s to replica
    # (s mod n_replicas) by accident — perfect affinity for free.
    rng.shuffle(prompts)
    span = system_len + tail_len + max_new_tokens
    if num_blocks is None:
        live = batch_slots * (-(-span // block_size))
        sys_blocks = n_systems * (system_len // block_size)
        # Between "my hash share fits" (sys_blocks / n_replicas) and
        # "everything fits" (sys_blocks): the bench's fleet effect.
        num_blocks = live + max(
            -(-sys_blocks // n_replicas) + 2,
            int(sys_blocks * 1.5 / n_replicas),
        )

    def one_run(policy: str) -> dict:
        # Engines and gateway share a VIRTUAL clock that advances one
        # unit per gateway tick: every latency/throughput statistic is
        # measured in ticks — one decode dispatch plus at most one
        # prefill chunk per engine, the device-cost unit — and is
        # exactly reproducible on a noisy shared host. A round-robin
        # tick carries MORE prefill work than an affinity tick (cold
        # prompts), so tick normalization UNDERSTATES the affinity
        # advantage; wall time is measured alongside for the req/s
        # headline.
        clock_box = [0.0]

        def clk():
            return clock_box[0]

        engines = [
            DecodeEngine(
                params, config, batch_slots=batch_slots,
                num_blocks=num_blocks, block_size=block_size,
                max_seq_len=span, prefill_chunk=block_size,
                # The virtual clock's device-cost unit is "one decode
                # dispatch + at most ONE prefill chunk per engine per
                # tick"; the packed prefill program would let a tick
                # carry up to prefill_batch chunks for free, silently
                # discounting exactly the prefill work that round-robin
                # pays more of. The fleet A/B measures ROUTING, so its
                # engines pin the serial prefill baseline; the packed
                # program has its own A/B (run_prefill_bench).
                prefill_batch=1,
                quantize_cache=quant_kv, clock=clk,
            )
            for _ in range(n_replicas)
        ]
        gw = ServingGateway(
            router=Router(
                policy=policy, block_size=block_size,
                affinity_blocks=system_len // block_size,
                # Throughput profile: affinity must not spill under the
                # submit-everything burst (latency SLOs are the smoke /
                # unit tests' business, not this measurement's).
                saturation_depth=10 ** 6, seed=seed,
            ),
            # No shedding, no deadline expiry: every request completes
            # or the bench is invalid.
            admission_policy=AdmissionPolicy(
                shed_watermark=10 ** 9, hard_watermark=10 ** 9,
                max_queue_delay_s={
                    lc: 10 ** 9
                    for lc in ("realtime", "interactive", "batch")
                },
            ),
            node_name="bench",
            clock=clk,
        )
        for i, eng in enumerate(engines):
            gw.add_replica(eng, f"bench-{policy}-{i}")
        # Warm each replica's two compiled programs outside the timed
        # window; stats reset after.
        from k8s_dra_driver_tpu.models.serving import ServingStats

        for eng in engines:
            eng.submit(prompts[0][: block_size // 2], max_new_tokens=2)
            eng.run()
            eng.stats = ServingStats()
        reqs = [
            gw.submit(p, max_new_tokens, latency_class="interactive")
            for p in prompts
        ]
        t0 = time.perf_counter()
        while gw._live:
            gw.tick()
            clock_box[0] += 1.0
        wall = time.perf_counter() - t0
        failed = [r for r in reqs if r.state != "finished"]
        if failed:
            raise RuntimeError(
                f"gateway bench lost {len(failed)} request(s) "
                f"(policy {policy})"
            )
        for eng in engines:
            eng.assert_no_leaks()
        intervals = sorted(
            t for eng in engines for t in eng.stats.token_interval_s
        )
        prompt_tokens = sum(e.stats.prompt_tokens for e in engines)
        hit_tokens = sum(e.stats.prefix_hit_tokens for e in engines)
        ticks = clock_box[0]
        tick_ms = wall / max(ticks, 1) * 1e3
        p99_ticks = (
            intervals[min(len(intervals) - 1, int(0.99 * len(intervals)))]
            if intervals else 0.0
        )
        return {
            "rps": n_requests / wall,
            "ticks": ticks,
            "rp1k_ticks": n_requests / ticks * 1e3,
            "tick_ms": tick_ms,
            "p99_token_ticks": p99_ticks,
            "p99_token_ms": p99_ticks * tick_ms,
            "hit_rate": hit_tokens / max(prompt_tokens, 1),
            "shed": gw.counters["shed"],
            "affinity_hit_rate": gw.affinity_hit_rate(),
            "compile_counts": [
                dict(e.compile_counts) for e in engines
            ],
            "evictions": sum(e.allocator.evictions for e in engines),
        }

    base = one_run("round-robin")
    runs = [one_run("affinity") for _ in range(max(1, repeats))]
    runs.sort(key=lambda r: r["rps"])
    hot = runs[len(runs) // 2]
    spread = (runs[-1]["rps"] - runs[0]["rps"]) / 2
    tags = "".join(
        t for t, on in (("-int8", quant), ("-kvq", quant_kv)) if on
    )
    family = "mixtral" if is_moe else "llama3"
    return {
        "metric": (
            f"{family}_{preset}{tags}_gateway_rps_r{n_replicas}"
            f"_b{batch_slots}"
        ),
        "value": round(hot["rps"], 2),
        "unit": "requests_per_s",
        "vs_baseline": 0.0,
        "repeats": max(1, repeats),
        "spread": round(spread, 2),
        "detail": {
            "n_replicas": n_replicas,
            "n_requests": n_requests,
            "n_systems": n_systems,
            "num_blocks_per_replica": num_blocks,
            # The acceptance pair (gate: >= 1.3x at equal-or-lower p99
            # token latency). speedup_rps_ticks is the DETERMINISTIC
            # tick-normalized ratio (same seed -> same value, and it
            # understates the advantage — see one_run); speedup_rps is
            # the wall-clock ratio, honest but host-noise-prone.
            "speedup_rps": round(
                hot["rps"] / max(base["rps"], 1e-9), 3
            ),
            "speedup_rps_ticks": round(
                base["ticks"] / max(hot["ticks"], 1), 3
            ),
            "ticks": hot["ticks"],
            "ticks_all": [r["ticks"] for r in runs],
            "ticks_round_robin": base["ticks"],
            "rps_round_robin": round(base["rps"], 2),
            "p99_token_ticks": hot["p99_token_ticks"],
            "p99_token_ticks_round_robin": base["p99_token_ticks"],
            "p99_token_ms": round(hot["p99_token_ms"], 2),
            "p99_token_ms_round_robin": round(base["p99_token_ms"], 2),
            "prefix_hit_rate": round(hot["hit_rate"], 4),
            "prefix_hit_rate_round_robin": round(base["hit_rate"], 4),
            "affinity_hit_rate": round(hot["affinity_hit_rate"], 4),
            "shed_rate": round(hot["shed"] / n_requests, 4),
            "evictions": hot["evictions"],
            "evictions_round_robin": base["evictions"],
            "compile_counts": hot["compile_counts"],
        },
    }


def run_speculative_bench(
    preset: str = "160m",
    draft_layers: int = 3,
    k: int = 4,
    prompt_len: int = 64,
    n_new: int = 96,
) -> dict:
    """Speculative decode with a shallow same-vocab draft, reporting the
    draft-acceptance rate in detail so speculation wins/losses are
    attributable (an untrained random draft pins the floor: acceptance
    near 0, pure drafting overhead). ``verify_impl`` records which
    paged-attention path the T=k+1 target verify pass dispatched —
    "pallas" (the fused prefill kernel) or "xla" (the gather
    reference) — so a verify-pass regression to the slow rail is
    visible in the bench record."""
    import dataclasses

    from k8s_dra_driver_tpu.models.llama import PRESETS, init_params
    from k8s_dra_driver_tpu.models.speculative import speculative_generate
    from k8s_dra_driver_tpu.ops.attention import paged_prefill_impl_label

    config = PRESETS[preset]
    draft_config = dataclasses.replace(config, n_layers=draft_layers)
    params = jax.jit(lambda key: init_params(config, key))(
        jax.random.PRNGKey(0)
    )
    draft = jax.jit(lambda key: init_params(draft_config, key))(
        jax.random.PRNGKey(1)
    )
    prompts = [
        jax.random.randint(jax.random.PRNGKey(10 + i), (1, prompt_len), 0,
                           config.vocab_size)
        for i in range(3)
    ]
    jax.block_until_ready(prompts)
    fn = jax.jit(
        lambda tp, dp, t: speculative_generate(
            tp, dp, t, config, draft_config, n_new, k=k, return_stats=True,
        )
    )
    out, stats = fn(params, draft, prompts[0])   # compile + warm
    float(out[0, -1])
    times = []
    rate = 0.0
    for p in prompts:
        t0 = time.perf_counter()
        out, stats = fn(params, draft, p)
        rate = float(stats["acceptance_rate"])
        float(out[0, -1])
        times.append(time.perf_counter() - t0)
    dt = sorted(times)[len(times) // 2]
    return {
        "metric": f"llama3_{preset}_specdecode_toks_k{k}_p{prompt_len}",
        "value": round(n_new / dt, 1),
        "unit": "tokens_per_s",
        "vs_baseline": 0.0,
        "detail": {
            "acceptance_rate": round(rate, 4),
            "rounds": int(stats["rounds"]),
            "accepted": int(stats["accepted"]),
            "k": k,
            "draft_layers": draft_layers,
            "verify_impl": paged_prefill_impl_label(),
        },
    }


def main():
    enable_compile_cache()
    quant_modes = set(
        m.strip()
        for m in os.environ.get("TPU_DRA_DECODE_QUANT", "").split(",")
        if m.strip()
    )
    r = run_decode_bench(
        preset=os.environ.get("TPU_DRA_DECODE_PRESET", "160m"),
        batch=8,
        prompt_len=int(os.environ.get("TPU_DRA_DECODE_PROMPT", "128")),
        quant="int8" in quant_modes,
        quant_kv="int8-kv" in quant_modes,
    )
    print(
        f"decode {r['metric']}: {r['detail']['step_ms']} ms/step, "
        f"{r['value']} tok/s aggregate "
        f"(HBM roofline ~{r['detail']['hbm_roofline_ms']} ms/step, "
        f"{r['vs_baseline']:.0%} of roofline)",
        flush=True,
    )
    for name in spread_flags([r]):
        print(
            f"WARNING: {name} repeat spread {r['spread']} exceeds 2% of "
            f"the mean — per-shape recompilation suspected", flush=True,
        )
    if os.environ.get("TPU_DRA_DECODE_SERVING"):
        s = run_serving_bench(
            preset=os.environ.get("TPU_DRA_DECODE_PRESET", "160m"),
            quant="int8" in quant_modes,
            quant_kv="int8-kv" in quant_modes,
        )
        print(
            f"serving {s['metric']}: {s['value']} req/s, "
            f"p99 token {s['detail']['p99_token_ms']} ms, "
            f"p99 ttft {s['detail']['p99_ttft_ms']} ms, "
            f"{s['detail']['preemptions']} preemptions", flush=True,
        )
        f = run_prefill_bench(
            preset=os.environ.get("TPU_DRA_DECODE_PRESET", "160m"),
            quant="int8" in quant_modes,
            quant_kv="int8-kv" in quant_modes,
        )
        print(
            f"prefill {f['metric']}: {f['value']} tok/s "
            f"(serial {f['detail']['prefill_toks_per_s_serial']} tok/s), "
            f"ttft p99 {f['detail']['ttft_p99_ticks']} ticks vs "
            f"{f['detail']['ttft_p99_ticks_serial']} serial "
            f"({f['detail']['ttft_p99_speedup_ticks']}x), "
            f"occupancy {f['detail']['prefill_batch_occupancy']:.0%}",
            flush=True,
        )
        p = run_prefix_cache_bench(
            preset=os.environ.get("TPU_DRA_DECODE_PRESET", "160m"),
            quant="int8" in quant_modes,
            quant_kv="int8-kv" in quant_modes,
        )
        print(
            f"prefix-cache {p['metric']}: {p['value']} req/s "
            f"({p['detail']['speedup_rps']}x vs cache-off "
            f"{p['detail']['rps_cache_off']} req/s), "
            f"hit rate {p['detail']['prefix_hit_rate']:.0%}, "
            f"p99 token {p['detail']['p99_token_ms']} ms "
            f"(off: {p['detail']['p99_token_ms_cache_off']} ms)",
            flush=True,
        )
        g = run_gateway_bench(
            preset=os.environ.get("TPU_DRA_DECODE_PRESET", "160m"),
            quant="int8" in quant_modes,
            quant_kv="int8-kv" in quant_modes,
        )
        print(
            f"gateway {g['metric']}: {g['value']} req/s affinity vs "
            f"{g['detail']['rps_round_robin']} round-robin "
            f"({g['detail']['speedup_rps']}x wall, "
            f"{g['detail']['speedup_rps_ticks']}x tick-normalized), "
            f"hit rate {g['detail']['prefix_hit_rate']:.0%} vs "
            f"{g['detail']['prefix_hit_rate_round_robin']:.0%}, "
            f"shed rate {g['detail']['shed_rate']:.0%}",
            flush=True,
        )


if __name__ == "__main__":
    main()
