"""Decode (serving) throughput on the chip: KV-cache autoregressive
tokens/s for the HBM-sized Llama preset.

Timing: ``generate`` (prefill + N-step while_loop decode) and ``prefill``
alone are each ONE compiled program; their time difference over distinct
prompts is N steady-state decode steps with the tunnel round-trip and
prompt processing cancelled. Decode is HBM-bound — every step streams
all weights except the embedding table, which is only gathered — so the
roofline companion is non_embed_params_bytes / HBM_bandwidth.
Remote compiles are minutes per program — this tool compiles exactly two.
"""
import os
import time

import jax

from k8s_dra_driver_tpu.models.decode import generate, prefill
from k8s_dra_driver_tpu.models.llama import PRESETS, init_params
from k8s_dra_driver_tpu.models.quant import quantize_params

# The 1b preset's generate program takes >15 min in the remote compiler
# (while_loop + layer scan + 128k-vocab head in one program); 160m keeps
# the tool usable (~2 min/program) and the per-step roofline comparison
# is the same shape. Knobs: TPU_DRA_DECODE_PRESET (e.g. 160m-gqa),
# TPU_DRA_DECODE_PROMPT (long-context cache costs), TPU_DRA_DECODE_QUANT
# ("int8" = weights, "int8-kv" = KV cache, "int8,int8-kv" = both).
PRESET = os.environ.get("TPU_DRA_DECODE_PRESET", "160m")
BATCH = 8
PROMPT = int(os.environ.get("TPU_DRA_DECODE_PROMPT", "128"))
N = 96
_quant_modes = set(
    m.strip() for m in os.environ.get("TPU_DRA_DECODE_QUANT", "").split(",")
    if m.strip()
)
QUANT = "int8" in _quant_modes
QUANT_KV = "int8-kv" in _quant_modes

config = PRESETS[PRESET]
params = jax.jit(lambda k: init_params(config, k))(jax.random.PRNGKey(0))
if QUANT:
    params = jax.jit(quantize_params)(params)

prompts = [
    jax.random.randint(
        jax.random.PRNGKey(10 + i), (BATCH, PROMPT), 0, config.vocab_size
    )
    for i in range(8)
]
jax.block_until_ready(prompts)

# Both programs size their KV cache identically so prefill cost matches.
gen = jax.jit(
    lambda p: generate(params, p, config, N, quantize_cache=QUANT_KV)
)
pre = jax.jit(
    lambda p: prefill(params, p, config, PROMPT + N, quantize_cache=QUANT_KV)
)


def run(fn, prompt, out_of):
    t0 = time.perf_counter()
    out = fn(prompt)
    float(out_of(out))  # forces execution through remote runtimes
    return time.perf_counter() - t0


t0 = time.perf_counter()
run(gen, prompts[6], lambda o: o[0, -1])
print(f"generate compiled in {time.perf_counter()-t0:.0f}s", flush=True)
t0 = time.perf_counter()
run(pre, prompts[7], lambda o: o[0][0, 0])
print(f"prefill compiled in {time.perf_counter()-t0:.0f}s", flush=True)

diffs = sorted(
    run(gen, prompts[2 * i], lambda o: o[0, -1])
    - run(pre, prompts[2 * i + 1], lambda o: o[0][0, 0])
    for i in range(3)
)
step = diffs[1] / N  # median
# Embedding rows are gathered, not streamed; everything else (incl. the
# lm_head matmul) is read in full every step. The cache read grows with
# the filled length; charge the mean over the measured decode span.
streamed = config.num_params() - config.vocab_size * config.hidden
w_bytes = 1 if QUANT else 2  # int8 vs bf16 (scales negligible)
mean_len = PROMPT + N / 2
cache_elems = (
    2 * config.n_layers * BATCH * config.n_kv_heads
    * mean_len * config.head_dim
)
c_bytes = 1 if QUANT_KV else 2
hbm_roofline_ms = (
    (streamed * w_bytes + cache_elems * c_bytes) / 810e9 * 1e3  # v5e HBM BW
)
tags = "".join(
    t for t, on in (("-int8", QUANT), ("-kvq", QUANT_KV)) if on
)
print(
    f"decode {PRESET}{tags} b{BATCH} prompt{PROMPT}: "
    f"{step*1e3:.2f} ms/step, {BATCH/step:.0f} tok/s aggregate "
    f"(HBM roofline ~{hbm_roofline_ms:.2f} ms/step)",
    flush=True,
)
