{{/* Image reference */}}
{{- define "tpu-dra-driver.image" -}}
{{ .Values.image.repository }}:{{ .Values.image.tag | default .Chart.AppVersion }}
{{- end -}}

{{/* Common labels */}}
{{- define "tpu-dra-driver.labels" -}}
app.kubernetes.io/name: tpu-dra-driver
app.kubernetes.io/version: {{ .Chart.AppVersion | quote }}
app.kubernetes.io/managed-by: {{ .Release.Service }}
{{- end -}}
