"""Experiment: dimension_semantics + per-direction blocks."""
import time, functools
import jax, jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
import k8s_dra_driver_tpu.ops.attention as A

def fetch(o):
    leaf = jax.tree_util.tree_leaves(o)[0]
    float(leaf.ravel()[0].astype(jnp.float32))

state = {}
def slope(name, fn, args, chain, n1=3, n2=12):
    state[name] = args
    def run(n):
        a = state[name]; out = None
        t0 = time.perf_counter()
        for _ in range(n):
            out = fn(*a)
            a = chain(a, out)
        fetch(out)
        state[name] = a
        return time.perf_counter() - t0
    run(2)
    return (run(n2) - run(n1)) / (n2 - n1)

k1, k2, k3 = jax.random.split(jax.random.PRNGKey(0), 3)
B, H, HKV, S, D = 8, 32, 8, 2048, 64
q = jax.random.normal(k1, (B, H, S, D), jnp.bfloat16)
kk = jax.random.normal(k2, (B, HKV, S, D), jnp.bfloat16)
vv = jax.random.normal(k3, (B, HKV, S, D), jnp.bfloat16)
useful = 2 * 2 * B * H * S * S * D * 0.5
chain = lambda a, o: (o.astype(a[0].dtype), *a[1:])
gchain = lambda a, o: (o[0].astype(a[0].dtype), *a[1:])

# Patch pallas_call to add dimension_semantics via monkey wrapper
orig_pallas_call = pl.pallas_call
def patched(kernel, **kw):
    kw.setdefault("compiler_params", pltpu.CompilerParams(
        dimension_semantics=("parallel", "arbitrary", "arbitrary")))
    return orig_pallas_call(kernel, **kw)

for label, patch in [("baseline", False), ("dimsem", True)]:
    pl.pallas_call = patched if patch else orig_pallas_call
    A._flash_attention_pallas.__globals__["pl"].pallas_call = pl.pallas_call
    for bq, bk in [(1024, 1024), (2048, 512)]:
        fa = jax.jit(lambda q,k,v,bq=bq,bk=bk: A._flash_diff(q, k, v, True, D**-0.5, False, bq, bk))
        try:
            dt = slope(f"{label}{bq}x{bk}", fa, (q, kk, vv), chain)
            print(f"{label} fwd {bq}x{bk}: {dt*1e3:.2f} ms ({useful/dt/1e12:.1f} TF/s)", flush=True)
        except Exception as e:
            print(f"{label} fwd {bq}x{bk}: FAIL {type(e).__name__} {str(e)[:80]}", flush=True)
    fab = jax.jit(jax.grad(lambda q,k,v: A._flash_diff(q, k, v, True, D**-0.5, False, 1024, 1024).astype(jnp.float32).sum(), argnums=(0,1,2)))
    try:
        dtb = slope(f"{label}b", fab, (q, kk, vv), gchain)
        print(f"{label} fwd+bwd 1024x1024: {dtb*1e3:.2f} ms ({useful*3.5/dtb/1e12:.1f} TF/s)", flush=True)
    except Exception as e:
        print(f"{label} fwd+bwd: FAIL {type(e).__name__} {str(e)[:80]}", flush=True)
pl.pallas_call = orig_pallas_call
