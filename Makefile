# Build/test entrypoints (reference: Makefile:58-102).
IMAGE ?= tpu-dra-driver
TAG ?= latest

.PHONY: all native test image lint verify verify-metrics chaos chaos-slow doctor decodebench moebench elastic allocbench allocbench-smoke gatewaybench tracesmoke kvsmoke computesmoke defragsmoke fleetsmoke clean e2e-kind

all: native

native:
	$(MAKE) -C k8s_dra_driver_tpu/native

test: native
	python -m pytest tests/ -q

# Deterministic chaos suite: seeded fault schedules (utils/faults.py)
# through the cluster sim, asserting the four robustness invariants
# (tests/test_chaos.py). The seed is FIXED so CI failures replay exactly;
# override with TPU_DRA_CHAOS_SEED=... to explore. Long randomized
# schedules are marked `slow` — run those with `make chaos-slow`.
TPU_DRA_CHAOS_SEED ?= 1234
chaos:
	TPU_DRA_CHAOS_SEED=$(TPU_DRA_CHAOS_SEED) \
		python -m pytest tests/test_chaos.py -q -m 'not slow'

chaos-slow:
	TPU_DRA_CHAOS_SEED=$(TPU_DRA_CHAOS_SEED) \
		python -m pytest tests/test_chaos.py -q

# Doctor gate: the support-bundle CLI against the cluster sim. A clean
# fleet must diagnose CLEAN (any drift finding fails the target),
# injected crash artifacts (orphan CDI spec + torn checkpoint) must be
# flagged by both the node auditor and the doctor, and an unallocatable
# claim must travel the explainability chain (typed AllocationError →
# /debug/allocations → the doctor's `explain` finding with its runbook
# hint).
doctor:
	python tools/run_doctor_sim.py

# Decode-engine smoke: fixed-seed traffic through the continuous-batching
# engine on CPU, asserting the compile-once invariant per serving variant
# (bf16/int8/kvq), deterministic token streams, and bounded repeat spread
# (tools/run_decode_smoke.py) — the fast gate for the BENCH_r05
# recompile-spread regression.
decodebench:
	python tools/run_decode_smoke.py

# MoE fast-path smoke: fixed-seed CPU gates for the sparse family
# (tools/run_moe_smoke.py) — compile-once per dispatch impl
# (MOE_TRACE_COUNTS oracle), einsum/binned/dropless equivalence at
# drop-free capacity, fused-kernel-vs-primitive parity
# (ops/moe_dispatch.py in interpret mode), the `auto` impl-selection
# policy against its recorded ranking, and a repeat-spread tripwire
# mirroring _decodebench.spread_flags for the mixtral metrics.
moebench:
	python tools/run_moe_smoke.py

# Elastic-training smoke: fixed-seed chip-unplug → gang shrink →
# live reshard → resume (then the symmetric grow) through the real
# Driver + allocator + ElasticTrainer on the CPU backend
# (tools/run_elastic_smoke.py). The StateAuditor is the no-drift
# oracle; loss continuity gates the resharding math. The long soak
# variant is the `slow`-marked test_chaos.py::TestElasticGangResize
# soak (run via `make chaos-slow`).
elastic:
	TPU_DRA_CHAOS_SEED=$(TPU_DRA_CHAOS_SEED) \
		python tools/run_elastic_smoke.py

# Allocator throughput + fragmentation bench (tools/run_alloc_bench.py):
# incremental-index solves/sec vs the from-scratch baseline (gated >=10x
# on the full profile), p50/p99 solve latency, and the scored-vs-first-fit
# large-gang admission comparison under seeded churn (gated: the scorer
# must not admit fewer). The full profile (10k devices / 1k claims)
# writes ALLOC_r01.json next to the BENCH files; `make verify` runs the
# small fixed-seed smoke profile.
ALLOC_BENCH_SEED ?= 1234
allocbench:
	ALLOC_BENCH_SEED=$(ALLOC_BENCH_SEED) \
		python tools/run_alloc_bench.py --profile full

allocbench-smoke:
	ALLOC_BENCH_SEED=$(ALLOC_BENCH_SEED) \
		python tools/run_alloc_bench.py --profile smoke

# Fleet-gateway smoke (tools/run_gateway_smoke.py): fixed-seed
# shared-prefix traffic through two real DecodeEngine replicas on CPU —
# prefix-affinity routing gated >= 1.3x round-robin fleet req/s
# (tick-normalized, deterministic) at equal-or-lower p99 token latency,
# compile-once per replica, plus a scripted-engine drain that must lose
# zero admitted requests.
gatewaybench:
	python tools/run_gateway_smoke.py

# Defrag-execution smoke (tools/run_defrag_smoke.py): a checkerboarded
# fleet leaves a 2-chip gang unsat; the DefragPlanner's plan is executed
# by the DefragExecutor through a seeded crash window at one of the
# defrag.* sites, then recovered by a "restarted" executor. PASS gates:
# the gang ends admitted on the freed box, allocator/node-state/checkpoint
# agree, the StateAuditor reports zero residual drift, no execution
# intent is orphaned, and every admitted serving request finishes.
defragsmoke:
	TPU_DRA_CHAOS_SEED=$(TPU_DRA_CHAOS_SEED) \
		python tools/run_defrag_smoke.py

# Fleet soak smoke (tools/run_fleet_smoke.py): the deterministic
# discrete-event fleet simulator (k8s_dra_driver_tpu/fleetsim/) drives
# the REAL gateway + plugin loop + allocator through a scripted day —
# diurnal load per tenant class, a shared-prefix flash crowd, chip
# unplug/flap chaos, an apiserver blackout, and a fragmentation-stranded
# gang un-stranded by defrag execution — then gates on zero admitted
# loss (typed), auditor silence, per-class p99 budgets, autoscaler
# efficiency vs the oracle schedule, and rebalancer min-share floors.
# Emits the byte-reproducible FLEET_r01.json artifact at the repo root.
fleetsmoke:
	TPU_DRA_CHAOS_SEED=$(TPU_DRA_CHAOS_SEED) \
		python tools/run_fleet_smoke.py

# Request-observability overhead smoke (tools/run_trace_smoke.py): the
# same fixed-seed serving profile with telemetry OFF vs ON — token
# streams, tick counts (the deterministic "within 3% req/s" enforcement)
# and compile-once must be identical, every submission must seal a
# timeline, and best-of-N wall clock must stay inside the
# TPU_DRA_TRACE_SMOKE_OVERHEAD tripwire (loose on CPU; 3% on TPU).
tracesmoke:
	python tools/run_trace_smoke.py

# KV-telemetry zero-cost smoke (tools/run_kv_smoke.py): the same
# fixed-seed churn profile per quantization variant (bf16/int8/kvq)
# with the KV lifecycle ledger unexported vs exported (KVTelemetry +
# registry scrapes mid-run) — token streams, tick counts, and
# compile-once must be bitwise identical, the residency digest must
# stay self-consistent under eviction churn, and best-of-N wall clock
# must stay inside the TPU_DRA_KV_SMOKE_OVERHEAD tripwire.
kvsmoke:
	python tools/run_kv_smoke.py

# Compute-telemetry zero-cost smoke (tools/run_compute_smoke.py): the
# same fixed-seed serving profile per quantization variant (bf16/int8/
# kvq) with the compute plane unobserved vs observed (ComputeTelemetry
# + registry scrapes mid-run) — token streams, tick counts, and
# compile-once must be bitwise identical, the CompileLedger must match
# the engine's compile_counts exactly with zero recompiles past the
# warm horizon, and best-of-N wall clock must stay inside the
# TPU_DRA_COMPUTE_SMOKE_OVERHEAD tripwire.
computesmoke:
	python tools/run_compute_smoke.py

# The full local gate: lint + unit/integration tests + chaos schedules +
# metrics exposition + the doctor/auditor drill + the decode-engine,
# MoE fast-path, elastic-training, allocator-bench, fleet-gateway,
# request-observability, KV-telemetry, compute-telemetry,
# defrag-execution, and fleet-soak smokes. What CI runs; what a PR must
# pass.
verify: lint test chaos verify-metrics doctor decodebench moebench elastic allocbench-smoke gatewaybench tracesmoke kvsmoke computesmoke defragsmoke fleetsmoke

# ruff when available (CI installs it; .golangci.yaml analog is
# [tool.ruff] in pyproject.toml), else the first-party AST lint floor.
lint:
	@if command -v ruff >/dev/null 2>&1; then \
		ruff check k8s_dra_driver_tpu tests tools bench.py __graft_entry__.py; \
	else \
		python tools/lint.py; \
	fi

# Scrape a started debug server (worst-case registry: escaping, ±Inf,
# aliases) and fail on malformed exposition lines. VERIFY_METRICS_URL=...
# points it at a live plugin/controller instead.
verify-metrics:
	@if [ -n "$(VERIFY_METRICS_URL)" ]; then \
		python tools/verify_metrics.py --url "$(VERIFY_METRICS_URL)"; \
	else \
		python tools/verify_metrics.py; \
	fi

image:
	docker build -t $(IMAGE):$(TAG) -f deployments/container/Dockerfile .

# The real-control-plane gate: kind + helm + the REAL scheduler
# allocating tpu-test1 end-to-end, cross-checked against the sim
# allocator. Needs docker/kind/kubectl/helm; exits 3 (skip) without
# them. Writes a transcript next to the script.
e2e-kind:
	demo/clusters/kind/e2e.sh

clean:
	$(MAKE) -C k8s_dra_driver_tpu/native clean
