# Build/test entrypoints (reference: Makefile:58-102).
IMAGE ?= tpu-dra-driver
TAG ?= latest

.PHONY: all native test image lint clean

all: native

native:
	$(MAKE) -C k8s_dra_driver_tpu/native

test: native
	python -m pytest tests/ -q

lint:
	python -m compileall -q k8s_dra_driver_tpu tests bench.py __graft_entry__.py

image:
	docker build -t $(IMAGE):$(TAG) -f deployments/container/Dockerfile .

clean:
	$(MAKE) -C k8s_dra_driver_tpu/native clean
