"""On-chip numerics: pallas kernels vs XLA oracle, fwd + grads."""
import jax, jax.numpy as jnp
import k8s_dra_driver_tpu.ops.attention as A

k1, k2, k3, k4 = jax.random.split(jax.random.PRNGKey(7), 4)
B, H, HKV, S, D = 2, 8, 2, 1024, 64
q = jax.random.normal(k1, (B, H, S, D), jnp.bfloat16)
k = jax.random.normal(k2, (B, HKV, S, D), jnp.bfloat16)
v = jax.random.normal(k3, (B, HKV, S, D), jnp.bfloat16)
do = jax.random.normal(k4, (B, H, S, D), jnp.bfloat16)

for causal in (True, False):
    def pal(q, k, v):
        return A._flash_diff(q, k, v, causal, D**-0.5, False, 512, 512)
    def xla(q, k, v):
        kk = jnp.repeat(k, H // HKV, axis=1)
        vv = jnp.repeat(v, H // HKV, axis=1)
        return A.attention_reference(q, kk, vv, causal=causal)
    o_p = jax.jit(pal)(q, k, v)
    o_x = jax.jit(xla)(q, k, v)
    err = float(jnp.max(jnp.abs(o_p.astype(jnp.float32) - o_x.astype(jnp.float32))))
    vjp_p = jax.jit(lambda q,k,v,do: jax.vjp(pal, q, k, v)[1](do))
    vjp_x = jax.jit(lambda q,k,v,do: jax.vjp(xla, q, k, v)[1](do))
    gp = vjp_p(q, k, v, do)
    gx = vjp_x(q, k, v, do)
    gerr = max(float(jnp.max(jnp.abs(a.astype(jnp.float32)-b.astype(jnp.float32)))) for a,b in zip(gp, gx))
    print(f"causal={causal}: fwd max err {err:.4f}, grad max err {gerr:.4f}")
    assert err < 0.03 and gerr < 0.06, (err, gerr)
print("on-chip kernel numerics OK")
