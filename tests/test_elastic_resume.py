"""Elastic training resume: preempt on one mesh, resume on another.

The scenario DRA scheduling creates: a training pod's slice is
reclaimed, the claim is re-allocated, and the pod comes back on a
DIFFERENT device layout. models/checkpoint.py claims orbax re-shards
onto whatever mesh the new allocation provides — this pins it: the
interrupted-and-relocated run must land where the uninterrupted run
lands (optimizer moments and step counter included), not merely
"restore without crashing".
"""


import jax
import numpy as np
import pytest

from k8s_dra_driver_tpu.models.checkpoint import (
    latest_step,
    restore_checkpoint,
    restore_template,
    save_checkpoint,
)
from k8s_dra_driver_tpu.models.llama import PRESETS
from k8s_dra_driver_tpu.models.train import (
    TrainState,
    init_train_state,
    make_optimizer,
    make_train_step,
)
from k8s_dra_driver_tpu.parallel import MeshConfig, build_mesh


@pytest.fixture(scope="module")
def devices():
    d = jax.devices()
    assert len(d) >= 8, "conftest must provide 8 virtual devices"
    return d


CFG = PRESETS["tiny"]
N_STEPS_BEFORE = 3
N_STEPS_AFTER = 2


def batches(n, batch=8):  # divisible by both meshes' (data x fsdp)
    return [
        jax.random.randint(
            jax.random.PRNGKey(100 + i), (batch, 65), 0, CFG.vocab_size
        )
        for i in range(n)
    ]


def run_steps(state, step_fn, toks):
    losses = []
    for t in toks:
        state, loss = step_fn(state, t)
        losses.append(float(loss))
    return state, losses


class TestElasticResume:
    def test_resume_on_a_different_mesh_matches_uninterrupted(
        self, tmp_path, devices
    ):
        opt = make_optimizer(warmup_steps=1, total_steps=10)
        toks = batches(N_STEPS_BEFORE + N_STEPS_AFTER)

        # Uninterrupted reference: all steps on mesh A (dp x tp).
        mesh_a = build_mesh(MeshConfig(data=2, tensor=2),
                            devices=devices[:4])
        step_a = make_train_step(CFG, mesh_a, opt)
        ref_state = init_train_state(CFG, mesh_a, opt)
        ref_state, ref_losses = run_steps(ref_state, step_a, toks)

        # Interrupted run: same init (same seed), preempted after 3 steps.
        state = init_train_state(CFG, mesh_a, opt)
        state, pre_losses = run_steps(
            state, step_a, toks[:N_STEPS_BEFORE]
        )
        np.testing.assert_allclose(
            pre_losses, ref_losses[:N_STEPS_BEFORE], rtol=1e-6
        )
        save_checkpoint(str(tmp_path / "ckpt"), state,
                        step=int(state.step))
        assert latest_step(str(tmp_path / "ckpt")) == N_STEPS_BEFORE

        # "Re-allocation": a DIFFERENT mesh — wider data axis, fsdp
        # instead of tensor — over a different device subset.
        mesh_b = build_mesh(MeshConfig(data=4, fsdp=2),
                            devices=devices[:8])
        skeleton = init_train_state(CFG, mesh_b, opt, seed=123)
        template = restore_template(skeleton, mesh_b)
        restored = restore_checkpoint(str(tmp_path / "ckpt"), template)
        assert isinstance(restored, TrainState)
        assert int(restored.step) == N_STEPS_BEFORE
        # Every leaf landed with mesh B's sharding, not mesh A's.
        for got, want in zip(
            jax.tree.leaves(restored), jax.tree.leaves(template)
        ):
            assert got.sharding == want.sharding

        step_b = make_train_step(CFG, mesh_b, opt)
        _, post_losses = run_steps(
            restored, step_b, toks[N_STEPS_BEFORE:]
        )
        # Different mesh = different reduction orders; agreement is
        # close, not bit-exact.
        np.testing.assert_allclose(
            post_losses, ref_losses[N_STEPS_BEFORE:], rtol=2e-4, atol=2e-4
        )

    def test_old_state_works_as_template_skeleton(self, tmp_path, devices):
        """The natural call: pass the PREEMPTED state itself as the
        skeleton with the new mesh — its PartitionSpecs transfer but
        every leaf re-anchors to the new mesh (a template pinned to the
        dead allocation's devices would be exactly the bug the helper
        exists to prevent)."""
        opt = make_optimizer(warmup_steps=1, total_steps=10)
        mesh_a = build_mesh(MeshConfig(data=2), devices=devices[:2])
        state = init_train_state(CFG, mesh_a, opt)
        save_checkpoint(str(tmp_path / "ckpt"), state, step=0)

        mesh_b = build_mesh(MeshConfig(data=2, fsdp=2),
                            devices=devices[4:8])
        template = restore_template(state, mesh_b)
        restored = restore_checkpoint(str(tmp_path / "ckpt"), template)
        for leaf in jax.tree.leaves(restored):
            assert leaf.sharding.mesh == mesh_b

    def test_restore_rejects_missing_checkpoint(self, tmp_path):
        import os

        from k8s_dra_driver_tpu.models.llama import init_params

        assert latest_step(str(tmp_path / "nope")) is None
        params = init_params(CFG, jax.random.PRNGKey(0))
        missing = tmp_path / "nope2"
        with pytest.raises(FileNotFoundError):
            restore_checkpoint(str(missing), params)
        # The failed restore must not mkdir the typo'd path.
        assert not os.path.exists(missing)
