"""Elastic training resume: preempt on one mesh, resume on another.

The scenario DRA scheduling creates: a training pod's slice is
reclaimed, the claim is re-allocated, and the pod comes back on a
DIFFERENT device layout. models/checkpoint.py claims orbax re-shards
onto whatever mesh the new allocation provides — this pins it: the
interrupted-and-relocated run must land where the uninterrupted run
lands (optimizer moments and step counter included), not merely
"restore without crashing".
"""


import jax
import numpy as np
import pytest

from k8s_dra_driver_tpu.models.checkpoint import (
    latest_step,
    restore_checkpoint,
    restore_template,
    save_checkpoint,
)
from k8s_dra_driver_tpu.models.llama import PRESETS
from k8s_dra_driver_tpu.models.train import (
    TrainState,
    init_train_state,
    make_optimizer,
    make_train_step,
)
from k8s_dra_driver_tpu.parallel import MeshConfig, build_mesh


@pytest.fixture(scope="module")
def devices():
    d = jax.devices()
    assert len(d) >= 8, "conftest must provide 8 virtual devices"
    return d


CFG = PRESETS["tiny"]
N_STEPS_BEFORE = 3
N_STEPS_AFTER = 2


def batches(n, batch=8):  # divisible by both meshes' (data x fsdp)
    return [
        jax.random.randint(
            jax.random.PRNGKey(100 + i), (batch, 65), 0, CFG.vocab_size
        )
        for i in range(n)
    ]


def run_steps(state, step_fn, toks):
    losses = []
    for t in toks:
        state, loss = step_fn(state, t)
        losses.append(float(loss))
    return state, losses


class TestElasticResume:
    @pytest.mark.slow  # trains the same run twice; elastic smoke gates resume
    def test_resume_on_a_different_mesh_matches_uninterrupted(
        self, tmp_path, devices
    ):
        opt = make_optimizer(warmup_steps=1, total_steps=10)
        toks = batches(N_STEPS_BEFORE + N_STEPS_AFTER)

        # Uninterrupted reference: all steps on mesh A (dp x tp).
        mesh_a = build_mesh(MeshConfig(data=2, tensor=2),
                            devices=devices[:4])
        step_a = make_train_step(CFG, mesh_a, opt)
        ref_state = init_train_state(CFG, mesh_a, opt)
        ref_state, ref_losses = run_steps(ref_state, step_a, toks)

        # Interrupted run: same init (same seed), preempted after 3 steps.
        state = init_train_state(CFG, mesh_a, opt)
        state, pre_losses = run_steps(
            state, step_a, toks[:N_STEPS_BEFORE]
        )
        np.testing.assert_allclose(
            pre_losses, ref_losses[:N_STEPS_BEFORE], rtol=1e-6
        )
        save_checkpoint(str(tmp_path / "ckpt"), state,
                        step=int(state.step))
        assert latest_step(str(tmp_path / "ckpt")) == N_STEPS_BEFORE

        # "Re-allocation": a DIFFERENT mesh — wider data axis, fsdp
        # instead of tensor — over a different device subset.
        mesh_b = build_mesh(MeshConfig(data=4, fsdp=2),
                            devices=devices[:8])
        skeleton = init_train_state(CFG, mesh_b, opt, seed=123)
        template = restore_template(skeleton, mesh_b)
        restored = restore_checkpoint(str(tmp_path / "ckpt"), template)
        assert isinstance(restored, TrainState)
        assert int(restored.step) == N_STEPS_BEFORE
        # Every leaf landed with mesh B's sharding, not mesh A's.
        for got, want in zip(
            jax.tree.leaves(restored), jax.tree.leaves(template)
        ):
            assert got.sharding == want.sharding

        step_b = make_train_step(CFG, mesh_b, opt)
        _, post_losses = run_steps(
            restored, step_b, toks[N_STEPS_BEFORE:]
        )
        # Different mesh = different reduction orders; agreement is
        # close, not bit-exact.
        np.testing.assert_allclose(
            post_losses, ref_losses[N_STEPS_BEFORE:], rtol=2e-4, atol=2e-4
        )

    def test_old_state_works_as_template_skeleton(self, tmp_path, devices):
        """The natural call: pass the PREEMPTED state itself as the
        skeleton with the new mesh — its PartitionSpecs transfer but
        every leaf re-anchors to the new mesh (a template pinned to the
        dead allocation's devices would be exactly the bug the helper
        exists to prevent)."""
        opt = make_optimizer(warmup_steps=1, total_steps=10)
        mesh_a = build_mesh(MeshConfig(data=2), devices=devices[:2])
        state = init_train_state(CFG, mesh_a, opt)
        save_checkpoint(str(tmp_path / "ckpt"), state, step=0)

        mesh_b = build_mesh(MeshConfig(data=2, fsdp=2),
                            devices=devices[4:8])
        template = restore_template(state, mesh_b)
        restored = restore_checkpoint(str(tmp_path / "ckpt"), template)
        for leaf in jax.tree.leaves(restored):
            assert leaf.sharding.mesh == mesh_b

    def test_restore_rejects_missing_checkpoint(self, tmp_path):
        import os

        from k8s_dra_driver_tpu.models.llama import init_params

        assert latest_step(str(tmp_path / "nope")) is None
        params = init_params(CFG, jax.random.PRNGKey(0))
        missing = tmp_path / "nope2"
        with pytest.raises(FileNotFoundError):
            restore_checkpoint(str(missing), params)
        # The failed restore must not mkdir the typo'd path.
        assert not os.path.exists(missing)

    def test_restore_onto_incompatible_mesh_raises_typed_error(
        self, tmp_path, devices
    ):
        """A mesh whose preserved degrees cannot hold the saved state
        must fail with the TYPED error naming both shapes — not a raw
        JAX divisibility error from inside the restore."""
        from k8s_dra_driver_tpu.models.checkpoint import (
            MeshShapeMismatchError,
        )

        opt = make_optimizer(warmup_steps=1, total_steps=10)
        mesh_a = build_mesh(MeshConfig(data=2, tensor=2),
                            devices=devices[:4])
        state = init_train_state(CFG, mesh_a, opt)
        save_checkpoint(str(tmp_path / "ckpt"), state, step=0)

        # tensor=8 cannot shard the tiny config's 2 kv heads (nor the
        # other tensor-sharded axes): the template is un-meshable.
        bad_mesh = build_mesh(MeshConfig(tensor=8), devices=devices[:8])
        template = restore_template(state, bad_mesh)
        with pytest.raises(MeshShapeMismatchError) as exc_info:
            restore_checkpoint(str(tmp_path / "ckpt"), template)
        msg = str(exc_info.value)
        assert "cannot be restored onto mesh" in msg
        assert "'tensor': 8" in msg  # the mesh shape is named
        assert "shape (" in msg      # ...and the array shape


class TestElasticLiveResize:
    """The resize coordinator's workload half (parallel/elastic.py):
    grow and non-power-of-two shrink through the LIVE reshard path, and
    the cold checkpoint fallback when survivors cannot cover the state."""

    def _trainer(self, devices, mesh_config, **kw):
        from k8s_dra_driver_tpu.parallel.elastic import ElasticTrainer

        opt = make_optimizer(warmup_steps=1, total_steps=10)
        return ElasticTrainer(
            CFG, opt, devices, mesh_config=mesh_config, global_batch=8,
            **kw,
        )

    def test_grow_spare_joins_and_state_reshards_live(self, devices):
        import numpy as np

        trainer = self._trainer(devices[:2], MeshConfig(tensor=2))
        toks = batches(4)
        pre = [trainer.step(t) for t in toks[:2]]
        before = jax.tree.map(np.array, trainer.state)

        event = trainer.resize(devices[:4], reason="spares restored")
        assert event.direction == "grow"
        assert event.path == "live", "grow must never touch a checkpoint"
        assert event.n_used == 4 and event.n_idled == 0
        assert trainer.mesh_config.tensor == 2  # preserved
        # The reshard moved the state, not changed it: every leaf is
        # bit-identical on the larger mesh.
        after = jax.tree.map(np.array, trainer.state)
        for a, b in zip(jax.tree.leaves(before), jax.tree.leaves(after)):
            np.testing.assert_array_equal(a, b)
        post = [trainer.step(t) for t in toks[2:]]
        assert all(np.isfinite(x) for x in pre + post)

    def test_non_pow2_shrink_idles_remainder(self, devices):
        import numpy as np

        # data=4 x tensor=2: params replicated across data, so any
        # single-device loss is covered by a surviving replica.
        trainer = self._trainer(devices, MeshConfig(data=4, tensor=2))
        toks = batches(4)
        pre = [trainer.step(t) for t in toks[:2]]

        # 7 survivors: 6 preserves tensor=2 but dp=3 does not divide the
        # 8-token batch — the largest VALID sub-mesh is 4 devices, with
        # the other 3 survivors idled (they rejoin on the next grow).
        event = trainer.resize(devices[:7], reason="chip 7 gone")
        assert event.direction == "shrink" and event.path == "live"
        assert event.n_used == 4 and event.n_idled == 3
        assert trainer.mesh_config.tensor == 2
        assert len(trainer.idled) == 3
        post = [trainer.step(t) for t in toks[2:]]
        assert all(np.isfinite(x) for x in pre + post)

    def test_uncoverable_shrink_falls_back_to_checkpoint(
        self, tmp_path, devices
    ):
        """fsdp=4 shards every parameter across all four devices with no
        replication: losing one device loses live shards, so the resize
        must take the COLD path — restore the last checkpoint onto the
        new mesh — and resume from the checkpointed step."""
        trainer = self._trainer(
            devices[:4], MeshConfig(fsdp=4),
            checkpoint_dir=str(tmp_path / "ckpt"),
        )
        toks = batches(3)
        trainer.step(toks[0])
        trainer.step(toks[1])
        trainer.save()
        trainer.step(toks[2])  # a step past the checkpoint, lost below
        assert trainer.step_count == 3

        event = trainer.resize(devices[:2], reason="chips 2+3 gone")
        assert event.path == "cold"
        # The cold restore rewinds to the saved step; training resumes.
        assert trainer.step_count == 2
        assert trainer.mesh_config.num_devices == 2
        loss = trainer.step(toks[2])
        assert trainer.step_count == 3
        import numpy as np

        assert np.isfinite(loss)

    def test_uncoverable_shrink_without_checkpoint_raises(self, devices):
        from k8s_dra_driver_tpu.parallel.elastic import ElasticResizeError

        trainer = self._trainer(devices[:4], MeshConfig(fsdp=4))
        trainer.step(batches(1)[0])
        state_before = trainer.state
        with pytest.raises(ElasticResizeError, match="no checkpoint"):
            trainer.resize(devices[:2], reason="chips 2+3 gone")
        # The failed resize left the trainer fully usable on its old mesh.
        assert trainer.state is state_before
        assert trainer.mesh_config.num_devices == 4
        trainer.step(batches(1)[0])

    def test_no_valid_submesh_raises(self, devices):
        from k8s_dra_driver_tpu.parallel.elastic import ElasticResizeError

        trainer = self._trainer(devices[:4], MeshConfig(data=2, tensor=2))
        with pytest.raises(ElasticResizeError, match="no valid sub-mesh"):
            trainer.resize(devices[:1], reason="only one survivor")
