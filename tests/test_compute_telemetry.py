"""Compute-plane observability (models/compute_telemetry.py +
parallel/collectives.py).

The contracts pinned here:

- **Ledger exactness**: the CompileLedger's per-program build counts
  equal the engine's own ``compile_counts`` — the ledger observes the
  trace-time seam, it never counts on its own. Builds after
  ``mark_warm()`` are recompiles: the storm signal travels under ONE
  program name through the ledger record, the
  ``tpu_dra_compute_recompiles_total`` label, and the doctor's DRIFT
  finding (the acceptance triple).
- **Roofline math** on a fake peak table: achieved rates, MFU, and the
  memory/compute/idle classification by arithmetic intensity against
  the ridge point.
- **HBM exactness**: the footprint decomposition equals the live params
  tree and paged pools to the byte, bf16 and quantized alike, through
  eviction churn.
- **Collective accounting**: the analytic ring-algorithm byte volumes
  (parallel/collectives.py docstring) match the MoE expert-parallel
  ring and psum paths on a fixed geometry, exactly.
- **Endpoint contract**: /debug/compute is 404 without a provider, 200
  JSON with one, 405 on writes.
"""

import dataclasses
import json
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k8s_dra_driver_tpu import doctor
from k8s_dra_driver_tpu.models import compute_telemetry as ct
from k8s_dra_driver_tpu.models.llama import PRESETS, init_params
from k8s_dra_driver_tpu.models.serving import DecodeEngine
from k8s_dra_driver_tpu.parallel import collectives
from k8s_dra_driver_tpu.utils.metrics import MetricsServer, Registry

TINY = PRESETS["tiny"]
DRIVER = "tpu.google.com"

# Ridge point 1e6 / 1e3 = 1000 FLOPs/byte: easy to straddle from a test.
FAKE_PEAKS = {
    "kind": "fake-chip", "matched": "fake",
    "peakFlopsPerS": 1.0e6, "peakBytesPerS": 1.0e3,
}


@pytest.fixture(scope="module")
def params():
    return init_params(TINY, jax.random.PRNGKey(0))


def _prompts(seed, lens):
    rng = np.random.RandomState(seed)
    return [list(rng.randint(0, TINY.vocab_size, size=n)) for n in lens]


def _engine(params, **kw):
    kw.setdefault("batch_slots", 2)
    kw.setdefault("num_blocks", 12)
    kw.setdefault("block_size", 8)
    kw.setdefault("max_seq_len", 48)
    kw.setdefault("prefill_chunk", 8)
    return DecodeEngine(params, TINY, **kw)


def _churn_prompts():
    # Shared prefix x varied tails, submitted twice: repeats hit the
    # radix cache, variety against the 12-block pool forces evictions.
    base = _prompts(11, (16,))[0]
    tails = _prompts(12, (5, 8, 11, 14))
    return [base + t for t in tails] * 2


def _drive(eng, prompts, n_new=8):
    reqs = [eng.submit(p, max_new_tokens=n_new) for p in prompts]
    eng.run()
    eng.assert_no_leaks()
    return reqs


class TestRooflineMath:
    """Pure roofline classification on the fake peak table."""

    def test_memory_bound(self):
        # Intensity 100 FLOPs/byte < ridge 1000 -> memory.
        r = ct.roofline(1e6, 1e4, 2.0, 1e6, 1e3)
        assert r["boundBy"] == "memory"
        assert r["flopsPerS"] == pytest.approx(5e5)
        assert r["bytesPerS"] == pytest.approx(5e3)
        assert r["mfu"] == pytest.approx(0.5)
        assert r["membwFraction"] == pytest.approx(5.0)
        assert r["intensity"] == pytest.approx(100.0)
        assert r["ridge"] == pytest.approx(1000.0)

    def test_compute_bound(self):
        # Intensity 1e4 > ridge 1000 -> compute.
        r = ct.roofline(1e6, 1e2, 1.0, 1e6, 1e3)
        assert r["boundBy"] == "compute"
        assert r["mfu"] == pytest.approx(1.0)

    def test_idle(self):
        r = ct.roofline(0.0, 0.0, 5.0, 1e6, 1e3)
        assert r["boundBy"] == "idle"
        assert r["mfu"] == 0.0
        assert r["windowS"] == 5.0

    def test_zero_window_is_idle(self):
        assert ct.roofline(1e6, 1e4, 0.0, 1e6, 1e3)["boundBy"] == "idle"

    def test_device_peaks_matches_known_kind(self):
        row = ct.device_peaks("TPU v5e chip")
        assert row["matched"] == "v5e"
        pf, pb = ct.PEAK_TABLE["v5e"]
        assert row["peakFlopsPerS"] == pf
        assert row["peakBytesPerS"] == pb

    def test_device_peaks_unknown_falls_back_to_cpu(self):
        row = ct.device_peaks("Quantum Banana 9000")
        assert row["matched"] == "cpu"
        assert row["kind"] == "Quantum Banana 9000"


class TestCollectiveConvention:
    """The analytic byte formulas and the zero-cost emit contract."""

    def test_formulas(self):
        assert collectives.permute_bytes(100, 4) == 400
        assert collectives.permute_bytes(100, 1) == 0  # self-permute
        assert collectives.all_gather_bytes(100, 4) == 1200
        assert collectives.all_to_all_bytes(100, 4) == 300
        assert collectives.all_reduce_bytes(100, 4) == 600
        x = jnp.zeros((3, 5), jnp.float32)
        assert collectives.payload_bytes(x.shape, x.dtype) == 60

    def test_emit_is_noop_without_ledger(self):
        assert not collectives._LEDGERS
        collectives.emit("nowhere", collectives.MEDIUM_ICI, 1 << 40)
        assert not collectives._LEDGERS

    def test_ledger_records_and_uninstalls(self):
        ledger = collectives.CollectiveLedger()
        ledger.install()
        try:
            collectives.emit("a.site", "ici", 100)
            collectives.emit("a.site", "ici", 50, invocations=2)
            collectives.emit("b.site", "dcn", 7)
        finally:
            ledger.uninstall()
        collectives.emit("a.site", "ici", 999)  # after uninstall: dropped
        snap = ledger.snapshot()
        assert snap == [
            {"site": "a.site", "medium": "ici",
             "bytes": 150, "invocations": 3},
            {"site": "b.site", "medium": "dcn",
             "bytes": 7, "invocations": 1},
        ]
        json.dumps(snap)


class TestCompileLedger:
    """Ledger invariants against a live engine's compile seam."""

    def test_builds_equal_engine_compile_counts(self, params):
        registry = Registry()
        tel = ct.ComputeTelemetry(registry)
        eng = _engine(params)
        tel.attach(eng, replica="r0", claim_uid="uid-1")
        try:
            _drive(eng, _prompts(0, (5, 11, 17)))
            counts = dict(eng.compile_counts)
            assert counts == {"decode_step": 1, "prefill_chunk": 1}
            snap = tel.ledger.snapshot()
            for program, n in counts.items():
                assert snap["builds"][program] == n, program
            # The model-forward trace seam reports too (prefill + decode
            # trace distinct shapes of the same forward).
            assert snap["builds"].get("forward", 0) >= 1
            # Not warm yet: first builds are builds, never recompiles.
            assert snap["recompilesSinceWarm"] == {}
            # Engine-program records carry wall time + cost estimate.
            timed = [r for r in snap["records"]
                     if r["program"] in counts]
            assert len(timed) == 2
            for r in timed:
                assert r["variant"] == "bf16"
                assert r["compileS"] > 0
                assert r["flops"] > 0 and r["bytes"] > 0
                assert r["afterWarm"] is False
        finally:
            tel.close()

    def test_steady_state_does_not_recompile(self, params):
        registry = Registry()
        tel = ct.ComputeTelemetry(registry)
        eng = _engine(params)
        tel.attach(eng, replica="r0")
        try:
            _drive(eng, _prompts(1, (6, 9)))
            tel.mark_warm()
            _drive(eng, _prompts(2, (7, 12)))  # same shapes, new prompts
            assert tel.ledger.snapshot()["recompilesSinceWarm"] == {}
            assert dict(eng.compile_counts) == {
                "decode_step": 1, "prefill_chunk": 1,
            }
        finally:
            tel.close()

    def test_variant_label_tracks_quantized_cache(self, params):
        registry = Registry()
        tel = ct.ComputeTelemetry(registry)
        eng = _engine(params, quantize_cache=True)
        tel.attach(eng, replica="r0")
        try:
            _drive(eng, _prompts(3, (6,)))
            recs = [r for r in tel.ledger.snapshot()["records"]
                    if r["program"] == "decode_step"]
            assert recs and all(r["variant"] == "kvq" for r in recs)
        finally:
            tel.close()


class TestHbmLedger:
    """The footprint decomposition is pool-exact, not an estimate."""

    def _assert_exact(self, eng):
        hbm = ct.engine_hbm(eng)
        assert hbm["weightsBytes"] == ct.tree_nbytes(eng.params)
        assert hbm["kvPoolBytes"] == sum(
            int(p.nbytes) for p in eng._pools
        )
        assert hbm["totalBytes"] == (
            hbm["weightsBytes"] + hbm["kvPoolBytes"]
        )
        occ = eng.allocator.occupancy()
        used = eng.allocator.num_blocks - occ["free"]
        assert hbm["kvUsedBlocks"] == used
        assert hbm["kvUsedBytes"] == (
            hbm["kvPoolBytes"] * used // eng.allocator.num_blocks
        )

    def test_exact_under_eviction_churn(self, params):
        eng = _engine(params)
        _drive(eng, _churn_prompts(), n_new=12)
        assert eng.kv_residency()["evictedBlocks"] > 0
        self._assert_exact(eng)

    def test_exact_quantized_pools(self, params):
        # int8 KV pools carry scales; "exact" must mean what was
        # actually allocated, not 2 bytes x elements.
        eng = _engine(params, quantize_cache=True)
        _drive(eng, _churn_prompts(), n_new=12)
        self._assert_exact(eng)

    def test_watermark_survives_drain(self, params):
        registry = Registry()
        tel = ct.ComputeTelemetry(registry)
        eng = _engine(params)
        tel.attach(eng, replica="r0")
        try:
            _drive(eng, _churn_prompts(), n_new=12)
            doc = tel.compute_debug()
            hbm = doc["hbm"]["r0"]
            assert hbm["watermarkBytes"] > 0
            # All requests retired: in-use is below the mid-run peak.
            assert hbm["watermarkBytes"] >= hbm["kvUsedBytes"]
            assert hbm["claimUid"] is None or isinstance(
                hbm["claimUid"], str
            )
        finally:
            tel.close()


class TestCollectiveRingVsPsum:
    """The MoE expert-parallel A/B: both EP paths' fabric traffic must
    equal the analytic ring-algorithm volumes on a fixed geometry."""

    @pytest.fixture(scope="class")
    def moe_setup(self):
        from k8s_dra_driver_tpu.models.moe import (
            MOE_PRESETS,
            init_params as moe_init,
            param_specs,
        )
        from k8s_dra_driver_tpu.parallel import MeshConfig, build_mesh
        from k8s_dra_driver_tpu.parallel.sharding import shard_pytree

        devices = jax.devices()
        assert len(devices) >= 4, "conftest must provide 8 virtual devices"
        cfg = MOE_PRESETS["tiny-moe"]
        mesh = build_mesh(MeshConfig(expert=4), devices=devices[:4])
        p = moe_init(cfg, jax.random.PRNGKey(0))
        sharded = shard_pytree(p, mesh, param_specs(cfg))
        tokens = jax.random.randint(
            jax.random.PRNGKey(1), (2, 64), 0, cfg.vocab_size
        )
        return cfg, mesh, sharded, tokens

    def _run(self, moe_setup, mode):
        from k8s_dra_driver_tpu.models.moe import forward

        cfg, mesh, sharded, tokens = moe_setup
        run_cfg = dataclasses.replace(
            cfg, moe_impl="dropless", ep_overlap=mode
        )
        ledger = collectives.CollectiveLedger()
        ledger.install()
        try:
            out, _ = jax.jit(
                lambda p, t: forward(p, t, run_cfg, mesh=mesh)
            )(sharded, tokens)
            jax.block_until_ready(out)
        except Exception as e:  # jaxlib without partial-manual support
            if "PartitionId" in str(e):
                pytest.skip(
                    "partial-manual shard_map unsupported on this jaxlib"
                )
            raise
        finally:
            ledger.uninstall()
        return {(s, m): tuple(c) for (s, m), c in ledger.sites.items()}

    def test_ring_path_matches_analytic_volumes(self, moe_setup):
        cfg, _, _, _ = moe_setup
        n_ep, e = 4, cfg.n_experts
        t, h = 2 * 64, cfg.hidden
        t_loc = t // n_ep
        item = 4  # tiny-moe is f32; the carrier is f32 by construction
        sites = self._run(moe_setup, "ring")
        # x hops: n_ep-1 permutes of the [t_loc, h] chunk (layers run
        # under lax.scan, so the site fires once per trace).
        assert sites[("moe.ep_ring.x", "ici")] == (
            (n_ep - 1) * n_ep * t_loc * h * item, n_ep - 1,
        )
        # y carrier: n_ep permutes of the f32 [t_loc, h] accumulator.
        assert sites[("moe.ep_ring.y", "ici")] == (
            n_ep * n_ep * t_loc * h * 4, n_ep,
        )
        # Order-restoring tiled all-gather of the local result.
        assert sites[("moe.ep_ring.all_gather", "ici")] == (
            n_ep * (n_ep - 1) * t_loc * h * 4, 1,
        )
        # Two [E] aux-stat pmeans.
        assert sites[("moe.ep_ring.aux", "ici")] == (
            2 * 2 * (n_ep - 1) * e * 4, 2,
        )
        assert ("moe.ep_psum.combine", "ici") not in sites

    def test_psum_path_matches_analytic_volume(self, moe_setup):
        cfg, _, _, _ = moe_setup
        n_ep = 4
        t, h = 2 * 64, cfg.hidden
        sites = self._run(moe_setup, "psum")
        # One all-reduce of the full f32 [t, h] contribution.
        assert sites[("moe.ep_psum.combine", "ici")] == (
            2 * (n_ep - 1) * t * h * 4, 1,
        )
        assert not any(s.startswith("moe.ep_ring") for s, _ in sites)

    def test_ring_per_hop_buffer_is_psum_fraction(self, moe_setup):
        """The A/B the accounting makes legible: the ring ships 1/n_ep
        of the tokens per hop where psum reduces the full [t, h]."""
        ring = self._run(moe_setup, "ring")
        psum = self._run(moe_setup, "psum")
        n_ep = 4
        # One shard's x-hop chunk: total x bytes / (hops x shards).
        chunk = ring[("moe.ep_ring.x", "ici")][0] // ((n_ep - 1) * n_ep)
        # The psum payload is the full [t, h] reduced in one shot.
        payload = psum[("moe.ep_psum.combine", "ici")][0] // (2 * (n_ep - 1))
        assert chunk * n_ep == payload


class TestExternalSteps:
    """observe_step: the roofline path for programs without an engine
    seam (train loops), on the fake peak table."""

    def test_roofline_and_counters(self):
        registry = Registry()
        tel = ct.ComputeTelemetry(registry, peaks=FAKE_PEAKS)
        try:
            tel.observe_step("train_step", 2.0, flops=1e6, nbytes=1e4,
                             steps=4, replica="t0")
            doc = tel.compute_debug()
            r = doc["programs"]["train_step"]["t0"]
            assert r["mfu"] == pytest.approx(0.5)
            assert r["flopsPerS"] == pytest.approx(5e5)
            assert r["boundBy"] == "memory"
            assert r["steps"] == 4
            assert doc["device"]["matched"] == "fake"
            body = registry.render()
            assert ('tpu_dra_compute_steps_total'
                    '{program="train_step",replica="t0"} 4') in body
        finally:
            tel.close()

    def test_train_trace_seam_records_build(self):
        from k8s_dra_driver_tpu.models import train
        from k8s_dra_driver_tpu.models.train import (
            init_train_state,
            make_optimizer,
            make_train_step,
            reshard_train_state,
        )
        from k8s_dra_driver_tpu.parallel import build_mesh

        registry = Registry()
        tel = ct.ComputeTelemetry(registry, peaks=FAKE_PEAKS)
        try:
            mesh = build_mesh()
            opt = make_optimizer()
            state = init_train_state(TINY, mesh, opt, seed=0)
            step = make_train_step(TINY, mesh, opt)
            # Batch must divide the data*fsdp mesh (8 virtual devices).
            tokens = jax.random.randint(
                jax.random.PRNGKey(2), (8, 17), 0, TINY.vocab_size
            )
            before = dict(train.TRACE_COUNTS)
            state, loss = step(state, tokens)
            assert float(loss) > 0
            assert train.TRACE_COUNTS["train_step:b8:s17"] == (
                before.get("train_step:b8:s17", 0) + 1
            )
            snap = tel.ledger.snapshot()
            assert snap["builds"].get("train_step", 0) >= 1
            rec = [r for r in snap["records"]
                   if r["program"] == "train_step"][-1]
            assert rec["shapes"] == {"batch": 8, "seq": 17}
            # The reshard is a host-level DCN site: bytes = the state
            # tree, exactly.
            state = reshard_train_state(state, mesh)
            expected = jax.tree.reduce(
                lambda acc, x: acc + int(getattr(x, "nbytes", 0)),
                state, 0,
            )
            rows = {(r["site"], r["medium"]): r
                    for r in tel.collectives.snapshot()}
            row = rows[("train.reshard", "dcn")]
            assert row["bytes"] == expected
            assert row["invocations"] == 1
        finally:
            tel.close()


class TestEndpointContract:
    def test_404_without_provider_200_with_405_on_write(self, params):
        registry = Registry()
        srv = MetricsServer(registry, host="127.0.0.1", port=0)
        srv.start()
        tel = ct.ComputeTelemetry(registry)
        try:
            base = f"http://127.0.0.1:{srv.port}"
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(f"{base}/debug/compute")
            assert ei.value.code == 404

            eng = _engine(params)
            tel.attach(eng, replica="r0", claim_uid="uid-ep")
            _drive(eng, _prompts(4, (6, 9)))
            srv.set_compute_provider(tel.compute_debug)
            served = json.loads(urllib.request.urlopen(
                f"{base}/debug/compute").read().decode())
            assert served["schema"] == "tpu-dra-compute-debug-v1"
            assert served["builds"]["decode_step"] == 1
            assert served["hbm"]["r0"]["claimUid"] == "uid-ep"
            assert served["hbm"]["r0"]["totalBytes"] == (
                served["hbm"]["r0"]["weightsBytes"]
                + served["hbm"]["r0"]["kvPoolBytes"]
            )

            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(f"{base}/debug/compute", data=b"x")
            assert ei.value.code == 405
            assert "GET" in (ei.value.headers.get("Allow") or "")
        finally:
            tel.close()
            srv.stop()


class TestDoctorComputeChecks:
    """The recompile-storm and mfu-regression findings."""

    @staticmethod
    def _scrape(compute):
        scrape = doctor.NodeScrape(name="node-a", url="http://x")
        scrape.compute = compute
        return scrape

    @staticmethod
    def _findings(scrape, bench_mfu=None):
        return doctor.fleet_findings(
            [scrape], {"resourceSlices": [], "resourceClaims": []},
            DRIVER, bench_mfu=bench_mfu,
        )

    def test_recompile_after_warm_is_drift(self):
        findings = self._findings(self._scrape({
            "warm": True, "recompilesSinceWarm": {"decode_step": 3},
        }))
        storm = [f for f in findings if f.check == "recompile-storm"]
        assert len(storm) == 1
        assert storm[0].severity == doctor.SEVERITY_DRIFT
        assert storm[0].subject == "node-a/decode_step"
        assert "3 recompile(s)" in storm[0].detail

    def test_builds_before_warm_are_not_storms(self):
        findings = self._findings(self._scrape({
            "warm": False, "recompilesSinceWarm": {},
            "builds": {"decode_step": 4},
        }))
        assert not any(f.check == "recompile-storm" for f in findings)

    def test_mfu_regression_needs_baseline_and_steps(self):
        compute = {
            "warm": True, "recompilesSinceWarm": {},
            "programs": {"decode_step": {
                "r0": {"mfu": 0.10, "steps": 50, "boundBy": "memory"},
            }},
        }
        # Under half the benched best -> drift.
        findings = self._findings(self._scrape(compute), bench_mfu=0.40)
        reg = [f for f in findings if f.check == "mfu-regression"]
        assert len(reg) == 1
        assert reg[0].subject == "node-a/r0/decode_step"
        assert "memory-bound" in reg[0].detail
        # Above half: fine.
        assert not any(
            f.check == "mfu-regression"
            for f in self._findings(self._scrape(compute), bench_mfu=0.15)
        )
        # No baseline: the check is skipped, never raised.
        assert not any(
            f.check == "mfu-regression"
            for f in self._findings(self._scrape(compute))
        )
        # An idle window (no steps) is not a regression.
        compute["programs"]["decode_step"]["r0"]["steps"] = 0
        assert not any(
            f.check == "mfu-regression"
            for f in self._findings(self._scrape(compute), bench_mfu=0.40)
        )

    def test_acceptance_triple_for_injected_storm(self, params):
        """ONE injected recompile storm must surface the SAME program
        name in the CompileLedger record, the recompiles_total label,
        and the doctor's DRIFT finding."""
        registry = Registry()
        tel = ct.ComputeTelemetry(registry)
        eng = _engine(params)
        tel.attach(eng, replica="r0")
        try:
            # Declare warm BEFORE any traffic: the first builds then
            # arrive through the real seam as post-warm recompiles.
            tel.mark_warm()
            _drive(eng, _prompts(5, (6, 9)))
            program = "decode_step"
            # 1: the ledger record.
            snap = tel.ledger.snapshot()
            assert snap["recompilesSinceWarm"][program] == 1
            rec = [r for r in snap["records"]
                   if r["program"] == program][-1]
            assert rec["afterWarm"] is True
            # 2: the counter label.
            body = registry.render()
            assert (f'tpu_dra_compute_recompiles_total'
                    f'{{program="{program}"}} 1') in body
            # 3: the doctor finding.
            scrape = doctor.NodeScrape(name="node-a", url="http://x")
            scrape.compute = tel.compute_debug()
            findings = doctor.fleet_findings(
                [scrape], {"resourceSlices": [], "resourceClaims": []},
                DRIVER,
            )
            storm = [f for f in findings
                     if f.check == "recompile-storm"
                     and f.subject == f"node-a/{program}"]
            assert len(storm) == 1
            assert storm[0].severity == doctor.SEVERITY_DRIFT
        finally:
            tel.close()


class TestBenchTrajectory:
    """The tolerant BENCH_r*.json loader: old rounds predate fields the
    newer ones carry and must normalize, not KeyError."""

    def _write(self, path, doc):
        path.write_text(json.dumps(doc))

    def test_loader_tolerates_old_rounds_and_junk(self, tmp_path):
        # r01: ancient single-metric round — no repeats/spread/detail.
        self._write(tmp_path / "BENCH_r01.json", {
            "n": 1, "parsed": {
                "metric": "llama3_tiny_train_mfu_b8_s128",
                "value": 0.31, "unit": "mfu_fraction",
            },
        })
        # r02: modern list round with full fields.
        self._write(tmp_path / "BENCH_r02.json", {
            "n": 2, "parsed": [
                {"metric": "llama3_tiny_train_mfu_b8_s128",
                 "value": 0.42, "unit": "mfu_fraction",
                 "repeats": 3, "spread": 0.01,
                 "detail": {"step_ms": 10.0}},
                {"metric": "llama3_tiny_decode_toks_b8_p128",
                 "value": 900.0, "unit": "tokens_per_s"},
                "not-a-dict",
            ],
        })
        (tmp_path / "BENCH_r03.json").write_text("{ truncated")
        rows = ct.load_bench_trajectory(str(tmp_path))
        assert [r["round"] for r in rows] == [1, 2, 2]
        old = rows[0]
        assert old["repeats"] == 1 and old["spread"] == 0.0
        assert old["detail"] == {}
        assert ct.bench_mfu_baseline(rows) == pytest.approx(0.42)

    def test_baseline_none_without_mfu_rounds(self, tmp_path):
        assert ct.bench_mfu_baseline([]) is None
        self._write(tmp_path / "BENCH_r01.json", {
            "n": 1, "parsed": [{
                "metric": "x_decode_toks", "value": 1.0,
                "unit": "tokens_per_s",
            }],
        })
        rows = ct.load_bench_trajectory(str(tmp_path))
        assert ct.bench_mfu_baseline(rows) is None

    def test_committed_trajectory_parses(self):
        # The repo's own BENCH history must stay loadable — this is the
        # doctor's --bench-dir input.
        import os

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        rows = ct.load_bench_trajectory(repo)
        if not rows:
            pytest.skip("no committed BENCH rounds in this checkout")
        assert ct.bench_mfu_baseline(rows) is not None
