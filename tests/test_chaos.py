"""Chaos harness: deterministic fault schedules against the cluster sim.

Every scenario drives the real stack (DeviceState / Driver / controller
over FakeKubeClient + FakeChipLib) through a failure schedule armed in
``utils/faults.py``, then asserts the four robustness invariants:

  I1. the checkpoint always reads back consistent;
  I2. no orphaned CDI claim spec survives a cleaner pass;
  I3. no ICI channel is recorded prepared by two claims;
  I4. no prepare ever succeeds onto a chip already marked unhealthy.

"Simulated seconds" are expressed as counted failed calls, not wall time —
schedules replay exactly. The default seed is fixed (``make chaos``);
``TPU_DRA_CHAOS_SEED`` overrides it, and the ``slow``-marked soak runs a
band of seeds.
"""

import os

import pytest

from k8s_dra_driver_tpu.cdi import CDIHandler
from k8s_dra_driver_tpu.kube import (
    EVENTS,
    NODES,
    RESOURCE_CLAIMS,
    RESOURCE_SLICES,
    ApiError,
    FakeKubeClient,
)
from k8s_dra_driver_tpu.kube.protos import dra_v1alpha4_pb2 as drapb
from k8s_dra_driver_tpu.plugin.checkpoint import CheckpointManager
from k8s_dra_driver_tpu.plugin.cleanup import OrphanCleaner
from k8s_dra_driver_tpu.plugin.device_state import (
    DeviceState,
    GangResizeError,
    PrepareError,
    UnhealthyDeviceError,
)
from k8s_dra_driver_tpu.plugin.audit import StateAuditor
from k8s_dra_driver_tpu.plugin.driver import Driver, DriverConfig
from k8s_dra_driver_tpu.tpulib import FakeChipLib
from k8s_dra_driver_tpu.utils import faults
from k8s_dra_driver_tpu.utils.metrics import Registry

import time

DRIVER = "tpu.google.com"
SEED = int(os.environ.get("TPU_DRA_CHAOS_SEED", "1234"))


def wait_for(pred, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.02)
    return False


@pytest.fixture(autouse=True)
def _always_disarm():
    """No schedule may leak into the next test (or the wider suite)."""
    yield
    faults.disarm()


def make_claim(uid, devices, name="c", namespace="default"):
    results = [
        {"request": f"req-{i}", "driver": DRIVER, "pool": "node-a",
         "device": d}
        for i, d in enumerate(devices)
    ]
    return {
        "apiVersion": "resource.k8s.io/v1beta1",
        "kind": "ResourceClaim",
        "metadata": {"name": name, "namespace": namespace, "uid": uid},
        "spec": {
            "devices": {
                "requests": [
                    {"name": r["request"],
                     "deviceClassName": "tpu.google.com"}
                    for r in results
                ]
            }
        },
        "status": {
            "allocation": {"devices": {"results": results, "config": []}}
        },
    }


def make_state(tmp_path, lib=None):
    lib = lib or FakeChipLib(generation="v5p", topology="2x2x1")
    return DeviceState(
        chiplib=lib,
        cdi=CDIHandler(str(tmp_path / "cdi")),
        checkpoint=CheckpointManager(str(tmp_path / "checkpoint.json")),
        driver_name=DRIVER,
        pool_name="node-a",
        state_dir=str(tmp_path / "state"),
    ), lib


def make_driver(tmp_path, lib=None, client=None, interval=0.05):
    client = client or FakeKubeClient()
    try:
        client.get(NODES, "node-a")
    except Exception:
        client.create(NODES, {"metadata": {"name": "node-a", "uid": "nu-1"}})
    lib = lib or FakeChipLib(generation="v5p", topology="2x2x1")
    config = DriverConfig(
        node_name="node-a",
        chiplib=lib,
        kube_client=client,
        cdi_root=str(tmp_path / "cdi"),
        plugin_root=str(tmp_path / "plugin"),
        registrar_root=str(tmp_path / "registry"),
        state_root=str(tmp_path / "state"),
        node_uid="nu-1",
        cleanup_interval_seconds=0,
        device_watch_interval_seconds=interval,
    )
    return Driver(config), client, lib


def prepare_via_rpc(driver, claim):
    """Drive the DRA node service the way kubelet would (in-band errors)."""
    req = drapb.NodePrepareResourcesRequest(claims=[
        drapb.Claim(
            uid=claim["metadata"]["uid"],
            name=claim["metadata"]["name"],
            namespace=claim["metadata"]["namespace"],
        )
    ])
    resp = driver.NodePrepareResources(req, None)
    return resp.claims[claim["metadata"]["uid"]]


def chip_uuid_of(state, device_name):
    dev = state.allocatable[device_name]
    return (dev.chip or dev.tensorcore.parent).uuid


def run_audit(state):
    """One auditor pass (fresh registry: kube-less, local checks only) —
    the production form of assert_invariants, used here as an ORACLE:
    schedules assert it reports exactly the drift the fault injected,
    and nothing when the fault left state consistent."""
    return StateAuditor(
        state=state, registry=Registry(), node_name="node-a"
    ).run_once()


def assert_invariants(state):
    """The four invariants (I2 assumes the caller ran a cleaner pass
    after any simulated crash, as a restarted plugin's timer would)."""
    # I1: checkpoint reads back consistent.
    ckpt = state.checkpoint.read()
    # I2: every CDI claim spec belongs to a checkpointed claim.
    orphans = set(state.cdi.list_claim_spec_uids()) - set(ckpt)
    assert not orphans, f"orphaned CDI specs: {orphans}"
    # I3: no ICI channel prepared by two live claims.
    seen_channels: dict[int, str] = {}
    for uid, rec in ckpt.items():
        for group in rec.get("groups", []):
            for dev in group.get("devices", []):
                ch = dev.get("channel")
                if ch is None:
                    continue
                assert seen_channels.setdefault(ch, uid) == uid, (
                    f"channel {ch} prepared by both "
                    f"{seen_channels[ch]} and {uid}"
                )
    # I4: no checkpointed claim prepared onto an ALREADY-unhealthy chip.
    # PreparedClaim.prepared_at orders each prepare against the health
    # transition timestamps: a claim on a now-unhealthy chip is legal
    # only when the chip sickened AFTER the prepare completed.
    for uid, rec in ckpt.items():
        prepared_at = rec.get("preparedAt", 0.0)
        for group in rec.get("groups", []):
            for dev in group.get("devices", []):
                for u in dev.get("uuids", []):
                    base = u.split("-core-")[0]
                    st = state.chip_health.get(base)
                    if st is None or st.is_healthy():
                        continue
                    assert st.since >= prepared_at, (
                        f"claim {uid} prepared at {prepared_at} on chip "
                        f"{base}, which was already {st.state} since "
                        f"{st.since}"
                    )


class TestUnplugMidPrepare:
    def test_unplug_between_cdi_and_checkpoint(self, tmp_path):
        """Chip 1 drops off the bus after the CDI claim spec is rendered
        but before the checkpoint records the claim — the narrowest
        mid-prepare window. The prepare completes (the devices were bound
        before the hardware died); the next health poll flags the chip,
        new prepares are refused, and invariants hold."""
        state, lib = make_state(tmp_path)
        uuid1 = chip_uuid_of(state, "tpu-1")
        plan = faults.FaultPlan()
        plan.call("checkpoint.write", lambda: lib.unplug_chip(1))
        with faults.armed(plan):
            devices = state.prepare(make_claim("uid-mid", ["tpu-1"]))
        assert devices[0].device_name == "tpu-1"

        # The health poll sees the unplug: transition logged, chip gone
        # from allocatable, published resources shrink.
        assert state.refresh_allocatable() is True
        transitions = state.drain_health_transitions()
        assert any(u == uuid1 and s.is_gone() for u, _, s in transitions)
        assert "tpu-1" not in state.allocatable
        pub = {d["name"] for d in state.published_resources()["devices"]}
        assert "tpu-1" not in pub

        # A retried prepare of a NEW claim for that chip is refused.
        with pytest.raises(PrepareError):
            state.prepare(make_claim("uid-new", ["tpu-1"], name="c2"))
        assert_invariants(state)
        # The mid-prepare claim unprepares cleanly despite the dead chip.
        state.unprepare("uid-mid")
        assert state.checkpoint.read() == {}

    def test_wedged_chip_refused_with_typed_error(self, tmp_path):
        """A degraded (present but erroring) chip stays enumerated and
        published unhealthy — and prepares onto it fail with the TYPED
        error, distinguishable from a malformed claim."""
        state, lib = make_state(tmp_path)
        lib.wedge_chip(0, reason="hbm uncorrectable errors")
        assert state.refresh_allocatable() is True
        assert "tpu-0" in state.allocatable  # still visible, drainable
        dev = next(
            d for d in state.published_resources()["devices"]
            if d["name"] == "tpu-0"
        )
        assert dev["basic"]["attributes"]["healthy"]["bool"] is False
        with pytest.raises(UnhealthyDeviceError, match="hbm uncorrectable"):
            state.prepare(make_claim("uid-w", ["tpu-0"]))
        # Its core partitions are equally refused (parent health governs).
        with pytest.raises(UnhealthyDeviceError):
            state.prepare(make_claim("uid-w2", ["tpu-0-core-0"], name="c3"))
        # Healthy neighbors are unaffected.
        state.prepare(make_claim("uid-ok", ["tpu-1"], name="c4"))
        assert_invariants(state)


class TestApiserverBlackout:
    def test_blackout_serves_prepares_from_checkpoint(self, tmp_path):
        """During a full apiserver blackout the plugin keeps serving
        kubelet retries of already-prepared claims from checkpointed
        state (degraded mode), readiness reads degraded-not-dead, and the
        queued slice republish converges once the server returns."""
        driver, client, lib = make_driver(tmp_path)
        driver.start()
        try:
            claim = make_claim("uid-bo", ["tpu-0"])
            client.create(RESOURCE_CLAIMS, claim, namespace="default")
            assert prepare_via_rpc(driver, claim).error == ""
            before = driver._m_degraded_prepares.value()

            # Blackout: every API verb fails (fault_injector is the fake
            # server's network cable).
            client.fault_injector = lambda verb, gvr, name: ApiError(
                "apiserver blackout", code=503
            )
            # A kubelet retry of the SAME claim still succeeds, served
            # from the checkpoint.
            result = prepare_via_rpc(driver, claim)
            assert result.error == ""
            assert [d.device_name for d in result.devices] == ["tpu-0"]
            assert driver._m_degraded_prepares.value() == before + 1
            # Readiness: degraded, not dead.
            ok, detail = driver._check_apiserver()
            assert not ok and "blackout" in detail
            for check in driver.readiness_checks().values():
                assert check()[0], "critical checks must stay green"

            # A NEVER-prepared claim cannot be served dark.
            c2 = make_claim("uid-bo2", ["tpu-1"], name="c2")
            result = prepare_via_rpc(driver, c2)
            assert result.error != ""

            # Inventory changes during the blackout queue behind the
            # republish backoff instead of being lost.
            lib.unplug_chip(1)
            assert wait_for(lambda: "tpu-1" not in driver.state.allocatable)

            # Server returns: republish converges to the post-blackout
            # truth without a restart.
            client.fault_injector = None
            assert wait_for(lambda: "tpu-1" not in {
                d["name"]
                for s in client.list(RESOURCE_SLICES)
                for d in s["spec"].get("devices", [])
            })
            # The first post-outage claim fetch flips readiness back.
            assert prepare_via_rpc(driver, claim).error == ""
            assert wait_for(lambda: driver._check_apiserver()[0])
            assert_invariants(driver.state)
        finally:
            client.fault_injector = None
            driver.shutdown()


class TestCrashRestart:
    def test_crash_between_cdi_write_and_checkpoint_write(self, tmp_path):
        """Simulated SIGKILL in the window where the claim CDI spec is on
        disk but the checkpoint is not: the restarted plugin must treat
        the claim as never-prepared, the cleaner reclaims the orphaned
        spec AND the leaked sharing hold, and the chip is reusable."""
        state, lib = make_state(tmp_path)
        plan = faults.FaultPlan().crash("checkpoint.write")
        with faults.armed(plan):
            with pytest.raises(faults.CrashPoint):
                state.prepare(make_claim("uid-crash", ["tpu-0"]))
        del state  # the dead incarnation

        restarted, _ = make_state(tmp_path)
        # I1 holds across the crash; the claim is NOT checkpointed.
        assert restarted.checkpoint.read() == {}
        # The orphaned CDI spec is visible pre-clean...
        assert restarted.cdi.list_claim_spec_uids() == ["uid-crash"]
        OrphanCleaner(restarted, kube_client=None,
                      interval_seconds=0).clean_once()
        # ...and all four invariants hold after the cleaner pass.
        assert_invariants(restarted)
        assert restarted.cdi.list_claim_spec_uids() == []
        # The chip is fully reusable (the leaked exclusive hold was
        # released by the share-state cleanup).
        devices = restarted.prepare(make_claim("uid-after", ["tpu-0"]))
        assert devices[0].device_name == "tpu-0"

    def test_corrupt_checkpoint_quarantined_on_restart(self, tmp_path):
        """A checkpoint torn by a node crash must not crash-loop the
        plugin: startup parks it at <path>.corrupt and continues empty."""
        state, _ = make_state(tmp_path)
        state.prepare(make_claim("uid-c", ["tpu-0"]))
        path = tmp_path / "checkpoint.json"
        path.write_text(path.read_text()[: len(path.read_text()) // 2])

        restarted, _ = make_state(tmp_path)  # must not raise
        assert restarted.checkpoint.read() == {}
        assert (tmp_path / "checkpoint.json.corrupt").exists()
        # Oracle: the quarantine emptied the checkpoint, so the surviving
        # CDI spec + sharing hold of uid-c ARE the drift — and exactly
        # that is what the auditor must report, until a cleaner pass.
        found = {(f.check, f.subject) for f in run_audit(restarted)}
        assert ("cdi", "uid-c") in found
        assert any(c == "sharing" for c, _ in found)
        assert_invariants_after_clean(restarted)
        assert run_audit(restarted) == []


def assert_invariants_after_clean(state):
    OrphanCleaner(state, kube_client=None, interval_seconds=0).clean_once()
    assert_invariants(state)


class TestWatchStreamDeath:
    def test_controller_reestablishes_node_watch(self, tmp_path):
        """The node watch dying without stop() (apiserver closed it) must
        not permanently wedge the controller: it relists, reconciles
        membership changes missed during the gap — including removals —
        and resumes streaming."""
        from k8s_dra_driver_tpu.controller.slice_manager import (
            SLICE_LABEL,
            IciSliceManager,
        )

        client = FakeKubeClient()
        client.create(NODES, {"metadata": {
            "name": "n1", "labels": {SLICE_LABEL: "s1"}}})
        mgr = IciSliceManager(client)
        mgr.start()
        try:
            assert wait_for(lambda: len(client.list(RESOURCE_SLICES)) == 1)
            dead = mgr._watch
            dead.stop()  # server-side stream death, NOT mgr.stop()

            # Changes during the dark window: one domain vanishes, one
            # appears.
            client.delete(NODES, "n1")
            client.create(NODES, {"metadata": {
                "name": "n2", "labels": {SLICE_LABEL: "s2"}}})

            assert wait_for(lambda: mgr.healthy()[0] and
                            mgr._watch is not dead)
            assert wait_for(lambda: [
                k.slice_id for k in mgr.domains()
            ] == ["s2"])
            # And the re-established STREAM works: a post-recovery event
            # reconciles too.
            client.create(NODES, {"metadata": {
                "name": "n3", "labels": {SLICE_LABEL: "s3"}}})
            assert wait_for(lambda: {
                k.slice_id for k in mgr.domains()
            } == {"s2", "s3"})
        finally:
            mgr.stop(cleanup=False)


class TestHealthEndToEnd:
    def test_degraded_chip_leaves_slices_and_returns_with_event_and_metric(
        self, tmp_path
    ):
        """Acceptance e2e: a chip carrying a prepared claim dies → it
        disappears from published ResourceSlices, a correlated Warning
        Event lands on the claim, and the health-transition metric moves;
        recovery republishes the chip and emits the Normal Event."""
        driver, client, lib = make_driver(tmp_path)
        driver.start()
        try:
            def slice_names():
                return {
                    d["name"]
                    for s in client.list(RESOURCE_SLICES)
                    for d in s["spec"].get("devices", [])
                }

            assert wait_for(lambda: "tpu-0" in slice_names())
            claim = make_claim("uid-e2e", ["tpu-0"], name="workload")
            client.create(RESOURCE_CLAIMS, claim, namespace="default")
            assert prepare_via_rpc(driver, claim).error == ""

            lib.unplug_chip(0, reason="pcie link down")
            assert wait_for(lambda: "tpu-0" not in slice_names())
            # Core partitions of the dead chip are gone too.
            assert wait_for(
                lambda: "tpu-0-core-0" not in slice_names()
            )
            assert driver._m_health_transitions.value(
                from_state="healthy", to="gone"
            ) >= 1
            driver.events.flush()
            assert wait_for(lambda: any(
                ev["reason"] == "ChipUnhealthy"
                and ev["involvedObject"]["name"] == "workload"
                and "pcie link down" in ev["message"]
                for ev in client.list(EVENTS)
            ))

            lib.restore_chip(0)
            assert wait_for(lambda: "tpu-0" in slice_names())
            assert driver._m_health_transitions.value(
                from_state="gone", to="healthy"
            ) >= 1
            driver.events.flush()
            assert wait_for(lambda: any(
                ev["reason"] == "ChipRecovered"
                and ev["involvedObject"]["name"] == "workload"
                for ev in client.list(EVENTS)
            ))
            assert_invariants(driver.state)
        finally:
            driver.shutdown()

    def test_flap_schedule_is_deterministic(self, tmp_path):
        """set_flap flips presence on the health-poll count: the same
        refresh sequence yields the same transition sequence, every run."""
        state, lib = make_state(tmp_path)
        lib.set_flap(1, period=2)
        states = []
        for _ in range(8):
            state.refresh_allocatable()
            states.append("tpu-1" in state.allocatable)
        # The flap clock advanced once during DeviceState init, so the
        # eight refreshes observe polls 2..9; with period=2 presence is
        # (poll // 2) even — a fixed pattern every run:
        assert states == [False, False, True, True, False, False, True,
                          True]
        transitions = [
            (old, s.state) for _, old, s in state.drain_health_transitions()
        ]
        assert transitions == [("healthy", "gone"), ("gone", "healthy"),
                               ("healthy", "gone"), ("gone", "healthy")]


class TestAuditorOracle:
    """Satellite tie-in: after a seeded fault, the auditor must report
    exactly the drift that fault injected — and stay silent for faults
    that leave state consistent (precision matters as much as recall:
    an auditor that cries wolf gets ignored)."""

    def test_crash_artifacts_reported_exactly_then_clean(self, tmp_path):
        state, lib = make_state(tmp_path)
        plan = faults.FaultPlan().crash("checkpoint.write")
        with faults.armed(plan):
            with pytest.raises(faults.CrashPoint):
                state.prepare(make_claim("uid-crash", ["tpu-0"]))
        del state  # the dead incarnation

        restarted, _ = make_state(tmp_path)
        findings = run_audit(restarted)
        # Exactly the two artifacts this crash window leaves: the CDI
        # spec written before the checkpoint, and the sharing hold
        # acquired before it. Nothing else.
        assert {(f.check, f.subject) for f in findings} == {
            ("cdi", "uid-crash"),
            ("sharing", chip_uuid_of(restarted, "tpu-0")),
        }
        OrphanCleaner(restarted, kube_client=None,
                      interval_seconds=0).clean_once()
        assert run_audit(restarted) == []

    def test_mid_prepare_unplug_is_not_drift(self, tmp_path):
        """A chip dying AFTER its prepare completed leaves checkpoint,
        CDI, sharing, and health timestamps all mutually consistent —
        the auditor must report nothing."""
        state, lib = make_state(tmp_path)
        plan = faults.FaultPlan()
        plan.call("checkpoint.write", lambda: lib.unplug_chip(1))
        with faults.armed(plan):
            state.prepare(make_claim("uid-mid", ["tpu-1"]))
        state.refresh_allocatable()
        assert not state.chip_health[
            chip_uuid_of_gone(state, lib, 1)
        ].is_healthy()
        assert run_audit(state) == []


def chip_uuid_of_gone(state, lib, index):
    """UUID of a chip that no longer enumerates (gone chips drop out of
    state.allocatable, so chip_uuid_of cannot resolve them)."""
    return next(
        c.uuid for c in lib._all_chips() if c.index == index
    )


def run_acceptance_schedule(tmp_path, seed):
    """The acceptance schedule: unplug mid-prepare, a 10-simulated-second
    apiserver blackout during republish, and a crash-restart between
    checkpoint write and CDI cleanup — seeded choices for which chip and
    how the blackout lands; all four invariants after every phase."""
    import random

    rng = random.Random(seed)
    driver, client, lib = make_driver(tmp_path)
    driver.start()
    try:
        # Phase 1: unplug a seeded chip mid-prepare.
        victim = rng.randrange(2)  # chips 0/1 (2/3 stay as healthy pool)
        claim1 = make_claim("uid-p1", [f"tpu-{victim}"], name="p1")
        client.create(RESOURCE_CLAIMS, claim1, namespace="default")
        plan = faults.FaultPlan()
        plan.call("checkpoint.write",
                  lambda: lib.unplug_chip(victim, reason="chaos unplug"))
        with faults.armed(plan):
            assert prepare_via_rpc(driver, claim1).error == ""
        assert wait_for(
            lambda: f"tpu-{victim}" not in driver.state.allocatable
        )
        assert_invariants(driver.state)
        # Oracle: the unplug raced the prepare but produced NO drift —
        # once the republish converges the auditor must read clean
        # (driver.auditor includes the published-slices comparison).
        assert wait_for(lambda: driver.auditor.run_once() == [])

        # Phase 2: apiserver blackout ("10 simulated seconds" = the dark
        # window spans ≥2 failed republish attempts plus a degraded-mode
        # prepare; counted events, not wall time, so it replays exactly).
        blackout_failures = {"n": 0}

        def injector(verb, gvr, name):
            blackout_failures["n"] += 1
            return ApiError("chaos blackout", code=503)

        client.fault_injector = injector
        retried = prepare_via_rpc(driver, claim1)  # kubelet retry, dark
        assert retried.error == ""                 # served from checkpoint
        survivor = 2 if victim != 2 else 3
        lib.wedge_chip(survivor, reason="chaos wedge")
        # The wedge reaches LOCAL state during the blackout; the
        # republish queues behind jittered backoff and keeps failing.
        assert wait_for(lambda: not driver.state.chip_health[
            chip_uuid_of(driver.state, f"tpu-{survivor}")
        ].is_healthy(), timeout=10)
        assert wait_for(
            lambda: not driver.plugin.slice_sync_health()[0], timeout=10
        )
        assert wait_for(lambda: blackout_failures["n"] >= 3, timeout=30)
        # Server returns: the queued republish converges, no restart.
        client.fault_injector = None
        assert wait_for(lambda: any(
            d["name"] == f"tpu-{survivor}"
            and d["basic"]["attributes"]["healthy"]["bool"] is False
            for s in client.list(RESOURCE_SLICES)
            for d in s["spec"].get("devices", [])
        ), timeout=30)
        with pytest.raises(UnhealthyDeviceError):
            driver.state.prepare(
                make_claim("uid-w", [f"tpu-{survivor}"], name="w")
            )
        assert_invariants(driver.state)
        # Oracle after the blackout: the wedge reached both the local
        # view and (post-recovery) the published slices; no drift.
        assert wait_for(lambda: driver.auditor.run_once() == [])

        # Phase 3: crash-restart between CDI write and checkpoint write.
        healthy = [i for i in range(4) if i not in (victim, survivor)]
        target = rng.choice(healthy)
        crash_claim = make_claim("uid-crash", [f"tpu-{target}"], name="cr")
        client.create(RESOURCE_CLAIMS, crash_claim, namespace="default")
        plan = faults.FaultPlan().crash("checkpoint.write")
        with faults.armed(plan):
            # CrashPoint is a BaseException: it tears through the RPC
            # surface the way SIGKILL tears through the process — no
            # in-band error, no rollback.
            with pytest.raises(faults.CrashPoint):
                prepare_via_rpc(driver, crash_claim)
        driver.shutdown()

        restarted, client2, lib2 = make_driver(tmp_path, interval=0.05)
        restarted.start()
        try:
            assert restarted.state.checkpoint.read().keys() == {"uid-p1"}
            # Oracle BEFORE the cleaner: exactly the crash window's two
            # artifacts (orphan CDI spec + leaked sharing hold), nothing
            # else. Local checks only — the fresh fake apiserver's slice
            # publication is still converging.
            pre = {
                (f.check, f.subject)
                for f in run_audit(restarted.state)
                if f.check != "slices"
            }
            assert pre == {
                ("cdi", "uid-crash"),
                ("sharing", chip_uuid_of(restarted.state,
                                         f"tpu-{target}")),
            }
            OrphanCleaner(restarted.state, kube_client=None,
                          interval_seconds=0).clean_once()
            assert_invariants(restarted.state)
            # The crashed claim re-prepares idempotently on retry.
            client2.create(RESOURCE_CLAIMS, crash_claim,
                           namespace="default")
            assert prepare_via_rpc(restarted, crash_claim).error == ""
            assert_invariants(restarted.state)
            # Oracle at schedule end: the full fleet state (slices
            # included) converges back to consistent.
            assert wait_for(lambda: restarted.auditor.run_once() == [])
        finally:
            restarted.shutdown()
    finally:
        client.fault_injector = None
        if getattr(driver, "plugin", None) is not None:
            try:
                driver.shutdown()
            except Exception:
                pass


def make_gang_claim(client, allocator, uid="uid-gang", name="train",
                    count=4, device_class="tpu.google.com"):
    """Allocate a count-N gang through the REAL sim allocator (so the
    elastic re-solve later operates on genuine reservations) and create
    the claim in the fake apiserver for the prepare path to fetch. The
    request is deliberately NOT named "gang": the elastic re-solve must
    reuse the claim's own request name, and a hardcoded one would hide
    that."""
    claim = {
        "apiVersion": "resource.k8s.io/v1beta1",
        "kind": "ResourceClaim",
        "metadata": {"name": name, "namespace": "default", "uid": uid},
        "spec": {"devices": {"requests": [{
            "name": "workers",
            "deviceClassName": device_class,
            "allocationMode": "ExactCount",
            "count": count,
        }]}},
    }
    allocator.allocate(claim, node_name="node-a")
    client.create(RESOURCE_CLAIMS, claim, namespace="default")
    return claim


class TestElasticGangResize:
    """The elastic-training acceptance scenario (ROADMAP item 5): a
    seeded chip-unplug DURING a multichip train step shrinks the gang
    claim, the allocator re-solves for the surviving topology, the mesh
    reshapes, the live TrainState reshards device-to-device (no
    checkpoint restore on the hot path), and training resumes with loss
    continuity against an uninterrupted run on the surviving topology —
    with the StateAuditor as the no-drift oracle. Then the symmetric
    grow when the chip is restored."""

    def _driver(self, tmp_path):
        from k8s_dra_driver_tpu.kube.allocator import ReferenceAllocator

        lib = FakeChipLib(generation="v5p", topology="4x1x1")
        driver, client, lib = make_driver(tmp_path, lib=lib)
        allocator = ReferenceAllocator(client, registry=Registry())
        driver.enable_elastic(allocator)
        return driver, client, lib, allocator

    @pytest.mark.slow  # full resize-resume-grow cycle; `make chaos-slow`
    def test_chip_unplug_mid_step_resize_resume_and_grow(self, tmp_path):
        import jax
        import numpy as np

        from k8s_dra_driver_tpu.models.llama import PRESETS
        from k8s_dra_driver_tpu.models.train import (
            make_optimizer,
            state_shardings,
        )
        from k8s_dra_driver_tpu.parallel import MeshConfig
        from k8s_dra_driver_tpu.parallel.elastic import ElasticTrainer

        cfg = PRESETS["tiny"]
        jax_devices = jax.devices()
        assert len(jax_devices) >= 8
        driver, client, lib, allocator = self._driver(tmp_path)
        resizes = []
        driver.add_resize_listener(resizes.append)
        driver.start()
        try:
            assert wait_for(lambda: len(client.list(RESOURCE_SLICES)) >= 1)
            claim = make_gang_claim(client, allocator)
            assert prepare_via_rpc(driver, claim).error == ""

            # Claim device tpu-i <-> jax device i: the workload-side view
            # of the DRA allocation (TPU_VISIBLE_CHIPS ordering).
            def jax_devs(names):
                return [jax_devices[int(n.split("-")[1])] for n in names]

            opt = make_optimizer(warmup_steps=1, total_steps=10)
            trainer = ElasticTrainer(
                cfg, opt, jax_devs(["tpu-0", "tpu-1", "tpu-2", "tpu-3"]),
                mesh_config=MeshConfig(data=2, tensor=2), global_batch=8,
            )
            # Uninterrupted reference on the SURVIVING topology (the
            # post-shrink 2-device tensor mesh), from the same init —
            # copied through host memory so the runs share no donated
            # buffers. (Both gangs end on 2 used devices whatever chip
            # the seed kills: 8-token batches only divide dp=1 or 2.)
            reference = ElasticTrainer(
                cfg, opt, jax_devices[:2],
                mesh_config=MeshConfig(tensor=2), global_batch=8,
            )
            host_init = jax.tree.map(np.array, trainer.state)
            reference.state = jax.device_put(
                host_init, state_shardings(reference.state, reference.mesh)
            )
            n_steps = 7
            toks = [
                jax.random.randint(
                    jax.random.PRNGKey(100 + i), (8, 65), 0, cfg.vocab_size
                )
                for i in range(n_steps)
            ]
            ref_losses = [reference.step(t) for t in toks]

            # Seeded chaos: the unplug lands at the TOP of train step 4 —
            # mid-training, between the plugin's health polls.
            import random

            victim = random.Random(SEED).randrange(4)
            plan = faults.FaultPlan()
            plan.call(
                "train.step",
                lambda: lib.unplug_chip(victim, reason="chaos unplug"),
                on_calls={4},
            )
            losses = []
            with faults.armed(plan):
                for t in toks[:4]:
                    losses.append(trainer.step(t))
            # The watch loop sees the unplug, the gang shrinks, and the
            # typed resize message reaches the workload.
            assert wait_for(lambda: len(resizes) >= 1, timeout=15)
            msg = resizes[0]
            assert msg.direction == "shrink"
            assert msg.claim_uid == "uid-gang"
            assert f"tpu-{victim}" in msg.removed
            assert f"tpu-{victim}" not in msg.devices
            assert msg.desired == 4 and msg.generation == 1
            # The checkpointed claim matches the message (protocol truth).
            view = driver.state.gang_view("uid-gang")
            assert tuple(n for n, _ in view["devices"]) == msg.devices
            # The re-solve reused the claim's OWN request name — kubelet
            # still matches every device to the spec's "workers" request.
            for d in driver.state.cached_devices("uid-gang"):
                assert d.request_names == ["workers"]

            # Live reshard onto the surviving gang; remainder idled.
            event = trainer.resize(
                jax_devs(msg.devices), reason=msg.reason
            )
            assert event.path == "live", (
                "the hot path must not touch the checkpoint"
            )
            assert event.n_used == 2
            assert event.n_used + event.n_idled == len(msg.devices)
            for t in toks[4:]:
                losses.append(trainer.step(t))
            # Loss continuity: the interrupted-and-reshaped run lands
            # where the uninterrupted run on the surviving topology
            # lands (different meshes = different reduction orders, so
            # close, not bit-exact).
            np.testing.assert_allclose(
                losses, ref_losses, rtol=2e-4, atol=2e-4
            )
            # No-drift oracle (slices comparison converges async).
            assert wait_for(lambda: driver.auditor.run_once() == [])
            assert driver._m_elastic_resizes.value(
                direction="shrink", outcome="ok"
            ) == 1

            # Symmetric grow: the chip is restored, the gang grows back
            # to its desired size, and the state reshards onto the
            # larger mesh.
            lib.restore_chip(victim)
            assert wait_for(lambda: len(resizes) >= 2, timeout=15)
            grow = resizes[1]
            assert grow.direction == "grow"
            assert set(grow.devices) == {
                "tpu-0", "tpu-1", "tpu-2", "tpu-3"
            }
            assert grow.generation == 2
            event = trainer.resize(jax_devs(grow.devices),
                                   reason=grow.reason)
            assert event.path == "live" and event.n_used == 4
            post_grow = [trainer.step(t) for t in toks]
            assert all(np.isfinite(loss) for loss in post_grow)
            assert wait_for(lambda: driver.auditor.run_once() == [])
            assert driver._m_elastic_resizes.value(
                direction="grow", outcome="ok"
            ) == 1
            # Operator surfaces: the Event and the resize trace.
            driver.events.flush()
            assert any(
                ev["reason"] == "GangResized"
                and ev["involvedObject"]["name"] == "train"
                for ev in client.list(EVENTS)
            )
            directions = [r["direction"] for r in driver.resize_trace()]
            assert directions == ["shrink", "grow"]
            assert_invariants(driver.state)
        finally:
            driver.shutdown()

    def test_no_survivors_emits_gang_resize_failed(self, tmp_path):
        """Every chip of the gang dying leaves nothing to shrink to —
        the coordinator must say so (typed failure, Warning Event,
        outcome metric), not resize to an empty gang. Driven without the
        watch thread so BOTH deaths land in one transition batch (a
        rack-power event, not two separate failures)."""
        driver, client, lib, allocator = self._driver(tmp_path)
        driver.publish_resources()
        assert wait_for(lambda: len(client.list(RESOURCE_SLICES)) >= 1)
        claim = make_gang_claim(client, allocator, uid="uid-all",
                                name="doomed", count=2)
        assert prepare_via_rpc(driver, claim).error == ""
        names = [
            r["device"]
            for r in claim["status"]["allocation"]["devices"]["results"]
        ]
        for n in names:
            lib.unplug_chip(int(n.split("-")[1]), reason="rack power")
        driver.state.refresh_allocatable()
        transitions = driver.state.drain_health_transitions()
        assert len(transitions) >= 2
        driver._maybe_elastic_resize(transitions)
        assert driver._m_elastic_resizes.value(
            direction="shrink", outcome="failed"
        ) >= 1
        assert driver._m_elastic_resizes.value(
            direction="shrink", outcome="ok"
        ) == 0
        driver.events.flush()
        assert any(
            ev["reason"] == "GangResizeFailed"
            and ev["involvedObject"]["name"] == "doomed"
            for ev in client.list(EVENTS)
        )
        # The claim's prepared record is untouched.
        view = driver.state.gang_view("uid-all")
        assert [n for n, _ in view["devices"]] == names

    def test_failed_resize_restores_allocator_reservations(
        self, tmp_path, monkeypatch
    ):
        """A re-solve that goes unsat at every size must put the
        allocator's reservations back: the claim keeps running on its
        prepared, exclusively-held devices, which must not be left
        looking free to the next solve."""
        from k8s_dra_driver_tpu.kube.allocator import AllocationError

        driver, client, lib, allocator = self._driver(tmp_path)
        driver.publish_resources()
        assert wait_for(lambda: len(client.list(RESOURCE_SLICES)) >= 1)
        claim = make_gang_claim(client, allocator, uid="uid-res",
                                name="res", count=2)
        assert prepare_via_rpc(driver, claim).error == ""
        names = [
            r["device"]
            for r in claim["status"]["allocation"]["devices"]["results"]
        ]
        keys = {("node-a", n) for n in names}
        assert all(
            allocator._reservations.get(k) == "uid-res" for k in keys
        )

        def unsat(*a, **k):
            raise AllocationError("forced unsat", reason="shortfall")

        monkeypatch.setattr(allocator, "allocate", unsat)
        lib.unplug_chip(int(names[1].split("-")[1]), reason="dead")
        driver.state.refresh_allocatable()
        driver._maybe_elastic_resize(
            driver.state.drain_health_transitions()
        )
        assert driver._m_elastic_resizes.value(
            direction="shrink", outcome="failed"
        ) >= 1
        # The gang (dead member included — the claim still nominally
        # holds it) is reserved again; nothing double-books it.
        assert all(
            allocator._reservations.get(k) == "uid-res" for k in keys
        )
        view = driver.state.gang_view("uid-res")
        assert [n for n, _ in view["devices"]] == names

    def test_device_class_from_checkpointed_types(self, tmp_path):
        """The re-solve DeviceClass comes from PreparedDevice.type, not
        from re-parsing device names — a tensorcore-partition gang must
        re-solve as tensorcores, and a mixed gang must refuse."""
        driver, client, lib, allocator = self._driver(tmp_path)
        driver.state.prepare(
            make_claim("uid-tc", ["tpu-0-core-0", "tpu-1-core-0"])
        )
        view = driver.state.gang_view("uid-tc")
        assert view["device_types"] == ["tensorcore"]
        assert driver._elastic_device_class(view) == (
            "tensorcore.tpu.google.com"
        )
        driver.state.prepare(
            make_claim("uid-mix", ["tpu-2", "tpu-3-core-0"], name="mix")
        )
        mixed = driver.state.gang_view("uid-mix")
        assert set(mixed["device_types"]) == {"chip", "tensorcore"}
        assert driver._elastic_device_class(mixed) is None

    @pytest.mark.slow
    def test_shrink_grow_soak(self, tmp_path):
        """Seeded unplug/restore cycles with a live trainer riding every
        resize; the auditor must read clean and the loss stay finite
        after each round."""
        import random

        import jax
        import numpy as np

        from k8s_dra_driver_tpu.models.llama import PRESETS
        from k8s_dra_driver_tpu.models.train import make_optimizer
        from k8s_dra_driver_tpu.parallel import MeshConfig
        from k8s_dra_driver_tpu.parallel.elastic import ElasticTrainer

        cfg = PRESETS["tiny"]
        jax_devices = jax.devices()
        rng = random.Random(SEED)
        driver, client, lib, allocator = self._driver(tmp_path)
        resizes = []
        driver.add_resize_listener(resizes.append)
        driver.start()
        try:
            assert wait_for(lambda: len(client.list(RESOURCE_SLICES)) >= 1)
            claim = make_gang_claim(client, allocator, uid="uid-soak",
                                    name="soak")
            assert prepare_via_rpc(driver, claim).error == ""
            opt = make_optimizer(warmup_steps=1, total_steps=100)
            trainer = ElasticTrainer(
                cfg, opt,
                [jax_devices[i] for i in range(4)],
                mesh_config=MeshConfig(data=2, tensor=2), global_batch=8,
            )
            step = 0
            for round_no in range(4):
                victim = rng.randrange(4)
                seen = len(resizes)
                lib.unplug_chip(victim, reason=f"soak round {round_no}")
                assert wait_for(lambda: len(resizes) > seen, timeout=15)
                trainer.resize(
                    [jax_devices[int(n.split("-")[1])]
                     for n in resizes[-1].devices],
                    reason=resizes[-1].reason,
                )
                for _ in range(2):
                    loss = trainer.step(jax.random.randint(
                        jax.random.PRNGKey(step), (8, 65), 0,
                        cfg.vocab_size,
                    ))
                    step += 1
                assert np.isfinite(loss)
                seen = len(resizes)
                lib.restore_chip(victim)
                assert wait_for(lambda: len(resizes) > seen, timeout=15)
                trainer.resize(
                    [jax_devices[int(n.split("-")[1])]
                     for n in resizes[-1].devices],
                    reason=resizes[-1].reason,
                )
                assert wait_for(
                    lambda: driver.auditor.run_once() == [], timeout=15
                )
            assert_invariants(driver.state)
        finally:
            driver.shutdown()


class TestElasticSnapshotDescent:
    """Satellite regression for the descending re-solve: the shrink
    loop's whole descent shares ONE allocator inventory snapshot (one
    apiserver read, not one per candidate size), while every attempt
    stays individually funnel-visible in /debug/allocations."""

    def test_descent_reuses_one_snapshot_with_funnel_per_attempt(
        self, tmp_path
    ):
        import threading

        from k8s_dra_driver_tpu.kube.allocator import ReferenceAllocator

        lib = FakeChipLib(generation="v5p", topology="4x1x1")
        driver, client, lib = make_driver(tmp_path, lib=lib, interval=0)
        allocator = ReferenceAllocator(client, registry=Registry())
        driver.enable_elastic(allocator)
        driver.start()
        real_list = client.list
        try:
            assert wait_for(lambda: len(client.list(RESOURCE_SLICES)) >= 1)
            claim = make_gang_claim(client, allocator)
            assert prepare_via_rpc(driver, claim).error == ""
            # Wedge the second chip: survivors {0,2,3} hold no
            # contiguous 3-box around the hole, so the descent must try
            # size 3 (unsat) before settling on the [2,3] pair.
            lib.wedge_chip(1, reason="snapshot descent test")
            assert driver.state.refresh_allocatable()
            transitions = driver.state.drain_health_transitions()
            assert transitions
            driver.publish_resources()

            me = threading.current_thread()
            list_calls = {"n": 0}

            def counting_list(*args, **kwargs):
                if threading.current_thread() is me:
                    list_calls["n"] += 1
                return real_list(*args, **kwargs)

            client.list = counting_list
            before = len(allocator.recent_decisions())
            driver._maybe_elastic_resize(transitions)
            attempts = allocator.recent_decisions()[before:]
            # Regression on attempt counts: each size of the descent is
            # its own decision record with its own funnel.
            assert [a["outcome"] for a in attempts] == ["unsat", "ok"]
            assert attempts[0]["funnels"][0]["wanted"] == 3
            assert attempts[0]["reason"] == "gang"
            assert attempts[1]["funnels"][0]["wanted"] == 2
            # The unhealthy chip was funnel-visible in both attempts.
            for a in attempts:
                assert a["funnels"][0]["rejected"].get("unhealthy") == 1
            # ONE inventory read (the snapshot's delta refresh) for the
            # whole descent — previously one full re-list per attempt.
            assert list_calls["n"] <= 1, (
                f"descent re-read the inventory {list_calls['n']} times"
            )
            view = driver.state.gang_view("uid-gang")
            assert [n for n, _ in view["devices"]] == ["tpu-2", "tpu-3"]
        finally:
            client.list = real_list
            driver.shutdown()


class TestElasticCrashConsistency:
    """The typed resize protocol's crash windows: the two-phase
    checkpoint (intent → apply → finalize) must roll forward at restart,
    and an intent recovery CANNOT complete must surface as the auditor's
    ``resize`` drift finding — never as silent corruption."""

    def _resize_results(self, names):
        return [
            {"request": "gang", "driver": DRIVER, "pool": "node-a",
             "device": n}
            for n in names
        ]

    def test_crash_before_intent_leaves_claim_untouched(self, tmp_path):
        lib = FakeChipLib(generation="v5p", topology="4x1x1")
        state, lib = make_state(tmp_path, lib=lib)
        state.prepare(make_claim("uid-r", ["tpu-0", "tpu-1", "tpu-2"]))
        plan = faults.FaultPlan().crash("checkpoint.write")
        with faults.armed(plan):
            with pytest.raises(faults.CrashPoint):
                state.resize_claim(
                    "uid-r", self._resize_results(["tpu-0", "tpu-1"])
                )
        restarted, _ = make_state(tmp_path, lib=lib)
        view = restarted.gang_view("uid-r")
        assert [n for n, _ in view["devices"]] == [
            "tpu-0", "tpu-1", "tpu-2"
        ]
        assert run_audit(restarted) == []
        assert_invariants(restarted)

    def test_crash_between_intent_and_finalize_rolls_forward(
        self, tmp_path
    ):
        """The narrowest window: intent checkpointed, holds/CDI
        rewritten, crash before the finalize write. Restart recovery
        re-applies the intent idempotently; the shrunken gang is the
        durable truth and the auditor reads clean."""
        lib = FakeChipLib(generation="v5p", topology="4x1x1")
        state, lib = make_state(tmp_path, lib=lib)
        state.prepare(make_claim("uid-r2", ["tpu-0", "tpu-1", "tpu-2"]))
        # checkpoint.write hit 1 = the resize intent, hit 2 = finalize.
        plan = faults.FaultPlan().crash("checkpoint.write", on_call=2)
        with faults.armed(plan):
            with pytest.raises(faults.CrashPoint):
                state.resize_claim(
                    "uid-r2", self._resize_results(["tpu-0", "tpu-1"]),
                    desired=3,
                )
        # The dead incarnation left the intent on disk.
        raw = CheckpointManager(str(tmp_path / "checkpoint.json")).read()
        assert "resize" in raw["uid-r2"]

        restarted, _ = make_state(tmp_path, lib=lib)
        view = restarted.gang_view("uid-r2")
        assert [n for n, _ in view["devices"]] == ["tpu-0", "tpu-1"]
        assert view["desired"] == 3
        assert "resize" not in restarted.checkpoint.read()["uid-r2"]
        # Startup consumers (the usage accountant's rebuild) must see
        # the ROLLED-FORWARD gang, not the pre-crash one.
        startup_names = [
            d["name"]
            for g in restarted.startup_prepared_records["uid-r2"]["groups"]
            for d in g["devices"]
        ]
        assert startup_names == ["tpu-0", "tpu-1"]
        assert run_audit(restarted) == []
        # The released chip is reusable immediately.
        restarted.prepare(make_claim("uid-n", ["tpu-2"], name="n"))
        assert_invariants(restarted)

    def test_failed_live_resize_rolls_back_intent(self, tmp_path):
        """A NON-crash apply failure (the added device is not
        allocatable) must roll the checkpointed intent BACK: the caller
        reports GangResizeFailed, so the claim must read exactly as it
        was — not leave perpetual 'resize' audit drift, and not leak or
        drop sharing holds."""
        lib = FakeChipLib(generation="v5p", topology="4x1x1")
        state, lib = make_state(tmp_path, lib=lib)
        state.prepare(make_claim("uid-rb", ["tpu-0", "tpu-1"]))
        held_before = {
            u: state.share_state.get(u).claims
            for u in state.share_state.list_chips()
        }
        with pytest.raises(GangResizeError, match="tpu-9"):
            state.resize_claim(
                "uid-rb",
                self._resize_results(["tpu-0", "tpu-1", "tpu-9"]),
            )
        rec = state.checkpoint.read()["uid-rb"]
        assert "resize" not in rec
        assert "elastic" not in rec  # a rollback is not a resize
        view = state.gang_view("uid-rb")
        assert [n for n, _ in view["devices"]] == ["tpu-0", "tpu-1"]
        assert run_audit(state) == []
        # The original exclusive holds survived the round-trip.
        held_after = {
            u: state.share_state.get(u).claims
            for u in state.share_state.list_chips()
        }
        assert held_after == held_before
        state.unprepare("uid-rb")
        assert run_audit(state) == []

    def test_rollback_after_partial_apply_restores_every_hold(
        self, tmp_path
    ):
        """The nastiest failure point: the apply has ALREADY released
        the removed device's hold and acquired the spare's when the CDI
        write fails. Rollback must re-acquire the removed device (or
        another claim double-books it) and release the spare (or it
        leaks to this claim forever) — checkpoint, CDI, and share state
        all back to the original gang."""
        lib = FakeChipLib(generation="v5p", topology="4x1x1")
        state, lib = make_state(tmp_path, lib=lib)
        state.prepare(make_claim("uid-ph", ["tpu-0", "tpu-1", "tpu-2"]))
        # Swap tpu-2 for the spare tpu-3; the claim-spec write (which
        # runs AFTER the hold rewrite) fails once, transiently.
        plan = faults.FaultPlan().fail(
            "cdi.claim-write", OSError("disk full"), times=1
        )
        with faults.armed(plan):
            # The ORIGINAL error surfaces (rollback never masks it).
            with pytest.raises(OSError, match="disk full"):
                state.resize_claim(
                    "uid-ph",
                    self._resize_results(["tpu-0", "tpu-1", "tpu-3"]),
                )
        rec = state.checkpoint.read()["uid-ph"]
        assert "resize" not in rec
        view = state.gang_view("uid-ph")
        assert [n for n, _ in view["devices"]] == [
            "tpu-0", "tpu-1", "tpu-2"
        ]
        assert run_audit(state) == []
        # tpu-2 is held again: a second claim cannot double-book it...
        uuid2 = chip_uuid_of(state, "tpu-2")
        assert "uid-ph" in state.share_state.get(uuid2).claims
        # ...and the spare's hold did not leak: a new claim prepares
        # tpu-3 cleanly.
        uuid3 = chip_uuid_of(state, "tpu-3")
        assert "uid-ph" not in state.share_state.get(uuid3).claims
        state.prepare(make_claim("uid-sp", ["tpu-3"], name="sp"))
        assert_invariants(state)

    def test_kept_devices_keep_their_request_names(self, tmp_path):
        """A resize whose results carry a different request name must
        not overwrite KEPT devices' checkpointed request names — kubelet
        matches devices to the ResourceClaim spec by these."""
        lib = FakeChipLib(generation="v5p", topology="4x1x1")
        state, lib = make_state(tmp_path, lib=lib)
        # make_claim names requests req-0/req-1/req-2 per device.
        state.prepare(make_claim("uid-rq", ["tpu-0", "tpu-1", "tpu-2"]))
        state.resize_claim(
            "uid-rq", self._resize_results(["tpu-0", "tpu-1"])
        )
        devices = state.cached_devices("uid-rq")
        assert [d.request_names for d in devices] == [["req-0"], ["req-1"]]

    def test_unrecoverable_intent_is_resize_drift(self, tmp_path):
        """An intent targeting a device that vanished while the plugin
        was down cannot roll forward: recovery leaves it in place and
        the auditor reports it under the ``resize`` check."""
        lib = FakeChipLib(generation="v5p", topology="4x1x1")
        state, lib = make_state(tmp_path, lib=lib)
        state.prepare(make_claim("uid-r3", ["tpu-0", "tpu-1"]))
        mgr = CheckpointManager(str(tmp_path / "checkpoint.json"))
        recs = mgr.read()
        recs["uid-r3"]["resize"] = {
            "to": ["tpu-0", "tpu-1", "tpu-9"],
            "requests": {},
            "startedAt": time.time(),
        }
        mgr.write(recs)
        del state

        restarted, _ = make_state(tmp_path, lib=lib)
        found = {(f.check, f.subject) for f in run_audit(restarted)}
        assert ("resize", "uid-r3") in found
        # The original gang is still intact and unprepares cleanly.
        restarted.unprepare("uid-r3")
        assert run_audit(restarted) == []


def make_process_shared_claim(uid, device="tpu-0", pct=30, hbm="4Gi"):
    """A ResourceClaim process-sharing one chip with a declared SLO —
    the rebalancer's subject matter."""
    return {
        "metadata": {"name": f"ps-{uid}", "namespace": "default",
                     "uid": uid},
        "status": {"allocation": {"devices": {"results": [{
            "request": "r0", "driver": DRIVER, "pool": "node-a",
            "device": device,
        }], "config": [{
            "requests": [], "source": "FromClaim",
            "opaque": {"driver": DRIVER, "parameters": {
                "apiVersion": "tpu.google.com/v1alpha1",
                "kind": "TpuChipConfig",
                "sharing": {
                    "strategy": "ProcessShared",
                    "processSharedConfig": {
                        "maxProcesses": 2,
                        "defaultActiveCorePercentage": pct,
                        "defaultHbmLimit": hbm,
                        "slo": {"latencyClass": "interactive",
                                "minTensorCorePercent": 20},
                    },
                },
            }},
        }]}}},
    }


class TestRebalanceCrashConsistency:
    """The limits-resize protocol's crash windows: the gang-resize
    two-phase checkpoint, extended from device-set changes to limit
    changes, must roll forward at restart with the sharing store, the
    limits file, and the checkpointed config all agreeing — the new
    ``sharing-limits`` audit check is the oracle."""

    def test_crash_before_intent_leaves_limits_untouched(self, tmp_path):
        state, lib = make_state(tmp_path)
        state.prepare(make_process_shared_claim("uid-l0", pct=30))
        plan = faults.FaultPlan().crash("checkpoint.write")
        with faults.armed(plan):
            with pytest.raises(faults.CrashPoint):
                state.resize_claim_limits(
                    "uid-l0", tensorcore_percent=60
                )
        restarted, _ = make_state(tmp_path, lib=lib)
        rec = restarted.checkpoint.read()["uid-l0"]
        psc = rec["groups"][0]["config"]["sharing"]["processSharedConfig"]
        assert psc["defaultActiveCorePercentage"] == 30
        assert "resize" not in rec
        assert run_audit(restarted) == []
        assert_invariants(restarted)

    def test_crash_between_intent_and_finalize_rolls_forward(
        self, tmp_path
    ):
        """The narrowest window: limits intent checkpointed, session
        re-rendered (store meta + limits file at generation 2), crash
        before the finalize write. Restart recovery re-applies the
        intent idempotently; the NEW limits are the durable truth in
        all three renderings and the auditor — including the
        sharing-limits cross-check — reads clean."""
        import json as _json
        import os as _os

        state, lib = make_state(tmp_path)
        state.prepare(make_process_shared_claim("uid-l1", pct=30))
        chip = chip_uuid_of(state, "tpu-0")
        # checkpoint.write hit 1 = the limits intent, hit 2 = finalize.
        plan = faults.FaultPlan().crash("checkpoint.write", on_call=2)
        with faults.armed(plan):
            with pytest.raises(faults.CrashPoint):
                state.resize_claim_limits(
                    "uid-l1", tensorcore_percent=60, hbm_limit="8Gi"
                )
        # The dead incarnation left the intent on disk.
        raw = CheckpointManager(
            str(tmp_path / "checkpoint.json")
        ).read()
        assert raw["uid-l1"]["resize"]["limits"] == {
            "tensorcorePercent": 60, "hbmLimit": "8Gi",
        }

        restarted, _ = make_state(tmp_path, lib=lib)
        rec = restarted.checkpoint.read()["uid-l1"]
        assert "resize" not in rec
        psc = rec["groups"][0]["config"]["sharing"]["processSharedConfig"]
        assert psc["defaultActiveCorePercentage"] == 60
        assert psc["defaultHbmLimit"] == "8Gi"
        # The dead incarnation already rendered generation 2 into the
        # limits file before the finalize crash; recovery must render
        # PAST it (a workload pinned at 2 would ignore a re-render AT
        # 2), and all three renderings must agree on the final number.
        gen = rec["sharing"]["generation"]
        assert gen >= 2
        meta = restarted.share_state.get(chip).claims["uid-l1"]
        assert meta["tensorcorePercent"] == 60
        assert meta["generation"] == gen
        run_dir = restarted.ps_manager.run_dir
        sess = [d for d in _os.listdir(run_dir)
                if d.startswith("uid-l1")]
        doc = _json.load(open(
            _os.path.join(run_dir, sess[0], "limits.json")
        ))
        assert doc["generation"] == gen
        assert doc["tensorcorePercent"] == 60
        # Zero drift: the sharing-limits check sees all three
        # renderings agreeing.
        assert run_audit(restarted) == []
        assert_invariants(restarted)
        restarted.unprepare("uid-l1")
        assert run_audit(restarted) == []

    def test_unfinished_intent_surfaces_as_resize_drift(self, tmp_path):
        """An intent recovery cannot complete (its session re-render
        keeps failing at restart) is LEFT ON DISK and surfaces as the
        auditor's resize finding — loud, never silent."""
        state, lib = make_state(tmp_path)
        state.prepare(make_process_shared_claim("uid-l2", pct=30))
        plan = faults.FaultPlan().crash("checkpoint.write", on_call=2)
        with faults.armed(plan):
            with pytest.raises(faults.CrashPoint):
                state.resize_claim_limits(
                    "uid-l2", tensorcore_percent=55
                )
        # Recovery's roll-forward fails too (simulated persistent
        # session-resize failure at startup).
        recovery_plan = faults.FaultPlan().fail(
            "rebalance.session-resize", OSError("still broken"),
            times=10,
        )
        with faults.armed(recovery_plan):
            restarted, _ = make_state(tmp_path, lib=lib)
            findings = run_audit(restarted)
        assert ("resize", "uid-l2") in [
            (f.check, f.subject) for f in findings
        ]
        # Once the condition clears, the next restart heals it.
        healed, _ = make_state(tmp_path, lib=lib)
        assert run_audit(healed) == []


class TestDefragCrashConsistency:
    """ISSUE 17 crash windows: the defrag executor's multi-claim intent
    protocol against the REAL node stack (DeviceState + CDI +
    checkpoint), StateAuditor as oracle. A crash at any ``defrag.*``
    site (or inside the mover's node-level resize) plus a restart
    converges — forward or back — leaving no orphaned holds, CDI
    specs, checkpoint records, or execution intent; drained serving
    replicas lose zero admitted requests; a relocated training gang
    keeps loss continuity."""

    def _frag_node(self, tmp_path):
        """Checkerboarded node-a: 4x1x1 slice, both middle chips held
        by movable single-chip claims that are ALSO prepared on the
        node (so a migration must rewrite holds/CDI/checkpoint through
        the elastic resize protocol), corners free — a 2-chip gang is
        unsat on fragmentation until a plan executes."""
        from test_allocator_explain import chip_claim, publish_host

        from k8s_dra_driver_tpu.kube.allocator import (
            ReferenceAllocator,
            Selector,
        )
        from k8s_dra_driver_tpu.kube.defrag import DefragPlanner

        client = FakeKubeClient()
        publish_host(client, "node-a", topology="4x1x1")
        reg = Registry()
        alloc = ReferenceAllocator(client, registry=reg)
        planner = DefragPlanner(alloc, registry=reg)
        lib = FakeChipLib(generation="v5p", topology="4x1x1")
        state, lib = make_state(tmp_path, lib=lib)
        for i, coord in enumerate(("1,0,0", "2,0,0")):
            alloc.allocate(
                chip_claim(f"uid-mid-{i}"),
                selectors={"r0": [Selector("coord", "eq", coord)]},
            )
            state.prepare(make_claim(
                f"uid-mid-{i}", [f"tpu-{i + 1}"], name=f"mid-{i}"
            ))
        return client, alloc, planner, state, lib

    def _stuck_plan(self, alloc, planner):
        from test_allocator_explain import chip_claim

        from k8s_dra_driver_tpu.kube.allocator import AllocationError

        with pytest.raises(AllocationError):
            alloc.allocate(chip_claim("uid-gang", count=2))
        plan = planner.recent_plans()[-1]
        assert plan["outcome"] == "planned"
        return plan

    def _executor(self, tmp_path, alloc, planner, state, gateway=None):
        from k8s_dra_driver_tpu.kube.defrag_executor import DefragExecutor

        return DefragExecutor(
            planner, alloc,
            intent_path=str(tmp_path / "defrag-intent.json"),
            state=state, gateway=gateway, registry=Registry(),
        )

    def _held_by(self, alloc, uid):
        return {n for (_, n), h in alloc._reservations.items() if h == uid}

    def _assert_converged(self, alloc, state, execu):
        """Allocator, node state, disk, and auditor all agree, and
        nothing defrag-related is orphaned."""
        assert len(self._held_by(alloc, "uid-gang")) == 2
        for uid in ("uid-mid-0", "uid-mid-1"):
            view = state.gang_view(uid)
            assert view is not None
            assert {n for n, _ in view["devices"]} == \
                self._held_by(alloc, uid)
        assert execu.orphaned_intent() is None
        auditor = StateAuditor(
            state=state, registry=Registry(), node_name="node-a"
        )
        auditor.defrag_executor = execu
        assert auditor.run_once() == []
        assert_invariants(state)

    def test_executed_plan_rewrites_node_state(self, tmp_path):
        """Baseline (no chaos): one executed plan un-strands the gang
        and the mover's node-local holds/CDI/checkpoint follow it."""
        client, alloc, planner, state, lib = self._frag_node(tmp_path)
        plan = self._stuck_plan(alloc, planner)
        mig = plan["migrations"][0]
        execu = self._executor(tmp_path, alloc, planner, state)
        record = execu.execute(plan)
        assert record["state"] == "completed"
        view = state.gang_view(mig["claimUid"])
        assert {n for n, _ in view["devices"]} == set(mig["to"])
        # The node-level resize finalized (no leftover intent there
        # either).
        assert "resize" not in state.checkpoint.read()[mig["claimUid"]]
        self._assert_converged(alloc, state, execu)

    @pytest.mark.parametrize("site", faults.sites_in("defrag."))
    def test_crash_at_each_site_restart_converges(self, tmp_path, site):
        """SIGKILL at every orchestration step, then the restarted
        plugin (fresh DeviceState from disk, fresh executor) recovers:
        the gang ends admitted, the auditor reads silent."""
        client, alloc, planner, state, lib = self._frag_node(tmp_path)
        plan = self._stuck_plan(alloc, planner)
        mig = plan["migrations"][0]
        execu = self._executor(tmp_path, alloc, planner, state)
        with faults.armed(faults.FaultPlan().crash(site)):
            with pytest.raises(faults.CrashPoint):
                execu.execute(plan)
        # Restart: node state re-reads checkpoint/CDI, executor
        # recovers the on-disk execution intent.
        restarted, _ = make_state(tmp_path, lib=lib)
        execu2 = self._executor(tmp_path, alloc, planner, restarted)
        rec = execu2.recover()
        if site == "defrag.intent-write":
            # Crash BEFORE the intent landed: nothing moved, nothing
            # to recover — the still-fresh plan executes normally.
            assert rec is None
            rec = execu2.execute(plan)
        assert rec["state"] == "completed"
        assert self._held_by(alloc, mig["claimUid"]) == set(mig["to"])
        self._assert_converged(alloc, restarted, execu2)

    @pytest.mark.parametrize("window", [1, 2])
    def test_crash_inside_the_movers_node_resize(self, tmp_path, window):
        """The deepest nesting: the crash lands inside the mover's
        two-phase node resize (checkpoint.write hit 1 = resize intent,
        hit 2 = finalize). The node protocol converges its own intent
        at restart; the executor's recovery then converges the plan on
        top of whichever way it went."""
        client, alloc, planner, state, lib = self._frag_node(tmp_path)
        plan = self._stuck_plan(alloc, planner)
        execu = self._executor(tmp_path, alloc, planner, state)
        fault = faults.FaultPlan().crash("checkpoint.write",
                                         on_call=window)
        with faults.armed(fault):
            with pytest.raises(faults.CrashPoint):
                execu.execute(plan)
        restarted, _ = make_state(tmp_path, lib=lib)
        execu2 = self._executor(tmp_path, alloc, planner, restarted)
        rec = execu2.recover()
        assert rec["state"] == "completed"
        self._assert_converged(alloc, restarted, execu2)

    def test_seeded_schedule_zero_admitted_loss(self, tmp_path):
        """A seeded fault schedule sprayed across the defrag.* family
        while both movers serve live traffic: every failed attempt
        rolls back clean (auditor silent between attempts), retries
        eventually admit the gang, and NO admitted request is ever
        lost — the acceptance criterion, pinned by seed."""
        from k8s_dra_driver_tpu.kube.defrag_executor import (
            DefragExecutionError,
        )
        from k8s_dra_driver_tpu.serving_gateway import ServingGateway
        from k8s_dra_driver_tpu.serving_gateway.sim import ScriptedEngine

        client, alloc, planner, state, lib = self._frag_node(tmp_path)
        gw = ServingGateway(Registry(), node_name="node-a")
        engines = {}
        for i in range(2):
            engines[i] = ScriptedEngine()
            gw.add_replica(engines[i], f"r-mid-{i}",
                           claim_uid=f"uid-mid-{i}")
        execu = self._executor(tmp_path, alloc, planner, state,
                               gateway=gw)
        reqs = [gw.submit([i] * 8, 2) for i in range(8)]
        gw.tick()  # some requests are admitted before the chaos starts

        schedule = faults.FaultPlan.seeded(
            SEED, faults.sites_in("defrag."), rounds=8, fail_rate=0.6
        )
        admitted = None
        with faults.armed(schedule):
            for _ in range(10):
                plan = self._stuck_plan(alloc, planner)
                try:
                    admitted = execu.execute(plan)
                    break
                except DefragExecutionError:
                    # Rolled back (or refused stale): the fleet must
                    # read exactly as consistent as before the attempt.
                    self._assert_rolled_back_clean(alloc, state, execu)
        assert admitted is not None and admitted["state"] == "completed"
        self._assert_converged(alloc, state, execu)
        # Zero admitted loss across the entire schedule.
        gw.run()
        assert all(r.state == "finished" for r in reqs)
        assert gw.counters["failed"] == 0
        for e in engines.values():
            e.assert_no_leaks()

    def _assert_rolled_back_clean(self, alloc, state, execu):
        assert execu.orphaned_intent() is None
        for uid in ("uid-mid-0", "uid-mid-1"):
            view = state.gang_view(uid)
            assert {n for n, _ in view["devices"]} == \
                self._held_by(alloc, uid)
        auditor = StateAuditor(
            state=state, registry=Registry(), node_name="node-a"
        )
        auditor.defrag_executor = execu
        assert auditor.run_once() == []

    def test_training_gang_keeps_loss_continuity(self, tmp_path):
        """The mover is a LIVE training gang: the migration listener
        live-reshards it via ElasticTrainer.relocate onto the planned
        destination, and its loss trajectory matches an uninterrupted
        run — no checkpoint restore, no lost step."""
        import jax
        import numpy as np

        from k8s_dra_driver_tpu.models.llama import PRESETS
        from k8s_dra_driver_tpu.models.train import (
            make_optimizer,
            state_shardings,
        )
        from k8s_dra_driver_tpu.parallel import MeshConfig
        from k8s_dra_driver_tpu.parallel.elastic import ElasticTrainer

        cfg = PRESETS["tiny"]
        jax_devices = jax.devices()
        assert len(jax_devices) >= 4
        client, alloc, planner, state, lib = self._frag_node(tmp_path)
        plan = self._stuck_plan(alloc, planner)
        mig = plan["migrations"][0]
        mover_uid = mig["claimUid"]

        def jax_devs(names):
            return [jax_devices[int(n.split("-")[1])] for n in names]

        opt = make_optimizer(warmup_steps=1, total_steps=10)
        trainer = ElasticTrainer(
            cfg, opt, jax_devs(mig["devices"]),
            mesh_config=MeshConfig(), global_batch=8,
        )
        reference = ElasticTrainer(
            cfg, opt, jax_devs(mig["devices"]),
            mesh_config=MeshConfig(), global_batch=8,
        )
        host_init = jax.tree.map(np.array, trainer.state)
        reference.state = jax.device_put(
            host_init, state_shardings(reference.state, reference.mesh)
        )
        toks = [
            jax.random.randint(
                jax.random.PRNGKey(200 + i), (8, 65), 0, cfg.vocab_size
            )
            for i in range(4)
        ]
        ref_losses = [reference.step(t) for t in toks]

        relocations = []
        execu = self._executor(tmp_path, alloc, planner, state)

        def on_migrate(uid, devices):
            if uid == mover_uid:
                relocations.append(trainer.relocate(
                    jax_devs(devices), reason="defrag migration"
                ))

        execu.add_migration_listener(on_migrate)
        losses = [trainer.step(t) for t in toks[:2]]
        record = execu.execute(plan)
        losses += [trainer.step(t) for t in toks[2:]]

        assert record["state"] == "completed"
        assert len(relocations) == 1
        assert relocations[0].path == "live", (
            "a defrag relocation must not touch the checkpoint"
        )
        np.testing.assert_allclose(losses, ref_losses,
                                   rtol=2e-4, atol=2e-4)
        self._assert_converged(alloc, state, execu)


class TestSeededSchedules:
    def test_acceptance_schedule_fixed_seed(self, tmp_path):
        run_acceptance_schedule(tmp_path, SEED)

    @pytest.mark.slow
    @pytest.mark.parametrize("seed", [SEED + i for i in range(1, 6)])
    def test_acceptance_schedule_seed_band(self, tmp_path, seed):
        run_acceptance_schedule(tmp_path, seed)

    @pytest.mark.slow
    def test_randomized_fault_soak(self, tmp_path):
        """Seeded random faults sprayed across every instrumented site
        while a prepare/unprepare/refresh workload runs; whatever the
        interleaving, a cleaner pass restores all four invariants."""
        import random

        rng = random.Random(SEED)
        state, lib = make_state(tmp_path)
        sites = ["checkpoint.write", "checkpoint.read", "cdi.claim-write",
                 "chiplib.enumerate", "kube.get"]
        for round_no in range(20):
            plan = faults.FaultPlan.seeded(
                rng.randrange(1 << 30), sites, rounds=4, fail_rate=0.5
            )
            uid = f"soak-{round_no}"
            with faults.armed(plan):
                try:
                    state.prepare(make_claim(
                        uid, [f"tpu-{rng.randrange(4)}"], name=uid
                    ))
                except faults.CrashPoint:
                    state, lib = make_state(tmp_path)
                except (faults.FaultError, PrepareError, OSError):
                    pass
                try:
                    state.refresh_allocatable()
                except faults.FaultError:
                    pass
                try:
                    state.unprepare(uid)
                except (faults.FaultError, OSError):
                    pass
                except faults.CrashPoint:
                    state, lib = make_state(tmp_path)
            assert_invariants_after_clean(state)
