"""SLO-aware dynamic sharing: the closed-loop rebalancer acceptance.

ROADMAP item 4: a bursty inference tenant with a latency SLO steals idle
TensorCores/HBM from a batch tenant through the hitless limits-resize
protocol, serves its burst, and gives the shares back when the batch
tenant applies pressure — with the state auditor asserting zero drift
across every resize, both workloads running continuously (the same shim
slot locks held throughout, no re-prepare), and the full decision trail
reconstructable from the /debug/rebalance snapshot plus the
``tpu_dra_slo_*`` metric families. Policy hysteresis/cool-down pinned by
a flap-storm test, and a seeded chaos schedule over the new
``sharing.*``/``rebalance.*`` fault sites passes with the auditor
silent.
"""

import json
import os

import pytest

from k8s_dra_driver_tpu.cdi import CDIHandler
from k8s_dra_driver_tpu.parallel.shim import (
    apply_sharing_env,
    poll_sharing_update,
    report_usage,
)
from k8s_dra_driver_tpu.plugin.audit import StateAuditor
from k8s_dra_driver_tpu.plugin.checkpoint import CheckpointManager
from k8s_dra_driver_tpu.plugin.device_state import (
    DeviceState,
    LimitResizeError,
)
from k8s_dra_driver_tpu.plugin.rebalancer import (
    ACTION_RESTORE_MIN,
    ACTION_RETURN,
    ACTION_STEAL_IDLE,
    OUTCOME_APPLIED,
    OUTCOME_COOLDOWN,
    OUTCOME_FAILED,
    OUTCOME_HYSTERESIS,
    FileDemandSource,
    MisoPolicy,
    Rebalancer,
)
from k8s_dra_driver_tpu.tpulib import FakeChipLib
from k8s_dra_driver_tpu.utils import faults
from k8s_dra_driver_tpu.utils.metrics import Registry

DRIVER = "tpu.google.com"
SEED = int(os.environ.get("TPU_DRA_CHAOS_SEED", "1234"))
GIB = 1 << 30


@pytest.fixture(autouse=True)
def _always_disarm():
    yield
    faults.disarm()


def shared_claim(uid, pct, hbm, slo, device="tpu-0", name=None):
    """A ResourceClaim (wire form) process-sharing one chip with a
    declared SLO."""
    return {
        "metadata": {"name": name or f"wl-{uid}", "namespace": "tenants",
                     "uid": uid},
        "status": {"allocation": {"devices": {"results": [{
            "request": "r0", "driver": DRIVER, "pool": "node-a",
            "device": device,
        }], "config": [{
            "requests": [], "source": "FromClaim",
            "opaque": {"driver": DRIVER, "parameters": {
                "apiVersion": "tpu.google.com/v1alpha1",
                "kind": "TpuChipConfig",
                "sharing": {
                    "strategy": "ProcessShared",
                    "processSharedConfig": {
                        "maxProcesses": 2,
                        "defaultActiveCorePercentage": pct,
                        "defaultHbmLimit": hbm,
                        "slo": slo,
                    },
                },
            }},
        }]}}},
    }


INFER_SLO = {
    "latencyClass": "realtime",
    "minTensorCorePercent": 30, "burstTensorCorePercent": 80,
    "minHbmPercent": 25, "burstHbmPercent": 75,
    "priority": 10,
}
BATCH_SLO = {
    "latencyClass": "batch",
    "minTensorCorePercent": 20, "burstTensorCorePercent": 100,
    "minHbmPercent": 25, "burstHbmPercent": 100,
}


def make_state(tmp_path):
    return DeviceState(
        chiplib=FakeChipLib(generation="v5e", topology="2x1x1"),
        cdi=CDIHandler(str(tmp_path / "cdi")),
        checkpoint=CheckpointManager(str(tmp_path / "checkpoint.json")),
        driver_name=DRIVER,
        pool_name="node-a",
        state_dir=str(tmp_path / "state"),
    )


def run_audit(state):
    """One auditor pass — the zero-drift oracle, including the new
    sharing-limits check."""
    return StateAuditor(
        state=state, registry=Registry(), node_name="node-a"
    ).run_once()


def session_dir(state, uid):
    run_dir = state.ps_manager.run_dir
    dirs = [d for d in os.listdir(run_dir) if d.startswith(uid)]
    assert len(dirs) == 1, dirs
    return os.path.join(run_dir, dirs[0])


def granted_shares(state, uid):
    rec = state.checkpoint.read()[uid]
    psc = (
        rec["groups"][0]["config"]["sharing"]["processSharedConfig"]
    )
    return (psc.get("defaultActiveCorePercentage"),
            psc.get("defaultHbmLimit"))


class TestAcceptance:
    """The cluster-sim scenario the ROADMAP names, end to end."""

    def _setup(self, tmp_path):
        state = make_state(tmp_path)
        state.prepare(shared_claim("uid-infer", 30, "4Gi", INFER_SLO,
                                   name="infer"))
        state.prepare(shared_claim("uid-batch", 70, "12Gi", BATCH_SLO,
                                   name="batch"))
        registry = Registry()
        demand = {}
        clock = [10_000.0]
        rebalancer = Rebalancer(
            state, registry, node_name="node-a",
            demand_source=lambda v: demand.get(v.claim_uid),
            clock=lambda: clock[0],
        )
        return state, registry, demand, clock, rebalancer

    def _workload(self, state, uid):
        """A simulated workload process of the claim: the shim applied
        once at startup (slot flock taken), then polled at step
        boundaries. Env mirrors what the container would see, with the
        shared dir pointing at the session dir's host path."""
        env = {
            "TPU_DRA_SHARING": "process-shared",
            "TPU_DRA_MAX_PROCESSES": "2",
            "TPU_DRA_SHARED_DIR": session_dir(state, uid),
            "TPU_DRA_CHIP_HBM_BYTES": str(16 * GIB),
        }
        rt = apply_sharing_env(env)
        assert rt is not None and rt.slot == 0
        return env, rt

    def test_burst_steal_and_return(self, tmp_path):
        state, registry, demand, clock, reb = self._setup(tmp_path)
        env_infer, rt_infer = self._workload(state, "uid-infer")
        env_batch, rt_batch = self._workload(state, "uid-batch")
        # The claim-level envelope starts at the prepare-time limits
        # (generation 1, observed by apply_sharing_env from the file).
        assert env_infer["XLA_PYTHON_CLIENT_MEM_FRACTION"] == "0.2500"
        assert env_batch["XLA_PYTHON_CLIENT_MEM_FRACTION"] == "0.7500"
        prepared_at = {
            uid: rec["preparedAt"]
            for uid, rec in state.checkpoint.read().items()
        }

        def tick():
            records = reb.run_once()
            clock[0] += 120.0  # beyond the policy cool-down
            assert run_audit(state) == []  # zero drift across EVERY resize
            return records

        # Phase 1 — the inference tenant bursts while batch is idle:
        # shares flow to infer up to its burst ceiling / batch's min.
        demand["uid-infer"] = {"busy": 1.0, "hbm": 1.0}
        demand["uid-batch"] = {"busy": 0.05, "hbm": 0.05}
        applied = []
        for _ in range(8):
            applied += [r for r in tick() if r["outcome"] == "applied"]
            if granted_shares(state, "uid-infer")[0] == 80:
                break
        tc, hbm = granted_shares(state, "uid-infer")
        assert tc == 80                      # burst ceiling respected
        assert hbm == "12288Mi"              # 75% of 16Gi
        tc_b, hbm_b = granted_shares(state, "uid-batch")
        assert tc_b == 20                    # donor floor respected
        assert hbm_b == "4096Mi"
        assert applied and all(
            r["action"] == ACTION_STEAL_IDLE for r in applied
        )

        # Both workloads observed the new generations at their step
        # boundaries — no restart, no re-prepare, slots still held.
        upd = poll_sharing_update(env_infer)
        assert upd is not None and upd.tensorcore_percent == 80
        assert env_infer["XLA_PYTHON_CLIENT_MEM_FRACTION"] == "0.7500"
        assert poll_sharing_update(env_infer) is None  # idempotent
        assert poll_sharing_update(env_batch).tensorcore_percent == 20
        assert env_batch["XLA_PYTHON_CLIENT_MEM_FRACTION"] == "0.2500"
        assert rt_infer._slot_lock is not None
        assert rt_batch._slot_lock is not None
        for uid, at in prepared_at.items():
            assert state.checkpoint.read()[uid]["preparedAt"] == at

        # Phase 2 — the batch tenant applies pressure while inference
        # idles: the stolen shares flow back (return-on-pressure).
        demand["uid-infer"] = {"busy": 0.02, "hbm": 0.02}
        demand["uid-batch"] = {"busy": 1.0, "hbm": 1.0}
        returned = []
        for _ in range(10):
            returned += [r for r in tick() if r["outcome"] == "applied"]
            if granted_shares(state, "uid-batch")[0] == 70:
                break
        assert granted_shares(state, "uid-batch") == (70, "12288Mi")
        tc, hbm = granted_shares(state, "uid-infer")
        assert tc == 30                      # infer's own min floor
        assert hbm == "4096Mi"
        assert returned and all(
            r["action"] == ACTION_RETURN for r in returned
        )
        assert poll_sharing_update(env_batch).tensorcore_percent == 70

        # The full decision trail is reconstructable from the snapshot
        # + metrics: every applied move is in the ring with its shares,
        # and the counters/gauges agree with the checkpointed truth.
        snap = reb.snapshot()
        ring_applied = [
            d for d in snap["decisions"] if d["outcome"] == "applied"
        ]
        assert len(ring_applied) == len(applied) + len(returned)
        assert reb._m_decisions.value(
            outcome=OUTCOME_APPLIED, action=ACTION_STEAL_IDLE
        ) == len(applied)
        assert reb._m_decisions.value(
            outcome=OUTCOME_APPLIED, action=ACTION_RETURN
        ) == len(returned)
        assert reb._m_granted.value(
            claim="uid-infer", resource="tensorcore") == 30
        assert reb._m_granted.value(
            claim="uid-batch", resource="tensorcore") == 70
        assert reb._m_min.value(
            claim="uid-infer", resource="tensorcore") == 30
        # Replaying the trail reproduces the final shares.
        final = {("uid-infer", "tensorcore"): 30,
                 ("uid-batch", "tensorcore"): 70}
        replay = {("uid-infer", "tensorcore"): 30,
                  ("uid-batch", "tensorcore"): 70}
        for d in snap["decisions"]:
            if d["outcome"] != "applied" or d["resource"] != "tensorcore":
                continue
            replay[(d["donor"]["claim"], "tensorcore")] = d["donor"]["to"]
            replay[(d["gainer"]["claim"], "tensorcore")] = d["gainer"]["to"]
        assert replay == final
        # No SLO violations: the mins were respected throughout.
        assert reb._m_violations.value(latency_class="realtime") == 0
        assert reb._m_violations.value(latency_class="batch") == 0
        rt_infer.release()
        rt_batch.release()

    def test_file_demand_source_closes_the_loop(self, tmp_path):
        """Demand published by the workload shim (report_usage) drives
        the same steal — the full production loop, no injection."""
        state, _registry, demand, clock, _ = self._setup(tmp_path)
        reb = Rebalancer(
            state, Registry(), node_name="node-a",
            demand_source=FileDemandSource(
                state.ps_manager.run_dir, clock=lambda: clock[0],
            ),
            clock=lambda: clock[0],
        )
        env_infer, rt_i = self._workload(state, "uid-infer")
        env_batch, rt_b = self._workload(state, "uid-batch")
        try:
            # No samples yet: demand unknown, nothing moves.
            assert reb.run_once() == []
            clock[0] += 120.0
            # Workloads report: infer hungry, batch idle.
            import time as _time
            real_offset = clock[0] - _time.time()
            assert report_usage(1.0, environ=env_infer)
            assert report_usage(0.0, environ=env_batch)
            # Freshness is wall-clock in report_usage but fake-clock in
            # the source; rewrite ts to the fake clock to keep the test
            # hermetic.
            for uid in ("uid-infer", "uid-batch"):
                p = os.path.join(
                    session_dir(state, uid), "usage-slot-0.json"
                )
                doc = json.load(open(p))
                doc["ts"] += real_offset
                json.dump(doc, open(p, "w"))
            records = reb.run_once()
            assert [r["outcome"] for r in records] == [OUTCOME_APPLIED]
            assert granted_shares(state, "uid-infer")[0] == 40
            assert run_audit(state) == []
        finally:
            rt_i.release()
            rt_b.release()


class TestPolicy:
    """The MISO-style policy knobs, pinned."""

    def _views(self, state):
        reb = Rebalancer(state, Registry(), demand_source=lambda v: None)
        return reb

    def test_hysteresis_band_blocks_noise(self, tmp_path):
        """Demand wandering inside the busy band moves nothing — the
        band IS the hysteresis."""
        state = make_state(tmp_path)
        state.prepare(shared_claim("uid-a", 50, "8Gi", INFER_SLO))
        state.prepare(shared_claim("uid-b", 50, "8Gi", BATCH_SLO))
        demand = {"uid-a": {"busy": 0.7}, "uid-b": {"busy": 0.5}}
        reb = Rebalancer(
            state, Registry(), node_name="node-a",
            demand_source=lambda v: demand.get(v.claim_uid),
        )
        for _ in range(5):
            assert reb.run_once() == []
        assert granted_shares(state, "uid-a")[0] == 50

    def test_flap_storm_is_bounded(self, tmp_path):
        """Oscillating load must produce a bounded number of applied
        rebalances: the cool-down pins the rate, and the skips are
        observable as cooldown decisions rather than silent."""
        state = make_state(tmp_path)
        state.prepare(shared_claim("uid-a", 50, "8Gi", INFER_SLO))
        state.prepare(shared_claim("uid-b", 50, "8Gi", BATCH_SLO))
        demand = {}
        clock = [50_000.0]
        reb = Rebalancer(
            state, Registry(), node_name="node-a",
            policy=MisoPolicy(cooldown_seconds=60.0),
            demand_source=lambda v: demand.get(v.claim_uid),
            clock=lambda: clock[0],
        )
        applied = cooldowns = 0
        ticks = 120
        for i in range(ticks):
            # Full flap every tick: the worst case for share stability.
            hot, cold = (("uid-a", "uid-b") if i % 2 == 0
                         else ("uid-b", "uid-a"))
            demand[hot] = {"busy": 1.0}
            demand[cold] = {"busy": 0.0}
            for r in reb.run_once():
                if r["outcome"] == OUTCOME_APPLIED:
                    applied += 1
                elif r["outcome"] == OUTCOME_COOLDOWN:
                    cooldowns += 1
            clock[0] += 1.0  # 1s ticks against a 60s cool-down
        # At most one applied move per cool-down window (+1 for the
        # very first move).
        assert applied <= ticks / 60.0 + 1, applied
        assert cooldowns > 0
        assert run_audit(state) == []

    def test_small_leftover_is_hysteresis_skipped(self, tmp_path):
        """A would-be move smaller than hysteresis_percent is recorded,
        not applied."""
        state = make_state(tmp_path)
        # Donor has only 3% headroom above its min.
        state.prepare(shared_claim("uid-a", 60, "8Gi", INFER_SLO))
        state.prepare(shared_claim("uid-b", 23, "8Gi", BATCH_SLO))
        demand = {"uid-a": {"busy": 1.0}, "uid-b": {"busy": 0.0}}
        reb = Rebalancer(
            state, Registry(), node_name="node-a",
            policy=MisoPolicy(hysteresis_percent=5),
            demand_source=lambda v: demand.get(v.claim_uid),
        )
        records = reb.run_once()
        assert [r["outcome"] for r in records] == [OUTCOME_HYSTERESIS]
        assert granted_shares(state, "uid-a")[0] == 60  # untouched

    def test_restore_min_bypasses_cooldown(self, tmp_path):
        """An SLO floor is not negotiable on a timer: a claim below its
        declared min is restored even inside the cool-down window."""
        state = make_state(tmp_path)
        # infer prepared BELOW its declared min of 30.
        state.prepare(shared_claim("uid-infer", 10, "4Gi", INFER_SLO))
        state.prepare(shared_claim("uid-batch", 90, "12Gi", BATCH_SLO))
        clock = [77_000.0]
        reb = Rebalancer(
            state, Registry(), node_name="node-a",
            demand_source=lambda v: None,  # demand unknown: still owed
            clock=lambda: clock[0],
        )
        records = reb.run_once()
        assert [r["action"] for r in records
                if r["outcome"] == OUTCOME_APPLIED] == [ACTION_RESTORE_MIN]
        assert granted_shares(state, "uid-infer")[0] == 30
        assert granted_shares(state, "uid-batch")[0] == 70
        assert run_audit(state) == []

    def test_violation_counted_after_grace(self, tmp_path):
        """A claim pinned below its min longer than its latency class
        allows increments the violation counter exactly once and shows
        in the snapshot's belowMinSeconds — the doctor's `slo` input."""
        state = make_state(tmp_path)
        # Both below-min-capable but no donor headroom anywhere: the
        # policy CANNOT heal (co-tenant at its own min), so the clock
        # runs.
        state.prepare(shared_claim("uid-infer", 10, "4Gi", INFER_SLO))
        state.prepare(shared_claim(
            "uid-batch", 20, "12Gi", BATCH_SLO))
        clock = [88_000.0]
        reb = Rebalancer(
            state, Registry(), node_name="node-a",
            demand_source=lambda v: None,
            clock=lambda: clock[0],
        )
        reb.run_once()
        assert reb._m_violations.value(latency_class="realtime") == 0
        clock[0] += 6.0  # realtime grace is 5s
        reb.run_once()
        assert reb._m_violations.value(latency_class="realtime") == 1
        clock[0] += 60.0
        reb.run_once()  # still violated: counted once, not re-counted
        assert reb._m_violations.value(latency_class="realtime") == 1
        snap = reb.snapshot()
        c = snap["claims"]["uid-infer"]
        assert c["belowMinSeconds"] > c["graceSeconds"]


class TestHitlessResize:
    """DeviceState.resize_claim_limits: the two-phase protocol extended
    from device-set changes to limit changes."""

    def test_two_phase_updates_all_three_renderings(self, tmp_path):
        state = make_state(tmp_path)
        state.prepare(shared_claim("uid-x", 30, "4Gi", INFER_SLO))
        out = state.resize_claim_limits(
            "uid-x", tensorcore_percent=55, hbm_limit="8Gi"
        )
        assert out["generation"] == 2
        # 1) checkpointed config
        assert granted_shares(state, "uid-x") == (55, "8Gi")
        rec = state.checkpoint.read()["uid-x"]
        assert rec["sharing"]["generation"] == 2
        assert "resize" not in rec
        # 2) store meta
        chip = state.allocatable["tpu-0"].chip.uuid
        meta = state.share_state.get(chip).claims["uid-x"]
        assert meta["tensorcorePercent"] == 55
        assert meta["hbmLimit"] == "8Gi"
        assert meta["generation"] == 2
        # 3) generation-stamped limits file
        doc = json.load(open(os.path.join(
            session_dir(state, "uid-x"), "limits.json"
        )))
        assert doc["generation"] == 2
        assert doc["tensorcorePercent"] == 55
        assert doc["hbmLimitBytes"] == 8 * GIB
        assert run_audit(state) == []

    def test_refuses_exclusive_claims(self, tmp_path):
        state = make_state(tmp_path)
        state.prepare({
            "metadata": {"name": "ex", "namespace": "d", "uid": "uid-ex"},
            "status": {"allocation": {"devices": {"results": [{
                "request": "r0", "driver": DRIVER, "pool": "node-a",
                "device": "tpu-1",
            }], "config": []}}},
        })
        with pytest.raises(LimitResizeError, match="ProcessShared"):
            state.resize_claim_limits("uid-ex", tensorcore_percent=50)
        with pytest.raises(LimitResizeError, match="not prepared"):
            state.resize_claim_limits("uid-nope", tensorcore_percent=50)

    def test_failed_apply_rolls_back(self, tmp_path):
        """A non-crash apply failure restores the original limits under
        a double generation bump (workloads that glimpsed the aborted
        render must re-apply the restored limits) — and the auditor
        stays silent."""
        state = make_state(tmp_path)
        state.prepare(shared_claim("uid-rb", 40, "4Gi", INFER_SLO))
        plan = faults.FaultPlan().fail(
            "rebalance.session-resize", OSError("disk full"), times=1
        )
        with faults.armed(plan):
            with pytest.raises(OSError, match="disk full"):
                state.resize_claim_limits("uid-rb", tensorcore_percent=70)
        rec = state.checkpoint.read()["uid-rb"]
        assert "resize" not in rec
        assert granted_shares(state, "uid-rb") == (40, "4Gi")
        assert rec["sharing"]["generation"] == 3  # 1 + the double bump
        chip = state.allocatable["tpu-0"].chip.uuid
        meta = state.share_state.get(chip).claims["uid-rb"]
        assert meta["tensorcorePercent"] == 40
        assert meta["generation"] == 3
        assert run_audit(state) == []

    def test_invalid_limits_are_typed_and_rolled_back(self, tmp_path):
        state = make_state(tmp_path)
        state.prepare(shared_claim("uid-v", 40, "4Gi", INFER_SLO))
        with pytest.raises(ValueError):
            state.resize_claim_limits("uid-v", tensorcore_percent=200)
        assert granted_shares(state, "uid-v") == (40, "4Gi")
        assert run_audit(state) == []


class TestAuditDriftDetection:
    def test_half_applied_rebalance_is_drift_not_silence(self, tmp_path):
        """Store meta disagreeing with the checkpointed limits — the
        state a crash could leave if it escaped the two-phase protocol
        — must surface as a sharing-limits finding."""
        state = make_state(tmp_path)
        state.prepare(shared_claim("uid-h", 30, "4Gi", INFER_SLO))
        assert run_audit(state) == []
        chip = state.allocatable["tpu-0"].chip.uuid
        meta = dict(state.share_state.get(chip).claims["uid-h"])
        meta["tensorcorePercent"] = 99  # the half-applied limit
        state.share_state.acquire(
            chip, "uid-h", "process-shared", meta
        )
        findings = run_audit(state)
        assert [f.check for f in findings] == ["sharing-limits"]
        assert "uid-h" in findings[0].subject or \
            findings[0].subject == "uid-h"

    def test_missing_hold_is_drift(self, tmp_path):
        state = make_state(tmp_path)
        state.prepare(shared_claim("uid-m", 30, "4Gi", INFER_SLO))
        chip = state.allocatable["tpu-0"].chip.uuid
        state.share_state.release(chip, "uid-m")
        findings = run_audit(state)
        assert "sharing-limits" in [f.check for f in findings]


class TestChaosSchedule:
    def test_seeded_schedule_over_rebalance_sites(self, tmp_path):
        """A seeded fault schedule over the sharing.*/rebalance.* sites:
        injected failures may fail individual decisions (reported as
        outcome=failed, never raised into the loop), and after the storm
        a restarted plugin's recovery leaves ZERO drift."""
        state = make_state(tmp_path)
        state.prepare(shared_claim("uid-infer", 30, "4Gi", INFER_SLO))
        state.prepare(shared_claim("uid-batch", 70, "12Gi", BATCH_SLO))
        demand = {}
        clock = [99_000.0]
        reb = Rebalancer(
            state, Registry(), node_name="node-a",
            demand_source=lambda v: demand.get(v.claim_uid),
            clock=lambda: clock[0],
        )
        sites = faults.sites_in("sharing.", "rebalance.")
        assert set(sites) == {
            "sharing.state-write", "rebalance.session-resize",
            "rebalance.shim-apply",
        }
        plan = faults.FaultPlan.seeded(
            SEED, sites, rounds=12, fail_rate=0.6, max_call=4
        )
        outcomes = []
        with faults.armed(plan):
            for i in range(10):
                hot, cold = (("uid-infer", "uid-batch") if i % 2 == 0
                             else ("uid-batch", "uid-infer"))
                demand[hot] = {"busy": 1.0}
                demand[cold] = {"busy": 0.0}
                outcomes += [r["outcome"] for r in reb.run_once()]
                clock[0] += 120.0
        # The loop survived every injection; failures were reported
        # in-band as decision outcomes, not raised.
        assert outcomes
        # Restart recovery (rolls any crash-left intent forward), then
        # the auditor must be silent.
        restarted = make_state(tmp_path)
        assert run_audit(restarted) == []


class TestReviewRegressions:
    """Review-found policy/view edge cases, pinned."""

    def test_damped_donor_does_not_shadow_viable_one(self, tmp_path):
        """A first-ranked donor whose headroom is below the hysteresis
        floor must not block the scan: the next donor with real
        headroom serves the needy tenant the same tick."""
        state = make_state(tmp_path)
        state.prepare(shared_claim("uid-needy", 30, "4Gi", INFER_SLO))
        # Donor A: 3% above its min (below the 5% hysteresis), idlest
        # and so sorted first.
        state.prepare(shared_claim("uid-donor-a", 23, "4Gi", {
            "latencyClass": "batch", "minTensorCorePercent": 20,
        }))
        # Donor B: 27% of headroom, slightly busier than A.
        state.prepare(shared_claim("uid-donor-b", 47, "8Gi", {
            "latencyClass": "batch", "minTensorCorePercent": 20,
        }))
        demand = {
            "uid-needy": {"busy": 1.0},
            "uid-donor-a": {"busy": 0.0},
            "uid-donor-b": {"busy": 0.1},
        }
        reb = Rebalancer(
            state, Registry(), node_name="node-a",
            demand_source=lambda v: demand.get(v.claim_uid),
        )
        records = reb.run_once()
        applied = [r for r in records if r["outcome"] == OUTCOME_APPLIED]
        assert len(applied) == 1
        assert applied[0]["donor"]["claim"] == "uid-donor-b"
        assert granted_shares(state, "uid-needy")[0] == 40
        assert granted_shares(state, "uid-donor-a")[0] == 23  # untouched
        assert granted_shares(state, "uid-donor-b")[0] == 37
        assert run_audit(state) == []

    def test_failed_gainer_restores_donor_and_cools_down(self, tmp_path):
        """A persistently failing gainer must not drain the donor one
        step per tick: the donor's share is given back and the pair
        cools down instead of retrying every tick."""
        state = make_state(tmp_path)
        state.prepare(shared_claim("uid-infer", 30, "4Gi", INFER_SLO))
        state.prepare(shared_claim("uid-batch", 70, "12Gi", BATCH_SLO))
        orig = state.resize_claim_limits

        def flaky(uid, **kw):
            if uid == "uid-infer":
                raise OSError("gainer session broken")
            return orig(uid, **kw)

        state.resize_claim_limits = flaky
        demand = {"uid-infer": {"busy": 1.0},
                  "uid-batch": {"busy": 0.0}}
        clock = [200_000.0]
        reb = Rebalancer(
            state, Registry(), node_name="node-a",
            demand_source=lambda v: demand.get(v.claim_uid),
            clock=lambda: clock[0],
        )
        records = reb.run_once()
        assert [r["outcome"] for r in records] == [OUTCOME_FAILED]
        assert "donor share restored" in records[0]["detail"]
        assert granted_shares(state, "uid-batch")[0] == 70  # restored
        # Inside the cool-down the move is NOT re-attempted.
        clock[0] += 1.0
        records = reb.run_once()
        assert [r["outcome"] for r in records] == [OUTCOME_COOLDOWN]
        assert granted_shares(state, "uid-batch")[0] == 70
        assert run_audit(state) == []

    def test_transiently_absent_device_keeps_hbm_view(self, tmp_path):
        """A prepared claim whose device is mid-rebind (absent from
        allocatable, pinned in the base spec) must keep its HBM share
        view — not read as an uncapped donor whose every move renders a
        0-byte limit."""
        state = make_state(tmp_path)
        state.prepare(shared_claim("uid-pin", 30, "4Gi", INFER_SLO))
        state.allocatable = {
            k: v for k, v in state.allocatable.items() if k != "tpu-0"
        }
        reb = Rebalancer(
            state, Registry(), node_name="node-a",
            demand_source=lambda v: None,
        )
        views = reb._claim_views()
        assert len(views) == 1
        assert views[0].chip_hbm_bytes == 16 * GIB
        assert views[0].granted["hbm"] == 25


class TestLegacyMeta:
    def test_pre_upgrade_store_meta_is_not_drift(self, tmp_path):
        """A hold written by a pre-limits-resize binary (meta was just
        {"maxProcesses": N}) on a never-rebalanced claim is legacy
        rendering, not a half-applied rebalance."""
        state = make_state(tmp_path)
        state.prepare(shared_claim("uid-old", 30, "4Gi", INFER_SLO))
        chip = state.allocatable["tpu-0"].chip.uuid
        state.share_state.acquire(
            chip, "uid-old", "process-shared", {"maxProcesses": 2}
        )
        assert run_audit(state) == []
        # ...but a legacy hold with the WRONG maxProcesses still drifts.
        state.share_state.acquire(
            chip, "uid-old", "process-shared", {"maxProcesses": 5}
        )
        assert [f.check for f in run_audit(state)] == ["sharing-limits"]


class TestRoundThreeRegressions:
    def test_hbm_restore_replays_exact_original_limit(self, tmp_path):
        """Restoring a donor after a failed gainer grow must replay the
        ORIGINAL checkpointed quantity, not the rounded-percent
        round-trip ('5Gi' -> 31% -> '5080Mi')."""
        state = make_state(tmp_path)
        # HBM-only SLOs (no tensorcore floor), so only hbm moves.
        state.prepare(shared_claim("uid-infer", None, "4Gi", {
            "latencyClass": "realtime", "minHbmPercent": 25,
            "burstHbmPercent": 75, "priority": 10,
        }))
        state.prepare(shared_claim("uid-batch", None, "5Gi", {
            "latencyClass": "batch", "minHbmPercent": 25,
        }))
        orig = state.resize_claim_limits

        def flaky(uid, **kw):
            if uid == "uid-infer":
                raise OSError("gainer session broken")
            return orig(uid, **kw)

        state.resize_claim_limits = flaky
        demand = {"uid-infer": {"busy": 0.5, "hbm": 1.0},
                  "uid-batch": {"busy": 0.5, "hbm": 0.0}}
        reb = Rebalancer(
            state, Registry(), node_name="node-a",
            demand_source=lambda v: demand.get(v.claim_uid),
        )
        records = reb.run_once()
        assert [r["outcome"] for r in records] == [OUTCOME_FAILED]
        assert "donor share restored" in records[0]["detail"]
        # The exact original quantity, not a percent round-trip.
        assert granted_shares(state, "uid-batch")[1] == "5Gi"
        assert run_audit(state) == []

    def test_departed_claim_gauge_series_are_dropped(self, tmp_path):
        """Claim uids are unique per claim lifetime: a departed claim's
        granted/min series must leave /metrics, not accumulate as
        zeroed series forever."""
        state = make_state(tmp_path)
        state.prepare(shared_claim("uid-gone", 30, "4Gi", INFER_SLO))
        reb = Rebalancer(
            state, Registry(), node_name="node-a",
            demand_source=lambda v: None,
        )
        reb.run_once()
        assert 'claim="uid-gone"' in "\n".join(reb._m_granted.render())
        state.unprepare("uid-gone")
        reb.run_once()
        assert 'claim="uid-gone"' not in "\n".join(
            reb._m_granted.render()
        )
        assert 'claim="uid-gone"' not in "\n".join(reb._m_min.render())
