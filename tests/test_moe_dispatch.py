"""Fused ragged MoE dispatch (ops/moe_dispatch.py): plan invariants,
kernel-vs-oracle parity (forward and custom VJP), uninitialized-tail
masking, int8 fusion, the grouped-kernel chooser, and the ring_permute
remote-DMA primitive.

Kernels run in interpret mode on CPU (same code path the TPU compiles),
forced via ``force_pallas`` — the repo-wide kernel-testing convention.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k8s_dra_driver_tpu.ops import moe_dispatch as md


def _problem(seed=0, t=24, h=32, e=4, k=2, m=16, foreign_frac=0.0,
             skew=False):
    rng = np.random.RandomState(seed)
    xf = jnp.asarray(rng.randn(t, h), jnp.float32)
    w_gu = jnp.asarray(rng.randn(e, h, 2, m) * 0.1, jnp.float32)
    w_down = jnp.asarray(rng.randn(e, m, h) * 0.1, jnp.float32)
    if skew:
        experts = np.zeros(t * k, np.int32)        # everything on expert 0
    else:
        experts = rng.randint(0, e, size=(t * k,)).astype(np.int32)
    if foreign_frac:
        mask = rng.rand(t * k) < foreign_frac
        experts = np.where(mask, e + 7, experts)   # foreign sentinel
    experts = jnp.asarray(experts)
    gates = jnp.asarray(rng.rand(t * k), jnp.float32)
    return xf, w_gu, w_down, experts, gates, (t, h, e, k, m)


class TestBuildPlan:
    def test_partition_invariants(self):
        *_, experts, _, (t, h, e, k, m) = _problem(seed=1)
        plan = md.build_plan(experts, t, e, k, tile_rows=8)
        row_ids = np.asarray(plan.row_ids)
        pair_ids = np.asarray(plan.pair_ids)
        slot = np.asarray(plan.slot_of_pair)
        # Every pair owns exactly one slot, and the maps are inverse.
        assert sorted(pair_ids[pair_ids < t * k]) == list(range(t * k))
        for p in range(t * k):
            assert pair_ids[slot[p]] == p
            assert row_ids[slot[p]] == p // k
        # Every m-tile holds rows of exactly one expert.
        te = np.asarray(plan.tile_expert)
        experts_np = np.asarray(experts)
        for tile in range(plan.r_pad // plan.tile_rows):
            rows = pair_ids[tile * plan.tile_rows:(tile + 1) * plan.tile_rows]
            owners = {experts_np[p] for p in rows[rows < t * k]}
            assert owners <= {te[tile]}, (tile, owners, te[tile])
        # Group regions are tile-aligned.
        assert (np.asarray(plan.sizes_aligned) % plan.tile_rows == 0).all()

    def test_foreign_pairs_get_no_slot(self):
        *_, experts, _, (t, h, e, k, m) = _problem(seed=2,
                                                   foreign_frac=0.5)
        plan = md.build_plan(experts, t, e, k, tile_rows=8)
        slot = np.asarray(plan.slot_of_pair)
        foreign = np.asarray(experts) >= e
        assert (slot[foreign] == plan.r_pad).all()
        assert (slot[~foreign] < plan.r_pad).all()
        # Local pairs still form an exact partition.
        pair_ids = np.asarray(plan.pair_ids)
        live = sorted(pair_ids[pair_ids < t * k])
        assert live == sorted(np.nonzero(~foreign)[0].tolist())

    def test_stable_within_expert(self):
        """Pair order within an expert region is token order — the
        deterministic tie-break impl-parity tests rely on."""
        *_, experts, _, (t, h, e, k, m) = _problem(seed=3)
        plan = md.build_plan(experts, t, e, k, tile_rows=8)
        pair_ids = np.asarray(plan.pair_ids)
        for g in range(e):
            rows = [p for p in pair_ids[pair_ids < t * k]
                    if np.asarray(experts)[p] == g]
            assert rows == sorted(rows)


class TestFusedVsOracle:
    def _run_both(self, seed=0, **kw):
        xf, w_gu, w_down, experts, gates, (t, h, e, k, m) = _problem(
            seed=seed, **kw
        )
        plan = md.build_plan(experts, t, e, k, tile_rows=8)
        ref = md.reference_moe_mlp(xf, w_gu, w_down, gates, plan)
        fused = md.fused_moe_mlp(
            xf, w_gu, w_down, gates, plan,
            force_pallas=True, interpret=True,
        )
        return ref, fused, experts, e

    def test_forward_matches_reference(self):
        ref, fused, *_ = self._run_both(seed=4)
        np.testing.assert_allclose(
            np.asarray(fused), np.asarray(ref), atol=1e-5, rtol=1e-5
        )

    def test_forward_under_jit(self):
        xf, w_gu, w_down, experts, gates, (t, h, e, k, m) = _problem(5)
        plan = md.build_plan(experts, t, e, k, tile_rows=8)
        ref = md.reference_moe_mlp(xf, w_gu, w_down, gates, plan)
        fused = jax.jit(
            lambda *a: md.fused_moe_mlp(
                *a, plan, force_pallas=True, interpret=True
            )
        )(xf, w_gu, w_down, gates)
        np.testing.assert_allclose(
            np.asarray(fused), np.asarray(ref), atol=1e-5, rtol=1e-5
        )

    def test_empty_experts_and_skew(self):
        """All pairs on one expert: the worst-case layout (three empty
        groups, one maximal) that exercises tile-aligned gaps."""
        ref, fused, *_ = self._run_both(seed=6, skew=True)
        np.testing.assert_allclose(
            np.asarray(fused), np.asarray(ref), atol=1e-5, rtol=1e-5
        )

    def test_foreign_tail_slots_are_zero(self):
        """The EP local view: foreign pairs must come back EXACTLY zero
        (the combine kernel's scatter skips them and the zero-aliased
        output guarantees it) — uninitialized tails here are the
        moe.py VJP-hazard class."""
        ref, fused, experts, e = self._run_both(seed=7, foreign_frac=0.5)
        foreign = np.asarray(experts) >= e
        assert (np.asarray(fused)[foreign] == 0).all()
        np.testing.assert_allclose(
            np.asarray(fused), np.asarray(ref), atol=1e-5, rtol=1e-5
        )

    @pytest.mark.parametrize("foreign_frac", [0.0, 0.5])
    def test_grads_match_reference_autodiff(self, foreign_frac):
        """The custom VJP against jax autodiff of the pure-XLA oracle,
        for every differentiable input — including the foreign-tail
        case, where megablox-style uninitialized rows would corrupt the
        router gradient if the backward didn't mask through the same
        index maps."""
        xf, w_gu, w_down, experts, gates, (t, h, e, k, m) = _problem(
            seed=8, foreign_frac=foreign_frac
        )
        plan = md.build_plan(experts, t, e, k, tile_rows=8)
        rng = np.random.RandomState(9)
        cot = jnp.asarray(rng.randn(t * k, h), jnp.float32)

        ref_grads = jax.grad(
            lambda *a: jnp.sum(
                md.reference_moe_mlp(*a, plan) * cot
            ),
            argnums=(0, 1, 2, 3),
        )(xf, w_gu, w_down, gates)
        fused_grads = jax.grad(
            lambda *a: jnp.sum(
                md.fused_moe_mlp(
                    *a, plan, force_pallas=True, interpret=True
                ) * cot
            ),
            argnums=(0, 1, 2, 3),
        )(xf, w_gu, w_down, gates)
        for name, a, b in zip(
            ("dxf", "dw_gu", "dw_down", "dgates"), ref_grads, fused_grads
        ):
            np.testing.assert_allclose(
                np.asarray(b), np.asarray(a), atol=2e-5, rtol=2e-4,
                err_msg=name,
            )

    def test_grads_finite_for_all_foreign(self):
        """A shard that owns NO pair this step (every expert foreign)
        must produce zero output and zero — not NaN/garbage — grads."""
        xf, w_gu, w_down, experts, gates, (t, h, e, k, m) = _problem(10)
        all_foreign = jnp.full_like(experts, e + 1)
        plan = md.build_plan(all_foreign, t, e, k, tile_rows=8)
        out, grads = jax.value_and_grad(
            lambda x: jnp.sum(md.fused_moe_mlp(
                x, w_gu, w_down, gates, plan,
                force_pallas=True, interpret=True,
            ))
        )(xf)
        assert float(out) == 0.0
        assert (np.asarray(grads) == 0).all()


class TestQuantFusion:
    def test_int8_fused_matches_int8_reference(self):
        from k8s_dra_driver_tpu.models.quant import quantize_tensor

        xf, w_gu, w_down, experts, gates, (t, h, e, k, m) = _problem(11)
        plan = md.build_plan(experts, t, e, k, tile_rows=8)
        q_gu = quantize_tensor(w_gu, axis=1)
        q_dn = quantize_tensor(w_down, axis=1)
        ref = md.reference_moe_mlp(xf, q_gu, q_dn, gates, plan)
        fused = md.fused_moe_mlp(
            xf, q_gu, q_dn, gates, plan,
            force_pallas=True, interpret=True,
        )
        np.testing.assert_allclose(
            np.asarray(fused), np.asarray(ref), atol=1e-5, rtol=1e-5
        )

    def test_int8_within_bf16_parity_of_float(self):
        """The satellite contract: int8 INSIDE the fusion stays within
        quantization tolerance of the float pipeline (no accuracy cliff
        from keeping the weights int8 into the dots)."""
        from k8s_dra_driver_tpu.models.quant import quantize_tensor

        xf, w_gu, w_down, experts, gates, _ = _problem(12)
        plan = md.build_plan(experts, xf.shape[0], 4, 2, tile_rows=8)
        full = md.reference_moe_mlp(xf, w_gu, w_down, gates, plan)
        fused = md.fused_moe_mlp(
            xf, quantize_tensor(w_gu, axis=1),
            quantize_tensor(w_down, axis=1), gates, plan,
            force_pallas=True, interpret=True,
        )
        denom = float(jnp.linalg.norm(full)) or 1.0
        rel = float(jnp.linalg.norm(fused - full)) / denom
        assert rel < 0.05, rel


class TestGroupedKernelChooser:
    def test_prime_rows_short_circuit(self):
        """No tile >= 8 divides a prime row count: the chooser must go
        straight to ragged_dot, not walk tm down to 1."""
        assert md.pick_m_tile(7919) is None
        assert md.pick_m_tile(17) is None

    def test_divisor_aware_tile(self):
        assert md.pick_m_tile(4096) == 512
        assert md.pick_m_tile(24) == 24
        assert md.pick_m_tile(1200) == 400   # largest 8k | m that is <= 512
        assert md.pick_m_tile(8) == 8

    def test_label_reports_backend_choice(self):
        # On CPU everything is the primitive.
        assert md.grouped_matmul_label(1024, 128, 256) == "ragged_dot"

    def test_grouped_matmul_matches_ragged_dot(self):
        rng = np.random.RandomState(13)
        lhs = jnp.asarray(rng.randn(24, 16), jnp.float32)
        rhs = jnp.asarray(rng.randn(3, 16, 8), jnp.float32)
        gs = jnp.asarray([10, 0, 9], jnp.int32)
        out = md.grouped_matmul(lhs, rhs, gs)
        ref = jax.lax.ragged_dot(lhs, rhs, gs)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref))

    def test_grouped_matmul_int8_stays_int8(self):
        from k8s_dra_driver_tpu.models.quant import quantize_tensor

        rng = np.random.RandomState(14)
        lhs = jnp.asarray(rng.randn(24, 16), jnp.float32)
        rhs = jnp.asarray(rng.randn(3, 16, 8), jnp.float32)
        gs = jnp.asarray([10, 5, 9], jnp.int32)
        qt = quantize_tensor(rhs, axis=1)
        out = md.grouped_matmul(lhs, qt, gs)
        # Oracle: dequantize first (the OLD formulation).
        ref = jax.lax.ragged_dot(
            lhs, qt.q.astype(jnp.float32) * qt.scale, gs
        )
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=1e-4, rtol=1e-4
        )

    def test_weight_grad_reference(self):
        rng = np.random.RandomState(15)
        rows, kk, nn, e = 24, 8, 6, 3
        lhs = jnp.asarray(rng.randn(rows, kk), jnp.float32)
        rhs = jnp.asarray(rng.randn(rows, nn), jnp.float32)
        sizes = np.array([8, 8, 8], np.int32)
        row_group = jnp.asarray(np.repeat(np.arange(e), 8), jnp.int32)
        out = md.grouped_weight_grad(
            lhs, rhs, jnp.asarray(sizes), row_group, e, use_pallas=False
        )
        for g in range(e):
            sl = slice(8 * g, 8 * (g + 1))
            np.testing.assert_allclose(
                np.asarray(out[g]),
                np.asarray(lhs[sl].T @ rhs[sl]),
                atol=1e-5, rtol=1e-5,
            )


class TestRingPermute:
    """The remote-DMA ring primitive (parallel/ring.py): interpret-mode
    kernel on a single-axis mesh (the jax interpret backend's remote-DMA
    constraint; composed meshes ride lax.ppermute — covered by the
    ring-EP tests in test_moe.py)."""

    def _mesh(self, n=4):
        if len(jax.devices()) < n:
            pytest.skip(f"needs {n} virtual devices")
        return jax.make_mesh((n,), ("expert",))

    @pytest.mark.parametrize("impl", ["xla", "pallas"])
    def test_rotation(self, impl):
        from k8s_dra_driver_tpu.parallel.compat import shard_map_compat
        from k8s_dra_driver_tpu.parallel.ring import ring_permute

        mesh = self._mesh()
        P = jax.sharding.PartitionSpec
        x = jnp.arange(4 * 8 * 16, dtype=jnp.float32).reshape(4, 8, 16)
        fn = shard_map_compat(
            lambda xs: ring_permute(
                xs[0], "expert", 4, impl=impl, interpret=True
            )[None],
            mesh=mesh,
            in_specs=P("expert"),
            out_specs=P("expert"),
            check_vma=False,
        )
        out = jax.jit(fn)(x)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(jnp.roll(x, 1, axis=0))
        )

    @pytest.mark.parametrize("impl", ["xla", "pallas"])
    def test_vjp_is_inverse_rotation(self, impl):
        from k8s_dra_driver_tpu.parallel.compat import shard_map_compat
        from k8s_dra_driver_tpu.parallel.ring import ring_permute

        mesh = self._mesh()
        P = jax.sharding.PartitionSpec
        x = jnp.arange(4 * 4 * 8, dtype=jnp.float32).reshape(4, 4, 8)
        w = jnp.asarray(
            np.random.RandomState(16).randn(4, 4, 8), jnp.float32
        )

        def loss(xs):
            fn = shard_map_compat(
                lambda a, b: (ring_permute(
                    a[0], "expert", 4, impl=impl, interpret=True
                )[None] * b).sum()[None],
                mesh=mesh,
                in_specs=(P("expert"), P("expert")),
                out_specs=P("expert"),
                check_vma=False,
            )
            return fn(xs, w).sum()

        g = jax.jit(jax.grad(loss))(x)
        # d/dx sum(rot(x) * w) = rot^{-1}(w).
        np.testing.assert_allclose(
            np.asarray(g), np.asarray(jnp.roll(w, -1, axis=0))
        )
