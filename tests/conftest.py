"""Test-wide configuration.

All tests run on CPU with a virtual 8-device platform so that multi-chip
sharding paths (dp/fsdp/tp/sp meshes, ring attention, collectives) compile and
execute without TPU hardware.  This is the testing seam the reference lacked
(SURVEY.md §4): its only integration story was "run on real GPUs".
"""

import os

# Must be set before jax is imported anywhere in the test session. Force —
# don't setdefault — because the environment may preset JAX_PLATFORMS to a
# real TPU platform plugin, and tests must run hermetically on the 8-device
# virtual CPU mesh.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

# A site plugin may re-register a hardware platform at jax import time and
# prepend it to jax_platforms; pin the config itself to be sure. Guarded so
# the pure-Kubernetes suites still run where jax is absent.
try:
    import jax  # noqa: E402
except ImportError:
    pass
else:
    jax.config.update("jax_platforms", "cpu")
