"""Test-wide configuration.

All tests run on CPU with a virtual 8-device platform so that multi-chip
sharding paths (dp/fsdp/tp/sp meshes, ring attention, collectives) compile and
execute without TPU hardware.  This is the testing seam the reference lacked
(SURVEY.md §4): its only integration story was "run on real GPUs".
"""

import os

# Must be set before jax is imported anywhere in the test session.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")
