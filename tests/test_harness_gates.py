"""Smoke tests for the harness gates: bench.py and __graft_entry__.

The driver records BENCH_r{N}.json by running bench.py and validates the
multi-chip story via __graft_entry__; a regression in either loses the
round's evidence silently. These run the same entry points hermetically
on CPU (bench auto-falls back to the tiny preset off-TPU).
"""

import json
import os
import subprocess
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TestBenchSmoke:
    def test_bench_emits_one_json_line(self):
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("XLA_FLAGS", None)
        proc = subprocess.run(
            [sys.executable, "bench.py"],
            cwd=REPO_ROOT,
            env=env,
            capture_output=True,
            text=True,
            timeout=600,
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        lines = [ln for ln in proc.stdout.splitlines() if ln.strip()]
        assert len(lines) == 1, proc.stdout
        result = json.loads(lines[0])
        for key in ("metric", "value", "unit", "vs_baseline"):
            assert key in result, result
        assert result["unit"] == "mfu_fraction"
        assert 0 < result["value"] <= 1.0
        # Loss must be a finite number — a NaN step would still "emit one
        # line" while measuring garbage.
        assert result["detail"]["loss"] == result["detail"]["loss"]

    def test_bench_rejects_unknown_model(self):
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["TPU_DRA_BENCH_MODEL"] = "nope"
        proc = subprocess.run(
            [sys.executable, "bench.py"],
            cwd=REPO_ROOT,
            env=env,
            capture_output=True,
            text=True,
            timeout=120,
        )
        assert proc.returncode == 2


class TestGraftEntry:
    def test_entry_compiles_and_runs(self):
        import jax

        sys.path.insert(0, REPO_ROOT)
        try:
            import __graft_entry__ as g
        finally:
            sys.path.pop(0)
        fn, args = g.entry()
        out = jax.jit(fn)(*args)
        assert all(
            bool(jax.numpy.isfinite(x).all())
            for x in jax.tree_util.tree_leaves(out)
        )
