"""Continuous-batching scheduler tests (models/serving.py).

The contract: whatever the admission order, chunking, or preemption
pressure, every request's final token stream equals running it alone
through ``generate()`` — the scheduler may only change WHEN work
happens, never WHAT comes out. Plus the fixed-shape guarantee (one
compile for the engine lifetime) and block hygiene after churn.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k8s_dra_driver_tpu.models.decode import generate
from k8s_dra_driver_tpu.models.llama import PRESETS, init_params
from k8s_dra_driver_tpu.models.paged import OutOfBlocksError
from k8s_dra_driver_tpu.models.serving import (
    RUNNING,
    DecodeEngine,
    Request,
)

TINY = PRESETS["tiny"]
N_NEW = 6


@pytest.fixture(scope="module")
def params():
    return init_params(TINY, jax.random.PRNGKey(0))


def _prompts(seed, lens):
    rng = np.random.RandomState(seed)
    return [list(rng.randint(0, TINY.vocab_size, size=n)) for n in lens]


def _reference(params, prompt, n=N_NEW):
    return np.asarray(
        generate(params, jnp.asarray([prompt], jnp.int32), TINY, n)
    )[0].tolist()


class TestTokenFidelity:
    def test_mixed_prompt_lengths_match_solo_generate(self, params):
        """Five requests with very different prompt lengths, three batch
        slots, chunked prefill: token-exact against solo generate()."""
        prompts = _prompts(0, (5, 11, 3, 17, 9))
        eng = DecodeEngine(
            params, TINY, batch_slots=3, num_blocks=24, block_size=8,
            max_seq_len=64, prefill_chunk=8,
        )
        reqs = [eng.submit(p, max_new_tokens=N_NEW) for p in prompts]
        eng.run()
        eng.assert_no_leaks()
        for r, p in zip(reqs, prompts):
            assert r.tokens == _reference(params, p), r.rid

    def test_long_prompt_does_not_stall_running_decodes(self, params):
        """Chunked prefill: while a long prompt is being prefilled chunk
        by chunk, an already-running request keeps producing tokens
        every tick (and both finish correct)."""
        short, long_ = _prompts(1, (4, 40))
        eng = DecodeEngine(
            params, TINY, batch_slots=2, num_blocks=16, block_size=8,
            max_seq_len=64, prefill_chunk=8,
        )
        r_short = eng.submit(short, max_new_tokens=12)
        # Let the short one reach RUNNING first.
        while r_short.state != RUNNING:
            eng.tick()
        r_long = eng.submit(long_, max_new_tokens=4)
        produced = []
        while r_long.state != RUNNING and not r_short.done:
            before = len(r_short.generated)
            eng.tick()
            produced.append(len(r_short.generated) - before)
        # The 40-token prompt needs 5 chunks; the short request must have
        # decoded on those same ticks, not waited.
        assert sum(produced) >= 3, produced
        eng.run()
        eng.assert_no_leaks()
        assert r_short.tokens == _reference(params, short, 12)
        assert r_long.tokens == _reference(params, long_, 4)

    def test_slot_reuse_after_finish(self, params):
        """More requests than slots: finishing sequences hand their slot
        and blocks to waiting ones at token granularity."""
        prompts = _prompts(2, (6, 6, 6, 6, 6, 6))
        eng = DecodeEngine(
            params, TINY, batch_slots=2, num_blocks=8, block_size=8,
            max_seq_len=32, prefill_chunk=8,
        )
        reqs = [eng.submit(p, max_new_tokens=N_NEW) for p in prompts]
        eng.run()
        eng.assert_no_leaks()
        assert eng.stats.completed == 6
        for r, p in zip(reqs, prompts):
            assert r.tokens == _reference(params, p)

    def test_eos_stops_early(self, params):
        """EOS termination frees the slot immediately."""
        prompt = _prompts(3, (6,))[0]
        ref = _reference(params, prompt, 12)
        eos = ref[len(prompt) + 2]   # third generated token
        eng = DecodeEngine(
            params, TINY, batch_slots=1, num_blocks=8, block_size=8,
            max_seq_len=32, prefill_chunk=8, eos_id=eos,
        )
        r = eng.submit(prompt, max_new_tokens=12)
        eng.run()
        eng.assert_no_leaks()
        assert r.generated[-1] == eos
        assert len(r.generated) == 3
        assert r.tokens == ref[: len(prompt) + 3]


class TestMoeServing:
    def test_moe_engine_matches_solo_generate(self):
        """Both model families serve through the same engine: a sparse
        MoE target under continuous batching stays token-exact against
        its solo generate()."""
        import dataclasses

        from k8s_dra_driver_tpu.models.moe import MOE_PRESETS
        from k8s_dra_driver_tpu.models.moe import init_params as moe_init

        cfg = dataclasses.replace(
            MOE_PRESETS["tiny-moe"], capacity_factor=8.0
        )
        params = moe_init(cfg, jax.random.PRNGKey(0))
        rng = np.random.RandomState(8)
        prompts = [
            rng.randint(0, cfg.vocab_size, size=n).tolist()
            for n in (5, 9, 13)
        ]
        eng = DecodeEngine(
            params, cfg, batch_slots=2, num_blocks=16, block_size=8,
            max_seq_len=32, prefill_chunk=8,
        )
        reqs = [eng.submit(p, max_new_tokens=4) for p in prompts]
        eng.run()
        eng.assert_no_leaks()
        assert eng.compile_counts == {
            "decode_step": 1, "prefill_chunk": 1,
        }
        for r, p in zip(reqs, prompts):
            ref = np.asarray(
                generate(params, jnp.asarray([p], jnp.int32), cfg, 4)
            )[0].tolist()
            assert r.tokens == ref, r.rid


class TestPreemption:
    def _starved_engine(self, params):
        # 6 blocks of 8 = 48 cache positions for 3 slots: decode growth
        # must steal blocks once everyone is long.
        return DecodeEngine(
            params, TINY, batch_slots=3, num_blocks=6, block_size=8,
            max_seq_len=48, prefill_chunk=8,
        )

    def test_preempted_requests_still_finish_correctly(self, params):
        eng = self._starved_engine(params)
        prompts = _prompts(4, (7, 9, 6, 8, 7))
        reqs = [eng.submit(p, max_new_tokens=10) for p in prompts]
        eng.run()
        eng.assert_no_leaks()
        assert eng.stats.preemptions > 0, "scenario must exercise eviction"
        for r, p in zip(reqs, prompts):
            assert r.done
            assert r.tokens == _reference(params, p, 10), (
                r.rid, r.preemptions
            )

    def test_never_evicts_running_when_prefill_victim_exists(self, params):
        """Victim policy: a sequence still in prefill is evicted before
        any running sequence loses work."""
        from k8s_dra_driver_tpu.models.serving import PREFILL

        eng = self._starved_engine(params)
        orig_preempt = eng._preempt_for
        orig_evict = eng._evict
        ctx = {"needy": None}

        def spy_preempt(needy):
            ctx["needy"] = needy
            orig_preempt(needy)

        def spy_evict(req, requeue):
            # The policy invariant, checked at the moment of eviction: a
            # RUNNING victim is only legal when no prefill-state sibling
            # (other than the requester itself) could take the hit.
            if requeue and req.state == RUNNING:
                prefill_victims = [
                    r for r in eng._slots
                    if r is not None and r is not req
                    and r is not ctx["needy"] and r.state == PREFILL
                ]
                assert not prefill_victims, (
                    f"evicted running rid={req.rid} while prefill-state "
                    f"victims existed: {[r.rid for r in prefill_victims]}"
                )
            orig_evict(req, requeue)

        eng._preempt_for = spy_preempt
        eng._evict = spy_evict
        prompts = _prompts(5, (16, 16, 16, 16))
        reqs = [eng.submit(p, max_new_tokens=8) for p in prompts]
        eng.run()
        eng.assert_no_leaks()
        # Despite the churn, everything completes — and correctly.
        assert eng.stats.completed == 4
        for r, p in zip(reqs, prompts):
            assert r.tokens == _reference(params, p, 8)

    def test_mid_tick_preemption_does_not_grow_evicted_request(self, params):
        """Regression: _decode_tick's block-growth loop iterates a
        snapshot of running requests; preempting one mid-loop used to
        grow the EVICTED request (slot -1), writing a neighbour's
        block-table row and attaching pool blocks to a WAITING request —
        the pool stayed short forever and the engine crashed."""
        eng = DecodeEngine(
            params, TINY, batch_slots=2, num_blocks=4, block_size=4,
            max_seq_len=16, prefill_chunk=4,
        )
        prompts = _prompts(30, (3, 3))
        reqs = [eng.submit(p, max_new_tokens=12) for p in prompts]
        eng.run()
        eng.assert_no_leaks()
        assert eng.stats.preemptions > 0, "scenario must exercise eviction"
        for r, p in zip(reqs, prompts):
            assert r.done
            assert r.tokens == _reference(params, p, 12), r.rid

    def test_zero_block_victim_does_not_abort_preemption(self, params):
        """Regression: evicting a freshly admitted prefill victim that
        holds no blocks yet frees nothing; _ensure_blocks must keep
        preempting instead of raising OutOfBlocksError while other
        evictable requests still hold blocks."""
        from k8s_dra_driver_tpu.models.serving import PREFILL, WAITING

        eng = DecodeEngine(
            params, TINY, batch_slots=3, num_blocks=4, block_size=4,
            max_seq_len=16, prefill_chunk=4,
        )
        a = eng.submit([1, 2, 3], max_new_tokens=4)
        b = eng.submit([1, 2, 3], max_new_tokens=4)
        c = eng.submit([1, 2, 3], max_new_tokens=4)
        eng._admit()
        # Same-tick admissions are budgeted (prompt+1 headroom each), so
        # the third request admits on the next round — blocks are
        # allocated lazily, which is how growth can still outrun a
        # not-yet-prefilled request's headroom (the scenario below).
        eng._admit()
        # Hand-build the state: a and c RUNNING holding two blocks each
        # (pool dry), b freshly admitted in PREFILL holding none.
        for req in (a, c):
            blocks = eng.allocator.alloc(2)
            req.blocks.extend(blocks)
            for i, blk in enumerate(blocks):
                eng._tables[req.slot, i] = blk
            req.state = RUNNING
        assert b.state == PREFILL and not b.blocks
        assert eng.allocator.num_free == 0
        # a needs a third block: evicting b frees nothing, so the engine
        # must go on to evict c rather than shed load.
        eng._ensure_blocks(a, 9)
        assert len(a.blocks) == 3
        assert b.state == WAITING and c.state == WAITING
        assert eng.stats.preemptions == 2

    def test_request_too_large_for_pool_is_typed_error(self, params):
        eng = DecodeEngine(
            params, TINY, batch_slots=1, num_blocks=4, block_size=8,
            max_seq_len=64, prefill_chunk=8,
        )
        # 40 positions fit the 64-token span but need 5 of 4 pool blocks.
        with pytest.raises(OutOfBlocksError):
            eng.submit(list(range(30)), max_new_tokens=10)

    def test_prompt_filling_exact_block_budget_still_admits(self, params):
        """Admission headroom is capped at the request's lifetime block
        need: a prompt that exactly fills its budget must admit into an
        idle pool instead of deadlocking on +1 headroom."""
        eng = DecodeEngine(
            params, TINY, batch_slots=1, num_blocks=4, block_size=8,
            max_seq_len=32,
        )
        r = eng.submit(list(np.arange(25) % TINY.vocab_size),
                       max_new_tokens=7)   # 32 positions = whole pool
        eng.run()
        eng.assert_no_leaks()
        assert r.done and len(r.generated) == 7

    def test_request_beyond_span_rejected(self, params):
        eng = DecodeEngine(
            params, TINY, batch_slots=1, num_blocks=64, block_size=8,
            max_seq_len=32, prefill_chunk=8,
        )
        with pytest.raises(ValueError, match="span"):
            eng.submit(list(range(30)), max_new_tokens=10)


class TestFixedShape:
    def test_one_compile_for_lifetime_across_mixed_traffic(self, params):
        """The whole point: admissions, evictions, block growth, slot
        reuse — one compiled decode step, one compiled prefill chunk."""
        eng = DecodeEngine(
            params, TINY, batch_slots=3, num_blocks=8, block_size=8,
            max_seq_len=48, prefill_chunk=8,
        )
        for seed in range(3):
            prompts = _prompts(10 + seed, (5, 13, 9))
            for p in prompts:
                eng.submit(p, max_new_tokens=5)
            eng.run()
        eng.assert_no_leaks()
        assert eng.compile_counts == {
            "decode_step": 1, "prefill_chunk": 1,
        }, eng.compile_counts

    def test_quantized_variants_compile_once_each(self, params):
        """int8 weights and int8 cache are their own programs — but each
        compiles exactly once too (pinned per-variant in
        tests/test_decode.py::TestCompileOnce; here the combined
        engine-level sweep)."""
        from k8s_dra_driver_tpu.models.quant import quantize_params

        qparams = quantize_params(params)
        eng = DecodeEngine(
            qparams, TINY, batch_slots=2, num_blocks=8, block_size=8,
            max_seq_len=32, prefill_chunk=8, quantize_cache=True,
        )
        for p in _prompts(20, (6, 11)):
            eng.submit(p, max_new_tokens=6)
        eng.run()
        eng.assert_no_leaks()
        assert eng.compile_counts == {
            "decode_step": 1, "prefill_chunk": 1,
        }


class TestBatchedPrefill:
    """Ragged multi-request prefill batching: the packed program may
    only change WHEN prompts are processed (TTFT), never what comes out
    — batched and serial engines must emit identical streams, stay
    compile-once, and the occupancy ledger must account every lane."""

    def _engine(self, params, pb, prefix_cache=True, clock=None, **kw):
        kw.setdefault("batch_slots", 4)
        kw.setdefault("num_blocks", 26)
        extra = {"clock": clock} if clock is not None else {}
        return DecodeEngine(
            params, TINY, block_size=8, max_seq_len=48, prefill_chunk=8,
            prefill_batch=pb, prefix_cache=prefix_cache, **kw, **extra,
        )

    @pytest.mark.parametrize("prefix_cache", [True, False])
    def test_batched_matches_serial_streams(self, params, prefix_cache):
        prompts = _prompts(7, (5, 19, 11, 23, 7, 13))
        streams = {}
        for pb in (4, 1):
            eng = self._engine(params, pb, prefix_cache=prefix_cache)
            reqs = [eng.submit(p, max_new_tokens=N_NEW) for p in prompts]
            eng.run()
            eng.assert_no_leaks()
            assert eng.compile_counts == {
                "decode_step": 1, "prefill_chunk": 1,
            }, (pb, eng.compile_counts)
            streams[pb] = [tuple(r.tokens) for r in reqs]
        assert streams[4] == streams[1]

    def test_matches_solo_generate(self, params):
        """The fidelity oracle directly: packed lanes vs generate()."""
        prompts = _prompts(8, (6, 14, 9, 17))
        eng = self._engine(params, 4)
        reqs = [eng.submit(p, max_new_tokens=N_NEW) for p in prompts]
        eng.run()
        eng.assert_no_leaks()
        for r, p in zip(reqs, prompts):
            assert r.tokens == _reference(params, p), r.rid

    def test_occupancy_ledger(self, params):
        """Four concurrent arrivals at prefill_batch=4 fill every lane;
        a lone request leaves three idle — both visible in the
        occupancy stat and the pinned snapshot key."""
        eng = self._engine(params, 4)
        for p in _prompts(9, (16, 16, 16, 16)):
            eng.submit(p, max_new_tokens=2)
        eng.run()
        st = eng.stats
        assert st.prefill_lanes_launched > 0
        assert st.prefill_lanes_used == st.prefill_chunks
        assert st.prefill_batch_occupancy() == 1.0
        solo = self._engine(params, 4)
        solo.submit(_prompts(10, (16,))[0], max_new_tokens=2)
        solo.run()
        assert solo.stats.prefill_batch_occupancy() == 0.25
        assert solo.snapshot()["prefillBatchOccupancy"] == 0.25

    def test_prefill_batch_clamped_to_slots(self, params):
        eng = DecodeEngine(
            params, TINY, batch_slots=2, num_blocks=12, block_size=8,
            max_seq_len=32, prefill_chunk=8, prefill_batch=16,
        )
        assert eng.prefill_batch == 2
        assert DecodeEngine(
            params, TINY, batch_slots=8, num_blocks=40, block_size=8,
            max_seq_len=32, prefill_chunk=8,
        ).prefill_batch == 4   # default min(4, slots)

    def test_burst_ttft_improves_in_ticks(self, params):
        """A burst of concurrent arrivals on a virtual tick clock: the
        packed program must cut tick-measured TTFT p99 vs the serial
        engine while decode-token cadence stays equal-or-better (the
        make-decodebench gate, unit-sized)."""
        prompts = _prompts(11, (24,) * 6)

        def run(pb):
            box = [0.0]
            eng = self._engine(
                params, pb, prefix_cache=False, clock=lambda: box[0],
                num_blocks=20,
            )
            reqs = [eng.submit(p, max_new_tokens=3) for p in prompts]
            while not eng.idle:
                eng.tick()
                box[0] += 1.0
            eng.assert_no_leaks()
            s = eng.stats
            return (
                [tuple(r.tokens) for r in reqs],
                s.pctl(s.ttft_s, 0.99),
                s.pctl(s.token_interval_s, 0.99),
            )

        toks_b, ttft_b, tok_b = run(4)
        toks_s, ttft_s, tok_s = run(1)
        assert toks_b == toks_s
        assert ttft_s / max(ttft_b, 1e-9) >= 1.5, (ttft_b, ttft_s)
        assert tok_b <= tok_s

    def test_pressure_preempts_mid_batch_and_stays_exact(self, params):
        """A pool too small for every lane: _ensure_blocks preempts a
        younger lane of the same packed batch; the survivor set is
        re-collected, every request still finishes with exact tokens,
        and nothing leaks."""
        prompts = _prompts(12, (15, 15, 15, 15))
        eng = self._engine(
            params, 4, prefix_cache=False, num_blocks=7,
        )
        reqs = [eng.submit(p, max_new_tokens=N_NEW) for p in prompts]
        eng.run()
        eng.assert_no_leaks()
        assert eng.stats.preemptions > 0
        for r, p in zip(reqs, prompts):
            assert r.tokens == _reference(params, p), r.rid


class TestPrefixReuse:
    """Cross-request KV reuse: whatever the cache does — radix hits,
    shared-block mapping, COW recompute, LRU eviction — every request's
    tokens stay equal to solo generate(), and serving the same prefix
    twice must actually skip prefill the second time."""

    def test_cache_hot_matches_cold_and_saves_prefill(self, params):
        prompt = _prompts(40, (20,))[0]
        eng = DecodeEngine(
            params, TINY, batch_slots=2, num_blocks=16, block_size=8,
            max_seq_len=64, prefill_chunk=8,
        )
        a = eng.submit(prompt, max_new_tokens=N_NEW)
        eng.run()
        chunks_cold = eng.stats.prefill_chunks
        b = eng.submit(prompt, max_new_tokens=N_NEW)
        eng.run()
        eng.assert_no_leaks()
        ref = _reference(params, prompt)
        assert a.tokens == ref
        assert b.tokens == ref                      # token-for-token
        assert b.cached_tokens > 0
        assert eng.stats.prefix_hit_tokens == b.cached_tokens
        # The hot pass prefilled strictly fewer chunks than the cold one.
        assert eng.stats.prefill_chunks - chunks_cold < chunks_cold
        assert eng.stats.hit_rate() > 0

    def test_full_cover_prompt_triggers_cow_recompute(self, params):
        """A block-aligned fully cached prompt maps all but its trailing
        block (copy-on-write by recompute): the final prompt token still
        runs, tokens stay exact, and the cached block is not mutated."""
        prompt = _prompts(41, (16,))[0]             # 2 full blocks of 8
        eng = DecodeEngine(
            params, TINY, batch_slots=1, num_blocks=12, block_size=8,
            max_seq_len=48, prefill_chunk=8,
        )
        a = eng.submit(prompt, max_new_tokens=N_NEW)
        eng.run()
        hit_blocks = eng.prefix_cache.lookup(prompt)
        assert len(hit_blocks) == 2                 # full cover cached
        import numpy as np

        pool_k = np.asarray(eng._pools[0])
        rows = slice(hit_blocks[-1] * 8, hit_blocks[-1] * 8 + 8)
        before = pool_k[:, :, rows, :].copy()
        b = eng.submit(prompt, max_new_tokens=N_NEW)
        eng.run()
        eng.assert_no_leaks()
        assert b.tokens == a.tokens == _reference(params, prompt)
        assert eng.stats.cow_recomputes == 1
        assert b.cached_tokens == 8                 # mapped 1 of 2 blocks
        after = np.asarray(eng._pools[0])[:, :, rows, :]
        np.testing.assert_array_equal(after, before)

    def test_shared_system_prompt_family(self, params):
        """The production shape: one system prompt, many tails. Every
        request matches solo generate; later requests hit the cache."""
        rng = np.random.RandomState(42)
        system = list(rng.randint(0, TINY.vocab_size, size=16))
        prompts = [
            system + list(rng.randint(0, TINY.vocab_size, size=5))
            for _ in range(4)
        ]
        eng = DecodeEngine(
            params, TINY, batch_slots=2, num_blocks=24, block_size=8,
            max_seq_len=64, prefill_chunk=8,
        )
        reqs = [eng.submit(p, max_new_tokens=N_NEW) for p in prompts]
        eng.run()
        eng.assert_no_leaks()
        for r, p in zip(reqs, prompts):
            assert r.tokens == _reference(params, p), r.rid
        assert eng.stats.prefix_hits >= 2
        assert eng.stats.prefix_hit_tokens >= 2 * 16

    def test_disabled_cache_is_bitwise_identical_to_enabled(self, params):
        """Flag gate: prefix_cache=False serves the same tokens (the
        bench baseline engine)."""
        prompts = _prompts(43, (9, 21, 9))          # a repeat in traffic
        outs = []
        for flag in (True, False):
            eng = DecodeEngine(
                params, TINY, batch_slots=2, num_blocks=16, block_size=8,
                max_seq_len=48, prefill_chunk=8, prefix_cache=flag,
            )
            reqs = [eng.submit(p, max_new_tokens=N_NEW) for p in prompts]
            eng.run()
            eng.assert_no_leaks()
            outs.append([r.tokens for r in reqs])
        assert outs[0] == outs[1]

    def test_preempting_shared_request_decrefs_not_frees(self, params):
        """Satellite: eviction paths understand refcounts. Preempt a
        request that maps cached blocks; the cached copies must survive
        (no double-free crash, pool-exact after drain) and its restart
        should hit the cache again."""
        prompt = _prompts(44, (16,))[0]
        eng = DecodeEngine(
            params, TINY, batch_slots=3, num_blocks=7, block_size=8,
            max_seq_len=56, prefill_chunk=8,
        )
        a = eng.submit(prompt, max_new_tokens=8)
        eng.run()                                    # seeds the cache
        # Same prompt again plus heavy private traffic on a starved pool.
        b = eng.submit(prompt, max_new_tokens=8)
        others = [eng.submit(p, max_new_tokens=10)
                  for p in _prompts(45, (12, 14))]
        eng.run()
        eng.assert_no_leaks()
        assert eng.stats.preemptions > 0, "scenario must exercise eviction"
        assert b.tokens == a.tokens == _reference(params, prompt, 8)
        for r, p in zip(others, _prompts(45, (12, 14))):
            assert r.tokens == _reference(params, p, 10)

    def test_admission_headroom_discounts_own_revived_hit_blocks(
        self, params
    ):
        """Regression (review-found): hit blocks sitting in the
        reclaimable LRU were counted as available headroom AND revived
        by the admission's share() — so a cache-hit request could admit
        into a pool too dry for its tail and then preempt a RUNNING
        request, violating the admission-never-preempts invariant."""
        big = _prompts(60, (16,))[0]
        eng = DecodeEngine(
            params, TINY, batch_slots=2, num_blocks=3, block_size=8,
            max_seq_len=24, prefill_chunk=8,
        )
        a = eng.submit(big, max_new_tokens=8)
        eng.run()                   # seeds the cache: 2 blocks in LRU
        assert eng.allocator.num_cached == 2
        small = _prompts(61, (6,))[0]
        b = eng.submit(small, max_new_tokens=2)   # takes the free block
        c = eng.submit(big, max_new_tokens=8)     # full-cover cache hit
        eng.run()
        eng.assert_no_leaks()
        # c must have WAITED for b's block instead of preempting it.
        assert eng.stats.preemptions == 0
        assert b.tokens == _reference(params, small, 2)
        assert c.tokens == a.tokens == _reference(params, big, 8)

    @pytest.mark.slow  # churn soak; faster PrefixReuse tests stay tier-1
    def test_leak_oracle_under_shared_and_private_churn(self, params):
        """Satellite: churn shared and private requests through a small
        pool (admissions, cache hits, COW, preemptions, LRU evictions)
        and assert pool-exact accounting after every drain."""
        rng = np.random.RandomState(7)
        system = list(rng.randint(0, TINY.vocab_size, size=8))
        eng = DecodeEngine(
            params, TINY, batch_slots=3, num_blocks=8, block_size=8,
            max_seq_len=48, prefill_chunk=8,
        )
        for round_ in range(4):
            prompts = []
            for i in range(3):
                tail = list(rng.randint(0, TINY.vocab_size,
                                        size=3 + (round_ + i) % 5))
                prompts.append(system + tail if i % 2 == 0 else tail)
            reqs = [eng.submit(p, max_new_tokens=6) for p in prompts]
            eng.run()
            eng.assert_no_leaks()
            alloc = eng.allocator
            assert alloc.num_allocated == 0
            assert alloc.num_free + alloc.num_cached == alloc.num_blocks
            for r, p in zip(reqs, prompts):
                assert r.tokens == _reference(params, p, 6), (
                    round_, r.rid, r.preemptions
                )
        assert eng.stats.prefix_hits > 0
        assert eng.allocator.evictions > 0, (
            "churn must exercise LRU eviction under pressure"
        )


class TestOverlap:
    """The double-buffered tick: dispatch N+1 while consuming N. Token
    streams must be identical to the synchronous tick, EOS after an
    already-dispatched step must drain cleanly, and the two-programs
    contract must hold."""

    def _serve(self, params, overlap, prompts, eos_id=None, n_new=8):
        eng = DecodeEngine(
            params, TINY, batch_slots=2, num_blocks=12, block_size=8,
            max_seq_len=48, prefill_chunk=8, overlap=overlap,
            eos_id=eos_id,
        )
        reqs = [eng.submit(p, max_new_tokens=n_new) for p in prompts]
        eng.run()
        eng.assert_no_leaks()
        return eng, [r.tokens for r in reqs]

    def test_overlap_matches_synchronous_tick(self, params):
        prompts = _prompts(50, (5, 17, 9, 4))
        eng_a, toks_a = self._serve(params, True, prompts)
        eng_b, toks_b = self._serve(params, False, prompts)
        assert toks_a == toks_b
        assert eng_a.compile_counts == {
            "decode_step": 1, "prefill_chunk": 1,
        }

    def test_eos_surprise_drains_wasted_step(self, params):
        """EOS lands while the next step is in flight: the request
        drains one tick, the wasted token is discarded, and its stream
        still matches the synchronous engine's."""
        prompt = _prompts(51, (6,))[0]
        ref = _reference(params, prompt, 12)
        eos = ref[len(prompt) + 3]                  # 4th generated token
        eng_o, toks_o = self._serve(params, True, [prompt],
                                    eos_id=eos, n_new=12)
        eng_s, toks_s = self._serve(params, False, [prompt],
                                    eos_id=eos, n_new=12)
        assert toks_o == toks_s
        assert toks_o[0] == ref[: len(prompt) + 4]
        # The wasted in-flight token was computed but never committed.
        assert eng_o.stats.decode_steps > eng_s.stats.decode_steps
        assert eng_o.stats.tokens_generated == eng_s.stats.tokens_generated

    def test_length_bounded_finish_never_wastes_a_step(self, params):
        """max_new_tokens finishes are predicted host-side: overlapped
        and synchronous engines run the same number of decode steps."""
        prompts = _prompts(52, (5, 9))
        eng_o, _ = self._serve(params, True, prompts)
        eng_s, _ = self._serve(params, False, prompts)
        assert eng_o.stats.decode_steps == eng_s.stats.decode_steps


class TestStats:
    def test_latency_and_throughput_accounting(self, params):
        t = [0.0]

        def clock():
            t[0] += 0.01
            return t[0]

        eng = DecodeEngine(
            params, TINY, batch_slots=2, num_blocks=8, block_size=8,
            max_seq_len=32, prefill_chunk=8, clock=clock,
        )
        reqs = [eng.submit(p, max_new_tokens=4)
                for p in _prompts(6, (5, 7))]
        eng.run()
        s = eng.stats
        assert s.completed == 2
        assert s.tokens_generated == sum(len(r.generated) for r in reqs)
        assert len(s.ttft_s) == 2 and all(x > 0 for x in s.ttft_s)
        assert len(s.request_latency_s) == 2
        assert s.p99_token_ms() >= s.p50_token_ms() > 0
        for r in reqs:
            assert r.first_token_at is not None
            assert r.finished_at >= r.first_token_at

    def test_request_handle_shape(self, params):
        eng = DecodeEngine(
            params, TINY, batch_slots=1, num_blocks=8, block_size=8,
            max_seq_len=32,
        )
        r = eng.submit([1, 2, 3], max_new_tokens=2)
        assert isinstance(r, Request)
        eng.run()
        assert r.tokens[:3] == [1, 2, 3] and len(r.tokens) == 5


class TestDrain:
    """Graceful stop (ISSUE 14 satellite): admission closes, admitted
    requests run to completion, queued ones come back for re-routing,
    and the pool is exactly clean afterwards."""

    def test_stop_admission_closes_submit(self, params):
        from k8s_dra_driver_tpu.models.serving import (
            AdmissionClosedError,
        )

        eng = DecodeEngine(
            params, TINY, batch_slots=1, num_blocks=8, block_size=8,
            max_seq_len=32,
        )
        eng.stop_admission()
        assert not eng.admission_open
        with pytest.raises(AdmissionClosedError):
            eng.submit([1, 2, 3], max_new_tokens=2)
        eng.resume_admission()
        eng.submit([1, 2, 3], max_new_tokens=2)
        eng.run()
        eng.assert_no_leaks()

    def test_drain_finishes_admitted_and_returns_queued(self, params):
        prompts = _prompts(70, (5, 9, 7, 11, 6))
        eng = DecodeEngine(
            params, TINY, batch_slots=2, num_blocks=24, block_size=8,
            max_seq_len=40, prefill_chunk=8,
        )
        reqs = [eng.submit(p, max_new_tokens=N_NEW) for p in prompts]
        eng.tick()  # admits the first two into the slots
        admitted = [r for r in reqs if r.admit_seq >= 0]
        assert len(admitted) == 2
        rerouted = eng.drain()
        assert [r.rid for r in rerouted] == [
            r.rid for r in reqs if r.admit_seq < 0
        ]
        for r in admitted:
            assert r.done
            assert r.tokens == _reference(params, r.prompt)
        for r in rerouted:
            assert r.state == "waiting" and not r.generated
        eng.assert_no_leaks()
        # The engine is reusable: reopen and serve the returned ones.
        eng.resume_admission()
        for r in rerouted:
            eng.submit(r.prompt, max_new_tokens=N_NEW)
        eng.run()
        eng.assert_no_leaks()

    def test_drain_under_block_pressure_loses_nothing(self, params):
        """A preemption mid-drain must re-admit (the victim was an
        admitted request): zero admitted-request loss even when the
        pool is tight enough to preempt."""
        prompts = _prompts(71, (9, 13, 11))
        eng = DecodeEngine(
            params, TINY, batch_slots=3, num_blocks=7, block_size=8,
            max_seq_len=40, prefill_chunk=8,
        )
        reqs = [eng.submit(p, max_new_tokens=N_NEW) for p in prompts]
        for _ in range(2):
            eng.tick()
        admitted = [r for r in reqs if r.admit_seq >= 0]
        assert admitted, "pressure scenario admitted nobody"
        eng.drain()
        for r in admitted:
            assert r.done, (r.rid, r.state)
            assert r.tokens == _reference(params, r.prompt)
        eng.assert_no_leaks()


class TestSnapshot:
    """The scrape contract the fleet gateway's demand sensor keys on:
    renaming a key must fail HERE, not silently zero a routing signal."""

    def test_stats_snapshot_keys_pinned(self):
        from k8s_dra_driver_tpu.models.serving import ServingStats

        snap = ServingStats().snapshot()
        assert tuple(snap) == ServingStats.SNAPSHOT_KEYS
        assert set(ServingStats.SNAPSHOT_KEYS) == {
            "completed", "preemptions", "ticks", "decodeSteps",
            "prefillChunks", "prefillBatchOccupancy", "tokensGenerated",
            "prefixHitRate", "prefillTokensSaved", "cowRecomputes",
            "prefixLookups", "prefixHits", "prefixHitTokens",
            "kvFootprintBlocksP50", "kvFootprintBlocksMax",
            "queueDepthMean", "queueDepthMax", "ttftP50Ms", "ttftP99Ms",
            "tokenIntervalP50Ms", "tokenIntervalP99Ms",
        }

    def test_engine_snapshot_live_fields(self, params):
        from k8s_dra_driver_tpu.models.serving import ServingStats

        eng = DecodeEngine(
            params, TINY, batch_slots=2, num_blocks=8, block_size=8,
            max_seq_len=32,
        )
        eng.submit([1, 2, 3, 4, 5], max_new_tokens=2)
        snap = eng.snapshot()
        assert set(snap) == {
            "queueDepth", "slotsBusy", "batchSlots", "admissionOpen",
            "blocksFree", "blocksAvailable", "blocksTotal",
            "blocksPrivate", "blocksIndexed", "blocksShared",
            "blocksCached", "kvEvictedBlocks", "kvEvictedTokens",
            "kvRevivals", "kvAllocMisses", "computeCompiles",
            *ServingStats.SNAPSHOT_KEYS,
        }
        assert snap["queueDepth"] == 1
        assert snap["slotsBusy"] == 0
        assert snap["admissionOpen"] is True
        assert snap["blocksTotal"] == 8
        eng.run()
        done = eng.snapshot()
        assert done["completed"] == 1
        assert done["queueDepth"] == 0


class TestKVLedger:
    """KV residency observability: the block-lifecycle ledger and the
    measured-residency digest stay consistent through eviction and
    preemption churn (``assert_no_leaks`` is the ground truth), and
    the exported telemetry is a pure observer."""

    def _churn_engine(self, params, **kw):
        kw.setdefault("batch_slots", 2)
        kw.setdefault("num_blocks", 12)
        kw.setdefault("block_size", 8)
        kw.setdefault("max_seq_len", 48)
        kw.setdefault("prefill_chunk", 8)
        return DecodeEngine(params, TINY, **kw)

    def _churn_prompts(self):
        # Shared 16-token system prefix x varied tails, each submitted
        # twice: repeats hit the radix cache (COW on the trailing
        # block); variety against the 12-block pool forces evictions.
        base = _prompts(11, (16,))[0]
        tails = _prompts(12, (5, 8, 11, 14))
        return [base + t for t in tails] * 2

    def test_digest_consistent_after_eviction_churn(self, params):
        eng = self._churn_engine(params)
        reqs = [eng.submit(p, max_new_tokens=12)
                for p in self._churn_prompts()]
        eng.run()
        eng.assert_no_leaks()
        assert all(r.done for r in reqs)
        digest = eng.kv_residency()
        assert digest["schema"] == "tpu-dra-kv-residency-v1"
        assert digest["evictedBlocks"] > 0, "scenario must evict"
        assert digest["indexedBlocks"] == (
            digest["insertedBlocks"] - digest["evictedBlocks"]
        )
        occ = eng.allocator.occupancy()
        assert sum(occ.values()) == eng.allocator.num_blocks
        for run in digest["runs"]:
            assert run["blocks"] > 0
            assert set(run["refs"]) == {"cached", "live", "shared"}

    def test_digest_consistent_after_preemption_churn(self, params):
        # The TestPreemption starvation profile, with the ledger now
        # audited after the dust settles.
        eng = self._churn_engine(params, batch_slots=3, num_blocks=6)
        reqs = [eng.submit(p, max_new_tokens=10)
                for p in _prompts(4, (7, 9, 6, 8, 7))]
        eng.run()
        eng.assert_no_leaks()
        assert eng.stats.preemptions > 0, "scenario must preempt"
        assert all(r.done for r in reqs)
        digest = eng.kv_residency()
        assert digest["indexedBlocks"] == (
            digest["insertedBlocks"] - digest["evictedBlocks"]
        )
        occ = eng.allocator.occupancy()
        assert sum(occ.values()) == eng.allocator.num_blocks

    def test_kv_debug_document_and_endpoint(self, params):
        import json
        import urllib.error
        import urllib.request

        from k8s_dra_driver_tpu.utils.metrics import (
            MetricsServer,
            Registry,
        )

        eng = self._churn_engine(params)
        reqs = [eng.submit(p, max_new_tokens=12)
                for p in self._churn_prompts()]
        eng.run()
        doc = eng.kv_debug()
        assert doc["schema"] == "tpu-dra-kv-debug-v1"
        assert sum(doc["occupancy"].values()) == doc["blocksTotal"]
        assert doc["footprintBlocks"]["samples"] == len(reqs)
        json.dumps(doc)
        srv = MetricsServer(Registry(), host="127.0.0.1", port=0)
        srv.set_kv_provider(eng.kv_debug)
        srv.start()
        try:
            base = f"http://127.0.0.1:{srv.port}"
            served = json.loads(urllib.request.urlopen(
                f"{base}/debug/kv").read().decode())
            assert served["schema"] == "tpu-dra-kv-debug-v1"
            assert served["residency"]["indexedBlocks"] == (
                served["residency"]["insertedBlocks"]
                - served["residency"]["evictedBlocks"]
            )
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(f"{base}/debug/kv", data=b"x")
            assert ei.value.code == 405
        finally:
            srv.stop()

    def test_telemetry_mirrors_ledger_and_detaches(self, params):
        from k8s_dra_driver_tpu.models.serving import KVTelemetry
        from k8s_dra_driver_tpu.utils.metrics import Registry

        registry = Registry()
        tel = KVTelemetry(registry)
        eng = self._churn_engine(params)
        tel.attach(eng, replica="kv-test")
        [eng.submit(p, max_new_tokens=12) for p in self._churn_prompts()]
        eng.run()
        body = registry.render()
        for family in ("tpu_dra_kv_pool_blocks",
                       "tpu_dra_kv_indexed_blocks",
                       "tpu_dra_kv_prefix_runs",
                       "tpu_dra_kv_evicted_blocks_total",
                       "tpu_dra_kv_evicted_tokens_total",
                       "tpu_dra_kv_alloc_misses_total",
                       "tpu_dra_kv_revivals_total",
                       "tpu_dra_kv_cow_recomputes_total",
                       "tpu_dra_kv_eviction_lru_age_ops",
                       "tpu_dra_kv_request_footprint_blocks"):
            assert family in body, family
        evicted = eng.kv_residency()["evictedBlocks"]
        assert evicted > 0
        assert (f'tpu_dra_kv_evicted_blocks_total{{replica="kv-test"}} '
                f"{evicted}") in body
        tel.detach("kv-test")
        after = registry.render()
        assert ('tpu_dra_kv_pool_blocks{replica="kv-test"'
                not in after), "departed replica's pool gauges linger"
        # Monotone history stays: counters keep their final values.
        assert (f'tpu_dra_kv_evicted_blocks_total{{replica="kv-test"}} '
                f"{evicted}") in after
