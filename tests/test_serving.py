"""Continuous-batching scheduler tests (models/serving.py).

The contract: whatever the admission order, chunking, or preemption
pressure, every request's final token stream equals running it alone
through ``generate()`` — the scheduler may only change WHEN work
happens, never WHAT comes out. Plus the fixed-shape guarantee (one
compile for the engine lifetime) and block hygiene after churn.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k8s_dra_driver_tpu.models.decode import generate
from k8s_dra_driver_tpu.models.llama import PRESETS, init_params
from k8s_dra_driver_tpu.models.paged import OutOfBlocksError
from k8s_dra_driver_tpu.models.serving import (
    RUNNING,
    DecodeEngine,
    Request,
)

TINY = PRESETS["tiny"]
N_NEW = 6


@pytest.fixture(scope="module")
def params():
    return init_params(TINY, jax.random.PRNGKey(0))


def _prompts(seed, lens):
    rng = np.random.RandomState(seed)
    return [list(rng.randint(0, TINY.vocab_size, size=n)) for n in lens]


def _reference(params, prompt, n=N_NEW):
    return np.asarray(
        generate(params, jnp.asarray([prompt], jnp.int32), TINY, n)
    )[0].tolist()


class TestTokenFidelity:
    def test_mixed_prompt_lengths_match_solo_generate(self, params):
        """Five requests with very different prompt lengths, three batch
        slots, chunked prefill: token-exact against solo generate()."""
        prompts = _prompts(0, (5, 11, 3, 17, 9))
        eng = DecodeEngine(
            params, TINY, batch_slots=3, num_blocks=24, block_size=8,
            max_seq_len=64, prefill_chunk=8,
        )
        reqs = [eng.submit(p, max_new_tokens=N_NEW) for p in prompts]
        eng.run()
        eng.assert_no_leaks()
        for r, p in zip(reqs, prompts):
            assert r.tokens == _reference(params, p), r.rid

    def test_long_prompt_does_not_stall_running_decodes(self, params):
        """Chunked prefill: while a long prompt is being prefilled chunk
        by chunk, an already-running request keeps producing tokens
        every tick (and both finish correct)."""
        short, long_ = _prompts(1, (4, 40))
        eng = DecodeEngine(
            params, TINY, batch_slots=2, num_blocks=16, block_size=8,
            max_seq_len=64, prefill_chunk=8,
        )
        r_short = eng.submit(short, max_new_tokens=12)
        # Let the short one reach RUNNING first.
        while r_short.state != RUNNING:
            eng.tick()
        r_long = eng.submit(long_, max_new_tokens=4)
        produced = []
        while r_long.state != RUNNING and not r_short.done:
            before = len(r_short.generated)
            eng.tick()
            produced.append(len(r_short.generated) - before)
        # The 40-token prompt needs 5 chunks; the short request must have
        # decoded on those same ticks, not waited.
        assert sum(produced) >= 3, produced
        eng.run()
        eng.assert_no_leaks()
        assert r_short.tokens == _reference(params, short, 12)
        assert r_long.tokens == _reference(params, long_, 4)

    def test_slot_reuse_after_finish(self, params):
        """More requests than slots: finishing sequences hand their slot
        and blocks to waiting ones at token granularity."""
        prompts = _prompts(2, (6, 6, 6, 6, 6, 6))
        eng = DecodeEngine(
            params, TINY, batch_slots=2, num_blocks=8, block_size=8,
            max_seq_len=32, prefill_chunk=8,
        )
        reqs = [eng.submit(p, max_new_tokens=N_NEW) for p in prompts]
        eng.run()
        eng.assert_no_leaks()
        assert eng.stats.completed == 6
        for r, p in zip(reqs, prompts):
            assert r.tokens == _reference(params, p)

    def test_eos_stops_early(self, params):
        """EOS termination frees the slot immediately."""
        prompt = _prompts(3, (6,))[0]
        ref = _reference(params, prompt, 12)
        eos = ref[len(prompt) + 2]   # third generated token
        eng = DecodeEngine(
            params, TINY, batch_slots=1, num_blocks=8, block_size=8,
            max_seq_len=32, prefill_chunk=8, eos_id=eos,
        )
        r = eng.submit(prompt, max_new_tokens=12)
        eng.run()
        eng.assert_no_leaks()
        assert r.generated[-1] == eos
        assert len(r.generated) == 3
        assert r.tokens == ref[: len(prompt) + 3]


class TestMoeServing:
    def test_moe_engine_matches_solo_generate(self):
        """Both model families serve through the same engine: a sparse
        MoE target under continuous batching stays token-exact against
        its solo generate()."""
        import dataclasses

        from k8s_dra_driver_tpu.models.moe import MOE_PRESETS
        from k8s_dra_driver_tpu.models.moe import init_params as moe_init

        cfg = dataclasses.replace(
            MOE_PRESETS["tiny-moe"], capacity_factor=8.0
        )
        params = moe_init(cfg, jax.random.PRNGKey(0))
        rng = np.random.RandomState(8)
        prompts = [
            rng.randint(0, cfg.vocab_size, size=n).tolist()
            for n in (5, 9, 13)
        ]
        eng = DecodeEngine(
            params, cfg, batch_slots=2, num_blocks=16, block_size=8,
            max_seq_len=32, prefill_chunk=8,
        )
        reqs = [eng.submit(p, max_new_tokens=4) for p in prompts]
        eng.run()
        eng.assert_no_leaks()
        assert eng.compile_counts == {
            "decode_step": 1, "prefill_chunk": 1,
        }
        for r, p in zip(reqs, prompts):
            ref = np.asarray(
                generate(params, jnp.asarray([p], jnp.int32), cfg, 4)
            )[0].tolist()
            assert r.tokens == ref, r.rid


class TestPreemption:
    def _starved_engine(self, params):
        # 6 blocks of 8 = 48 cache positions for 3 slots: decode growth
        # must steal blocks once everyone is long.
        return DecodeEngine(
            params, TINY, batch_slots=3, num_blocks=6, block_size=8,
            max_seq_len=48, prefill_chunk=8,
        )

    def test_preempted_requests_still_finish_correctly(self, params):
        eng = self._starved_engine(params)
        prompts = _prompts(4, (7, 9, 6, 8, 7))
        reqs = [eng.submit(p, max_new_tokens=10) for p in prompts]
        eng.run()
        eng.assert_no_leaks()
        assert eng.stats.preemptions > 0, "scenario must exercise eviction"
        for r, p in zip(reqs, prompts):
            assert r.done
            assert r.tokens == _reference(params, p, 10), (
                r.rid, r.preemptions
            )

    def test_never_evicts_running_when_prefill_victim_exists(self, params):
        """Victim policy: a sequence still in prefill is evicted before
        any running sequence loses work."""
        from k8s_dra_driver_tpu.models.serving import PREFILL

        eng = self._starved_engine(params)
        orig_preempt = eng._preempt_for
        orig_evict = eng._evict
        ctx = {"needy": None}

        def spy_preempt(needy):
            ctx["needy"] = needy
            orig_preempt(needy)

        def spy_evict(req, requeue):
            # The policy invariant, checked at the moment of eviction: a
            # RUNNING victim is only legal when no prefill-state sibling
            # (other than the requester itself) could take the hit.
            if requeue and req.state == RUNNING:
                prefill_victims = [
                    r for r in eng._slots
                    if r is not None and r is not req
                    and r is not ctx["needy"] and r.state == PREFILL
                ]
                assert not prefill_victims, (
                    f"evicted running rid={req.rid} while prefill-state "
                    f"victims existed: {[r.rid for r in prefill_victims]}"
                )
            orig_evict(req, requeue)

        eng._preempt_for = spy_preempt
        eng._evict = spy_evict
        prompts = _prompts(5, (16, 16, 16, 16))
        reqs = [eng.submit(p, max_new_tokens=8) for p in prompts]
        eng.run()
        eng.assert_no_leaks()
        # Despite the churn, everything completes — and correctly.
        assert eng.stats.completed == 4
        for r, p in zip(reqs, prompts):
            assert r.tokens == _reference(params, p, 8)

    def test_mid_tick_preemption_does_not_grow_evicted_request(self, params):
        """Regression: _decode_tick's block-growth loop iterates a
        snapshot of running requests; preempting one mid-loop used to
        grow the EVICTED request (slot -1), writing a neighbour's
        block-table row and attaching pool blocks to a WAITING request —
        the pool stayed short forever and the engine crashed."""
        eng = DecodeEngine(
            params, TINY, batch_slots=2, num_blocks=4, block_size=4,
            max_seq_len=16, prefill_chunk=4,
        )
        prompts = _prompts(30, (3, 3))
        reqs = [eng.submit(p, max_new_tokens=12) for p in prompts]
        eng.run()
        eng.assert_no_leaks()
        assert eng.stats.preemptions > 0, "scenario must exercise eviction"
        for r, p in zip(reqs, prompts):
            assert r.done
            assert r.tokens == _reference(params, p, 12), r.rid

    def test_zero_block_victim_does_not_abort_preemption(self, params):
        """Regression: evicting a freshly admitted prefill victim that
        holds no blocks yet frees nothing; _ensure_blocks must keep
        preempting instead of raising OutOfBlocksError while other
        evictable requests still hold blocks."""
        from k8s_dra_driver_tpu.models.serving import PREFILL, WAITING

        eng = DecodeEngine(
            params, TINY, batch_slots=3, num_blocks=4, block_size=4,
            max_seq_len=16, prefill_chunk=4,
        )
        a = eng.submit([1, 2, 3], max_new_tokens=4)
        b = eng.submit([1, 2, 3], max_new_tokens=4)
        c = eng.submit([1, 2, 3], max_new_tokens=4)
        eng._admit()
        # Hand-build the state: a and c RUNNING holding two blocks each
        # (pool dry), b freshly admitted in PREFILL holding none.
        for req in (a, c):
            blocks = eng.allocator.alloc(2)
            req.blocks.extend(blocks)
            for i, blk in enumerate(blocks):
                eng._tables[req.slot, i] = blk
            req.state = RUNNING
        assert b.state == PREFILL and not b.blocks
        assert eng.allocator.num_free == 0
        # a needs a third block: evicting b frees nothing, so the engine
        # must go on to evict c rather than shed load.
        eng._ensure_blocks(a, 9)
        assert len(a.blocks) == 3
        assert b.state == WAITING and c.state == WAITING
        assert eng.stats.preemptions == 2

    def test_request_too_large_for_pool_is_typed_error(self, params):
        eng = DecodeEngine(
            params, TINY, batch_slots=1, num_blocks=4, block_size=8,
            max_seq_len=64, prefill_chunk=8,
        )
        # 40 positions fit the 64-token span but need 5 of 4 pool blocks.
        with pytest.raises(OutOfBlocksError):
            eng.submit(list(range(30)), max_new_tokens=10)

    def test_prompt_filling_exact_block_budget_still_admits(self, params):
        """Admission headroom is capped at the request's lifetime block
        need: a prompt that exactly fills its budget must admit into an
        idle pool instead of deadlocking on +1 headroom."""
        eng = DecodeEngine(
            params, TINY, batch_slots=1, num_blocks=4, block_size=8,
            max_seq_len=32,
        )
        r = eng.submit(list(np.arange(25) % TINY.vocab_size),
                       max_new_tokens=7)   # 32 positions = whole pool
        eng.run()
        eng.assert_no_leaks()
        assert r.done and len(r.generated) == 7

    def test_request_beyond_span_rejected(self, params):
        eng = DecodeEngine(
            params, TINY, batch_slots=1, num_blocks=64, block_size=8,
            max_seq_len=32, prefill_chunk=8,
        )
        with pytest.raises(ValueError, match="span"):
            eng.submit(list(range(30)), max_new_tokens=10)


class TestFixedShape:
    def test_one_compile_for_lifetime_across_mixed_traffic(self, params):
        """The whole point: admissions, evictions, block growth, slot
        reuse — one compiled decode step, one compiled prefill chunk."""
        eng = DecodeEngine(
            params, TINY, batch_slots=3, num_blocks=8, block_size=8,
            max_seq_len=48, prefill_chunk=8,
        )
        for seed in range(3):
            prompts = _prompts(10 + seed, (5, 13, 9))
            for p in prompts:
                eng.submit(p, max_new_tokens=5)
            eng.run()
        eng.assert_no_leaks()
        assert eng.compile_counts == {
            "decode_step": 1, "prefill_chunk": 1,
        }, eng.compile_counts

    def test_quantized_variants_compile_once_each(self, params):
        """int8 weights and int8 cache are their own programs — but each
        compiles exactly once too (pinned per-variant in
        tests/test_decode.py::TestCompileOnce; here the combined
        engine-level sweep)."""
        from k8s_dra_driver_tpu.models.quant import quantize_params

        qparams = quantize_params(params)
        eng = DecodeEngine(
            qparams, TINY, batch_slots=2, num_blocks=8, block_size=8,
            max_seq_len=32, prefill_chunk=8, quantize_cache=True,
        )
        for p in _prompts(20, (6, 11)):
            eng.submit(p, max_new_tokens=6)
        eng.run()
        eng.assert_no_leaks()
        assert eng.compile_counts == {
            "decode_step": 1, "prefill_chunk": 1,
        }


class TestStats:
    def test_latency_and_throughput_accounting(self, params):
        t = [0.0]

        def clock():
            t[0] += 0.01
            return t[0]

        eng = DecodeEngine(
            params, TINY, batch_slots=2, num_blocks=8, block_size=8,
            max_seq_len=32, prefill_chunk=8, clock=clock,
        )
        reqs = [eng.submit(p, max_new_tokens=4)
                for p in _prompts(6, (5, 7))]
        eng.run()
        s = eng.stats
        assert s.completed == 2
        assert s.tokens_generated == sum(len(r.generated) for r in reqs)
        assert len(s.ttft_s) == 2 and all(x > 0 for x in s.ttft_s)
        assert len(s.request_latency_s) == 2
        assert s.p99_token_ms() >= s.p50_token_ms() > 0
        for r in reqs:
            assert r.first_token_at is not None
            assert r.finished_at >= r.first_token_at

    def test_request_handle_shape(self, params):
        eng = DecodeEngine(
            params, TINY, batch_slots=1, num_blocks=8, block_size=8,
            max_seq_len=32,
        )
        r = eng.submit([1, 2, 3], max_new_tokens=2)
        assert isinstance(r, Request)
        eng.run()
        assert r.tokens[:3] == [1, 2, 3] and len(r.tokens) == 5
