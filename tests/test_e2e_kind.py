"""The kind e2e gate and its supporting machinery.

The gate itself (`make e2e-kind`) needs docker/kind/kubectl/helm and a
real control plane, so it only runs when explicitly requested AND the
tools exist; everything it depends on — the script inventory, the skip
exit code, the sim cross-check tool, and the kubelet registration
auto-detect — is pinned hermetically here so the gate cannot rot
between docker-equipped runs.
"""

import json
import os
import shutil
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
E2E = os.path.join(REPO, "demo", "clusters", "kind", "e2e.sh")


class TestGatePlumbing:
    def test_scripts_exist_and_parse(self):
        for rel in (
            "demo/clusters/kind/e2e.sh",
            "demo/clusters/kind/create-cluster.sh",
            "demo/clusters/kind/install-dra-driver.sh",
            "demo/clusters/kind/run-demo.sh",
            "demo/clusters/kind/delete-cluster.sh",
            "demo/clusters/gke/create-cluster.sh",
            "demo/clusters/gke/install-dra-driver.sh",
            "demo/clusters/gke/delete-cluster.sh",
        ):
            path = os.path.join(REPO, rel)
            assert os.access(path, os.X_OK), f"{rel} not executable"
            subprocess.run(["bash", "-n", path], check=True)

    def test_makefile_has_gate_target(self):
        mk = open(os.path.join(REPO, "Makefile")).read()
        assert "e2e-kind:" in mk

    @pytest.mark.skipif(
        shutil.which("docker") is not None,
        reason="docker present; the skip path is exercised only without it",
    )
    def test_gate_skips_cleanly_without_docker(self):
        """Exit 3 = skip: CI without docker records the gate as skipped,
        never failed, and never half-creates a cluster."""
        r = subprocess.run([E2E], capture_output=True, text=True)
        assert r.returncode == 3, (r.returncode, r.stdout, r.stderr)
        assert "SKIP" in (r.stdout + r.stderr)


class TestSimCrossCheck:
    """tools/sim_check_allocation.py — the step of the gate that feeds
    the REAL API server's slices back through the sim allocator. Driven
    here on sim-published slices (shape-identical to real ones)."""

    def _publish(self, tmp_path):
        from k8s_dra_driver_tpu.kube import RESOURCE_SLICES, FakeKubeClient
        from k8s_dra_driver_tpu.kube.resourceslice import (
            DriverResources,
            Pool,
            ResourceSliceController,
        )
        from k8s_dra_driver_tpu.tpulib import FakeChipLib

        client = FakeKubeClient()
        lib = FakeChipLib(generation="v5e", topology="2x2x1", slice_id="s")
        lib.init()
        devices = lib.enumerate_all_possible_devices({"chip"})
        ctl = ResourceSliceController(client, "tpu.google.com", scope="n1")
        ctl.update(DriverResources(pools={
            "n1": Pool(
                devices=[d.get_device() for d in devices.values()],
                node_name="n1",
            )
        }))
        ctl.sync_once()
        return client.list(RESOURCE_SLICES)

    def run_tool(self, tmp_path, slices, claims):
        sf = tmp_path / "slices.json"
        cf = tmp_path / "claims.json"
        sf.write_text(json.dumps({"items": slices}))
        cf.write_text(json.dumps({"items": claims}))
        return subprocess.run(
            [sys.executable,
             os.path.join(REPO, "tools", "sim_check_allocation.py"),
             str(sf), str(cf)],
            capture_output=True, text=True, cwd=REPO,
        )

    def test_agreement_passes(self, tmp_path):
        slices = self._publish(tmp_path)
        claims = [{
            "metadata": {"name": "c1", "namespace": "d", "uid": "u1"},
            "spec": {"devices": {"requests": [
                {"name": "r", "deviceClassName": "tpu.google.com"}
            ]}},
            # What a real scheduler would have recorded.
            "status": {"allocation": {"devices": {"results": [
                {"request": "r", "driver": "tpu.google.com",
                 "device": "tpu-0", "pool": "n1"}
            ]}}},
        }]
        r = self.run_tool(tmp_path, slices, claims)
        assert r.returncode == 0, r.stderr + r.stdout
        assert "OK: sim agrees" in r.stdout

    def test_unknown_real_device_fails(self, tmp_path):
        """A real allocation naming a device the slices never published
        means the two sides disagree about the world — the gate fails."""
        slices = self._publish(tmp_path)
        claims = [{
            "metadata": {"name": "c1", "namespace": "d", "uid": "u1"},
            "spec": {"devices": {"requests": [
                {"name": "r", "deviceClassName": "tpu.google.com"}
            ]}},
            "status": {"allocation": {"devices": {"results": [
                {"request": "r", "driver": "tpu.google.com",
                 "device": "tpu-99", "pool": "n1"}
            ]}}},
        }]
        r = self.run_tool(tmp_path, slices, claims)
        assert r.returncode == 1
        assert "unknown devices" in r.stderr

    def test_empty_inputs_fail(self, tmp_path):
        r = self.run_tool(tmp_path, [], [])
        assert r.returncode == 1


class TestRegistrationAutoDetect:
    """--plugin-api-versions=auto probes kubeletVersion from the Node
    object fetched once at startup (weak spot from the round-3 review:
    the deploy knob failed silently when held wrong across cluster
    generations)."""

    @staticmethod
    def _node(kubelet_version):
        return {
            "metadata": {"name": "n1", "uid": "u"},
            "status": {"nodeInfo": {"kubeletVersion": kubelet_version}},
        }

    def test_131_gets_semver_scheme(self):
        from k8s_dra_driver_tpu.plugin.main import (
            resolve_registration_versions,
        )

        assert resolve_registration_versions(
            "auto", self._node("v1.31.4"), "n1"
        ) == ("1.0.0",)

    def test_132_gets_service_name_scheme(self):
        from k8s_dra_driver_tpu.plugin.main import (
            resolve_registration_versions,
        )

        assert resolve_registration_versions(
            "auto", self._node("v1.32.0"), "n1"
        ) == ("v1beta1.DRAPlugin", "1.0.0")

    def test_probe_failure_falls_back_loudly(self, caplog):
        import logging

        from k8s_dra_driver_tpu.plugin.main import (
            resolve_registration_versions,
        )

        with caplog.at_level(logging.WARNING):
            out = resolve_registration_versions("auto", None, "ghost")
        assert out == ("1.0.0",)
        assert any("kubeletVersion" in r.message for r in caplog.records)

    def test_explicit_list_passes_through(self):
        from k8s_dra_driver_tpu.plugin.main import (
            resolve_registration_versions,
        )

        assert resolve_registration_versions(
            "v1beta1.DRAPlugin,1.0.0", None, "n1"
        ) == ("v1beta1.DRAPlugin", "1.0.0")
        assert resolve_registration_versions("1.0.0", None, "n1") == ("1.0.0",)


@pytest.mark.skipif(
    os.environ.get("TPU_DRA_E2E_KIND") != "1"
    or shutil.which("docker") is None
    or shutil.which("kind") is None,
    reason="set TPU_DRA_E2E_KIND=1 with docker+kind installed to run the "
           "full gate (it creates and deletes a kind cluster)",
)
class TestFullGate:
    def test_e2e_kind(self):
        r = subprocess.run([E2E], capture_output=True, text=True,
                           timeout=1800)
        sys.stdout.write(r.stdout)
        assert r.returncode == 0, r.stderr
        assert "e2e-kind PASSED" in r.stdout
