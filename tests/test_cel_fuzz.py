"""Grammar-driven fuzz of the CEL-subset engine.

The engine's contract (kube/cel.py): any selector string either evaluates
to a bool or raises CelError — no raw Python exception may escape, because
the allocator maps CelError to "claim unallocatable" while anything else
would kill the controller loop (the round-2 advisory bug class). A
hand-rolled generator walks the supported grammar plus deliberate
out-of-grammar mutations; every sample must keep the contract.
"""

import random

import pytest

from k8s_dra_driver_tpu.kube.cel import CelError, evaluate

DRIVER = "tpu.google.com"

ATTRS = {
    "type": {"string": "chip"},
    "generation": {"string": "v5p"},
    "index": {"int": 2},
    "cores": {"int": 2},
    "coordX": {"int": 1},
    "uuid": {"string": "TPU-abc"},
    "healthy": {"bool": True},
    "driverVersion": {"version": "1.2.3"},
}
CAPACITY = {"hbm": "95Gi", "tensorcores": "2"}

ATTR_NAMES = list(ATTRS) + ["missing", "slice-id"]
STRINGS = ['"chip"', '"v5p"', '"TPU-abc"', '""', '"x"']
INTS = ["0", "1", "2", "-3", "95"]
CMPS = ["==", "!=", "<", "<=", ">", ">="]


def gen_atom(rng: random.Random, depth: int) -> str:
    roll = rng.random()
    if roll < 0.35:
        name = rng.choice(ATTR_NAMES)
        form = rng.random()
        if form < 0.5:
            return f'device.attributes["{DRIVER}"].{name}'
        if form < 0.8:
            return f'device.attributes["{DRIVER}"]["{name}"]'
        return f'device.capacity["{DRIVER}"].{rng.choice(list(CAPACITY))}'
    if roll < 0.5:
        return rng.choice(STRINGS)
    if roll < 0.65:
        return rng.choice(INTS)
    if roll < 0.75:
        return rng.choice(["true", "false"])
    if depth > 2:
        return rng.choice(INTS)
    return "(" + gen_expr(rng, depth + 1) + ")"


def gen_expr(rng: random.Random, depth: int = 0) -> str:
    roll = rng.random()
    a = gen_atom(rng, depth)
    if roll < 0.45:
        return f"{a} {rng.choice(CMPS)} {gen_atom(rng, depth)}"
    if roll < 0.65 and depth < 3:
        return (f"{gen_expr(rng, depth + 1)} "
                f"{rng.choice(['&&', '||'])} {gen_expr(rng, depth + 1)}")
    if roll < 0.75:
        return f"!({gen_expr(rng, depth + 1)})"
    return a


def mutate(rng: random.Random, expr: str) -> str:
    """Push samples OUT of the grammar: truncations, garbage splices."""
    kind = rng.random()
    if kind < 0.3 and expr:
        cut = rng.randrange(len(expr))
        return expr[:cut]
    if kind < 0.6:
        junk = rng.choice(["@@", "0x", "def ", "||&&", '"', "].["])
        pos = rng.randrange(len(expr) + 1)
        return expr[:pos] + junk + expr[pos:]
    return expr + rng.choice(["==", "&&", ".", "[", "~"])


@pytest.mark.parametrize("seed", range(8))
def test_fuzz_contract_holds(seed):
    rng = random.Random(seed)
    for i in range(300):
        expr = gen_expr(rng)
        if i % 3 == 0:
            expr = mutate(rng, expr)
        try:
            out = evaluate(expr, DRIVER, ATTRS, CAPACITY)
        except CelError:
            continue  # rejecting is fine; HOW it rejects is the contract
        assert isinstance(out, bool), (expr, out)


def test_known_type_mismatches_stay_in_contract():
    """The advisory's exact bug class: comparisons across types must not
    leak TypeError."""
    cases = [
        f'device.attributes["{DRIVER}"].uuid >= 16',
        f'device.capacity["{DRIVER}"].hbm >= 16',
        f'device.attributes["{DRIVER}"].index == "two" && true',
        f'!(device.attributes["{DRIVER}"].healthy >= "yes")',
    ]
    for expr in cases:
        try:
            out = evaluate(expr, DRIVER, ATTRS, CAPACITY)
            assert isinstance(out, bool), expr
        except CelError:
            pass
