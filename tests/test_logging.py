"""setup_logging / JsonFormatter tests: env overrides, extra-field merge,
and span correlation in log lines."""

import json
import logging

import pytest

from k8s_dra_driver_tpu.utils.logging import JsonFormatter, setup_logging
from k8s_dra_driver_tpu.utils.tracing import Tracer


@pytest.fixture(autouse=True)
def _restore_root_logger():
    root = logging.getLogger()
    handlers, level = root.handlers[:], root.level
    yield
    root.handlers[:] = handlers
    root.setLevel(level)


def _record(msg="hello", **extra):
    record = logging.LogRecord(
        "test.logger", logging.INFO, __file__, 1, msg, (), None
    )
    for k, v in extra.items():
        setattr(record, k, v)
    return record


class TestJsonFormatter:
    def test_basic_fields(self):
        out = json.loads(JsonFormatter().format(_record()))
        assert out["msg"] == "hello"
        assert out["level"] == "info"
        assert out["logger"] == "test.logger"

    def test_extra_fields_merged(self):
        out = json.loads(JsonFormatter().format(
            _record(claim="default/c1", devices=3)
        ))
        assert out["claim"] == "default/c1"
        assert out["devices"] == 3

    def test_extra_cannot_clobber_core_fields(self):
        record = _record()
        record.__dict__["ts"] = "spoofed"
        out = json.loads(JsonFormatter().format(record))
        assert out["ts"] != "spoofed"

    def test_unserializable_extra_degrades_to_repr(self):
        out = json.loads(JsonFormatter().format(_record(obj=object())))
        assert "object object" in out["obj"]

    def test_span_ids_injected(self):
        t = Tracer()
        with t.span("op", claim_uid="uid-log") as sp:
            out = json.loads(JsonFormatter().format(_record()))
        assert out["traceId"] == sp.trace_id
        assert out["spanId"] == sp.span_id
        assert out["claimUid"] == "uid-log"
        # Outside the span: no trace fields.
        out = json.loads(JsonFormatter().format(_record()))
        assert "traceId" not in out


class TestSetupLogging:
    def _root_state(self):
        root = logging.getLogger()
        return root.level, isinstance(
            root.handlers[0].formatter, JsonFormatter
        )

    def test_env_override_applies_when_unset(self, monkeypatch):
        monkeypatch.setenv("TPU_DRA_LOG_LEVEL", "DEBUG")
        monkeypatch.setenv("TPU_DRA_LOG_FORMAT", "json")
        setup_logging()
        level, is_json = self._root_state()
        assert level == logging.DEBUG
        assert is_json

    def test_cli_args_beat_env(self, monkeypatch):
        monkeypatch.setenv("TPU_DRA_LOG_LEVEL", "DEBUG")
        monkeypatch.setenv("TPU_DRA_LOG_FORMAT", "json")
        setup_logging(level="WARNING", json_format=False)
        level, is_json = self._root_state()
        assert level == logging.WARNING
        assert not is_json

    def test_defaults_without_env(self, monkeypatch):
        monkeypatch.delenv("TPU_DRA_LOG_LEVEL", raising=False)
        monkeypatch.delenv("TPU_DRA_LOG_FORMAT", raising=False)
        setup_logging()
        level, is_json = self._root_state()
        assert level == logging.INFO
        assert not is_json
